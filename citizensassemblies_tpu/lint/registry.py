"""graftcheck-IR core registry: the manifest of hot jitted cores.

The IR verifier (``citizensassemblies_tpu.lint.ir``) can only check what it
can *trace*, so every hot jitted core in the repo registers itself here with
representative abstract shapes. Registration lives next to the core it
describes: each solver module defines a small builder function decorated with
:func:`register_ir_core`, which records (name, source file, line, builder)
without importing jax — the builder constructs the actual
:class:`IRCase` (the jitted callable plus ``jax.ShapeDtypeStruct`` example
arguments) lazily, only when the IR pass runs. The :data:`MANIFEST` lists
the modules that carry registrations, so ``collect()`` can enumerate the
fleet deterministically; a module added to the hot path without a manifest
entry is invisible to the verifier, which is why the manifest is part of the
review surface (README "IR-level verification & cost budgets").

Shapes are deliberately SMALL (a few hundred elements): the IR checks are
about program *structure* — which primitives appear, which donations alias,
how FLOPs/bytes scale per compiled program — not about runtime, so tracing
tiny buckets on CPU keeps ``make check-ir`` inside plain CI.
"""

from __future__ import annotations

import dataclasses
import importlib
import inspect
from pathlib import Path
from typing import Any, Callable, Dict, List, Optional, Tuple


@dataclasses.dataclass
class IRCase:
    """A traceable description of one jitted core.

    ``fn`` must be the jitted callable itself (it needs ``.lower``);
    ``args`` are example operands — normally ``jax.ShapeDtypeStruct``s or
    pytrees of them — and ``static`` the static keyword arguments.
    ``donate_expected`` is how many input→output buffer aliases the
    compiled executable must realize (normally ``len(donate_argnums)``);
    the donation check fails when the lowered module shows fewer, i.e. a
    declared donation was silently dropped. ``allow_f64`` tags the cert
    cores whose arithmetic is float64 *on purpose* — there the dtype check
    inverts and flags f64→f32 ``convert_element_type`` narrowing instead.
    ``x64_trace=False`` skips the enable-x64 dtype trace for kernels whose
    tracing is dtype-pinned some other way.

    ``arg_roles`` is the graftspmd S2 sharding contract: one
    ``dist/partition.py`` role name (a ``ROLE_BUILDERS`` key) per argument,
    or ``None`` for an argument the builder left undeclared. The spmd pass
    attaches each declared role's NamedSharding to the example aval before
    lowering and cross-references the ``mhlo.sharding`` annotations the
    compiler actually emits; an *undeclared* operand above the
    ``spmd_replicated_bytes_max`` threshold is flagged as an implicitly
    replicated mega-operand.

    ``arg_ranges``/``prec_demote`` are the graftgrade P1 contract
    (``lint/prec.py``): ``arg_ranges`` seeds the error-flow abstract
    interpretation with one ``(lo, hi, exact)`` triple per argument —
    ``exact=True`` declares the operand's concrete values are exactly
    representable at bf16 (small-integer composition/constraint entries;
    the runtime ``demote_operator`` round-trip enforces it per array) —
    ``None`` for an argument with no declared range (seeded wide,
    inexact). ``prec_demote`` lists the argument indices the registration
    NOMINATES for bf16 operand demotion; graftgrade certifies (or
    refuses) each nomination and the committed PRECISION_PLAN.json is
    what the runtime actually applies.
    """

    fn: Any
    args: Tuple[Any, ...]
    static: Dict[str, Any] = dataclasses.field(default_factory=dict)
    donate_expected: int = 0
    allow_f64: bool = False
    x64_trace: bool = True
    arg_roles: Optional[Tuple[Optional[str], ...]] = None
    arg_ranges: Optional[Tuple[Optional[Tuple[float, float, bool]], ...]] = None
    prec_demote: Tuple[int, ...] = ()


@dataclasses.dataclass(frozen=True)
class CoreEntry:
    """One registered core: identity, provenance, and the lazy builder.

    ``dense_ref`` names the DENSE registry core this (structured-sparse)
    core is the ELL twin of, registered at the SAME problem shape — the IR
    pass then emits a measured dense→sparse flops/bytes delta for the pair
    into the budget-diff artifact (``lint.ir.budget_diff``).

    ``span``/``span_optout`` are the grafttrace wiring contract (graftlint
    R8): ``span`` names the ``obs.hooks.dispatch_span`` that wraps this
    core's public entry point (the name must appear in a ``dispatch_span``
    call in the registering module); ``span_optout`` is the explicit
    reasoned exemption for cores with no runtime entry of their own (e.g.
    a dense IR comparator whose production dispatch rides another core's
    span).
    """

    name: str
    path: str  # repo-relative source file of the registration (reports)
    line: int  # line of the builder (file:line in PASS/FAIL output)
    build: Callable[[], IRCase]
    dense_ref: Optional[str] = None
    span: Optional[str] = None
    span_optout: Optional[str] = None


@dataclasses.dataclass(frozen=True)
class SpmdEntry:
    """One mesh-consuming core's SPMD registration (graftspmd, ``lint/spmd.py``).

    ``build`` takes the virtual mesh the verifier is sweeping (1/2/4/8
    devices) and returns the :class:`IRCase` for THAT mesh — normally by
    calling the same memoized mesh-keyed factory the production path uses,
    with ``arg_roles`` naming the declared ``dist/partition.py`` layout of
    every argument. ``loop_collectives`` is the reasoned exemption from the
    S2 no-collective-inside-a-while-body check, for cores whose per-iteration
    communication is algorithmically required (the row-sharded GEMV
    reduction); without it, a collective reachable from a ``while`` body is
    a named FAIL.
    """

    name: str
    path: str
    line: int
    build: Callable[[Any], IRCase]  # mesh -> IRCase
    loop_collectives: Optional[str] = None


#: name -> entry, populated by importing the MANIFEST modules
_REGISTRY: Dict[str, CoreEntry] = {}

#: name -> mesh-parameterized SPMD registration (a subset of _REGISTRY names)
_SPMD_REGISTRY: Dict[str, SpmdEntry] = {}

#: every module that registers at least one core. ``collect()`` imports
#: these; keep the list sorted by package path so reports are deterministic.
MANIFEST: Tuple[str, ...] = (
    "citizensassemblies_tpu.kernels.ell_matvec",
    "citizensassemblies_tpu.kernels.pdhg_megakernel",
    "citizensassemblies_tpu.models.legacy",
    "citizensassemblies_tpu.parallel.mc",
    "citizensassemblies_tpu.parallel.solver",
    "citizensassemblies_tpu.parallel.sweep",
    "citizensassemblies_tpu.solvers.batch_lp",
    "citizensassemblies_tpu.solvers.delta",
    "citizensassemblies_tpu.solvers.device_pricing",
    "citizensassemblies_tpu.solvers.face_decompose",
    "citizensassemblies_tpu.solvers.lp_pdhg",
    "citizensassemblies_tpu.solvers.qp",
)


def _rel_path(file: str) -> str:
    """Source path relative to the repo root (the package's parent)."""
    p = Path(file).resolve()
    pkg_root = Path(__file__).resolve().parent.parent.parent
    try:
        return str(p.relative_to(pkg_root))
    except ValueError:
        return str(p)


def register_ir_core(
    name: str,
    dense_ref: Optional[str] = None,
    span: Optional[str] = None,
    span_optout: Optional[str] = None,
) -> Callable:
    """Decorator: register ``build`` as the lazy IRCase builder for ``name``.

    The decorated function takes no arguments and returns an :class:`IRCase`;
    it may import jax freely (it only runs when the IR pass does). The
    registration's ``file:line`` is what the verifier reports for this core.
    ``dense_ref`` marks this core as the structured-sparse (ELL) twin of a
    dense core registered at the same shape (see :class:`CoreEntry`).
    ``span`` names the ``dispatch_span`` wrapping the core's entry point;
    ``span_optout`` is the reasoned exemption — graftlint R8 requires
    exactly one of the two on every registration.
    """

    def deco(build: Callable[[], IRCase]) -> Callable[[], IRCase]:
        src = inspect.getsourcefile(build) or "<unknown>"
        _REGISTRY[name] = CoreEntry(
            name=name,
            path=_rel_path(src),
            line=build.__code__.co_firstlineno,
            build=build,
            dense_ref=dense_ref,
            span=span,
            span_optout=span_optout,
        )
        return build

    return deco


def register_spmd_core(
    name: str,
    loop_collectives: Optional[str] = None,
) -> Callable:
    """Decorator: register ``build(mesh)`` as the SPMD builder for ``name``.

    The decorated function takes the virtual mesh graftspmd is sweeping and
    returns an :class:`IRCase` whose ``arg_roles`` declare each argument's
    ``dist/partition.py`` layout. ``loop_collectives`` is the reasoned
    exemption from the mid-loop-collective check (see :class:`SpmdEntry`);
    leave it ``None`` unless per-iteration communication is the algorithm.
    """

    def deco(build: Callable[[Any], IRCase]) -> Callable[[Any], IRCase]:
        src = inspect.getsourcefile(build) or "<unknown>"
        _SPMD_REGISTRY[name] = SpmdEntry(
            name=name,
            path=_rel_path(src),
            line=build.__code__.co_firstlineno,
            build=build,
            loop_collectives=loop_collectives,
        )
        return build

    return deco


def sparse_pairs() -> Dict[str, str]:
    """``{ell core name: dense twin name}`` for every registered pair —
    the budget-diff artifact's dense→sparse delta table keys off this."""
    return {
        name: e.dense_ref for name, e in _REGISTRY.items() if e.dense_ref
    }


def collect() -> List[CoreEntry]:
    """Import every MANIFEST module and return the registered cores, sorted.

    Import errors propagate: a hot module that no longer imports is itself a
    CI-worthy failure, not something to skip silently.
    """
    for mod in MANIFEST:
        importlib.import_module(mod)
    return [
        _REGISTRY[name] for name in sorted(_REGISTRY)
    ]


def build_cases() -> List[Tuple[str, IRCase]]:
    """``(name, built IRCase)`` for every registered core — the shape
    manifest graftboot's cache builder replays: each case's example avals
    are exactly the budget shapes the IR pass certifies, so recording them
    through the ``aot_seeded`` wrappers seeds the executable cache with
    every core the verifier knows about (``aot/build.py``)."""
    return [(entry.name, entry.build()) for entry in collect()]


def collect_spmd() -> List[SpmdEntry]:
    """Import every MANIFEST module and return the mesh-parameterized SPMD
    registrations, sorted — the cores graftspmd sweeps across virtual mesh
    sizes (every other registered core is censused at its default build)."""
    for mod in MANIFEST:
        importlib.import_module(mod)
    return [_SPMD_REGISTRY[name] for name in sorted(_SPMD_REGISTRY)]
