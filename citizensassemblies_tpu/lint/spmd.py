"""graftspmd: static SPMD verification — collective census, sharding
contracts, and precision flow.

graftcheck-IR (``lint/ir.py``) sees the single-device jaxpr; this pass sees
what the SPMD partitioner builds. The reshard bug class it exists to catch:
one careless ``jnp`` op in a sharded core makes XLA insert an all-gather
that costs nothing on the 1-device CI host and everything on an 8-host mesh
— today observed only after the fact by the ``dist_reshards`` runtime gauge.
Every registered core is AOT-compiled (``fn.lower(...).compile()``), the
mesh-consuming cores additionally under 1/2/4/8-device virtual meshes
(``--xla_force_host_platform_device_count``), and three check families run
over the result:

* **S1 collective census** — ``all-gather`` / ``all-reduce`` /
  ``reduce-scatter`` / ``all-to-all`` / ``collective-permute`` instruction
  counts per core per mesh size, ratcheted against the committed
  ``SPMD_BUDGET.json`` exactly like IR4: a new collective kind or a count
  increase in any core is a named FAIL; ``--update-spmd-budget``
  regenerates the file deliberately. The census runs on *compiled* HLO —
  partitioner-inserted collectives (the silent-reshard class) never appear
  in the pre-SPMD StableHLO.
* **S2 sharding contracts** — each SPMD registration declares its
  arguments' ``dist/partition.py`` roles (``IRCase.arg_roles``); the pass
  attaches the declared NamedShardings, lowers, and cross-references the
  ``mhlo.sharding`` annotations the compiler actually placed on the main
  parameters. It also flags *undeclared* (implicitly replicated) operands
  above ``Config.spmd_replicated_bytes_max``, and any collective reachable
  from a ``while``-loop body — per-iteration comms; the PDHG cores keep
  collectives at check-every boundaries — unless the registration carries
  a reasoned ``loop_collectives`` exemption (the row-sharded GEMV's psum
  is the algorithm, not a regression).
* **S3 precision flow** — dtype propagation through each core's jaxpr,
  classifying every intermediate as ``bf16_safe`` / ``f32_pinned`` /
  ``f64_certification`` (the IR2 cert-tagged cores are the f64 sinks) into
  ``PRECISION_FLOW.json`` — the prerequisite artifact for the
  mixed-precision PDHG (ROADMAP item 5). The classification is per scope:
  comparison/callback consumers, scope outputs and anything feeding an
  f64-producing equation are pinned, so no ``bf16_safe`` value can touch a
  certification path (``cert_isolated``, verified per core).

Run as ``python -m citizensassemblies_tpu.lint --spmd`` (or ``make
check-spmd``); reports use graftlint's ``file:line`` contract, pointing at
each core's registration site.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import re
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

from citizensassemblies_tpu.lint.engine import Violation
from citizensassemblies_tpu.lint.ir import _trace_jaxpr
from citizensassemblies_tpu.lint.registry import (
    CoreEntry,
    IRCase,
    SpmdEntry,
    collect,
    collect_spmd,
)

#: the committed collective-census budget, at the repo root next to the
#: package (same placement as ANALYSIS_BUDGET.json)
SPMD_BUDGET_PATH = (
    Path(__file__).resolve().parent.parent.parent / "SPMD_BUDGET.json"
)

#: the committed precision-flow artifact (S3)
PRECISION_FLOW_PATH = (
    Path(__file__).resolve().parent.parent.parent
    / "artifacts"
    / "PRECISION_FLOW.json"
)

#: the virtual mesh sizes the SPMD registrations are swept across
MESH_SIZES = (1, 2, 4, 8)

#: compiled-HLO collective opcodes (S1). ``-start``/``-done`` async pairs
#: count once, via the start.
COLLECTIVE_OPS = (
    "all-gather",
    "all-reduce",
    "reduce-scatter",
    "all-to-all",
    "collective-permute",
)

_COLL_RE = re.compile(
    r"(?<![%\w-])(" + "|".join(COLLECTIVE_OPS) + r")(-start)?\("
)
_HDR_RE = re.compile(r"^(?:ENTRY\s+)?%?([\w.\-]+)\s*\(.*\{\s*$")
_CALL_REF_RE = re.compile(
    r"(?:to_apply|calls|condition|body|branch_computations)="
    r"(?:%([\w.\-]+)|\{([^}]*)\})"
)
_WHILE_RE = re.compile(r"(?<![%\w-])while\(")
# the attribute dict can nest braces inside quoted strings (the
# mhlo.sharding value itself is "{devices=[2,1]<=[2]}"), so the dict match
# must treat quoted spans as opaque
_ARG_RE = re.compile(r"%arg(\d+): tensor<[^>]*>\s*(\{(?:[^{}\"]|\"[^\"]*\")*\})?")
_MHLO_SHARDING_RE = re.compile(r'mhlo\.sharding = "([^"]+)"')

#: jaxpr consumers that pin their float operands at f32 (S3): comparisons
#: (convergence/feasibility tests — the 1e-3 contract is decided here),
#: host callbacks, and value-ordering primitives whose ties flip under
#: narrowing
_PIN_PRIMS = frozenset(
    {
        "lt", "le", "gt", "ge", "eq", "ne",
        "sort", "argmax", "argmin", "reduce_max", "reduce_min",
        "pure_callback", "io_callback", "debug_callback", "callback",
        "custom_call",
    }
)


# --- compiled-HLO parsing (S1 + the mid-loop check) --------------------------


def _parse_hlo(text: str):
    """``(computations, whiles)`` from compiled-HLO text: per computation
    the collective opcodes it contains and the computations it references;
    plus every ``while`` instruction's (condition, body) computation names."""
    comps: Dict[str, Dict[str, Any]] = {}
    whiles: List[Tuple[Optional[str], Optional[str]]] = []
    cur: Optional[str] = None
    for line in text.splitlines():
        m = _HDR_RE.match(line)
        if m:
            cur = m.group(1)
            comps[cur] = {"colls": [], "calls": set()}
            continue
        if cur is None:
            continue
        for cm in _COLL_RE.finditer(line):
            comps[cur]["colls"].append(cm.group(1))
        for rm in _CALL_REF_RE.finditer(line):
            if rm.group(1):
                comps[cur]["calls"].add(rm.group(1))
            else:
                comps[cur]["calls"].update(
                    t.strip().lstrip("%") for t in rm.group(2).split(",") if t.strip()
                )
        if _WHILE_RE.search(line):
            c = re.search(r"condition=%?([\w.\-]+)", line)
            b = re.search(r"body=%?([\w.\-]+)", line)
            whiles.append((c.group(1) if c else None, b.group(1) if b else None))
    return comps, whiles


def collective_census(hlo_text: str) -> Dict[str, int]:
    """S1: ``{collective opcode: instruction count}`` over a compiled module."""
    census: Dict[str, int] = {}
    comps, _ = _parse_hlo(hlo_text)
    for comp in comps.values():
        for op in comp["colls"]:
            census[op] = census.get(op, 0) + 1
    return census


def loop_collectives(hlo_text: str) -> List[str]:
    """Collective opcodes transitively reachable from any ``while`` BODY
    computation — per-iteration communication. Condition computations are
    deliberately out of scope: a convergence all-reduce at the check-every
    boundary is the contract, not a violation."""
    comps, whiles = _parse_hlo(hlo_text)

    def reach(start: Optional[str]):
        seen: set = set()
        stack = [start] if start else []
        while stack:
            name = stack.pop()
            if name in seen or name not in comps:
                continue
            seen.add(name)
            stack.extend(comps[name]["calls"])
        return seen

    found: set = set()
    for _cond, body in whiles:
        for comp in reach(body):
            found.update(comps[comp]["colls"])
    return sorted(found)


# --- lowered-StableHLO parameter shardings (S2) ------------------------------


def param_shardings(stablehlo_text: str) -> List[Optional[str]]:
    """Per-parameter ``mhlo.sharding`` annotation of the ``@main`` entry
    function, ``None`` for an unannotated (implicitly replicated) one."""
    start = stablehlo_text.find("@main(")
    if start < 0:
        return []
    # the signature normally prints on one line; accumulate until the body
    # opens in case a formatter ever wraps it
    sig_lines: List[str] = []
    for line in stablehlo_text[start:].splitlines():
        sig_lines.append(line)
        if line.rstrip().endswith("{"):
            break
    sig = " ".join(sig_lines)
    out: List[Optional[str]] = []
    for m in _ARG_RE.finditer(sig):
        idx, attrs = int(m.group(1)), m.group(2) or ""
        sh = _MHLO_SHARDING_RE.search(attrs)
        while len(out) <= idx:
            out.append(None)
        out[idx] = sh.group(1) if sh else None
    return out


def _expected_annotation(sharding, ndim: int) -> Optional[str]:
    """The mhlo.sharding string a declared NamedSharding should lower to."""
    try:
        hlo = sharding._to_xla_hlo_sharding(ndim)
    except Exception:  # pragma: no cover - jax internals moved
        return None
    return str(hlo)


# --- S3 precision flow -------------------------------------------------------


def _all_jaxprs(jaxpr):
    """``jaxpr`` and every sub-jaxpr (scan/while/cond/pjit bodies)."""
    yield jaxpr
    for eqn in jaxpr.eqns:
        for value in eqn.params.values():
            items = value if isinstance(value, (list, tuple)) else [value]
            for item in items:
                sub = getattr(item, "jaxpr", item if hasattr(item, "eqns") else None)
                if sub is not None:
                    yield from _all_jaxprs(sub)


def precision_flow(jaxpr) -> Dict[str, Any]:
    """Classify every intermediate value of ``jaxpr`` (recursively, per
    scope) as ``bf16_safe`` / ``f32_pinned`` / ``f64_certification`` /
    ``non_float``.

    A float32/bfloat16 value is *pinned* when a comparison, sort/extremum,
    callback or custom call consumes it, when it is a scope output, or when
    it feeds an equation producing (or converting to) strong float64 — so by
    construction no ``bf16_safe`` value is an operand of the certification
    arithmetic. ``cert_isolated`` re-verifies that invariant explicitly.
    """
    counts = {"bf16_safe": 0, "f32_pinned": 0, "f64_certification": 0, "non_float": 0}
    cert_isolated = True
    classes: Dict[Any, str] = {}
    for sub in _all_jaxprs(jaxpr):
        outvars = {v for v in sub.outvars if hasattr(v, "aval")}
        consumers: Dict[Any, List[Any]] = {}
        for eqn in sub.eqns:
            for var in eqn.invars:
                if hasattr(var, "aval") and not hasattr(var, "val"):
                    consumers.setdefault(var, []).append(eqn)
        for eqn in sub.eqns:
            for var in eqn.outvars:
                aval = getattr(var, "aval", None)
                dtype = str(getattr(aval, "dtype", ""))
                if not dtype.startswith(("float", "bfloat")):
                    cls = "non_float"
                elif dtype == "float64" and not getattr(aval, "weak_type", False):
                    cls = "f64_certification"
                else:
                    cls = "bf16_safe"
                    if var in outvars:
                        cls = "f32_pinned"
                    for consumer in consumers.get(var, []):
                        if consumer.primitive.name in _PIN_PRIMS:
                            cls = "f32_pinned"
                            break
                        feeds_f64 = any(
                            str(getattr(o.aval, "dtype", "")) == "float64"
                            and not getattr(o.aval, "weak_type", False)
                            for o in consumer.outvars
                            if hasattr(o, "aval")
                        )
                        if feeds_f64:
                            cls = "f32_pinned"
                            break
                counts[cls] += 1
                classes[var] = cls
        # the explicit invariant: no bf16-safe value is a direct operand of
        # an f64-producing equation in its scope
        for eqn in sub.eqns:
            produces_f64 = any(
                str(getattr(o.aval, "dtype", "")) == "float64"
                and not getattr(o.aval, "weak_type", False)
                for o in eqn.outvars
                if hasattr(o, "aval")
            )
            if not produces_f64:
                continue
            for var in eqn.invars:
                if classes.get(var) == "bf16_safe":
                    cert_isolated = False
    total = sum(counts.values())
    return {**counts, "total": total, "cert_isolated": cert_isolated}


# --- per-core verification ---------------------------------------------------


@dataclasses.dataclass
class SpmdCoreReport:
    """Verification outcome for one registered core across its builds."""

    name: str
    path: str
    line: int
    violations: List[Violation] = dataclasses.field(default_factory=list)
    #: {"base": {op: n}, "mesh1": {op: n}, ...} — the measured S1 census
    census: Optional[Dict[str, Dict[str, int]]] = None
    #: the S3 classification of the base build
    precision: Optional[Dict[str, Any]] = None
    #: reasoned mid-loop-collective exemption, when registered
    loop_exempt: Optional[str] = None

    @property
    def ok(self) -> bool:
        return not self.violations


@dataclasses.dataclass
class SpmdReport:
    """The whole pass: per-core reports plus budget bookkeeping."""

    cores: List[SpmdCoreReport]
    budget_path: str
    mesh_sizes: List[int]
    updated: bool = False

    @property
    def violations(self) -> List[Violation]:
        return [v for c in self.cores for v in c.violations]

    @property
    def ok(self) -> bool:
        return not self.violations


def _viol(entry, rule: str, name: str, message: str) -> Violation:
    return Violation(
        path=entry.path, line=entry.line, col=0, rule=rule, name=name,
        message=f"[{entry.name}] {message}",
    )


def _replicated_bytes_max() -> int:
    from citizensassemblies_tpu.utils.config import default_config

    return int(default_config().spmd_replicated_bytes_max)


def _aval_bytes(a) -> int:
    import numpy as np

    shape = getattr(a, "shape", ())
    dtype = getattr(a, "dtype", None)
    if dtype is None:
        return 0
    return int(np.prod(shape, dtype=np.int64) * np.dtype(dtype).itemsize) if shape else int(np.dtype(dtype).itemsize)


def _sharded_args(case: IRCase, mesh):
    """The example avals with each declared role's NamedSharding attached
    (undeclared arguments stay as built — implicitly replicated)."""
    import jax

    from citizensassemblies_tpu.dist import partition as dist_partition

    roles = case.arg_roles or (None,) * len(case.args)
    out = []
    for a, role in zip(case.args, roles):
        if role is None:
            out.append(a)
            continue
        sharding = dist_partition.role_sharding(mesh, role, len(a.shape))
        out.append(jax.ShapeDtypeStruct(a.shape, a.dtype, sharding=sharding))
    return tuple(out)


def _lower(case: IRCase, args):
    return case.fn.lower(*args, **case.static)


def _census_one(
    entry,
    report: SpmdCoreReport,
    case: IRCase,
    args,
    size_key: str,
    exempt: Optional[str],
) -> Optional[Dict[str, int]]:
    """Compile one build, record its census, run the mid-loop check."""
    try:
        hlo = _lower(case, args).compile().as_text()
    except Exception as exc:  # noqa: BLE001 - reported, not raised
        report.violations.append(
            _viol(
                entry, "S0", "uncompilable-core",
                f"lower/compile failed at {size_key}: {exc!r}",
            )
        )
        return None
    census = collective_census(hlo)
    in_loop = loop_collectives(hlo)
    if in_loop and exempt is None:
        report.violations.append(
            _viol(
                entry, "S2", "collective-in-loop-body",
                f"collective(s) {', '.join(in_loop)} reachable from a "
                f"while-loop body at {size_key} — per-iteration communication; "
                "keep collectives at check-every boundaries, or register the "
                "core with a reasoned loop_collectives= exemption if the "
                "per-iteration reduction IS the algorithm",
            )
        )
    return census


def _check_contract(entry, report: SpmdCoreReport, case: IRCase, mesh, size_key: str):
    """S2: declared roles vs actual mhlo.sharding annotations, plus the
    implicitly-replicated mega-operand check."""
    from citizensassemblies_tpu.dist import partition as dist_partition

    args = _sharded_args(case, mesh)
    try:
        text = _lower(case, args).as_text()
    except Exception as exc:  # noqa: BLE001
        report.violations.append(
            _viol(
                entry, "S0", "unlowerable-core",
                f"lower failed at {size_key}: {exc!r}",
            )
        )
        return
    actual = param_shardings(text)
    roles = case.arg_roles or (None,) * len(case.args)
    threshold = _replicated_bytes_max()
    n_devices = int(mesh.devices.size)
    for i, (a, role) in enumerate(zip(case.args, roles)):
        got = actual[i] if i < len(actual) else None
        if role is None:
            if n_devices > 1 and _aval_bytes(a) > threshold:
                report.violations.append(
                    _viol(
                        entry, "S2", "implicit-replication",
                        f"argument {i} ({_aval_bytes(a)} bytes) has no "
                        f"declared dist/partition.py role at {size_key} — "
                        "implicitly replicated on every device; declare its "
                        "role in arg_roles ('replicated' if that IS the "
                        "layout) or shard it",
                    )
                )
            continue
        if n_devices == 1:
            # every layout over one device is the same layout; XLA
            # canonicalizes them all to "{maximal device=0}"
            continue
        expected = _expected_annotation(
            dist_partition.role_sharding(mesh, role, len(a.shape)), len(a.shape)
        )
        if expected is None:
            continue  # jax internals unavailable — contract not checkable
        if got is None and expected == "{replicated}":
            continue  # unannotated == replicated
        if got != expected:
            report.violations.append(
                _viol(
                    entry, "S2", "sharding-contract-mismatch",
                    f"argument {i} declared role '{role}' lowers to "
                    f"{got or '<unannotated>'} instead of {expected} at "
                    f"{size_key} — the declared dist/partition.py spec and "
                    "the compiled layout disagree",
                )
            )


def verify_spmd_core(
    entry: CoreEntry,
    spmd_entry: Optional[SpmdEntry],
    budget: Optional[Dict[str, Dict[str, int]]],
    mesh_sizes: Sequence[int],
) -> SpmdCoreReport:
    """Run S1–S3 for one registered core; check failures become violations,
    never exceptions (a core that no longer builds is reported too)."""
    report = SpmdCoreReport(name=entry.name, path=entry.path, line=entry.line)
    report.loop_exempt = spmd_entry.loop_collectives if spmd_entry else None
    try:
        base_case = entry.build()
    except Exception as exc:  # noqa: BLE001
        report.violations.append(
            _viol(entry, "S0", "untraceable-core", f"builder failed: {exc!r}")
        )
        return report

    measured: Dict[str, Dict[str, int]] = {}
    base_census = _census_one(
        entry, report, base_case, base_case.args, "base", report.loop_exempt
    )
    if base_census is not None:
        measured["base"] = base_census

    # --- S3: precision flow of the base build ------------------------------
    try:
        closed = _trace_jaxpr(
            base_case, x64=base_case.allow_f64 and base_case.x64_trace
        )
        report.precision = precision_flow(closed.jaxpr)
        report.precision["cert_sink"] = bool(base_case.allow_f64)
        if not report.precision["cert_isolated"]:
            report.violations.append(
                _viol(
                    entry, "S3", "bf16-unsafe-cert-contact",
                    "a bf16-safe intermediate is a direct operand of the "
                    "float64 certification arithmetic — the precision-flow "
                    "classification must pin every value feeding an f64 sink",
                )
            )
    except Exception as exc:  # noqa: BLE001
        report.violations.append(
            _viol(entry, "S0", "untraceable-core", f"precision trace failed: {exc!r}")
        )

    # --- the virtual-mesh sweep (SPMD registrations only) ------------------
    if spmd_entry is not None:
        from citizensassemblies_tpu.dist.runtime import topology_mesh

        for size in mesh_sizes:
            key = f"mesh{size}"
            mesh = topology_mesh(size)
            try:
                case = spmd_entry.build(mesh)
            except Exception as exc:  # noqa: BLE001
                report.violations.append(
                    _viol(
                        entry, "S0", "untraceable-core",
                        f"spmd builder failed at {key}: {exc!r}",
                    )
                )
                continue
            args = _sharded_args(case, mesh)
            census = _census_one(entry, report, case, args, key, report.loop_exempt)
            if census is not None:
                measured[key] = census
            _check_contract(entry, report, case, mesh, key)

    report.census = measured

    # --- S1: the ratchet ----------------------------------------------------
    if budget is None:
        report.violations.append(
            _viol(
                entry, "S1", "missing-budget",
                "no entry in the SPMD budget — run 'python -m "
                "citizensassemblies_tpu.lint --spmd --update-spmd-budget' "
                "and commit the result",
            )
        )
        return report
    for size_key, census in sorted(measured.items()):
        allowed = budget.get(size_key)
        if allowed is None:
            report.violations.append(
                _viol(
                    entry, "S1", "missing-budget",
                    f"no budgeted census for {size_key} — re-ratchet with "
                    "--update-spmd-budget",
                )
            )
            continue
        for op, count in sorted(census.items()):
            if op not in allowed:
                report.violations.append(
                    _viol(
                        entry, "S1", "new-collective",
                        f"collective '{op}' ({count}x) at {size_key} is new "
                        "to this core — the silent-reshard class; find the "
                        "op that introduced it, or re-ratchet with "
                        "--update-spmd-budget if the communication is "
                        "deliberate",
                    )
                )
            elif count > int(allowed[op]):
                report.violations.append(
                    _viol(
                        entry, "S1", "collective-count-exceeded",
                        f"collective '{op}' count regressed at {size_key}: "
                        f"{count} > budgeted {allowed[op]} — re-ratchet with "
                        "--update-spmd-budget if intentional",
                    )
                )
    return report


# --- budget file -------------------------------------------------------------


def load_spmd_budget(path: Path) -> Dict[str, Any]:
    if not path.exists():
        return {}
    data = json.loads(path.read_text(encoding="utf-8"))
    return dict(data.get("cores", {}))


def write_spmd_budget(
    path: Path, reports: Sequence[SpmdCoreReport], mesh_sizes: Sequence[int]
) -> None:
    import jax

    data = {
        "_meta": {
            "jax": jax.__version__,
            "mesh_sizes": list(mesh_sizes),
            "generated_by": (
                "python -m citizensassemblies_tpu.lint --spmd "
                "--update-spmd-budget"
            ),
        },
        "cores": {r.name: r.census for r in reports if r.census is not None},
    }
    path.write_text(
        json.dumps(data, indent=1, sort_keys=True) + "\n", encoding="utf-8"
    )


def spmd_budget_provenance(path: Optional[Path] = None) -> Dict[str, Any]:
    """Compact provenance of the committed SPMD budget, for bench evidence
    rows — the same attribution contract as ``ir.budget_provenance``."""
    path = path or SPMD_BUDGET_PATH
    if not path.exists():
        return {"file": path.name, "missing": True}
    raw = path.read_bytes()
    data = json.loads(raw.decode("utf-8"))
    meta = data.get("_meta", {})
    return {
        "file": path.name,
        "sha256": hashlib.sha256(raw).hexdigest()[:12],
        "cores": len(data.get("cores", {})),
        "mesh_sizes": meta.get("mesh_sizes"),
        "jax": meta.get("jax"),
    }


# --- the pass ----------------------------------------------------------------


def available_mesh_sizes() -> List[int]:
    """The MESH_SIZES the current backend can actually build (CI bootstraps
    8 virtual CPU devices; a smaller host still verifies what it can)."""
    import jax

    n = len(jax.devices())
    return [s for s in MESH_SIZES if s <= n]


def run_spmd_checks(
    entries: Optional[Sequence[CoreEntry]] = None,
    spmd_entries: Optional[Sequence[SpmdEntry]] = None,
    budget_path: Optional[Path] = None,
    update_budget: bool = False,
    mesh_sizes: Optional[Sequence[int]] = None,
    precision_out: Optional[Path] = None,
) -> SpmdReport:
    """Verify every registered core (or ``entries``) against the SPMD budget.

    ``update_budget=True`` re-measures and REWRITES the budget file (the
    deliberate ratchet move); S1 violations are then dropped — the new
    budget is the measurement — while S2/S3 still fail. ``precision_out``
    writes the S3 artifact (``PRECISION_FLOW.json`` in CI).
    """
    budget_path = Path(budget_path) if budget_path is not None else SPMD_BUDGET_PATH
    entries = list(entries) if entries is not None else collect()
    spmd_by_name = {
        e.name: e
        for e in (spmd_entries if spmd_entries is not None else collect_spmd())
    }
    sizes = list(mesh_sizes) if mesh_sizes is not None else available_mesh_sizes()
    budgets = load_spmd_budget(budget_path)

    reports = [
        verify_spmd_core(e, spmd_by_name.get(e.name), budgets.get(e.name), sizes)
        for e in entries
    ]

    if update_budget:
        write_spmd_budget(budget_path, reports, sizes)
        for rep in reports:
            rep.violations = [v for v in rep.violations if v.rule != "S1"]
    else:
        known = {e.name for e in entries}
        for name in sorted(set(budgets) - known):
            reports.append(
                SpmdCoreReport(
                    name=name,
                    path=str(budget_path.name),
                    line=1,
                    violations=[
                        Violation(
                            path=str(budget_path.name), line=1, col=0,
                            rule="S1", name="stale-budget-entry",
                            message=(
                                f"[{name}] SPMD budget entry has no "
                                "registered core — remove it via "
                                "--update-spmd-budget"
                            ),
                        )
                    ],
                )
            )

    report = SpmdReport(
        cores=reports,
        budget_path=str(budget_path),
        mesh_sizes=sizes,
        updated=update_budget,
    )
    if precision_out is not None:
        Path(precision_out).write_text(
            json.dumps(precision_report(report), indent=1, sort_keys=True) + "\n",
            encoding="utf-8",
        )
    return report


def precision_report(report: SpmdReport) -> Dict[str, Any]:
    """The S3 artifact: every core's intermediate classification counts and
    the per-core cert-isolation verdict."""
    import jax

    return {
        "_meta": {
            "jax": jax.__version__,
            "classes": ["bf16_safe", "f32_pinned", "f64_certification", "non_float"],
            "generated_by": "python -m citizensassemblies_tpu.lint --spmd",
        },
        "cores": {
            r.name: r.precision for r in report.cores if r.precision is not None
        },
    }


def spmd_budget_diff(report: SpmdReport) -> Dict[str, Any]:
    """Measured-vs-budget comparison for the CI build artifact, with the
    ``spmd_deltas`` communication-scaling table (the mesh-size growth of
    each swept core's collective count — the weak-scaling comm evidence,
    mirroring ``sparse_deltas`` in the IR diff)."""
    budgets = load_spmd_budget(Path(report.budget_path))
    cores: Dict[str, Any] = {}
    deltas: Dict[str, Any] = {}
    for rep in report.cores:
        entry: Dict[str, Any] = {"status": "PASS" if rep.ok else "FAIL"}
        if rep.census is not None:
            entry["measured"] = rep.census
            budget = budgets.get(rep.name)
            if budget:
                entry["budget"] = budget
        cores[rep.name] = entry
        mesh_keys = sorted(
            (k for k in (rep.census or {}) if k.startswith("mesh")),
            key=lambda k: int(k[4:]),
        )
        if len(mesh_keys) >= 2:
            per_size = {
                k: sum(rep.census[k].values()) for k in mesh_keys
            }
            first, last = mesh_keys[0], mesh_keys[-1]
            deltas[rep.name] = {
                "per_size": per_size,
                f"{first}_total": per_size[first],
                f"{last}_total": per_size[last],
                "growth": per_size[last] - per_size[first],
                "loop_exempt": rep.loop_exempt,
            }
    return {
        "budget_file": report.budget_path,
        "mesh_sizes": report.mesh_sizes,
        "provenance": spmd_budget_provenance(Path(report.budget_path)),
        "spmd_deltas": deltas,
        "cores": cores,
    }


def render_spmd_report(report: SpmdReport) -> str:
    """graftlint-style text: violations in file:line form, then per-core
    PASS/FAIL lines, then the summary tail."""
    lines = [v.render() for v in report.violations]
    for rep in sorted(report.cores, key=lambda r: r.name):
        status = "PASS" if rep.ok else "FAIL"
        extra = ""
        if rep.census is not None:
            total = sum(sum(c.values()) for c in rep.census.values())
            extra = f" (collectives={total} over {len(rep.census)} build(s))"
        lines.append(f"{rep.path}:{rep.line}: {status} [{rep.name}]{extra}")
    n_fail = sum(1 for r in report.cores if not r.ok)
    lines.append(
        f"graftspmd: {len(report.cores)} core(s) verified at mesh sizes "
        f"{report.mesh_sizes}, {n_fail} failing, budget={report.budget_path}"
        + (" (updated)" if report.updated else "")
    )
    return "\n".join(lines)


def spmd_report_as_json(report: SpmdReport) -> Dict[str, Any]:
    """Stable JSON schema shared with the AST and IR passes."""
    return {
        "schema_version": 1,
        "pass": "spmd",
        "ok": report.ok,
        "budget": report.budget_path,
        "mesh_sizes": report.mesh_sizes,
        "updated": report.updated,
        "cores": [
            {
                "core": rep.name,
                "path": rep.path,
                "line": rep.line,
                "status": "PASS" if rep.ok else "FAIL",
                "census": rep.census,
                "precision": rep.precision,
            }
            for rep in sorted(report.cores, key=lambda r: r.name)
        ],
        "violations": [dataclasses.asdict(v) for v in report.violations],
    }
