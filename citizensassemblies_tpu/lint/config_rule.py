"""graftlint R6: Config-knob hygiene (cross-file).

Every field of the frozen ``Config`` dataclass must be

* **read somewhere in the package** — an attribute access ``cfg.field``, a
  ``getattr(x, "field")``, or membership in a string registry (a tuple/list/
  dict of field-name strings, e.g. the analysis cache's ``_KEY_FIELDS``);
  docstrings and bare comments do NOT count, so a knob nothing consumes is
  dead config and fails; and
* **documented in README** — the field name must appear verbatim in the
  repo's README (the "Configuration knobs" table).

The rule finds the Config class by walking the scanned modules for a
``class Config`` with dataclass-style annotated fields, so it works on any
package layout (and on the self-test fixtures).
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from citizensassemblies_tpu.lint.engine import ModuleSource, Violation


def _config_fields(mod: ModuleSource) -> List[Tuple[str, int]]:
    """(field, line) pairs of the annotated fields of a Config class."""
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.ClassDef) and node.name == "Config":
            return [
                (st.target.id, st.lineno)
                for st in node.body
                if isinstance(st, ast.AnnAssign) and isinstance(st.target, ast.Name)
            ]
    return []


def _reads_in_module(mod: ModuleSource) -> Set[str]:
    """Names this module plausibly READS as config knobs: attribute
    accesses, getattr literals, and strings inside container literals
    (registry pattern). Docstrings are plain Expr constants and excluded by
    the container requirement."""
    reads: Set[str] = set()
    for node in ast.walk(mod.tree):
        if isinstance(node, ast.Attribute) and isinstance(node.ctx, ast.Load):
            reads.add(node.attr)
        elif isinstance(node, ast.Call):
            d = node.func
            if isinstance(d, ast.Name) and d.id == "getattr" and len(node.args) >= 2:
                arg = node.args[1]
                if isinstance(arg, ast.Constant) and isinstance(arg.value, str):
                    reads.add(arg.value)
        elif isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            for elt in node.elts:
                if isinstance(elt, ast.Constant) and isinstance(elt.value, str):
                    reads.add(elt.value)
        elif isinstance(node, ast.Dict):
            for key in node.keys:
                if isinstance(key, ast.Constant) and isinstance(key.value, str):
                    reads.add(key.value)
    return reads


def _find_readme(modules: Sequence[ModuleSource], explicit: Optional[Path]) -> Optional[Path]:
    if explicit is not None:
        return explicit if explicit.exists() else None
    # nearest README.md above the config module
    for mod in modules:
        if mod.path.name == "config.py":
            for parent in mod.path.resolve().parents:
                candidate = parent / "README.md"
                if candidate.exists():
                    return candidate
    return None


class ConfigKnobRule:
    rule_id = "R6"
    name = "config-knob-hygiene"
    description = "every Config field must be read in-package and README-documented"

    def check_package(
        self, modules: Sequence[ModuleSource], readme: Optional[Path] = None
    ) -> List[Violation]:
        config_mod: Optional[ModuleSource] = None
        fields: List[Tuple[str, int]] = []
        for mod in modules:
            got = _config_fields(mod)
            if got:
                config_mod, fields = mod, got
                break
        if config_mod is None:
            return []

        # "read somewhere" means read IN THE PACKAGE that owns the Config:
        # with the lint scope extended to bench.py and tests/, a knob whose
        # only consumer is a test would otherwise stop counting as dead
        pkg_root = config_mod.rel.replace("\\", "/").split("/", 1)[0]
        reads: Set[str] = set()
        for mod in modules:
            if mod is config_mod:
                continue
            rel = mod.rel.replace("\\", "/")
            if "/" in config_mod.rel.replace("\\", "/") and not rel.startswith(
                pkg_root + "/"
            ):
                continue
            reads |= _reads_in_module(mod)

        readme_path = _find_readme(modules, readme)
        readme_text = readme_path.read_text(encoding="utf-8") if readme_path else ""

        out: List[Violation] = []
        for field, line in fields:
            if field not in reads:
                out.append(
                    Violation(
                        path=config_mod.rel, line=line, col=4,
                        rule=self.rule_id, name=self.name,
                        message=(
                            f"Config.{field} is never read in the package — "
                            "dead knob: wire it or remove it"
                        ),
                    )
                )
            if readme_text and field not in readme_text:
                out.append(
                    Violation(
                        path=config_mod.rel, line=line, col=4,
                        rule=self.rule_id, name=self.name,
                        message=(
                            f"Config.{field} is not documented in "
                            f"{readme_path.name} — add it to the "
                            "configuration-knob table"
                        ),
                    )
                )
        return out
