"""graftcheck-IR: jaxpr/HLO-level invariant verification with cost budgets.

The AST linter (``lint.rules``, R1–R7) sees *source text*; this pass sees
what the compiler actually builds. Every core in the registry
(``lint.registry``) is traced with ``jax.make_jaxpr`` and AOT-compiled via
``fn.lower(...).compile()`` on whatever backend is present (CPU in CI), and
four invariant classes are checked against the IR:

* **IR1 callback-in-core** — no ``pure_callback`` / ``io_callback`` /
  ``debug_callback`` / host-callback primitive anywhere in a core's jaxpr
  (recursively through pjit/scan/while/cond sub-jaxprs). A callback inside a
  hot core serializes the device pipeline on the host every dispatch.
* **IR2 f64-in-core** — the core is retraced under ``enable_x64`` and every
  equation output aval is checked: a *strong* float64 anywhere outside the
  cert-tagged cores means an explicit f64 request survived into the program
  (with x64 off it silently truncates to f32 — the bug R4 can only see when
  it is spelled ``jnp.float64`` in source). Weak-typed f64 scalars (python
  floats) are exempt — they canonicalize to f32 in the real x64-off runtime.
  Cert-tagged cores (``allow_f64``) invert the check: no strong-f64 →
  float32 ``convert_element_type`` narrowing inside them.
* **IR3 dropped-donation** — the lowered module must realize exactly the
  declared number of input→output buffer aliases (``tf.aliasing_output`` in
  the StableHLO). jax only *warns* when a donation is unusable; here the
  silently-dropped donation is a named FAIL.
* **IR4 cost-budget** — XLA ``cost_analysis()`` FLOPs + bytes accessed plus
  the jaxpr primitive histogram, checked against the committed
  ``ANALYSIS_BUDGET.json`` with a tolerance ratchet: CI fails when a core's
  cost regresses beyond ``(1 + tolerance)×`` its budget or a new primitive
  class appears; ``--update-budget`` regenerates the file deliberately.

Run as ``python -m citizensassemblies_tpu.lint --ir`` (or ``make check-ir``);
reports use graftlint's ``file:line`` contract, pointing at each core's
registration site.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

from citizensassemblies_tpu.lint.engine import Violation
from citizensassemblies_tpu.lint.registry import CoreEntry, IRCase, collect

#: default headroom of the cost ratchet: measured ≤ budget × (1 + tolerance).
#: Wide enough to absorb minor XLA-version drift, tight enough that a doubled
#: matvec or an un-fused pass shows up.
DEFAULT_TOLERANCE = 0.25

#: primitives that execute host code from inside a compiled program
_CALLBACK_PRIMS = frozenset(
    {
        "pure_callback",
        "io_callback",
        "debug_callback",
        "callback",
        "outside_call",  # legacy host_callback
    }
)

#: the default committed budget file, at the repo root next to the package
BUDGET_PATH = Path(__file__).resolve().parent.parent.parent / "ANALYSIS_BUDGET.json"


@dataclasses.dataclass
class CoreReport:
    """Verification outcome for one registered core."""

    name: str
    path: str
    line: int
    violations: List[Violation] = dataclasses.field(default_factory=list)
    measured: Optional[Dict[str, Any]] = None  # {"flops", "bytes", "prims"}

    @property
    def ok(self) -> bool:
        return not self.violations


@dataclasses.dataclass
class IRReport:
    """The whole pass: per-core reports plus budget bookkeeping."""

    cores: List[CoreReport]
    budget_path: str
    tolerance: float
    updated: bool = False

    @property
    def violations(self) -> List[Violation]:
        return [v for c in self.cores for v in c.violations]

    @property
    def ok(self) -> bool:
        return not self.violations


# --- jaxpr walking ----------------------------------------------------------


def _sub_jaxprs(value):
    """Yield Jaxpr objects reachable from one eqn param value."""
    items = value if isinstance(value, (list, tuple)) else [value]
    for item in items:
        if hasattr(item, "jaxpr"):  # ClosedJaxpr
            yield item.jaxpr
        elif hasattr(item, "eqns"):  # Jaxpr
            yield item


def iter_eqns(jaxpr):
    """Every equation in ``jaxpr``, recursing through sub-jaxprs (pjit
    bodies, scan/while carries, cond branches, pallas kernels, ...)."""
    for eqn in jaxpr.eqns:
        yield eqn
        for value in eqn.params.values():
            for sub in _sub_jaxprs(value):
                yield from iter_eqns(sub)


def primitive_histogram(jaxpr) -> Dict[str, int]:
    hist: Dict[str, int] = {}
    for eqn in iter_eqns(jaxpr):
        hist[eqn.primitive.name] = hist.get(eqn.primitive.name, 0) + 1
    return hist


def _strong_f64_prims(jaxpr) -> List[str]:
    """Primitive names producing a strong-typed float64 output."""
    out: List[str] = []
    for eqn in iter_eqns(jaxpr):
        for var in eqn.outvars:
            aval = var.aval
            if (
                hasattr(aval, "dtype")
                and str(aval.dtype) == "float64"
                and not getattr(aval, "weak_type", False)
            ):
                out.append(eqn.primitive.name)
                break
    return out


def _f64_narrowing_count(jaxpr) -> int:
    """``convert_element_type`` equations narrowing strong f64 → f32."""
    count = 0
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        ins = [v.aval for v in eqn.invars if hasattr(v.aval, "dtype")]
        outs = [v.aval for v in eqn.outvars if hasattr(v.aval, "dtype")]
        if not ins or not outs:
            continue
        if (
            str(ins[0].dtype) == "float64"
            and not getattr(ins[0], "weak_type", False)
            and str(outs[0].dtype) == "float32"
        ):
            count += 1
    return count


# --- per-core verification --------------------------------------------------


def _viol(entry: CoreEntry, rule: str, name: str, message: str) -> Violation:
    return Violation(
        path=entry.path, line=entry.line, col=0, rule=rule, name=name,
        message=f"[{entry.name}] {message}",
    )


def _trace_jaxpr(case: IRCase, x64: bool):
    import jax
    from functools import partial

    traced = partial(case.fn, **case.static) if case.static else case.fn
    if x64:
        with jax.experimental.enable_x64():
            return jax.make_jaxpr(traced)(*case.args)
    return jax.make_jaxpr(traced)(*case.args)


def _cost_analysis(compiled) -> Dict[str, float]:
    got = compiled.cost_analysis()
    if isinstance(got, (list, tuple)):
        got = got[0] if got else {}
    return dict(got or {})


def verify_core(
    entry: CoreEntry,
    budget: Optional[Dict[str, Any]],
    tolerance: float,
) -> CoreReport:
    """Run IR1–IR4 for one registered core; never raises on check failures
    (they become violations), only on infrastructure errors (a core that no
    longer traces is reported as a violation too, with the exception text)."""
    report = CoreReport(name=entry.name, path=entry.path, line=entry.line)
    try:
        case = entry.build()
    except Exception as exc:  # noqa: BLE001 - reported, not raised
        report.violations.append(
            _viol(entry, "IR0", "untraceable-core", f"builder failed: {exc!r}")
        )
        return report

    # --- trace (normal mode): callbacks + primitive histogram --------------
    try:
        closed = _trace_jaxpr(case, x64=False)
    except Exception as exc:  # noqa: BLE001
        report.violations.append(
            _viol(entry, "IR0", "untraceable-core", f"make_jaxpr failed: {exc!r}")
        )
        return report
    hist = primitive_histogram(closed.jaxpr)
    for prim in sorted(set(hist) & _CALLBACK_PRIMS):
        report.violations.append(
            _viol(
                entry, "IR1", "callback-in-core",
                f"'{prim}' primitive inside the jitted core "
                f"({hist[prim]}×) — host callbacks serialize the device "
                "pipeline every dispatch; hoist the host work out of the core",
            )
        )

    # --- dtype discipline under enable_x64 ----------------------------------
    if case.x64_trace:
        try:
            closed64 = _trace_jaxpr(case, x64=True)
        except Exception as exc:  # noqa: BLE001
            report.violations.append(
                _viol(
                    entry, "IR2", "f64-in-core",
                    f"core does not trace under enable_x64 ({exc!r}) — "
                    "dtype-pin the offending literals (see kernels/ell_matvec) "
                    "or tag the registration x64_trace=False with a reason",
                )
            )
        else:
            if case.allow_f64:
                narrowed = _f64_narrowing_count(closed64.jaxpr)
                if narrowed:
                    report.violations.append(
                        _viol(
                            entry, "IR2", "f64-narrowed-in-cert-core",
                            f"{narrowed} float64→float32 convert_element_type "
                            "inside a cert-tagged core — the certification "
                            "arithmetic must stay float64 end to end",
                        )
                    )
            else:
                bad = sorted(set(_strong_f64_prims(closed64.jaxpr)))
                if bad:
                    report.violations.append(
                        _viol(
                            entry, "IR2", "f64-in-core",
                            "strong float64 output(s) from "
                            f"{', '.join(bad)} — with x64 disabled these "
                            "silently truncate to float32 at runtime; make "
                            "the dtype explicit or move the arithmetic to "
                            "the host float64 path",
                        )
                    )

    # --- AOT compile: donation aliasing + cost model ------------------------
    try:
        lowered = case.fn.lower(*case.args, **case.static)
        mlir = lowered.as_text()
        compiled = lowered.compile()
    except Exception as exc:  # noqa: BLE001
        report.violations.append(
            _viol(entry, "IR0", "uncompilable-core", f"lower/compile failed: {exc!r}")
        )
        return report

    realized = mlir.count("tf.aliasing_output")
    if realized != case.donate_expected:
        verb = "dropped" if realized < case.donate_expected else "extra"
        report.violations.append(
            _viol(
                entry, "IR3", "dropped-donation",
                f"declared {case.donate_expected} donated buffer(s) but the "
                f"compiled module realizes {realized} input/output alias(es) "
                f"— {verb} donation(s); a dropped donation allocates a fresh "
                "carry every call (jax only warns once, at lowering)",
            )
        )

    cost = _cost_analysis(compiled)
    measured = {
        "flops": float(cost.get("flops", 0.0)),
        "bytes": float(cost.get("bytes accessed", 0.0)),
        "prims": {k: hist[k] for k in sorted(hist)},
    }
    report.measured = measured

    if budget is None:
        report.violations.append(
            _viol(
                entry, "IR4", "missing-budget",
                "no entry in the analysis budget — run "
                "'python -m citizensassemblies_tpu.lint --ir --update-budget' "
                "and commit the result",
            )
        )
        return report

    for metric in ("flops", "bytes"):
        allowed = float(budget.get(metric, 0.0)) * (1.0 + tolerance)
        if measured[metric] > allowed:
            report.violations.append(
                _viol(
                    entry, "IR4", f"{metric}-budget-exceeded",
                    f"{metric} regressed: measured {measured[metric]:.0f} > "
                    f"budget {float(budget.get(metric, 0.0)):.0f} × "
                    f"(1 + {tolerance:g}) — if intentional, re-ratchet with "
                    "--update-budget",
                )
            )
    budget_prims: Dict[str, int] = dict(budget.get("prims", {}))
    for prim, count in measured["prims"].items():
        if prim not in budget_prims:
            report.violations.append(
                _viol(
                    entry, "IR4", "new-primitive",
                    f"primitive '{prim}' ({count}×) is new to this core — "
                    "not in its budgeted histogram; re-ratchet with "
                    "--update-budget if intentional",
                )
            )
            continue
        allowed_n = math.ceil(budget_prims[prim] * (1.0 + tolerance))
        if count > allowed_n:
            report.violations.append(
                _viol(
                    entry, "IR4", "primitive-count-exceeded",
                    f"primitive '{prim}' count regressed: {count} > "
                    f"{budget_prims[prim]} × (1 + {tolerance:g})",
                )
            )
    return report


# --- budget file ------------------------------------------------------------


def load_budget(path: Path) -> Tuple[Dict[str, Any], float]:
    """(cores dict, tolerance) from a budget file; empty when absent."""
    if not path.exists():
        return {}, DEFAULT_TOLERANCE
    data = json.loads(path.read_text(encoding="utf-8"))
    meta = data.get("_meta", {})
    return dict(data.get("cores", {})), float(
        meta.get("tolerance", DEFAULT_TOLERANCE)
    )


def write_budget(path: Path, reports: Sequence[CoreReport], tolerance: float) -> None:
    import jax

    data = {
        "_meta": {
            "tolerance": tolerance,
            "jax": jax.__version__,
            "generated_by": (
                "python -m citizensassemblies_tpu.lint --ir --update-budget"
            ),
        },
        "cores": {
            r.name: r.measured for r in reports if r.measured is not None
        },
    }
    path.write_text(json.dumps(data, indent=1, sort_keys=True) + "\n", encoding="utf-8")


def budget_provenance(path: Optional[Path] = None) -> Dict[str, Any]:
    """Compact provenance of the committed budget, for bench evidence rows:
    which ratchet state a measurement was taken against."""
    path = path or BUDGET_PATH
    if not path.exists():
        return {"file": path.name, "missing": True}
    raw = path.read_bytes()
    data = json.loads(raw.decode("utf-8"))
    meta = data.get("_meta", {})
    return {
        "file": path.name,
        "sha256": hashlib.sha256(raw).hexdigest()[:12],
        "cores": len(data.get("cores", {})),
        "tolerance": meta.get("tolerance"),
        "jax": meta.get("jax"),
    }


# --- the pass ---------------------------------------------------------------


def run_ir_checks(
    entries: Optional[Sequence[CoreEntry]] = None,
    budget_path: Optional[Path] = None,
    update_budget: bool = False,
    tolerance: Optional[float] = None,
) -> IRReport:
    """Verify every registered core (or ``entries``) against the budget.

    ``update_budget=True`` re-measures and REWRITES the budget file from the
    current IR (the deliberate ratchet move); IR4 violations are then
    dropped — the new budget is the measurement — while IR1–IR3 still fail.
    """
    budget_path = Path(budget_path) if budget_path is not None else BUDGET_PATH
    entries = list(entries) if entries is not None else collect()
    budgets, file_tol = load_budget(budget_path)
    tol = float(tolerance) if tolerance is not None else file_tol

    reports = [verify_core(e, budgets.get(e.name), tol) for e in entries]

    if update_budget:
        write_budget(budget_path, reports, tol)
        for rep in reports:
            rep.violations = [v for v in rep.violations if v.rule != "IR4"]
    else:
        # stale entries: a budget line for a core that no longer exists is
        # dead ratchet state — flag it on the budget file itself
        known = {e.name for e in entries}
        for name in sorted(set(budgets) - known):
            reports.append(
                CoreReport(
                    name=name,
                    path=str(budget_path.name),
                    line=1,
                    violations=[
                        Violation(
                            path=str(budget_path.name), line=1, col=0,
                            rule="IR4", name="stale-budget-entry",
                            message=(
                                f"[{name}] budget entry has no registered "
                                "core — remove it via --update-budget"
                            ),
                        )
                    ],
                )
            )

    return IRReport(
        cores=reports,
        budget_path=str(budget_path),
        tolerance=tol,
        updated=update_budget,
    )


def budget_diff(report: IRReport) -> Dict[str, Any]:
    """Measured-vs-budget comparison for the CI build artifact."""
    budgets, _ = load_budget(Path(report.budget_path))
    cores: Dict[str, Any] = {}
    for rep in report.cores:
        if rep.measured is None:
            cores[rep.name] = {"status": "FAIL" if not rep.ok else "PASS"}
            continue
        entry: Dict[str, Any] = {
            "status": "PASS" if rep.ok else "FAIL",
            "measured": {
                "flops": rep.measured["flops"],
                "bytes": rep.measured["bytes"],
            },
        }
        budget = budgets.get(rep.name)
        if budget:
            entry["budget"] = {
                "flops": budget.get("flops"),
                "bytes": budget.get("bytes"),
            }
            for metric in ("flops", "bytes"):
                ref = float(budget.get(metric) or 0.0)
                if ref > 0:
                    entry.setdefault("ratio", {})[metric] = round(
                        rep.measured[metric] / ref, 4
                    )
        cores[rep.name] = entry
    # dense→sparse deltas: every ELL core registered with a dense_ref sits
    # at the SAME problem shape as its dense twin, so the measured ratio IS
    # the structured-sparsity win the cost model certifies (IR4) — this is
    # the headline evidence the CI artifact carries
    from citizensassemblies_tpu.lint.registry import sparse_pairs

    deltas: Dict[str, Any] = {}
    measured = {
        r.name: r.measured for r in report.cores if r.measured is not None
    }
    for ell_name, dense_name in sorted(sparse_pairs().items()):
        ell_m = measured.get(ell_name)
        dense_m = measured.get(dense_name)
        if not ell_m or not dense_m:
            continue
        entry = {"dense": dense_name}
        for metric in ("flops", "bytes"):
            d, e = float(dense_m[metric]), float(ell_m[metric])
            entry[f"dense_{metric}"] = d
            entry[f"ell_{metric}"] = e
            if e > 0:
                entry[f"{metric}_reduction"] = round(d / e, 2)
        deltas[ell_name] = entry
    return {
        "budget_file": report.budget_path,
        "tolerance": report.tolerance,
        "provenance": budget_provenance(Path(report.budget_path)),
        "sparse_deltas": deltas,
        "cores": cores,
    }


def render_ir_report(report: IRReport) -> str:
    """graftlint-style text: violations in file:line form, then per-core
    PASS/FAIL lines, then the summary tail."""
    lines = [v.render() for v in report.violations]
    for rep in sorted(report.cores, key=lambda r: r.name):
        status = "PASS" if rep.ok else "FAIL"
        extra = ""
        if rep.measured is not None:
            extra = (
                f" (flops={rep.measured['flops']:.0f}"
                f" bytes={rep.measured['bytes']:.0f})"
            )
        lines.append(f"{rep.path}:{rep.line}: {status} [{rep.name}]{extra}")
    n_fail = sum(1 for r in report.cores if not r.ok)
    lines.append(
        f"graftcheck-ir: {len(report.cores)} core(s) verified, "
        f"{n_fail} failing, budget={report.budget_path}"
        + (" (updated)" if report.updated else "")
    )
    return "\n".join(lines)


def ir_report_as_json(report: IRReport) -> Dict[str, Any]:
    """Stable JSON schema shared with the AST linter's ``--format json``."""
    return {
        "schema_version": 1,
        "pass": "ir",
        "ok": report.ok,
        "budget": report.budget_path,
        "tolerance": report.tolerance,
        "updated": report.updated,
        "cores": [
            {
                "core": rep.name,
                "path": rep.path,
                "line": rep.line,
                "status": "PASS" if rep.ok else "FAIL",
                "measured": rep.measured,
            }
            for rep in sorted(report.cores, key=lambda r: r.name)
        ],
        "violations": [dataclasses.asdict(v) for v in report.violations],
    }
