"""graftlint — static analysis + IR-level verification of the JAX invariants.

The flagship speedups rest on invariants nothing in the type system enforces:
jitted cores must stay host-sync-free, jits must be constructed once (not per
call or per loop iteration), donated buffers must never be read after the
donating call, the float64 certification arithmetic must not silently
downcast, Python control flow must not branch on tracers, every ``Config``
knob must be genuinely read and documented, and worker threads must not write
shared state unlocked. graftlint walks the package and enforces all of it
(rules R1–R7), with ``file:line`` reports and an explicit suppression syntax
(``# graftlint: disable=R1 -- reason``; an unused suppression is itself an
error).

A second, compiler-level pass — graftcheck-IR (``lint.ir`` + the core
registry in ``lint.registry``) — traces every registered hot jitted core via
``jax.make_jaxpr`` / AOT ``lower().compile()`` and verifies what the AST
cannot see: no host-callback primitive inside a core (IR1), dtype discipline
at the IR level (IR2), declared donations realized as input/output aliases in
the compiled executable (IR3), and a static cost model (XLA ``cost_analysis``
FLOPs/bytes + jaxpr primitive histograms) ratcheted against the committed
``ANALYSIS_BUDGET.json`` (IR4).

Run the AST pass as ``python -m citizensassemblies_tpu.lint [paths...]``
(``make lint``) and the IR pass as ``python -m citizensassemblies_tpu.lint
--ir`` (``make check-ir``); the test suite runs both over the real package
(``tests/test_lint.py``, ``tests/test_ir_check.py``), so a new violation
fails tier-1. ``--format json`` emits the stable machine schema.

The AST side is deliberately dependency-free (stdlib ``ast`` only — no jax
import), so linting is fast and runs anywhere; the IR side traces on
whatever backend is present (plain CPU in CI).
"""

from citizensassemblies_tpu.lint.engine import (
    LintReport,
    Violation,
    all_rules,
    lint_paths,
    render_report,
)

__all__ = [
    "LintReport",
    "Violation",
    "all_rules",
    "lint_paths",
    "render_report",
]
