"""graftlint — AST-based static analysis for this repo's JAX invariants.

The flagship speedups rest on invariants nothing in the type system enforces:
jitted cores must stay host-sync-free, jits must be constructed once (not per
call or per loop iteration), donated buffers must never be read after the
donating call, the float64 certification arithmetic must not silently
downcast, Python control flow must not branch on tracers, and every
``Config`` knob must be genuinely read and documented. graftlint walks the
package and enforces all of it, with ``file:line`` reports and an explicit
suppression syntax (``# graftlint: disable=R1 -- reason``).

Run it as ``python -m citizensassemblies_tpu.lint [paths...]`` or via
``make lint``; the test suite runs the same pass over the real package
(``tests/test_lint.py``), so a new violation fails tier-1.

The package is deliberately dependency-free (stdlib ``ast`` only — no jax
import), so linting is fast and runs anywhere, including editors and CI
runners without an accelerator stack.
"""

from citizensassemblies_tpu.lint.engine import (
    LintReport,
    Violation,
    all_rules,
    lint_paths,
    render_report,
)

__all__ = [
    "LintReport",
    "Violation",
    "all_rules",
    "lint_paths",
    "render_report",
]
