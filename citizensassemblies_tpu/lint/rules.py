"""graftlint rules R1–R10: per-module AST analyses of the JAX invariants.

Each rule is small and self-contained; shared helpers (dotted-name
resolution, jit-decorator parsing, parent maps) live at the top. The rules
are deliberately *heuristic where they must be* (static reachability, memo
detection) and written so that every false positive has an explicit escape:
``# graftlint: disable=Rn -- reason``.
"""

from __future__ import annotations

import ast
import dataclasses
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

from citizensassemblies_tpu.lint.engine import ModuleSource, Violation

# --- shared helpers ---------------------------------------------------------

#: bare / dotted names that construct a jit-compiled callable
_JIT_NAMES = {"jit", "pjit", "pmap"}
_JIT_DOTTED_SUFFIXES = ("shard_map", "shard_map_compat")


def dotted(node: ast.AST) -> Optional[str]:
    """``a.b.c`` for Name/Attribute chains, else None."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def _is_jit_ref(node: ast.AST) -> bool:
    """Is this expression a reference to jit/pjit/pmap/shard_map itself?"""
    d = dotted(node)
    if d is None:
        return False
    last = d.rsplit(".", 1)[-1]
    return last in _JIT_NAMES or any(d.endswith(s) for s in _JIT_DOTTED_SUFFIXES)


def _is_partial_ref(node: ast.AST) -> bool:
    d = dotted(node)
    return d is not None and d.rsplit(".", 1)[-1] == "partial"


def jit_construction(node: ast.AST) -> Optional[ast.Call]:
    """The Call that constructs a jitted callable, if ``node`` is one.

    Matches ``jax.jit(...)``, ``jit(...)``, ``partial(jax.jit, ...)`` and
    the shard_map variants. Returns the Call whose keywords carry
    static/donate metadata (the partial call for the partial form).
    """
    if not isinstance(node, ast.Call):
        return None
    if _is_jit_ref(node.func):
        return node
    if _is_partial_ref(node.func) and node.args and _is_jit_ref(node.args[0]):
        return node
    return None


def _const_strs(node: ast.AST) -> List[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for elt in node.elts:
            out.extend(_const_strs(elt))
        return out
    return []


def _const_ints(node: ast.AST) -> List[int]:
    if isinstance(node, ast.Constant) and isinstance(node.value, int):
        return [node.value]
    if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
        out = []
        for elt in node.elts:
            out.extend(_const_ints(elt))
        return out
    return []


@dataclasses.dataclass
class JitMeta:
    """Parsed jit construction: static/donated argument metadata."""

    static_names: Set[str]
    static_nums: Set[int]
    donate_nums: Set[int]


def parse_jit_meta(call: ast.Call) -> JitMeta:
    static_names: Set[str] = set()
    static_nums: Set[int] = set()
    donate_nums: Set[int] = set()
    for kw in call.keywords:
        if kw.arg == "static_argnames":
            static_names.update(_const_strs(kw.value))
        elif kw.arg == "static_argnums":
            static_nums.update(_const_ints(kw.value))
        elif kw.arg == "donate_argnums":
            donate_nums.update(_const_ints(kw.value))
    return JitMeta(static_names, static_nums, donate_nums)


def jit_decorator_meta(fn: ast.AST) -> Optional[JitMeta]:
    """JitMeta when ``fn`` is decorated by a jit construction (else None)."""
    if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
        return None
    for dec in fn.decorator_list:
        if _is_jit_ref(dec):
            return JitMeta(set(), set(), set())
        call = jit_construction(dec)
        if call is not None:
            return parse_jit_meta(call)
    return None


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    parents: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            parents[child] = node
    return parents


def enclosing(node: ast.AST, parents, kinds) -> Optional[ast.AST]:
    cur = parents.get(node)
    while cur is not None:
        if isinstance(cur, kinds):
            return cur
        cur = parents.get(cur)
    return None


def numpy_aliases(tree: ast.Module) -> Set[str]:
    """Module-level names bound to the ``numpy`` module (``np`` usually)."""
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "numpy":
                    out.add(alias.asname or "numpy")
    return out


def jnp_aliases(tree: ast.Module) -> Set[str]:
    out: Set[str] = set()
    for node in ast.walk(tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name == "jax.numpy":
                    out.add(alias.asname or "jax.numpy")
    return out


def positional_params(fn: ast.FunctionDef) -> List[str]:
    return [a.arg for a in fn.args.posonlyargs + fn.args.args]


# --- R1: host syncs reachable from jitted code ------------------------------


class HostSyncInJitRule:
    """R1 — host-synchronizing calls inside functions reachable from
    ``jit``/``shard_map``-decorated code.

    ``.item()``, ``.tolist()``, ``.block_until_ready()``, ``np.asarray`` /
    ``np.array``, ``jax.device_get`` and ``float()/int()/bool()`` on
    non-literal operands all force a device→host sync (or fail outright on a
    tracer); none belong anywhere a jitted core can reach. Reachability is
    the transitive closure over same-module calls-by-name starting from
    every jit/shard_map-decorated function (nested defs and lambdas are
    scanned as part of their parent's subtree).
    """

    rule_id = "R1"
    name = "host-sync-in-jit"
    description = "host-sync call reachable from jitted code"

    _SYNC_ATTRS = {"item", "tolist", "block_until_ready"}
    _NP_SYNC_FUNCS = {"asarray", "array", "copy", "save"}
    _CAST_BUILTINS = {"float", "int", "bool"}

    def check_module(self, mod: ModuleSource) -> List[Violation]:
        tree = mod.tree
        np_alias = numpy_aliases(tree)

        # module-level function table (for reachability resolution)
        table: Dict[str, ast.FunctionDef] = {
            node.name: node
            for node in tree.body
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef))
        }

        # roots: decorated functions anywhere + functions wrapped by name
        roots: List[ast.FunctionDef] = []
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if jit_decorator_meta(node) is not None:
                    roots.append(node)
            call = jit_construction(node)
            if call is not None:
                # jax.jit(f) / partial(jax.jit)(f): resolve a Name operand
                operands = call.args[1:] if _is_partial_ref(call.func) else call.args
                for arg in operands:
                    if isinstance(arg, ast.Name) and arg.id in table:
                        roots.append(table[arg.id])

        # transitive closure over same-module calls by bare name
        reachable: List[ast.FunctionDef] = []
        seen: Set[ast.AST] = set()
        work = list(roots)
        while work:
            fn = work.pop()
            if fn in seen:
                continue
            seen.add(fn)
            reachable.append(fn)
            for node in ast.walk(fn):
                if isinstance(node, ast.Call) and isinstance(node.func, ast.Name):
                    target = table.get(node.func.id)
                    if target is not None and target not in seen:
                        work.append(target)

        out: List[Violation] = []

        def flag(node: ast.AST, what: str) -> None:
            out.append(
                Violation(
                    path=mod.rel, line=node.lineno, col=node.col_offset,
                    rule=self.rule_id, name=self.name,
                    message=f"{what} forces a host sync inside jit-reachable code",
                )
            )

        flagged: Set[Tuple[int, int]] = set()
        for fn in reachable:
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                key = (node.lineno, node.col_offset)
                if key in flagged:
                    continue
                func = node.func
                if isinstance(func, ast.Attribute) and func.attr in self._SYNC_ATTRS:
                    flagged.add(key)
                    flag(node, f".{func.attr}()")
                    continue
                d = dotted(func)
                if d is not None:
                    head, _, last = d.rpartition(".")
                    if head in np_alias and last in self._NP_SYNC_FUNCS:
                        flagged.add(key)
                        flag(node, f"{d}()")
                        continue
                    if d.endswith("device_get"):
                        flagged.add(key)
                        flag(node, f"{d}()")
                        continue
                if (
                    isinstance(func, ast.Name)
                    and func.id in self._CAST_BUILTINS
                    and node.args
                    and not isinstance(node.args[0], ast.Constant)
                ):
                    flagged.add(key)
                    flag(node, f"{func.id}() on a non-literal")
        return out


# --- R2: jit constructed per call / inside loops ----------------------------


class JitConstructionRule:
    """R2 — ``jax.jit`` constructed inside a loop or per call.

    Every fresh ``jax.jit(f)`` object owns a fresh compilation cache, so
    constructing one per call (or per loop iteration) recompiles the same
    program forever. jits must be module-level, decorators on module-level
    functions, or memoized — a function-local construction is accepted only
    when the enclosing function shows a memo pattern (a ``global`` statement,
    or a store into a module-level cache dict/attribute), which is how
    ``face_decompose._get_move_screen_core`` and ``parallel.solver._run_core``
    cache their compiled cores, or when the enclosing function is a *factory*
    that returns the constructed callable (``mesh.shard_map_compat``) — the
    per-call judgement then falls on the factory's call sites, which are
    themselves jit constructions to this rule.
    """

    rule_id = "R2"
    name = "jit-per-call"
    description = "jit constructed inside a loop or per call"

    def check_module(self, mod: ModuleSource) -> List[Violation]:
        tree = mod.tree
        parents = parent_map(tree)
        module_names: Set[str] = {
            t.id
            for node in tree.body
            if isinstance(node, ast.Assign)
            for t in node.targets
            if isinstance(t, ast.Name)
        } | {
            node.target.id
            for node in tree.body
            if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name)
        }

        def is_factory(fn: ast.AST, constructed: ast.AST, anchor: ast.AST) -> bool:
            """The enclosing function returns the constructed callable —
            directly, via a local name it was bound to, or via the name of
            the decorated nested function."""
            bound: Set[str] = set()
            if isinstance(anchor, (ast.FunctionDef, ast.AsyncFunctionDef)):
                bound.add(anchor.name)
            assign = parents.get(constructed)
            if isinstance(assign, ast.Assign):
                bound.update(
                    t.id for t in assign.targets if isinstance(t, ast.Name)
                )
            for node in ast.walk(fn):
                if isinstance(node, ast.Return) and node.value is not None:
                    if node.value is constructed:
                        return True
                    if isinstance(node.value, ast.Name) and node.value.id in bound:
                        return True
            return False

        def has_memo_pattern(fn: ast.AST) -> bool:
            for node in ast.walk(fn):
                if isinstance(node, ast.Global):
                    return True
                if isinstance(node, ast.Assign):
                    for t in node.targets:
                        if (
                            isinstance(t, ast.Subscript)
                            and isinstance(t.value, ast.Name)
                            and t.value.id in module_names
                        ):
                            return True
                        if (
                            isinstance(t, ast.Attribute)
                            and isinstance(t.value, ast.Name)
                            and t.value.id in module_names
                        ):
                            return True
            return False

        out: List[Violation] = []
        for node in ast.walk(tree):
            call = jit_construction(node)
            if call is None or call is not node:
                continue
            loop = enclosing(node, parents, (ast.For, ast.While, ast.AsyncFor))
            if loop is not None:
                out.append(
                    Violation(
                        path=mod.rel, line=node.lineno, col=node.col_offset,
                        rule=self.rule_id, name=self.name,
                        message=(
                            "jit constructed inside a loop — every iteration "
                            "compiles from scratch; hoist it to module level "
                            "or memoize"
                        ),
                    )
                )
                continue
            # decorator? judge by the *decorated function's* nesting level
            anchor = node
            parent = parents.get(node)
            if (
                isinstance(parent, (ast.FunctionDef, ast.AsyncFunctionDef))
                and node in parent.decorator_list
            ):
                anchor = parent
            fn = enclosing(anchor, parents, (ast.FunctionDef, ast.AsyncFunctionDef))
            if fn is None:
                continue  # module level (incl. decorators on top-level defs)
            if has_memo_pattern(fn) or is_factory(fn, node, anchor):
                continue
            out.append(
                Violation(
                    path=mod.rel, line=node.lineno, col=node.col_offset,
                    rule=self.rule_id, name=self.name,
                    message=(
                        f"jit constructed per call of '{getattr(fn, 'name', '?')}' "
                        "with no visible memoization — hoist to module level "
                        "or cache the compiled callable"
                    ),
                )
            )
        return out


# --- R3: donated buffers read after the donating call -----------------------


class DonatedBufferReuseRule:
    """R3 — a donated argument read after its ``donate_argnums`` call site.

    Donation hands the input buffer to XLA for reuse; reading the python
    binding afterwards returns a deleted array (on accelerators) or silently
    stale data. The rule collects every jitted callable with
    ``donate_argnums`` (decorator or ``x = jax.jit(f, donate_argnums=...)``
    form), then flags loads of a donated Name argument after the call,
    stopping at rebinds.
    """

    rule_id = "R3"
    name = "donated-buffer-reuse"
    description = "donated buffer read after the donating call"

    def check_package(self, modules: Sequence[ModuleSource], readme=None) -> List[Violation]:
        # pass 1: package-wide donor table, bare-name keyed
        donors: Dict[str, Set[int]] = {}
        for mod in modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    meta = jit_decorator_meta(node)
                    if meta is not None and meta.donate_nums:
                        donors[node.name] = meta.donate_nums
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    t = node.targets[0]
                    call = jit_construction(node.value)
                    if isinstance(t, ast.Name) and call is not None:
                        meta = parse_jit_meta(call)
                        if meta.donate_nums:
                            donors[t.id] = meta.donate_nums

        out: List[Violation] = []
        for mod in modules:
            out.extend(self._check_calls(mod, donors))
        return out

    def _check_calls(self, mod: ModuleSource, donors: Dict[str, Set[int]]) -> List[Violation]:
        parents = parent_map(mod.tree)
        out: List[Violation] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Name):
                continue
            donated = donors.get(node.func.id)
            if not donated:
                continue
            donated_names = {
                node.args[i].id
                for i in donated
                if i < len(node.args) and isinstance(node.args[i], ast.Name)
            }
            if not donated_names:
                continue
            fn = enclosing(node, parents, (ast.FunctionDef, ast.AsyncFunctionDef))
            if fn is None:
                continue
            # the statement containing the call: its assignment targets are
            # rebinds that happen AFTER the call evaluates
            stmt = enclosing(node, parents, (ast.Assign, ast.AugAssign, ast.AnnAssign))
            rebound_by_stmt: Set[str] = set()
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    for n in ast.walk(t):
                        if isinstance(n, (ast.Name,)) and isinstance(n.ctx, ast.Store):
                            rebound_by_stmt.add(n.id)
                    if isinstance(t, (ast.Tuple, ast.List)):
                        pass  # names collected above
            call_end = (node.end_lineno or node.lineno, node.end_col_offset or 0)
            live = set(donated_names) - rebound_by_stmt
            refs = sorted(
                (
                    ((n.lineno, n.col_offset), n)
                    for n in ast.walk(fn)
                    if isinstance(n, ast.Name) and n.id in donated_names
                ),
                key=lambda kv: kv[0],
            )
            for pos, ref in refs:
                if pos <= call_end:
                    continue
                if ref.id not in live:
                    continue
                if isinstance(ref.ctx, ast.Store):
                    live.discard(ref.id)
                    continue
                out.append(
                    Violation(
                        path=mod.rel, line=ref.lineno, col=ref.col_offset,
                        rule=self.rule_id, name=self.name,
                        message=(
                            f"'{ref.id}' was donated to '{node.func.id}' at "
                            f"line {node.lineno} and read afterwards — the "
                            "buffer belongs to XLA now"
                        ),
                    )
                )
                live.discard(ref.id)
        return out


# --- R4: dtype discipline ---------------------------------------------------


class DtypeDisciplineRule:
    """R4 — float64 only in the x64-enabled certification paths, and no
    float32 downcasts inside them.

    ``jax_enable_x64`` is off everywhere in this stack, so a ``jnp.float64``
    request outside the host-side float64 paths silently materializes
    float32 — the worst kind of precision bug, invisible until a
    certification threshold flips. Conversely the certification modules
    (``solvers/lp_util.py``, ``solvers/compositions.py``) do their residual
    arithmetic in float64 numpy on host, and a float32 cast there quietly
    downgrades an accept-threshold comparison.
    """

    rule_id = "R4"
    name = "dtype-discipline"
    description = "float64/float32 discipline of the certification paths"

    _F64_WHITELIST = ("solvers/lp_util.py", "solvers/compositions.py")

    def check_module(self, mod: ModuleSource) -> List[Violation]:
        jnp = jnp_aliases(mod.tree)
        np_alias = numpy_aliases(mod.tree)
        in_whitelist = any(mod.rel.endswith(w) for w in self._F64_WHITELIST)
        out: List[Violation] = []

        def viol(node: ast.AST, msg: str) -> None:
            out.append(
                Violation(
                    path=mod.rel, line=node.lineno, col=node.col_offset,
                    rule=self.rule_id, name=self.name, message=msg,
                )
            )

        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Attribute):
                base = dotted(node.value)
                if node.attr == "float64" and base in jnp and not in_whitelist:
                    viol(
                        node,
                        "jnp.float64 outside the x64-enabled certification "
                        "paths silently materializes float32 (x64 is "
                        "disabled) — use float32 explicitly or move the "
                        "arithmetic to the host float64 path",
                    )
                if (
                    node.attr == "float32"
                    and in_whitelist
                    and base is not None
                    and (base in np_alias or base in jnp)
                ):
                    viol(
                        node,
                        "float32 cast inside the float64 certification path "
                        "— the residual/threshold arithmetic must stay "
                        "float64",
                    )
            if isinstance(node, ast.Call):
                for kw in node.keywords:
                    if (
                        kw.arg == "dtype"
                        and isinstance(kw.value, ast.Constant)
                        and kw.value.value == "float64"
                        and not in_whitelist
                    ):
                        d = dotted(node.func) or ""
                        if d.split(".", 1)[0] in jnp:
                            viol(
                                node,
                                'dtype="float64" on a jnp call outside the '
                                "certification paths silently materializes "
                                "float32",
                            )
        return out


# --- R5: tracer branching & static-arg hygiene ------------------------------


class ThreadDisciplineRule:
    """R7 — shared state written from a worker thread without a lock.

    The host-overlap pipeline (``solvers/face_decompose._AnchorPricer``
    double-buffering MILPs against the device master, and the chunked native
    slice streams in ``solvers/native_oracle``) is the repo's only threaded
    code, and its discipline is: a worker runs *pure* functions over
    pre-partitioned buffers; all cross-thread handoff goes through the
    ``Future``/``Queue`` machinery, and any shared mutable state takes a
    ``Lock``. The rule enforces exactly that, scoped to modules that import
    ``threading``/``concurrent.futures``: find the worker roots (first
    argument of ``<executor>.submit(...)``/``<executor>.map(...)`` for names
    bound to a ``ThreadPoolExecutor``, plus ``Thread(target=...)``), take the
    transitive same-module closure (bare-name and ``self.method`` calls), and
    flag writes to module-level state (``global`` rebinding, stores into a
    module-level dict/attribute) or instance state (``self.attr = ...``)
    that are not under a ``with <…lock…>:`` block.
    """

    rule_id = "R7"
    name = "thread-discipline"
    description = "unlocked shared-state write reachable from a worker thread"

    _THREAD_MODULES = ("threading", "concurrent.futures", "concurrent")

    @staticmethod
    def _imports_threading(tree: ast.Module) -> bool:
        for node in ast.walk(tree):
            if isinstance(node, ast.Import):
                if any(
                    a.name.split(".")[0] in ("threading", "concurrent")
                    for a in node.names
                ):
                    return True
            if isinstance(node, ast.ImportFrom) and node.module:
                if node.module.split(".")[0] in ("threading", "concurrent"):
                    return True
        return False

    @staticmethod
    def _executor_names(tree: ast.Module) -> Set[str]:
        """Bare names and attribute names bound to a ThreadPoolExecutor
        construction: ``pool = ThreadPoolExecutor(...)``, ``with
        ThreadPoolExecutor(...) as pool:``, ``self._pool = (ThreadPool…)``.
        Conditional expressions (``X if overlap else None``) are unwrapped.
        """

        def is_executor_call(node: ast.AST) -> bool:
            if isinstance(node, ast.IfExp):
                return is_executor_call(node.body) or is_executor_call(node.orelse)
            if not isinstance(node, ast.Call):
                return False
            d = dotted(node.func)
            return d is not None and d.rsplit(".", 1)[-1] == "ThreadPoolExecutor"

        names: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.Assign) and is_executor_call(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
                    elif isinstance(t, ast.Attribute):
                        names.add(t.attr)
            if isinstance(node, ast.With):
                for item in node.items:
                    if (
                        is_executor_call(item.context_expr)
                        and isinstance(item.optional_vars, ast.Name)
                    ):
                        names.add(item.optional_vars.id)
        return names

    @staticmethod
    def _function_table(tree: ast.Module) -> Dict[str, List[ast.FunctionDef]]:
        """Every FunctionDef (module-level, nested, methods) keyed by name."""
        table: Dict[str, List[ast.FunctionDef]] = {}
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                table.setdefault(node.name, []).append(node)
        return table

    def _worker_roots(self, tree: ast.Module) -> List[ast.AST]:
        """Function/lambda nodes handed to a worker thread."""
        executors = self._executor_names(tree)
        table = self._function_table(tree)
        roots: List[ast.AST] = []

        def resolve(ref: ast.AST) -> None:
            if isinstance(ref, ast.Lambda):
                roots.append(ref)
            elif isinstance(ref, ast.Name):
                roots.extend(table.get(ref.id, []))
            elif (
                isinstance(ref, ast.Attribute)
                and isinstance(ref.value, ast.Name)
                and ref.value.id == "self"
            ):
                roots.extend(table.get(ref.attr, []))

        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            func = node.func
            if isinstance(func, ast.Attribute) and func.attr in ("submit", "map"):
                recv = func.value
                recv_name = (
                    recv.id if isinstance(recv, ast.Name)
                    else recv.attr if isinstance(recv, ast.Attribute)
                    else None
                )
                if recv_name in executors and node.args:
                    resolve(node.args[0])
            d = dotted(func)
            if d is not None and d.rsplit(".", 1)[-1] == "Thread":
                for kw in node.keywords:
                    if kw.arg == "target":
                        resolve(kw.value)
        return roots

    @staticmethod
    def _under_lock(node: ast.AST, parents) -> bool:
        """Is this statement inside a ``with`` whose context mentions a
        lock? Matched by name (…lock…, case-insensitive) or a direct
        ``Lock()``/``RLock()`` construction — the explicit escape for
        anything subtler is ``# graftlint: disable=R7 -- reason``."""
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.With):
                for item in cur.items:
                    expr = item.context_expr
                    d = dotted(expr) or ""
                    if isinstance(expr, ast.Call):
                        d = dotted(expr.func) or ""
                    last = d.rsplit(".", 1)[-1].lower()
                    if "lock" in last:
                        return True
            cur = parents.get(cur)
        return False

    def check_module(self, mod: ModuleSource) -> List[Violation]:
        tree = mod.tree
        if not self._imports_threading(tree):
            return []
        roots = self._worker_roots(tree)
        if not roots:
            return []
        parents = parent_map(tree)
        table = self._function_table(tree)
        module_names: Set[str] = {
            t.id
            for node in tree.body
            if isinstance(node, ast.Assign)
            for t in node.targets
            if isinstance(t, ast.Name)
        } | {
            node.target.id
            for node in tree.body
            if isinstance(node, ast.AnnAssign) and isinstance(node.target, ast.Name)
        }

        # transitive closure over same-module calls (bare name, self.method)
        reachable: List[ast.AST] = []
        seen: Set[ast.AST] = set()
        work = list(roots)
        while work:
            fn = work.pop()
            if fn in seen:
                continue
            seen.add(fn)
            reachable.append(fn)
            for node in ast.walk(fn):
                if not isinstance(node, ast.Call):
                    continue
                targets: List[ast.FunctionDef] = []
                if isinstance(node.func, ast.Name):
                    targets = table.get(node.func.id, [])
                elif (
                    isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"
                ):
                    targets = table.get(node.func.attr, [])
                work.extend(t for t in targets if t not in seen)

        out: List[Violation] = []

        def flag(node: ast.AST, what: str) -> None:
            out.append(
                Violation(
                    path=mod.rel, line=node.lineno, col=node.col_offset,
                    rule=self.rule_id, name=self.name,
                    message=(
                        f"{what} written from worker-thread code without a "
                        "Lock/Queue mediating it — the overlap pipeline's "
                        "workers must stay pure over pre-partitioned buffers"
                    ),
                )
            )

        flagged: Set[Tuple[int, int]] = set()
        for fn in reachable:
            globals_here: Set[str] = set()
            for node in ast.walk(fn):
                if isinstance(node, ast.Global):
                    globals_here.update(node.names)
            for node in ast.walk(fn):
                if not isinstance(node, (ast.Assign, ast.AugAssign, ast.AnnAssign)):
                    continue
                key = (node.lineno, node.col_offset)
                if key in flagged or self._under_lock(node, parents):
                    continue
                targets = (
                    node.targets if isinstance(node, ast.Assign) else [node.target]
                )
                for t in targets:
                    if isinstance(t, ast.Name) and t.id in globals_here:
                        flagged.add(key)
                        flag(node, f"module global '{t.id}'")
                    elif isinstance(t, ast.Attribute):
                        base = t.value
                        if isinstance(base, ast.Name) and base.id == "self":
                            flagged.add(key)
                            flag(node, f"instance state 'self.{t.attr}'")
                        elif isinstance(base, ast.Name) and base.id in module_names:
                            flagged.add(key)
                            flag(node, f"module state '{base.id}.{t.attr}'")
                    elif isinstance(t, ast.Subscript):
                        base = t.value
                        if isinstance(base, ast.Name) and base.id in module_names:
                            flagged.add(key)
                            flag(node, f"module container '{base.id}[...]'")
        return out


class TracerBranchRule:
    """R5 — Python ``if``/``while`` on tracer values, and unhashable values
    passed for static arguments.

    Inside a jitted function, branching on a non-static parameter either
    fails at trace time (ConcretizationTypeError) or — worse — got baked in
    at trace time by accident. ``is None`` / ``is not None`` tests are
    exempt (argument-presence dispatch resolves at trace time). The second
    half checks call sites of known jitted callables: a list/dict/set
    literal passed for a ``static_argnames`` parameter is unhashable and
    fails the jit cache lookup.
    """

    rule_id = "R5"
    name = "tracer-branch"
    description = "python branching on tracers / unhashable statics"

    def check_package(self, modules: Sequence[ModuleSource], readme=None) -> List[Violation]:
        # package-wide table of jitted callables' static names
        statics: Dict[str, Set[str]] = {}
        for mod in modules:
            for node in ast.walk(mod.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    meta = jit_decorator_meta(node)
                    if meta is not None and meta.static_names:
                        statics[node.name] = meta.static_names
        out: List[Violation] = []
        for mod in modules:
            out.extend(self._check_module(mod, statics))
        return out

    @staticmethod
    def _is_none_test(test: ast.AST) -> bool:
        """True when the test resolves at trace time: pure is/is-not
        comparisons, possibly combined with and/or/not."""
        if isinstance(test, ast.BoolOp):
            return all(TracerBranchRule._is_none_test(v) for v in test.values)
        if isinstance(test, ast.UnaryOp) and isinstance(test.op, ast.Not):
            return TracerBranchRule._is_none_test(test.operand)
        if isinstance(test, ast.Compare):
            return all(isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops)
        return False

    def _check_module(self, mod: ModuleSource, statics: Dict[str, Set[str]]) -> List[Violation]:
        out: List[Violation] = []
        for fn in ast.walk(mod.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            meta = jit_decorator_meta(fn)
            if meta is None:
                continue
            params = positional_params(fn)
            traced = {
                p
                for i, p in enumerate(params)
                if p not in meta.static_names and i not in meta.static_nums
            }
            for node in ast.walk(fn):
                if not isinstance(node, (ast.If, ast.While)):
                    continue
                if self._is_none_test(node.test):
                    continue
                names = {
                    n.id
                    for n in ast.walk(node.test)
                    if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
                }
                hot = sorted(names & traced)
                if hot:
                    out.append(
                        Violation(
                            path=mod.rel, line=node.lineno, col=node.col_offset,
                            rule=self.rule_id, name=self.name,
                            message=(
                                f"python {'if' if isinstance(node, ast.If) else 'while'} "
                                f"branches on traced argument(s) {', '.join(hot)} "
                                f"of jitted '{fn.name}' — use lax.cond/select "
                                "or mark the argument static"
                            ),
                        )
                    )
        # unhashable values at static call sites
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call) or not isinstance(node.func, ast.Name):
                continue
            static_names = statics.get(node.func.id)
            if not static_names:
                continue
            for kw in node.keywords:
                if kw.arg in static_names and isinstance(
                    kw.value, (ast.List, ast.Dict, ast.Set)
                ):
                    out.append(
                        Violation(
                            path=mod.rel, line=kw.value.lineno, col=kw.value.col_offset,
                            rule=self.rule_id, name=self.name,
                            message=(
                                f"unhashable literal for static argument "
                                f"'{kw.arg}' of jitted '{node.func.id}' — "
                                "static values must be hashable (tuple, str, "
                                "int)"
                            ),
                        )
                    )
        return out


class CoreSpanRule:
    """R8 — every ``@register_ir_core``-registered hot core must be wired
    into grafttrace: the registration declares ``span="<name>"`` and the
    SAME module contains a ``dispatch_span("<name>", …)`` call wrapping the
    core's public entry point, OR it declares ``span_optout="reason"`` (a
    core with no runtime entry of its own — e.g. a dense IR comparator
    whose production dispatch rides another core's span).

    The IR manifest is the repo's authoritative list of hot jitted cores;
    a core that can burn device time without appearing in a request's trace
    is exactly the observability gap this PR exists to close, so the
    checklist is enforced the same way the manifest itself is (statically,
    per registration site). Span names are matched against the string
    constants inside ``dispatch_span(...)`` calls — a conditional name
    (``"a" if exact else "b"``) matches both literals.
    """

    rule_id = "R8"
    name = "core-span-coverage"
    description = "registered IR cores must declare a dispatch span or opt out"

    @staticmethod
    def _register_calls(mod: ModuleSource) -> List[ast.Call]:
        out = []
        for node in ast.walk(mod.tree):
            if isinstance(node, ast.Call):
                d = dotted(node.func)
                if d is not None and d.rsplit(".", 1)[-1] == "register_ir_core":
                    out.append(node)
        return out

    @staticmethod
    def _span_literals(mod: ModuleSource) -> Set[str]:
        """String constants appearing inside ``dispatch_span(...)`` calls."""
        names: Set[str] = set()
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d is None or d.rsplit(".", 1)[-1] != "dispatch_span":
                continue
            if node.args:
                for c in ast.walk(node.args[0]):
                    if isinstance(c, ast.Constant) and isinstance(c.value, str):
                        names.add(c.value)
        return names

    def check_module(self, mod: ModuleSource) -> List[Violation]:
        regs = self._register_calls(mod)
        if not regs:
            return []
        spans_here = self._span_literals(mod)
        out: List[Violation] = []

        def flag(node: ast.AST, message: str) -> None:
            out.append(
                Violation(
                    path=mod.rel, line=node.lineno, col=node.col_offset,
                    rule=self.rule_id, name=self.name, message=message,
                )
            )

        for call in regs:
            core = None
            if call.args and isinstance(call.args[0], ast.Constant):
                core = call.args[0].value
            kw = {k.arg: k.value for k in call.keywords}
            span_v = kw.get("span")
            opt_v = kw.get("span_optout")
            if span_v is None and opt_v is None:
                flag(
                    call,
                    f"registered core {core!r} is not traced: declare "
                    "span=\"<name>\" (and wrap the entry point in "
                    "dispatch_span) or span_optout=\"reason\"",
                )
                continue
            if span_v is not None and opt_v is not None:
                flag(
                    call,
                    f"registered core {core!r} declares BOTH span= and "
                    "span_optout= — pick one",
                )
                continue
            if opt_v is not None:
                if not (
                    isinstance(opt_v, ast.Constant)
                    and isinstance(opt_v.value, str)
                    and opt_v.value.strip()
                ):
                    flag(
                        call,
                        f"registered core {core!r}: span_optout needs a "
                        "non-empty literal reason",
                    )
                continue
            if not (
                isinstance(span_v, ast.Constant) and isinstance(span_v.value, str)
            ):
                flag(
                    call,
                    f"registered core {core!r}: span= must be a string literal",
                )
                continue
            if span_v.value not in spans_here:
                flag(
                    call,
                    f"registered core {core!r} declares span="
                    f"'{span_v.value}' but no dispatch_span('{span_v.value}', "
                    "…) call exists in this module — wrap the entry point "
                    "(obs.hooks.dispatch_span)",
                )
        return out


class FaultSiteRule:
    """R9 — every fault-injection site literal must be catalogued.

    ``inject.site("<name>")`` / ``inject.raise_if("<name>")`` calls are the
    hot-boundary consults of the graftfault registry
    (``robust/inject.FAULT_SITES``). The rule enforces, per call site:

    * the site name is a string LITERAL (a computed name cannot be audited
      or reproduced from a chaos spec);
    * the literal is registered in ``FAULT_SITES`` (parsed statically from
      ``robust/inject.py`` when it is in the lint scope);
    * the literal is documented in the README's fault-site catalogue (the
      name must appear verbatim in backticks — the same README-as-contract
      enforcement shape as R6's knob table and R8's span coverage).

    A fault site that can fire in production chaos runs but is absent from
    the operator-facing catalogue is exactly the undocumented blast radius
    this rule exists to prevent.
    """

    rule_id = "R9"
    name = "fault-site-catalogue"
    description = "inject.site literals must be registered and README-documented"

    _CALL_NAMES = ("site", "raise_if")

    @staticmethod
    def _registry_sites(modules: Sequence[ModuleSource]) -> Optional[Set[str]]:
        """FAULT_SITES keys parsed from robust/inject.py, or None when the
        registry module is outside the lint scope (README check still runs).
        """
        for mod in modules:
            if mod.path.name != "inject.py" or "robust" not in str(mod.path):
                continue
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.AnnAssign) and not isinstance(
                    node, ast.Assign
                ):
                    continue
                targets = (
                    [node.target] if isinstance(node, ast.AnnAssign)
                    else node.targets
                )
                named = any(
                    isinstance(t, ast.Name) and t.id == "FAULT_SITES"
                    for t in targets
                )
                if not named or not isinstance(node.value, ast.Dict):
                    continue
                return {
                    k.value
                    for k in node.value.keys
                    if isinstance(k, ast.Constant) and isinstance(k.value, str)
                }
        return None

    def check_package(
        self, modules: Sequence[ModuleSource], readme=None
    ) -> List[Violation]:
        from citizensassemblies_tpu.lint.config_rule import _find_readme

        registry = self._registry_sites(modules)
        readme_path = _find_readme(modules, readme)
        readme_text = (
            readme_path.read_text(encoding="utf-8")
            if readme_path is not None
            else ""
        )
        out: List[Violation] = []
        for mod in modules:
            if mod.path.name == "inject.py" and "robust" in str(mod.path):
                continue  # the registry itself
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call):
                    continue
                d = dotted(node.func)
                if d is None:
                    continue
                parts = d.rsplit(".", 2)
                if parts[-1] not in self._CALL_NAMES:
                    continue
                # only the inject module's consults: inject.site(...) /
                # inject.raise_if(...) (bare `site(...)` is too generic to
                # claim — the repo convention imports the module)
                if len(parts) < 2 or parts[-2] != "inject":
                    continue

                def flag(message: str) -> None:
                    out.append(
                        Violation(
                            path=mod.rel, line=node.lineno, col=node.col_offset,
                            rule=self.rule_id, name=self.name, message=message,
                        )
                    )

                if not node.args or not (
                    isinstance(node.args[0], ast.Constant)
                    and isinstance(node.args[0].value, str)
                ):
                    flag(
                        f"{parts[-1]}() needs a string LITERAL site name — a "
                        "computed site cannot be audited against the "
                        "catalogue or replayed from a chaos spec"
                    )
                    continue
                site_name = node.args[0].value
                if registry is not None and site_name not in registry:
                    flag(
                        f"fault site '{site_name}' is not registered in "
                        "robust/inject.FAULT_SITES — register it (with a "
                        "description) before consulting it"
                    )
                    continue
                if readme_text and f"`{site_name}`" not in readme_text:
                    flag(
                        f"fault site '{site_name}' is missing from the "
                        "README fault-injection catalogue — document the "
                        "site (name in backticks) in the \"Fault tolerance "
                        "& degradation\" section"
                    )
        return out


class MeshHygieneRule:
    """R10 — collective axis names and mesh-closed jit callables stay honest.

    The graftpod topology module (``dist/runtime.py``) is the single
    definition site of the collective axis names (``AXIS_CHAINS`` /
    ``AXIS_AGENTS``). Two failure modes erode that:

    * **Hardcoded axis literals.** A ``psum(..., "chains")`` or
      ``P("chains", None)`` spelled as a string literal outside the topology
      module keeps working until the axis is renamed or re-laid-out — then
      fails at runtime, on the biggest mesh, inside a collective. The rule
      flags known axis-name literals appearing inside Mesh/PartitionSpec
      constructions and collective calls anywhere else; call sites must
      import the constants. The known names are parsed statically from the
      topology module when it is in the lint scope (fallback: the canonical
      pair), so a renamed axis retargets the rule automatically.

    * **Unmemoized mesh closures.** ``shard_map``/``pjit`` callables close
      over their mesh, so a compiled one is only reusable for THE mesh it
      was built with — the established idiom (``parallel/mc.py``'s
      ``_DRAW_CACHE``, ``parallel/solver.py``'s ``_CORE_CACHE``) memoizes in
      a module-level container keyed on the mesh. A construction inside a
      function that takes a mesh but shows no mesh-keyed memo store
      recompiles per call on every mesh size the bench sweeps. Factories
      that *return* the constructed callable (``mesh.shard_map_compat``) are
      exempt, same as R2 — the judgement falls on their call sites.
    """

    rule_id = "R10"
    name = "mesh-hygiene"
    description = "axis-name literals / unmemoized mesh-closed jit callables"

    #: the axis-name definition site (literals are legal only here)
    _TOPOLOGY_SUFFIX = "dist/runtime.py"
    #: calls whose string arguments name collective axes
    _AXIS_CALL_SUFFIXES = {
        "PartitionSpec", "P", "Mesh", "make_mesh", "topology_mesh",
        "psum", "pmax", "pmin", "pmean", "pall", "pany",
        "all_gather", "all_to_all", "ppermute", "axis_index", "psum_scatter",
    }
    _FALLBACK_AXES = frozenset({"chains", "agents"})
    #: constructions that close over a mesh
    _MESH_CLOSURE_SUFFIXES = ("shard_map", "shard_map_compat", "pjit")

    @classmethod
    def _is_topology(cls, mod: ModuleSource) -> bool:
        return str(mod.path).replace("\\", "/").endswith(cls._TOPOLOGY_SUFFIX)

    @classmethod
    def _axis_names(cls, modules: Sequence[ModuleSource]) -> Set[str]:
        """``AXIS_* = "<name>"`` constants of the topology module, or the
        canonical fallback pair when it is outside the lint scope."""
        for mod in modules:
            if not cls._is_topology(mod):
                continue
            found: Set[str] = set()
            for node in mod.tree.body:
                targets: List[ast.expr] = []
                value = None
                if isinstance(node, ast.Assign):
                    targets, value = node.targets, node.value
                elif isinstance(node, ast.AnnAssign) and node.value is not None:
                    targets, value = [node.target], node.value
                if not (
                    isinstance(value, ast.Constant)
                    and isinstance(value.value, str)
                ):
                    continue
                for t in targets:
                    if isinstance(t, ast.Name) and t.id.startswith("AXIS_"):
                        found.add(value.value)
            if found:
                return found
        return set(cls._FALLBACK_AXES)

    @staticmethod
    def _module_container_names(tree: ast.Module) -> Set[str]:
        out: Set[str] = {
            t.id
            for node in tree.body
            if isinstance(node, ast.Assign)
            for t in node.targets
            if isinstance(t, ast.Name)
        }
        out |= {
            node.target.id
            for node in tree.body
            if isinstance(node, ast.AnnAssign)
            and isinstance(node.target, ast.Name)
        }
        return out

    @staticmethod
    def _has_mesh_keyed_memo(fn: ast.AST, module_names: Set[str]) -> bool:
        """A store into a module-level container whose key expression — or a
        local variable the key was built from — mentions ``mesh``."""
        keyish: Set[str] = {"mesh"}
        for node in ast.walk(fn):
            if isinstance(node, ast.Assign):
                mentions_mesh = any(
                    isinstance(n, ast.Name)
                    and n.id in keyish
                    and isinstance(n.ctx, ast.Load)
                    for n in ast.walk(node.value)
                )
                if mentions_mesh:
                    keyish.update(
                        t.id for t in node.targets if isinstance(t, ast.Name)
                    )
        for node in ast.walk(fn):
            if not isinstance(node, ast.Assign):
                continue
            for t in node.targets:
                if (
                    isinstance(t, ast.Subscript)
                    and isinstance(t.value, ast.Name)
                    and t.value.id in module_names
                ):
                    names = {
                        n.id
                        for n in ast.walk(t.slice)
                        if isinstance(n, ast.Name)
                    }
                    if names & keyish:
                        return True
        return False

    @staticmethod
    def _is_factory(fn: ast.AST, constructed: ast.AST, parents) -> bool:
        bound: Set[str] = set()
        assign = parents.get(constructed)
        if isinstance(assign, ast.Assign):
            bound.update(t.id for t in assign.targets if isinstance(t, ast.Name))
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                if node.value is constructed:
                    return True
                if isinstance(node.value, ast.Name) and node.value.id in bound:
                    return True
        return False

    def check_package(
        self, modules: Sequence[ModuleSource], readme=None
    ) -> List[Violation]:
        axes = self._axis_names(modules)
        out: List[Violation] = []
        for mod in modules:
            if self._is_topology(mod):
                continue
            out.extend(self._check_axis_literals(mod, axes))
            out.extend(self._check_mesh_closures(mod))
        return out

    def _check_axis_literals(
        self, mod: ModuleSource, axes: Set[str]
    ) -> List[Violation]:
        out: List[Violation] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d is None or d.rsplit(".", 1)[-1] not in self._AXIS_CALL_SUFFIXES:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for c in ast.walk(arg):
                    if (
                        isinstance(c, ast.Constant)
                        and isinstance(c.value, str)
                        and c.value in axes
                    ):
                        out.append(
                            Violation(
                                path=mod.rel, line=c.lineno, col=c.col_offset,
                                rule=self.rule_id, name=self.name,
                                message=(
                                    f"hardcoded collective axis name "
                                    f"'{c.value}' — import the axis "
                                    "constant from the graftpod topology "
                                    "module (dist/runtime.py) instead of "
                                    "spelling the literal"
                                ),
                            )
                        )
        return out

    def _check_mesh_closures(self, mod: ModuleSource) -> List[Violation]:
        parents = parent_map(mod.tree)
        module_names = self._module_container_names(mod.tree)
        out: List[Violation] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d is None:
                continue
            last = d.rsplit(".", 1)[-1]
            if last not in self._MESH_CLOSURE_SUFFIXES:
                continue
            fn = enclosing(node, parents, (ast.FunctionDef, ast.AsyncFunctionDef))
            if fn is None:
                continue  # module level: built once for a fixed mesh
            # only claim constructions that actually close over a mesh —
            # a Name `mesh` anywhere in the call, or a mesh parameter on
            # the enclosing function
            refs_mesh = any(
                isinstance(n, ast.Name) and n.id == "mesh"
                for n in ast.walk(node)
            ) or "mesh" in positional_params(fn)
            if not refs_mesh:
                continue
            if self._has_mesh_keyed_memo(fn, module_names):
                continue
            if self._is_factory(fn, node, parents):
                continue
            out.append(
                Violation(
                    path=mod.rel, line=node.lineno, col=node.col_offset,
                    rule=self.rule_id, name=self.name,
                    message=(
                        f"{last} callable built per call of "
                        f"'{getattr(fn, 'name', '?')}' with no mesh-keyed "
                        "memo — a compiled mesh closure is reusable only "
                        "for ITS mesh; store it in a module-level cache "
                        "keyed on the mesh (the _DRAW_CACHE/_CORE_CACHE "
                        "idiom)"
                    ),
                )
            )
        return out


class MetricHygieneRule:
    """R11 — every metric name literal must come from the catalogue.

    ``obs/catalog.py`` is the single registry of metric series names
    (``METRIC_SERIES``) and dynamic-prefix families (``METRIC_PREFIXES``).
    The failure mode this kills: a typo'd counter name silently mints a
    brand-new series, dashboards keep reading the old (now frozen) one, and
    the regression goes unobserved. Per emission call site
    (``log.count``/``gauge``/``timer`` and registry
    ``counter``/``gauge``/``timer``/``histogram``), the rule enforces:

    * the name is a string LITERAL — or an ``IfExp`` choosing between
      literals, or an f-string whose literal LEADING fragment is a
      registered dynamic prefix (the per-key families: fault sites, ladder
      rungs, schedule buckets);
    * every literal so reachable is catalogued (exact ``METRIC_SERIES``
      membership, or a ``METRIC_PREFIXES`` prefix).

    ``count`` is a generic method name (``itertools.count``,
    ``str.count``), so it is only claimed on log-like receivers
    (``log`` / ``*_log`` / ``metrics`` / ``*_metrics`` tails); the
    distinctive emission methods are claimed on any receiver. The metrics
    plumbing that forwards caller-supplied names (``utils/logging.py``,
    ``obs/metrics.py``) and the catalogue itself are exempt, as are test
    modules (tests mint ad-hoc names for fixtures).
    """

    rule_id = "R11"
    name = "metric-hygiene"
    description = "metric name literals must be registered in obs/catalog.py"

    #: distinctive emission methods, claimed on ANY receiver
    _METHODS = ("gauge", "timer", "counter", "histogram")
    #: generic method, claimed only on log-like receivers
    _COUNT_TAILS = ("log", "metrics")

    _EXEMPT = ("obs/catalog.py", "obs/metrics.py", "utils/logging.py")

    @staticmethod
    def _catalogue(
        modules: Sequence[ModuleSource],
    ) -> Optional[Tuple[Set[str], Set[str]]]:
        """(series, prefixes) parsed statically from obs/catalog.py, or
        None when the catalogue module is outside the lint scope."""
        for mod in modules:
            if mod.path.name != "catalog.py" or "obs" not in str(mod.path):
                continue
            series: Set[str] = set()
            prefixes: Set[str] = set()
            for node in ast.walk(mod.tree):
                if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                    continue
                targets = (
                    [node.target] if isinstance(node, ast.AnnAssign)
                    else node.targets
                )
                names = {
                    t.id for t in targets if isinstance(t, ast.Name)
                }
                if "METRIC_SERIES" in names and isinstance(
                    node.value, ast.Dict
                ):
                    series = {
                        k.value
                        for k in node.value.keys
                        if isinstance(k, ast.Constant)
                        and isinstance(k.value, str)
                    }
                elif "METRIC_PREFIXES" in names and node.value is not None:
                    prefixes = {
                        c.value
                        for c in ast.walk(node.value)
                        if isinstance(c, ast.Constant)
                        and isinstance(c.value, str)
                    }
            return series, prefixes
        return None

    @classmethod
    def _skip_module(cls, mod: ModuleSource) -> bool:
        rel = str(mod.path).replace("\\", "/")
        if any(rel.endswith(e) for e in cls._EXEMPT):
            return True
        name = mod.path.name
        return (
            "tests" in mod.path.parts
            or name.startswith("test_")
            or name == "conftest.py"
        )

    @staticmethod
    def _name_literals(node: ast.AST) -> Optional[List[str]]:
        """All string literals the name expression can evaluate to, or
        None when a branch is not statically known. IfExp recurses so
        ``"a" if p else "b"`` contributes both arms."""
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return [node.value]
        if isinstance(node, ast.IfExp):
            body = MetricHygieneRule._name_literals(node.body)
            orelse = MetricHygieneRule._name_literals(node.orelse)
            if body is None or orelse is None:
                return None
            return body + orelse
        return None

    def check_package(
        self, modules: Sequence[ModuleSource], readme=None
    ) -> List[Violation]:
        catalogue = self._catalogue(modules)
        if catalogue is None:
            return []  # catalogue outside the scope: nothing to judge against
        series, prefixes = catalogue
        out: List[Violation] = []
        for mod in modules:
            if self._skip_module(mod):
                continue
            for node in ast.walk(mod.tree):
                if not isinstance(node, ast.Call) or not isinstance(
                    node.func, ast.Attribute
                ):
                    continue
                method = node.func.attr
                receiver = dotted(node.func.value)
                tail = receiver.rsplit(".", 1)[-1] if receiver else ""
                if method == "count":
                    if not (
                        tail in self._COUNT_TAILS
                        or tail.endswith("_log")
                        or tail.endswith("_metrics")
                    ):
                        continue
                elif method not in self._METHODS:
                    continue

                def flag(message: str) -> None:
                    out.append(
                        Violation(
                            path=mod.rel, line=node.lineno,
                            col=node.col_offset, rule=self.rule_id,
                            name=self.name, message=message,
                        )
                    )

                arg = node.args[0] if node.args else None
                if arg is None:
                    for kw in node.keywords:
                        if kw.arg == "name":
                            arg = kw.value
                            break
                if arg is None:
                    continue  # no name operand (not an emission call)
                if isinstance(arg, ast.JoinedStr):
                    # dynamic family: the literal LEADING fragment must be
                    # a registered prefix
                    lead = (
                        arg.values[0].value
                        if arg.values
                        and isinstance(arg.values[0], ast.Constant)
                        and isinstance(arg.values[0].value, str)
                        else ""
                    )
                    if not any(lead.startswith(p) for p in prefixes):
                        flag(
                            f"f-string metric name leads with '{lead}', "
                            "which no METRIC_PREFIXES family covers — "
                            "register the prefix in obs/catalog.py or use "
                            "a catalogued literal"
                        )
                    continue
                literals = self._name_literals(arg)
                if literals is None:
                    flag(
                        f"{method}() metric name is computed — a name the "
                        "catalogue cannot see can silently mint a new "
                        "series; use a literal (or an IfExp over literals) "
                        "registered in obs/catalog.py"
                    )
                    continue
                for lit in literals:
                    if lit not in series and not any(
                        lit.startswith(p) for p in prefixes
                    ):
                        flag(
                            f"metric name '{lit}' is not registered in "
                            "obs/catalog.py METRIC_SERIES (or a "
                            "METRIC_PREFIXES family) — register the series "
                            "(with a help line) before emitting it"
                        )
        return out


class ShardingSpecHygieneRule:
    """R12 — sharding specs are declared once, in ``dist/partition.py``.

    graftspmd's S2 contract check can only cross-reference layouts that are
    *declared* — a ``NamedSharding`` spelled inline at a call site is
    invisible to it, and historically that is exactly where the
    ``dist_reshards`` bugs came from: two stages each hand-rolling "the"
    spec, drifting apart by one ``None``. Two findings:

    * **Inline spec constructions.** ``NamedSharding(...)`` anywhere outside
      the partition module is a violation — call a ``ROLE_BUILDERS`` role
      (or add one) instead. ``PartitionSpec``/``P`` constructions are legal
      only inside functions that build a mesh closure
      (``shard_map``/``shard_map_compat``/``pjit``): there they are the
      per-device block specs of the closure itself, not a placement
      contract. Factories that return the constructed spec and functions
      with a mesh-keyed memo store are exempt, same judgement as R2/R10.

    * **Unknown collective axis literals.** R10 flags *known* axis names
      spelled as literals; this rule closes the complement — a string
      literal axis argument to a collective that is NOT one of the topology
      module's ``AXIS_*`` names is either a typo or an undeclared axis,
      and fails on the biggest mesh first. Names, attributes and parameters
      pass: only literals are claimed.

    Test modules are exempt (fixtures construct ad-hoc specs on purpose).
    """

    rule_id = "R12"
    name = "sharding-spec-hygiene"
    description = "inline NamedSharding/PartitionSpec constructions, unknown collective axis literals"

    #: the spec definition site (constructions are legal only here)
    _PARTITION_SUFFIX = "dist/partition.py"
    _SPEC_NAMES = frozenset({"NamedSharding", "PartitionSpec"})
    _COLLECTIVE_SUFFIXES = frozenset({
        "psum", "pmax", "pmin", "pmean", "pall", "pany",
        "all_gather", "all_to_all", "ppermute", "axis_index", "psum_scatter",
    })

    @classmethod
    def _is_partition(cls, mod: ModuleSource) -> bool:
        return str(mod.path).replace("\\", "/").endswith(cls._PARTITION_SUFFIX)

    @staticmethod
    def _skip_module(mod: ModuleSource) -> bool:
        name = mod.path.name
        return (
            "tests" in mod.path.parts
            or name.startswith("test_")
            or name == "conftest.py"
        )

    @classmethod
    def _spec_aliases(cls, tree: ast.Module) -> Set[str]:
        """Local names bound to jax.sharding spec constructors — only these
        are claimed, so an unrelated local ``P`` helper never trips."""
        out: Set[str] = set()
        for node in ast.walk(tree):
            if isinstance(node, ast.ImportFrom) and node.module == "jax.sharding":
                for alias in node.names:
                    if alias.name in cls._SPEC_NAMES:
                        out.add(alias.asname or alias.name)
        return out

    @classmethod
    def _builds_mesh_closure(cls, fn: ast.AST) -> bool:
        """Does ``fn`` reference a shard_map/pjit builder anywhere — called
        directly OR handed to ``functools.partial`` as a decorator?"""
        suffixes = MeshHygieneRule._MESH_CLOSURE_SUFFIXES
        for node in ast.walk(fn):
            if isinstance(node, ast.Name) and node.id in suffixes:
                return True
            if isinstance(node, ast.Attribute) and node.attr in suffixes:
                return True
        return False

    def check_package(
        self, modules: Sequence[ModuleSource], readme=None
    ) -> List[Violation]:
        axes = MeshHygieneRule._axis_names(modules)
        out: List[Violation] = []
        for mod in modules:
            if self._is_partition(mod) or self._skip_module(mod):
                continue
            out.extend(self._check_spec_constructions(mod))
            out.extend(self._check_axis_literals(mod, axes))
        return out

    def _check_spec_constructions(self, mod: ModuleSource) -> List[Violation]:
        aliases = self._spec_aliases(mod.tree)
        parents = parent_map(mod.tree)
        module_names = MeshHygieneRule._module_container_names(mod.tree)
        out: List[Violation] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d is None:
                continue
            last = d.rsplit(".", 1)[-1]
            is_named = last == "NamedSharding" and (
                last in aliases or d.endswith("sharding.NamedSharding")
            )
            is_pspec = (last in aliases and last != "NamedSharding") or (
                d.endswith("sharding.PartitionSpec")
            )
            if not (is_named or is_pspec):
                continue
            fn = enclosing(node, parents, (ast.FunctionDef, ast.AsyncFunctionDef))
            if fn is not None:
                if is_pspec and self._builds_mesh_closure(fn):
                    continue  # per-device block specs of the closure itself
                if MeshHygieneRule._has_mesh_keyed_memo(fn, module_names):
                    continue
                if MeshHygieneRule._is_factory(fn, node, parents):
                    continue
            kind = "NamedSharding" if is_named else "PartitionSpec"
            out.append(
                Violation(
                    path=mod.rel, line=node.lineno, col=node.col_offset,
                    rule=self.rule_id, name=self.name,
                    message=(
                        f"inline {kind} construction outside the partition "
                        "module — declare the layout as a dist/partition.py "
                        "role (ROLE_BUILDERS) so graftspmd can verify the "
                        "contract; ad-hoc specs are where dist_reshards "
                        "come from"
                    ),
                )
            )
        return out

    def _check_axis_literals(
        self, mod: ModuleSource, axes: Set[str]
    ) -> List[Violation]:
        out: List[Violation] = []
        for node in ast.walk(mod.tree):
            if not isinstance(node, ast.Call):
                continue
            d = dotted(node.func)
            if d is None or d.rsplit(".", 1)[-1] not in self._COLLECTIVE_SUFFIXES:
                continue
            for arg in list(node.args) + [kw.value for kw in node.keywords]:
                for c in ast.walk(arg):
                    if (
                        isinstance(c, ast.Constant)
                        and isinstance(c.value, str)
                        and c.value not in axes
                    ):
                        out.append(
                            Violation(
                                path=mod.rel, line=c.lineno, col=c.col_offset,
                                rule=self.rule_id, name=self.name,
                                message=(
                                    f"collective axis literal '{c.value}' is "
                                    "not an AXIS_* name from the graftpod "
                                    "topology module — a typo'd or "
                                    "undeclared axis fails at runtime on the "
                                    "biggest mesh; use the dist.runtime "
                                    "constants"
                                ),
                            )
                        )
        return out

# --- R13: dtype-literal hygiene of the mixed-precision hot paths -------------


class DtypeLiteralHygieneRule:
    """R13 — precision policy lives in ``utils/precision.py``, nowhere else.

    graftgrade's whole contract is that the COMMITTED plan decides what runs
    at bf16: the certifier walks the jaxpr, the ratchet records the verdict,
    the runtime applies it through ``demote_operator``. A raw 16-bit dtype
    spelled at a call site bypasses all three — an uncertified demotion the
    plan never sees — and an operand-derived ``dtype=`` in a solver hot path
    is the dual failure: once any operand legitimately rides at bf16, a
    ``jnp.ones(n, dtype=K.dtype)`` iterate silently inherits it and the
    1e-6 KKT tolerance becomes unreachable (8 significand bits resolve
    ~4e-3). Two findings, scoped to the ``solvers/``/``kernels/`` hot paths:

    * **Raw 16-bit dtype literals.** ``jnp.bfloat16`` / ``jnp.float16`` /
      ``np.float16`` attribute references and ``"bfloat16"``/``"float16"``/
      ``"bf16"`` dtype strings anywhere outside the precision-policy module
      — route through ``utils/precision.demote_dtype`` so the demotion is
      the certified one.
    * **Operand-derived dtype policy.** A ``dtype=<expr>.dtype`` keyword or
      a ``name = <expr>.dtype`` policy assignment not wrapped in
      ``iterate_dtype(...)`` — the floor-at-f32 helper is what keeps
      iterates, scaling vectors and while-carry dtypes convergence-safe
      when the operand itself is demoted.

    Test modules are exempt (fixtures construct half-precision operands on
    purpose), as are the R4 float64 certification modules (host numpy
    arithmetic, no demotion surface).
    """

    rule_id = "R13"
    name = "dtype-literal-hygiene"
    description = "raw 16-bit dtype literals / un-floored operand-derived dtype= in solver hot paths"

    #: the one module allowed to spell the demotion target
    _POLICY_SUFFIX = "utils/precision.py"
    _HALF_ATTRS = frozenset({"bfloat16", "float16"})
    _HALF_STRS = frozenset({"bfloat16", "float16", "bf16", "f16"})

    @staticmethod
    def _in_scope(mod: ModuleSource) -> bool:
        rel = mod.rel.replace("\\", "/")
        name = mod.path.name
        if (
            "tests" in mod.path.parts
            or name.startswith("test_")
            or name == "conftest.py"
        ):
            return False
        if any(rel.endswith(w) for w in DtypeDisciplineRule._F64_WHITELIST):
            return False
        return "solvers/" in rel or "kernels/" in rel

    def check_module(self, mod: ModuleSource) -> List[Violation]:
        rel = mod.rel.replace("\\", "/")
        if rel.endswith(self._POLICY_SUFFIX) or not self._in_scope(mod):
            return []
        jnp = jnp_aliases(mod.tree)
        np_alias = numpy_aliases(mod.tree)
        out: List[Violation] = []

        def viol(node: ast.AST, msg: str) -> None:
            out.append(
                Violation(
                    path=mod.rel, line=node.lineno, col=node.col_offset,
                    rule=self.rule_id, name=self.name, message=msg,
                )
            )

        def is_dtype_attr(node: ast.AST) -> bool:
            return isinstance(node, ast.Attribute) and node.attr == "dtype"

        for node in ast.walk(mod.tree):
            # finding A: raw 16-bit dtype literals
            if isinstance(node, ast.Attribute) and node.attr in self._HALF_ATTRS:
                base = dotted(node.value)
                if base is not None and (base in jnp or base in np_alias):
                    viol(
                        node,
                        f"raw {node.attr} literal in a solver/kernel hot "
                        "path bypasses the graftgrade plan — only "
                        "utils/precision.py spells the demotion target "
                        "(demote_operator applies the certified plan)",
                    )
            if isinstance(node, ast.Call):
                for kw in node.keywords:
                    if kw.arg != "dtype":
                        continue
                    if (
                        isinstance(kw.value, ast.Constant)
                        and isinstance(kw.value.value, str)
                        and kw.value.value in self._HALF_STRS
                    ):
                        viol(
                            node,
                            f'dtype="{kw.value.value}" literal in a '
                            "solver/kernel hot path bypasses the graftgrade "
                            "plan — route through utils/precision.py",
                        )
                    # finding B1: operand-derived dtype= kwarg, un-floored
                    if is_dtype_attr(kw.value):
                        viol(
                            node,
                            f"operand-derived dtype={dotted(kw.value)} in a "
                            "hot path: once the plan demotes that operand, "
                            "iterates built from it inherit bf16 and the "
                            "KKT tolerance becomes unreachable — wrap in "
                            "utils/precision.iterate_dtype(...) to floor at "
                            "f32",
                        )
            # finding B2: a dtype POLICY assignment (a wrapped
            # iterate_dtype(...) value is a Call, not an Attribute, so the
            # floored form never matches)
            if isinstance(node, ast.Assign) and is_dtype_attr(node.value):
                tgt = node.targets[0]
                tname = tgt.id if isinstance(tgt, ast.Name) else "?"
                viol(
                    node,
                    f"dtype policy assignment {tname} = "
                    f"{dotted(node.value)} is un-floored: every array "
                    "built with it follows the operand down to bf16 — "
                    "wrap in utils/precision.iterate_dtype(...)",
                )
        return out
