"""graftlint CLI: ``python -m citizensassemblies_tpu.lint [paths...]``.

Exit code 0 when clean, 1 on violations — pipeline-ready. With no paths the
package that contains this module is linted. ``--ir`` switches to the
jaxpr/HLO-level verifier (``lint.ir``): every registered hot core is traced
and checked for callbacks, f64 leaks, dropped donations and cost-budget
regressions against ``ANALYSIS_BUDGET.json`` (``--update-budget`` re-ratchets
the file deliberately). ``--spmd`` runs the third pass (``lint.spmd``):
every registered core is AOT-compiled — the mesh-consuming ones under
1/2/4/8-device virtual meshes — and checked for collective-census
regressions against ``SPMD_BUDGET.json`` (``--update-spmd-budget``
re-ratchets), sharding-contract violations, and precision-flow isolation
(``--precision-out`` writes ``artifacts/PRECISION_FLOW.json``). ``--prec``
runs the fourth pass (``lint.prec``, graftgrade): every registered core's
jaxpr is walked by the error-flow abstract interpreter, the verdict is
ratcheted against ``PRECISION_PLAN.json`` (``--update-prec-plan``
re-certifies), and each committed demotion is cross-checked against the
compiled HLO's dtype census. ``--format json`` emits the stable machine
schema for any pass — the four passes share the
``{"schema_version", "pass", "ok", ..., "violations": [...]}`` envelope.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from pathlib import Path
from typing import Optional, Sequence

from citizensassemblies_tpu.lint.engine import lint_paths, render_report


def _ast_report_as_json(report) -> dict:
    """Stable schema shared with the IR and SPMD passes: rule, path, line,
    message inside the common pass envelope."""
    return {
        "schema_version": 1,
        "pass": "ast",
        "ok": report.ok,
        "files": report.files,
        "suppressed": report.suppressed,
        "violations": [dataclasses.asdict(v) for v in report.violations],
    }


def _bootstrap_virtual_devices() -> None:
    """Give the SPMD sweep its 8 virtual CPU devices when jax has not been
    imported yet — exactly what ``tests/conftest.py`` and the Makefile
    targets do; a late call (jax already up) leaves the environment alone
    and the sweep verifies whatever sizes the backend exposes."""
    if "jax" in sys.modules:
        return
    os.environ.setdefault("JAX_PLATFORMS", "cpu")
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m citizensassemblies_tpu.lint",
        description=(
            "graftlint: static analysis of this repo's JAX invariants "
            "(R1 host-sync-in-jit, R2 jit-per-call, R3 donated-buffer-reuse, "
            "R4 dtype-discipline, R5 tracer-branch, R6 config-knob-hygiene, "
            "R7 thread-discipline, R8 core-span-coverage). Suppress with "
            "'# graftlint: disable=R1 -- reason'; a suppression that matches "
            "no finding is itself an error. --ir runs the jaxpr/HLO-level "
            "verifier over the registered hot cores instead."
        ),
    )
    parser.add_argument(
        "paths", nargs="*", type=Path,
        help="files/directories to lint (default: the installed package)",
    )
    parser.add_argument(
        "--readme", type=Path, default=None,
        help="README checked by R6 (default: nearest README.md above config.py)",
    )
    parser.add_argument(
        "-q", "--quiet", action="store_true", help="print violations only"
    )
    parser.add_argument(
        "--format", choices=("text", "json"), default="text",
        help="output format (json: stable rule/path/line/message schema)",
    )
    parser.add_argument(
        "--ir", action="store_true",
        help="run the IR-level verifier (callbacks, f64, donation, budgets) "
        "over the registered jitted cores instead of the AST rules",
    )
    parser.add_argument(
        "--budget", type=Path, default=None,
        help="cost-budget file for --ir (default: ANALYSIS_BUDGET.json at "
        "the repo root)",
    )
    parser.add_argument(
        "--update-budget", action="store_true",
        help="with --ir: re-measure every core and REWRITE the budget file "
        "(the deliberate ratchet move); IR1-IR3 still fail",
    )
    parser.add_argument(
        "--diff-out", type=Path, default=None,
        help="with --ir/--spmd/--prec: write the measured-vs-budget diff "
        "JSON here (the CI build artifact)",
    )
    parser.add_argument(
        "--spmd", action="store_true",
        help="run the SPMD verifier (collective census vs SPMD_BUDGET.json, "
        "sharding contracts, precision flow) over the registered cores — "
        "mesh-consuming cores swept across 1/2/4/8 virtual devices",
    )
    parser.add_argument(
        "--spmd-budget", type=Path, default=None,
        help="collective-census budget file for --spmd (default: "
        "SPMD_BUDGET.json at the repo root)",
    )
    parser.add_argument(
        "--update-spmd-budget", action="store_true",
        help="with --spmd: re-measure every core's collective census and "
        "REWRITE the budget file (the deliberate ratchet move); S2/S3 "
        "still fail",
    )
    parser.add_argument(
        "--precision-out", type=Path, default=None,
        help="with --spmd: write the S3 precision-flow artifact here "
        "(artifacts/PRECISION_FLOW.json in CI)",
    )
    parser.add_argument(
        "--prec", action="store_true",
        help="run the graftgrade precision certifier (error-flow abstract "
        "interpretation, PRECISION_PLAN.json ratchet, compiled-HLO dtype "
        "census of every committed bf16 demotion) over the registered cores",
    )
    parser.add_argument(
        "--prec-plan", type=Path, default=None,
        help="precision-plan file for --prec (default: PRECISION_PLAN.json "
        "at the repo root)",
    )
    parser.add_argument(
        "--update-prec-plan", action="store_true",
        help="with --prec: re-certify every core and REWRITE the plan file "
        "(the deliberate ratchet move); P1/P3 still fail",
    )
    args = parser.parse_args(argv)

    if args.update_budget and not args.ir:
        parser.error("--update-budget requires --ir")
    if args.update_spmd_budget and not args.spmd:
        parser.error("--update-spmd-budget requires --spmd")
    if args.update_prec_plan and not args.prec:
        parser.error("--update-prec-plan requires --prec")
    if sum(1 for f in (args.ir, args.spmd, args.prec) if f) > 1:
        parser.error("--ir, --spmd and --prec are separate passes; run them "
                     "separately")
    if args.prec:
        if args.paths:
            parser.error("--prec certifies the registered cores; paths are "
                         "for the AST pass")
        _bootstrap_virtual_devices()
        from citizensassemblies_tpu.lint.prec import (
            prec_plan_diff,
            prec_report_as_json,
            render_prec_report,
            run_prec_checks,
        )

        report = run_prec_checks(
            plan_path=args.prec_plan, update_plan=args.update_prec_plan
        )
        if args.diff_out is not None:
            args.diff_out.write_text(
                json.dumps(prec_plan_diff(report), indent=1, sort_keys=True)
                + "\n",
                encoding="utf-8",
            )
        if args.format == "json":
            print(json.dumps(prec_report_as_json(report), indent=1))
        else:
            rendered = render_prec_report(report)
            if args.quiet:
                rendered = "\n".join(v.render() for v in report.violations)
            if rendered:
                print(rendered)
        return 0 if report.ok else 1
    if args.spmd:
        if args.paths:
            parser.error("--spmd verifies the registered cores; paths are "
                         "for the AST pass")
        _bootstrap_virtual_devices()
        from citizensassemblies_tpu.lint.spmd import (
            render_spmd_report,
            run_spmd_checks,
            spmd_budget_diff,
            spmd_report_as_json,
        )

        report = run_spmd_checks(
            budget_path=args.spmd_budget,
            update_budget=args.update_spmd_budget,
            precision_out=args.precision_out,
        )
        if args.diff_out is not None:
            args.diff_out.write_text(
                json.dumps(spmd_budget_diff(report), indent=1, sort_keys=True)
                + "\n",
                encoding="utf-8",
            )
        if args.format == "json":
            print(json.dumps(spmd_report_as_json(report), indent=1))
        else:
            rendered = render_spmd_report(report)
            if args.quiet:
                rendered = "\n".join(v.render() for v in report.violations)
            if rendered:
                print(rendered)
        return 0 if report.ok else 1
    if args.ir:
        if args.paths:
            parser.error("--ir verifies the registered cores; paths are "
                         "for the AST pass")
        from citizensassemblies_tpu.lint.ir import (
            budget_diff,
            ir_report_as_json,
            render_ir_report,
            run_ir_checks,
        )

        report = run_ir_checks(
            budget_path=args.budget, update_budget=args.update_budget
        )
        if args.diff_out is not None:
            args.diff_out.write_text(
                json.dumps(budget_diff(report), indent=1, sort_keys=True) + "\n",
                encoding="utf-8",
            )
        if args.format == "json":
            print(json.dumps(ir_report_as_json(report), indent=1))
        else:
            rendered = render_ir_report(report)
            if args.quiet:
                rendered = "\n".join(v.render() for v in report.violations)
            if rendered:
                print(rendered)
        return 0 if report.ok else 1

    paths = args.paths or [Path(__file__).resolve().parent.parent]
    report = lint_paths(paths, readme=args.readme)
    if args.format == "json":
        print(json.dumps(_ast_report_as_json(report), indent=1))
        return 0 if report.ok else 1
    rendered = render_report(report)
    if args.quiet:
        rendered = "\n".join(v.render() for v in report.violations)
    if rendered:
        print(rendered)
    return 0 if report.ok else 1


if __name__ == "__main__":  # pragma: no cover - exercised via __main__
    sys.exit(main())
