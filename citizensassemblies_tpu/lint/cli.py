"""graftlint CLI: ``python -m citizensassemblies_tpu.lint [paths...]``.

Exit code 0 when clean, 1 on violations — pipeline-ready. With no paths the
package that contains this module is linted.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import Optional, Sequence

from citizensassemblies_tpu.lint.engine import lint_paths, render_report


def main(argv: Optional[Sequence[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m citizensassemblies_tpu.lint",
        description=(
            "graftlint: static analysis of this repo's JAX invariants "
            "(R1 host-sync-in-jit, R2 jit-per-call, R3 donated-buffer-reuse, "
            "R4 dtype-discipline, R5 tracer-branch, R6 config-knob-hygiene). "
            "Suppress with '# graftlint: disable=R1 -- reason'."
        ),
    )
    parser.add_argument(
        "paths", nargs="*", type=Path,
        help="files/directories to lint (default: the installed package)",
    )
    parser.add_argument(
        "--readme", type=Path, default=None,
        help="README checked by R6 (default: nearest README.md above config.py)",
    )
    parser.add_argument(
        "-q", "--quiet", action="store_true", help="print violations only"
    )
    args = parser.parse_args(argv)

    paths = args.paths or [Path(__file__).resolve().parent.parent]
    report = lint_paths(paths, readme=args.readme)
    rendered = render_report(report)
    if args.quiet:
        rendered = "\n".join(v.render() for v in report.violations)
    if rendered:
        print(rendered)
    return 0 if report.ok else 1


if __name__ == "__main__":  # pragma: no cover - exercised via __main__
    sys.exit(main())
