import sys

from citizensassemblies_tpu.lint.cli import main

sys.exit(main())
