"""graftgrade: jaxpr-level precision-flow certification (the fourth pass).

graftspmd's S3 classifies precision per *scope*; this pass is the
per-primitive refinement that lets the repo actually SPEND the roofline
headroom: a static certificate of where bf16 operand demotion is safe, a
ratcheted plan artifact recording the verdict, and a compiled-truth census
proving the applied plan survived XLA. Three check families:

* **P1 error-flow abstract interpretation** — for every ``@register_ir_core``
  entry the jaxpr (sub-jaxprs included) is walked propagating, per variable,
  a dynamic-range interval and a relative-error amplification bound over the
  primitive set the repo actually uses (dot/ELL gather-scatter, prox/clip,
  segment reductions, while-carry fixpoints via the sentinel contract: carry
  seeds are TOP, so nothing derived from a fixpoint iterate ever certifies).
  Every intermediate is classified ``bf16_safe`` / ``f32_required`` /
  ``f64_cert`` — accumulation outputs and comparison operands are pinned
  ``>=f32`` by rule, and an input argument certifies for demotion only when
  its registration declares it exactly representable at bf16
  (``IRCase.arg_ranges``) AND the walk proves the demoted storage adds zero
  relative error. The certifier proves LOSSLESS demotion; the runtime
  (``utils/precision.demote_operator``) enforces the same property per
  concrete array, so engaged-vs-off stays bit-identical.
* **P2 ratcheted plan artifact** — the classification is committed as
  ``PRECISION_PLAN.json`` (root, next to ANALYSIS_BUDGET / SPMD_BUDGET) and
  ratcheted with the same discipline: missing / stale (jaxpr fingerprint) /
  downgraded (plan claims more bf16 than the analysis certifies) / doctored
  (class counts no longer cover the traced variables) entries are named
  FAILs; ``--update-prec-plan`` regenerates deliberately; the plan sha256 is
  stamped on bench rows (:func:`prec_plan_provenance`).
* **P3 compiled-truth cross-check** — each demoted core is re-lowered with
  its certified arguments at bf16 and the compiled HLO is censused: the
  demoted parameter must still be bf16 in the entry signature (no silent
  XLA re-upcast on the demoted edge), a cert core (``allow_f64``) must show
  ZERO bf16 anywhere (no bf16 into an S3 ``f64_cert`` sink — cross-checked
  against ``precision_flow``'s ``cert_isolated`` on the demoted trace), and
  the static operand-bytes traffic model records the HBM reduction per core
  (CPU/interpret regime: the README records the hardware waiver — XLA:CPU
  legalizes bf16 through f32 converts, so the bytes win is measured at the
  operand interface, not the CPU cost model).

Run as ``python -m citizensassemblies_tpu.lint --prec`` (or ``make
check-prec``); reports use graftlint's ``file:line`` contract.
"""

from __future__ import annotations

import dataclasses
import hashlib
import json
import math
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence, Tuple

from citizensassemblies_tpu.lint.engine import Violation
from citizensassemblies_tpu.lint.ir import _trace_jaxpr
from citizensassemblies_tpu.lint.registry import CoreEntry, IRCase, collect
from citizensassemblies_tpu.utils.precision import PLAN_PATH

#: unit roundoffs of the three storage formats the certifier reasons about
BF16_EPS = 2.0 ** -8
F32_EPS = 2.0 ** -24
F64_EPS = 2.0 ** -53

#: bf16 shares f32's exponent range; overflow is ~3.39e38 and integers are
#: exactly representable up to 2**8 (8-bit significand)
BF16_MAX = 3.38e38
BF16_EXACT_INT = 256.0

#: relative-error bounds are capped here in reports (inf ⇒ "unbounded",
#: serialized as null)
_REL_CAP = 1e30

#: accumulation primitives: their OUTPUTS are pinned >=f32 by rule — a bf16
#: accumulator loses the 1e-6 KKT resolution no matter how exact the terms
ACCUM_PRIMS = frozenset(
    {
        "dot_general", "reduce_sum", "cumsum", "add_any",
        "segment_sum", "scatter-add", "scatter_add",
    }
)

#: consumers that pin their float operands >=f32 (the S3 set): comparisons
#: decide convergence/KKT acceptance, ordering ties flip under narrowing,
#: callbacks/custom calls are opaque
from citizensassemblies_tpu.lint.spmd import _PIN_PRIMS, precision_flow  # noqa: E402


# --- P1: the abstract domain -------------------------------------------------


@dataclasses.dataclass(frozen=True)
class AbsVal:
    """One variable's abstract state: dynamic-range interval ``[lo, hi]``,
    relative-error amplification bound ``rel`` (an upper bound on
    |computed − exact| / |exact| accumulated from storage roundoff and
    primitive rounding; ``inf`` = unbounded, e.g. past a cancellation), and
    ``exact`` — the value is exactly representable at bf16 (integer-valued,
    magnitude ≤ 256) with zero accumulated error."""

    lo: float
    hi: float
    rel: float
    exact: bool = False

    @property
    def mag(self) -> float:
        return max(abs(self.lo), abs(self.hi))

    def nonneg(self) -> bool:
        return self.lo >= 0.0

    def nonpos(self) -> bool:
        return self.hi <= 0.0


#: the lattice top: unknown range, unbounded error (while-carry seeds, any
#: primitive without a transfer function)
TOP = AbsVal(-math.inf, math.inf, math.inf, False)


def _join(a: AbsVal, b: AbsVal) -> AbsVal:
    return AbsVal(
        min(a.lo, b.lo), max(a.hi, b.hi), max(a.rel, b.rel),
        a.exact and b.exact,
    )


def _mul_bound(x: float, y: float) -> float:
    """Interval-endpoint product with 0·inf = 0 (an exactly-zero endpoint
    annihilates even an unbounded one)."""
    if x == 0.0 or y == 0.0:
        return 0.0
    return x * y


def _interval_mul(a: AbsVal, b: AbsVal) -> Tuple[float, float]:
    cands = [
        _mul_bound(a.lo, b.lo), _mul_bound(a.lo, b.hi),
        _mul_bound(a.hi, b.lo), _mul_bound(a.hi, b.hi),
    ]
    return min(cands), max(cands)


def _compose_rel(*rels: float, eps: float = F32_EPS, steps: int = 1) -> float:
    """Sound first-order-free composition: Π(1+rᵢ)·(1+eps)^steps − 1."""
    acc = 1.0
    for r in rels:
        if math.isinf(r):
            return math.inf
        acc *= 1.0 + r
    for _ in range(min(steps, 64)):
        acc *= 1.0 + eps
    if steps > 64:
        acc *= math.exp(steps * eps)  # ≥ (1+eps)^steps for eps ≥ 0
    return acc - 1.0


def _same_sign(a: AbsVal, b: AbsVal) -> bool:
    return (a.nonneg() and b.nonneg()) or (a.nonpos() and b.nonpos())


def _add(a: AbsVal, b: AbsVal, sub: bool = False) -> AbsVal:
    if sub:
        b = AbsVal(-b.hi, -b.lo, b.rel, b.exact)
    lo, hi = a.lo + b.lo, a.hi + b.hi
    if _same_sign(a, b):
        # no cancellation: the result is a convex-ish mix of the operand
        # errors, plus one rounding
        rel = _compose_rel(max(a.rel, b.rel))
    else:
        # possible cancellation: relative error is unbounded at the zero
        # crossing — sound, and exactly why iterate arithmetic pins f32
        rel = math.inf
    exact = (
        a.exact and b.exact
        and max(abs(lo), abs(hi)) <= BF16_EXACT_INT
    )
    return AbsVal(lo, hi, 0.0 if exact else rel, exact)


def _mul(a: AbsVal, b: AbsVal) -> AbsVal:
    lo, hi = _interval_mul(a, b)
    rel = _compose_rel(a.rel, b.rel)
    exact = a.exact and b.exact and max(abs(lo), abs(hi)) <= BF16_EXACT_INT
    return AbsVal(lo, hi, 0.0 if exact else rel, exact)


def _div(a: AbsVal, b: AbsVal) -> AbsVal:
    if b.lo <= 0.0 <= b.hi:
        return AbsVal(-math.inf, math.inf, math.inf, False)
    inv = AbsVal(1.0 / b.hi, 1.0 / b.lo, b.rel, False)
    lo, hi = _interval_mul(a, inv)
    return AbsVal(lo, hi, _compose_rel(a.rel, b.rel), False)


def _reduce_sum_like(a: AbsVal, n: int) -> AbsVal:
    n = max(int(n), 1)
    lo = min(n * a.lo, a.lo)
    hi = max(n * a.hi, a.hi)
    if a.nonneg() or a.nonpos():
        rel = _compose_rel(a.rel, steps=n)
    else:
        rel = math.inf
    return AbsVal(lo, hi, rel, False)


def _passthrough(ins: List[AbsVal]) -> AbsVal:
    out = ins[0]
    for v in ins[1:]:
        out = _join(out, v)
    return out


def _reduction_count(eqn) -> int:
    """Number of terms each output element of a reduction accumulates."""
    in_sz = max(
        (int(math.prod(getattr(v.aval, "shape", ()) or (1,))) for v in eqn.invars if hasattr(v, "aval")),
        default=1,
    )
    out_sz = max(
        (int(math.prod(getattr(v.aval, "shape", ()) or (1,))) for v in eqn.outvars if hasattr(v, "aval")),
        default=1,
    )
    return max(in_sz // max(out_sz, 1), 1)


def _transfer(eqn, ins: List[AbsVal]) -> AbsVal:
    """The per-primitive transfer function; conservative TOP default."""
    name = eqn.primitive.name
    if name in ("add",):
        return _add(ins[0], ins[1])
    if name in ("sub",):
        return _add(ins[0], ins[1], sub=True)
    if name == "mul":
        return _mul(ins[0], ins[1])
    if name == "div":
        return _div(ins[0], ins[1])
    if name == "neg":
        a = ins[0]
        return AbsVal(-a.hi, -a.lo, a.rel, a.exact)
    if name == "abs":
        a = ins[0]
        lo = 0.0 if a.lo <= 0.0 <= a.hi else min(abs(a.lo), abs(a.hi))
        return AbsVal(lo, a.mag, a.rel, a.exact)
    if name in ("max", "min"):
        a, b = ins[0], ins[1]
        if name == "max":
            lo, hi = max(a.lo, b.lo), max(a.hi, b.hi)
        else:
            lo, hi = min(a.lo, b.lo), min(a.hi, b.hi)
        # a rounding-perturbed max can switch branch, but the returned value
        # is one of the operands: error ≤ max of operand errors (+ the gap
        # at a near-tie, absorbed by the operand bound)
        return AbsVal(lo, hi, max(a.rel, b.rel), a.exact and b.exact)
    if name in ("clamp",):  # clamp(lo, x, hi) — prox/projection steps
        lo_v, x, hi_v = ins[0], ins[1], ins[2]
        return AbsVal(
            max(x.lo, lo_v.lo), min(x.hi, hi_v.hi),
            max(x.rel, lo_v.rel, hi_v.rel), False,
        )
    if name == "sqrt":
        a = ins[0]
        if a.lo < 0.0:
            return TOP
        return AbsVal(
            math.sqrt(a.lo), math.sqrt(a.hi),
            _compose_rel(0.5 * a.rel if not math.isinf(a.rel) else a.rel),
            False,
        )
    if name == "exp":
        a = ins[0]
        if math.isinf(a.mag) or math.isinf(a.rel):
            return TOP
        # d(e^x)/e^x = dx: relative error scales with |x| · rel_abs; bound
        # via the absolute perturbation |x|·rel
        pert = a.mag * a.rel
        if pert > 700.0:
            return TOP
        return AbsVal(
            math.exp(a.lo), math.exp(a.hi),
            _compose_rel(math.expm1(pert) if pert < 700 else math.inf),
            False,
        )
    if name in ("reduce_sum", "cumsum", "add_any"):
        return _reduce_sum_like(_passthrough(ins), _reduction_count(eqn))
    if name == "dot_general":
        prod = _mul(ins[0], ins[1])
        dims = eqn.params.get("dimension_numbers")
        n = 1
        try:
            (lhs_c, _), _ = dims
            shape = tuple(getattr(eqn.invars[0].aval, "shape", ()))
            for d in lhs_c:
                n *= int(shape[d])
        except Exception:  # noqa: BLE001 - fall back to the coarse count
            n = _reduction_count(eqn)
        return _reduce_sum_like(prod, n)
    if name in ("reduce_max", "reduce_min", "argmax", "argmin"):
        a = _passthrough(ins)
        return AbsVal(a.lo, a.hi, a.rel, False)
    if name in ("gather", "take", "dynamic_slice", "slice", "squeeze",
                "reshape", "broadcast_in_dim", "transpose", "rev",
                "expand_dims", "copy", "stop_gradient", "dynamic_update_slice",
                "concatenate", "pad", "select_n", "where"):
        # structural / selection: values are drawn from the operands
        return _passthrough([v for v in ins if v is not None] or [TOP])
    if name in ("segment_sum", "scatter-add", "scatter_add"):
        return _reduce_sum_like(_passthrough(ins), _reduction_count(eqn))
    if name == "convert_element_type":
        a = ins[0]
        new = str(eqn.params.get("new_dtype", ""))
        if new.startswith("bfloat16"):
            if a.exact:
                return a  # lossless by construction
            return AbsVal(a.lo, a.hi, _compose_rel(a.rel, eps=BF16_EPS), False)
        if new.startswith("float"):
            return AbsVal(a.lo, a.hi, _compose_rel(a.rel), a.exact)
        return AbsVal(a.lo, a.hi, a.rel, a.exact)
    if name in ("integer_pow",):
        p = int(eqn.params.get("y", 2))
        out = ins[0]
        for _ in range(max(p - 1, 0)):
            out = _mul(out, ins[0])
        return out
    if name in ("sign", "floor", "ceil", "round", "iota", "eq", "ne", "lt",
                "le", "gt", "ge", "and", "or", "not", "xor", "is_finite"):
        # boolean / integral outputs: exact by construction
        return AbsVal(-math.inf, math.inf, 0.0, False)
    return TOP


# --- P1: the jaxpr walk ------------------------------------------------------


def _const_absval(val) -> AbsVal:
    import numpy as np

    try:
        arr = np.asarray(val)
        if arr.size == 0:
            return AbsVal(0.0, 0.0, 0.0, True)
        lo = float(np.min(arr))
        hi = float(np.max(arr))
        exact = bool(
            np.issubdtype(arr.dtype, np.integer)
            or (
                np.issubdtype(arr.dtype, np.floating)
                and max(abs(lo), abs(hi)) <= BF16_EXACT_INT
                and bool(np.all(arr == np.round(arr)))
            )
        )
        return AbsVal(lo, hi, 0.0, exact)
    except Exception:  # noqa: BLE001 - opaque const
        return TOP


def _range_absval(rng: Optional[Tuple[float, float, bool]]) -> AbsVal:
    if rng is None:
        return AbsVal(-math.inf, math.inf, F32_EPS, False)
    lo, hi, exact = float(rng[0]), float(rng[1]), bool(rng[2])
    if exact and max(abs(lo), abs(hi)) <= BF16_EXACT_INT:
        return AbsVal(lo, hi, 0.0, True)
    return AbsVal(lo, hi, F32_EPS, False)


@dataclasses.dataclass
class Analysis:
    """P1 outcome for one core."""

    classes: Dict[str, int]
    n_vars: int
    arg_classes: List[str]
    certified_demote: List[int]
    out_rel: Optional[float]  # None = unbounded
    jaxpr_sha: str


def _sub_jaxpr_of(item):
    return getattr(item, "jaxpr", item if hasattr(item, "eqns") else None)


class _Interp:
    """The error-flow abstract interpreter (one instance per core trace)."""

    def __init__(self):
        self.counts = {
            "bf16_safe": 0, "f32_required": 0, "f64_cert": 0, "non_float": 0,
        }
        self.n_vars = 0

    def _read(self, env, var) -> AbsVal:
        if hasattr(var, "val"):  # Literal
            return _const_absval(var.val)
        return env.get(var, TOP)

    def _classify_scope(self, jaxpr, env) -> None:
        """Assign a class to every eqn output of THIS scope (sub-jaxprs are
        classified by their own eval calls)."""
        outvars = {v for v in jaxpr.outvars if hasattr(v, "aval")}
        consumers: Dict[Any, List[Any]] = {}
        for eqn in jaxpr.eqns:
            for var in eqn.invars:
                if hasattr(var, "aval") and not hasattr(var, "val"):
                    consumers.setdefault(var, []).append(eqn)
        for eqn in jaxpr.eqns:
            accum = eqn.primitive.name in ACCUM_PRIMS
            for var in eqn.outvars:
                aval = getattr(var, "aval", None)
                dtype = str(getattr(aval, "dtype", ""))
                self.n_vars += 1
                if not dtype.startswith(("float", "bfloat")):
                    self.counts["non_float"] += 1
                    continue
                if dtype == "float64" and not getattr(aval, "weak_type", False):
                    self.counts["f64_cert"] += 1
                    continue
                av = env.get(var, TOP)
                pinned = accum or var in outvars
                if not pinned:
                    for consumer in consumers.get(var, []):
                        if consumer.primitive.name in _PIN_PRIMS:
                            pinned = True
                            break
                safe = (
                    not pinned
                    and av.exact
                    and av.mag <= BF16_MAX
                )
                self.counts["bf16_safe" if safe else "f32_required"] += 1

    def eval_jaxpr(self, jaxpr, in_vals: Sequence[AbsVal], const_vals: Sequence[AbsVal]) -> List[AbsVal]:
        env: Dict[Any, AbsVal] = {}
        for var, val in zip(jaxpr.constvars, const_vals):
            env[var] = val
        for var, val in zip(jaxpr.invars, in_vals):
            env[var] = val
        for eqn in jaxpr.eqns:
            ins = [self._read(env, v) for v in eqn.invars]
            outs = self._eval_eqn(eqn, ins)
            for var, val in zip(eqn.outvars, outs):
                env[var] = val
        self._classify_scope(jaxpr, env)
        return [self._read(env, v) for v in jaxpr.outvars]

    def _eval_eqn(self, eqn, ins: List[AbsVal]) -> List[AbsVal]:
        name = eqn.primitive.name
        params = eqn.params
        if name == "pjit" or name in ("closed_call", "core_call", "custom_jvp_call", "custom_vjp_call"):
            closed = params.get("jaxpr") or params.get("call_jaxpr")
            sub = _sub_jaxpr_of(closed)
            if sub is not None:
                consts = [_const_absval(c) for c in getattr(closed, "consts", [])]
                return self.eval_jaxpr(sub, ins, consts)
            return [TOP] * len(eqn.outvars)
        if name == "while":
            cn = int(params.get("cond_nconsts", 0))
            bn = int(params.get("body_nconsts", 0))
            body = _sub_jaxpr_of(params.get("body_jaxpr"))
            cond = _sub_jaxpr_of(params.get("cond_jaxpr"))
            body_consts = ins[cn: cn + bn]
            n_carry = len(ins) - cn - bn
            # the sentinel contract: a fixpoint carry is TOP — nothing
            # derived from the iterate certifies, only loop-invariant
            # closure operands (the packed operator) keep their state
            carry = [TOP] * n_carry
            if body is not None:
                self.eval_jaxpr(body, list(body_consts) + carry, [])
            if cond is not None:
                self.eval_jaxpr(cond, list(ins[:cn]) + carry, [])
            return [TOP] * len(eqn.outvars)
        if name == "scan":
            closed = params.get("jaxpr")
            sub = _sub_jaxpr_of(closed)
            nc = int(params.get("num_consts", 0))
            ncar = int(params.get("num_carry", 0))
            if sub is not None:
                consts = list(ins[:nc])
                carry = [TOP] * ncar
                xs = list(ins[nc + ncar:])
                self.eval_jaxpr(sub, consts + carry + xs, [])
            return [TOP] * len(eqn.outvars)
        if name == "cond":
            branches = params.get("branches", ())
            outs: Optional[List[AbsVal]] = None
            for br in branches:
                sub = _sub_jaxpr_of(br)
                if sub is None:
                    continue
                consts = [_const_absval(c) for c in getattr(br, "consts", [])]
                got = self.eval_jaxpr(sub, ins[1:], consts)
                outs = got if outs is None else [
                    _join(a, b) for a, b in zip(outs, got)
                ]
            return outs if outs is not None else [TOP] * len(eqn.outvars)
        # generic sub-jaxpr fallback (pallas kernels, remat, ...) — walk for
        # classification coverage, return TOP
        walked = False
        for value in params.values():
            items = value if isinstance(value, (list, tuple)) else [value]
            for item in items:
                sub = _sub_jaxpr_of(item)
                if sub is not None:
                    walked = True
                    self.eval_jaxpr(sub, [TOP] * len(sub.invars), [
                        _const_absval(c) for c in getattr(item, "consts", [])
                    ])
        if walked:
            return [TOP] * len(eqn.outvars)
        out = _transfer(eqn, ins)
        return [out] * len(eqn.outvars)


def jaxpr_fingerprint(closed) -> str:
    """Stable fingerprint of a traced core (the P2 staleness key)."""
    return hashlib.sha256(str(closed.jaxpr).encode("utf-8")).hexdigest()[:12]


def analyze_case(case: IRCase) -> Analysis:
    """P1 for one built core: trace, walk, classify, certify demotions."""
    closed = _trace_jaxpr(case, x64=case.allow_f64 and case.x64_trace)
    interp = _Interp()
    ranges = case.arg_ranges or (None,) * len(case.args)
    flat_in: List[AbsVal] = []
    flat_map: List[int] = []  # flat position -> original arg index
    import jax

    for i, a in enumerate(case.args):
        leaves = jax.tree_util.tree_leaves(a)
        for _ in leaves:
            flat_in.append(_range_absval(ranges[i] if i < len(ranges) else None))
            flat_map.append(i)
    consts = [_const_absval(c) for c in closed.consts]
    outs = interp.eval_jaxpr(closed.jaxpr, flat_in, consts)

    # input certification: nominated + declared exact + float32 + consumed
    # only through promoting arithmetic (the walk pinned everything else)
    arg_classes: List[str] = []
    certified: List[int] = []
    invars = closed.jaxpr.invars
    for pos, var in enumerate(invars):
        i = flat_map[pos] if pos < len(flat_map) else pos
        aval = getattr(var, "aval", None)
        dtype = str(getattr(aval, "dtype", ""))
        if not dtype.startswith(("float", "bfloat")):
            arg_classes.append("non_float")
            continue
        if dtype == "float64" and not getattr(aval, "weak_type", False):
            arg_classes.append("f64_cert")
            continue
        av = flat_in[pos] if pos < len(flat_in) else TOP
        nominated = i in tuple(case.prec_demote or ())
        if nominated and av.exact and not case.allow_f64:
            arg_classes.append("bf16_safe")
            if i not in certified:
                certified.append(i)
        else:
            arg_classes.append("f32_required")

    out_rel: Optional[float] = 0.0
    for pos, var in enumerate(closed.jaxpr.outvars):
        dtype = str(getattr(getattr(var, "aval", None), "dtype", ""))
        if not dtype.startswith(("float", "bfloat")):
            continue
        r = outs[pos].rel if pos < len(outs) else math.inf
        if math.isinf(r) or r > _REL_CAP:
            out_rel = None
            break
        out_rel = max(out_rel, r)

    return Analysis(
        classes=dict(interp.counts),
        n_vars=interp.n_vars,
        arg_classes=arg_classes,
        certified_demote=sorted(certified),
        out_rel=out_rel,
        jaxpr_sha=jaxpr_fingerprint(closed),
    )


def chain_error_bound(fn, arg_specs, arg_ranges=None, static=None) -> Optional[float]:
    """Static relative-error bound of ``fn``'s outputs (P1 walk), for the
    bound-soundness property tests: the returned bound must dominate the
    measured f32-vs-f64 relative error on any operands drawn inside
    ``arg_ranges``. ``None`` = the walk could not bound the chain
    (cancellation / fixpoint) — vacuously sound."""
    case = IRCase(
        fn=fn, args=tuple(arg_specs), static=dict(static or {}),
        arg_ranges=tuple(arg_ranges) if arg_ranges is not None else None,
    )
    import jax

    closed = jax.make_jaxpr(
        (lambda *a: fn(*a, **case.static)) if case.static else fn
    )(*case.args)
    interp = _Interp()
    ranges = case.arg_ranges or (None,) * len(case.args)
    flat_in = [
        _range_absval(ranges[i] if i < len(ranges) else None)
        for i in range(len(case.args))
    ]
    consts = [_const_absval(c) for c in closed.consts]
    outs = interp.eval_jaxpr(closed.jaxpr, flat_in, consts)
    worst = 0.0
    for av in outs:
        if math.isinf(av.rel) or av.rel > _REL_CAP:
            return None
        worst = max(worst, av.rel)
    return worst


# --- traffic model -----------------------------------------------------------


def _leaf_bytes(a, itemsize: Optional[int] = None) -> int:
    import numpy as np

    shape = tuple(getattr(a, "shape", ()) or ())
    dtype = getattr(a, "dtype", None)
    if dtype is None:
        return 0
    size = int(np.dtype(dtype).itemsize) if itemsize is None else itemsize
    n = 1
    for d in shape:
        n *= int(d)
    return n * size


def traffic_model(case: IRCase, demote_args: Sequence[int]) -> Dict[str, Any]:
    """Static operand-bytes model of the demotion: committed-dtype input
    bytes vs the same inputs with the certified arguments at bf16. This is
    the jaxpr-level HBM-traffic evidence — deliberately NOT the XLA:CPU
    cost model, which re-upcasts bf16 through f32 converts and would report
    a traffic *increase* on the CI host (the recorded hardware waiver)."""
    import jax

    base = 0
    demoted = 0
    dem = set(int(i) for i in demote_args)
    for i, a in enumerate(case.args):
        for leaf in jax.tree_util.tree_leaves(a):
            b = _leaf_bytes(leaf)
            base += b
            if i in dem:
                dt = str(getattr(leaf, "dtype", ""))
                if dt == "float32":
                    b = b // 2
            demoted += b
    pct = 100.0 * (base - demoted) / base if base else 0.0
    return {
        "operand_bytes_f32": int(base),
        "operand_bytes_demoted": int(demoted),
        "reduction_pct": round(pct, 2),
    }


# --- P3: compiled truth ------------------------------------------------------


import re  # noqa: E402

_PARAM_RE = re.compile(r"=\s*([a-z0-9]+)\[[^\]]*\][^\n]*?\bparameter\((\d+)\)")


def hlo_param_dtypes(text: str) -> Dict[int, str]:
    """``{parameter index: dtype token}`` from compiled-HLO text."""
    out: Dict[int, str] = {}
    for m in _PARAM_RE.finditer(text):
        out[int(m.group(2))] = m.group(1)
    return out


def hlo_dtype_census(text: str) -> Dict[str, int]:
    """Occurrence counts of the floating dtype tokens in compiled HLO."""
    return {
        dt: len(re.findall(rf"(?<![\w]){dt}\[", text))
        for dt in ("bf16", "f16", "f32", "f64")
    }


def demoted_args(case: IRCase, demote: Sequence[int]):
    """The example args with the certified arguments at bf16."""
    import jax
    import jax.numpy as jnp

    dem = set(int(i) for i in demote)
    out = []
    for i, a in enumerate(case.args):
        if i not in dem:
            out.append(a)
            continue

        def to16(leaf):
            dt = str(getattr(leaf, "dtype", ""))
            if dt != "float32":
                return leaf
            if isinstance(leaf, jax.ShapeDtypeStruct):
                return jax.ShapeDtypeStruct(leaf.shape, jnp.bfloat16)
            return jnp.asarray(leaf).astype(jnp.bfloat16)

        out.append(jax.tree_util.tree_map(to16, a))
    return tuple(out)


# --- per-core verification ---------------------------------------------------


@dataclasses.dataclass
class PrecCoreReport:
    """graftgrade outcome for one registered core."""

    name: str
    path: str
    line: int
    violations: List[Violation] = dataclasses.field(default_factory=list)
    analysis: Optional[Analysis] = None
    plan_entry: Optional[Dict[str, Any]] = None
    #: committed-plan demotions this run verified at the compiled level
    applied_demote: List[int] = dataclasses.field(default_factory=list)
    traffic: Optional[Dict[str, Any]] = None
    census: Optional[Dict[str, int]] = None
    cert_isolated: Optional[bool] = None

    @property
    def ok(self) -> bool:
        return not self.violations


@dataclasses.dataclass
class PrecReport:
    cores: List[PrecCoreReport]
    plan_path: str
    updated: bool = False

    @property
    def violations(self) -> List[Violation]:
        return [v for c in self.cores for v in c.violations]

    @property
    def ok(self) -> bool:
        return not self.violations


def _viol(entry, rule: str, name: str, message: str) -> Violation:
    return Violation(
        path=entry.path, line=entry.line, col=0, rule=rule, name=name,
        message=f"[{entry.name}] {message}",
    )


def measured_plan_entry(analysis: Analysis, case: IRCase) -> Dict[str, Any]:
    """The PRECISION_PLAN.json entry this run would commit for one core."""
    return {
        "jaxpr_sha": analysis.jaxpr_sha,
        "classes": dict(analysis.classes),
        "n_vars": analysis.n_vars,
        "demote_args": list(analysis.certified_demote),
        "out_rel_bound": analysis.out_rel,
        "traffic": traffic_model(case, analysis.certified_demote),
    }


def verify_prec_core(
    entry: CoreEntry,
    plan_entry: Optional[Dict[str, Any]],
    update_plan: bool = False,
) -> PrecCoreReport:
    """Run P1–P3 for one registered core; check failures become violations,
    never exceptions."""
    report = PrecCoreReport(name=entry.name, path=entry.path, line=entry.line)
    report.plan_entry = plan_entry
    try:
        case = entry.build()
    except Exception as exc:  # noqa: BLE001 - reported, not raised
        report.violations.append(
            _viol(entry, "P1", "untraceable-core", f"builder failed: {exc!r}")
        )
        return report
    report._case = case  # type: ignore[attr-defined]  # for the plan writer

    # --- P1 ------------------------------------------------------------------
    try:
        analysis = analyze_case(case)
    except Exception as exc:  # noqa: BLE001
        report.violations.append(
            _viol(entry, "P1", "untraceable-core", f"error-flow walk failed: {exc!r}")
        )
        return report
    report.analysis = analysis

    nominated = set(int(i) for i in (case.prec_demote or ()))
    refused = sorted(nominated - set(analysis.certified_demote))
    if refused:
        report.violations.append(
            _viol(
                entry, "P1", "uncertified-demotion",
                f"argument(s) {refused} are nominated in prec_demote but the "
                "error-flow walk refuses them — declare an exact arg_ranges "
                "triple the operand actually satisfies, or drop the nomination",
            )
        )

    # --- P2: the ratchet -----------------------------------------------------
    if plan_entry is None:
        report.violations.append(
            _viol(
                entry, "P2", "missing-plan-entry",
                "no entry in PRECISION_PLAN.json — run 'python -m "
                "citizensassemblies_tpu.lint --prec --update-prec-plan' and "
                "commit the result",
            )
        )
        if not update_plan:
            return report
        plan_demote: List[int] = list(analysis.certified_demote)
    else:
        if str(plan_entry.get("jaxpr_sha")) != analysis.jaxpr_sha:
            report.violations.append(
                _viol(
                    entry, "P2", "stale-plan-entry",
                    f"committed jaxpr fingerprint {plan_entry.get('jaxpr_sha')} "
                    f"!= traced {analysis.jaxpr_sha} — the core changed under "
                    "the plan; re-certify with --update-prec-plan",
                )
            )
        plan_classes = dict(plan_entry.get("classes", {}))
        plan_n = int(plan_entry.get("n_vars", -1))
        if (
            plan_n != analysis.n_vars
            or sum(int(v) for v in plan_classes.values()) != plan_n
        ):
            report.violations.append(
                _viol(
                    entry, "P2", "unclassified-var",
                    f"plan classes cover {sum(int(v) for v in plan_classes.values())} "
                    f"of n_vars={plan_n} vs {analysis.n_vars} traced variables "
                    "— every intermediate must carry a classification; "
                    "re-certify with --update-prec-plan",
                )
            )
        if int(plan_classes.get("bf16_safe", 0)) > analysis.classes["bf16_safe"]:
            report.violations.append(
                _viol(
                    entry, "P2", "plan-downgrade",
                    f"plan claims {plan_classes.get('bf16_safe')} bf16_safe "
                    f"intermediates but the walk certifies only "
                    f"{analysis.classes['bf16_safe']} — a downgraded entry "
                    "(someone widened the plan without re-certifying)",
                )
            )
        plan_demote = [int(i) for i in plan_entry.get("demote_args", [])]
        over = sorted(set(plan_demote) - set(analysis.certified_demote))
        if over:
            rule_name = (
                "bf16-into-cert-sink" if case.allow_f64 else "plan-downgrade"
            )
            msg = (
                f"plan demotes argument(s) {over} of a float64 certification "
                "core — bf16 must never reach an f64_cert sink"
                if case.allow_f64
                else f"plan demotes argument(s) {over} the walk does not "
                "certify — a downgraded entry; re-certify with "
                "--update-prec-plan"
            )
            report.violations.append(_viol(entry, "P2", rule_name, msg))

    # --- P3: compiled truth of the APPLIED plan ------------------------------
    applied = sorted(set(plan_demote) & set(analysis.certified_demote))
    report.applied_demote = applied
    report.traffic = traffic_model(case, applied)
    try:
        if applied:
            args16 = demoted_args(case, applied)
            hlo = case.fn.lower(*args16, **case.static).compile().as_text()
            closed16 = _trace_jaxpr(
                dataclasses.replace(case, args=args16),
                x64=case.allow_f64 and case.x64_trace,
            )
        else:
            hlo = case.fn.lower(*case.args, **case.static).compile().as_text()
            closed16 = None
    except Exception as exc:  # noqa: BLE001
        report.violations.append(
            _viol(entry, "P3", "uncompilable-core", f"demoted lower/compile failed: {exc!r}")
        )
        return report
    report.census = hlo_dtype_census(hlo)
    if applied:
        params = hlo_param_dtypes(hlo)
        import jax

        # flat parameter positions of the demoted args (pytree leaves)
        flat_pos = 0
        for i, a in enumerate(case.args):
            for leaf in jax.tree_util.tree_leaves(a):
                if i in applied and str(getattr(leaf, "dtype", "")) == "float32":
                    got = params.get(flat_pos)
                    if got is not None and got != "bf16":
                        report.violations.append(
                            _viol(
                                entry, "P3", "silent-upcast",
                                f"demoted argument {i} (parameter {flat_pos}) "
                                f"lowers to {got} in the compiled HLO — XLA "
                                "re-upcast the demoted edge; the plan's bytes "
                                "saving is fictional for this core",
                            )
                        )
                flat_pos += 1
        if report.census.get("bf16", 0) == 0:
            report.violations.append(
                _viol(
                    entry, "P3", "silent-upcast",
                    "no bf16 appears anywhere in the demoted core's compiled "
                    "HLO — the demotion was erased before codegen",
                )
            )
        if closed16 is not None:
            flow = precision_flow(closed16.jaxpr)
            report.cert_isolated = bool(flow.get("cert_isolated", True))
            if not report.cert_isolated:
                report.violations.append(
                    _viol(
                        entry, "P3", "bf16-into-cert-sink",
                        "the demoted trace feeds a bf16-safe value into the "
                        "float64 certification arithmetic (precision_flow "
                        "cert_isolated=False)",
                    )
                )
    if case.allow_f64 and report.census is not None:
        n16 = report.census.get("bf16", 0) + report.census.get("f16", 0)
        if n16 > 0:
            report.violations.append(
                _viol(
                    entry, "P3", "bf16-into-cert-sink",
                    f"{n16} half-precision tensor(s) in the compiled HLO of a "
                    "float64 certification core — cert arithmetic must stay "
                    "untouched by the mixed-precision lowering",
                )
            )
    return report


# --- plan file ---------------------------------------------------------------


def load_prec_plan(path: Path) -> Dict[str, Any]:
    if not path.exists():
        return {}
    data = json.loads(path.read_text(encoding="utf-8"))
    return dict(data.get("cores", {}))


def write_prec_plan(path: Path, reports: Sequence[PrecCoreReport]) -> None:
    import jax

    data = {
        "_meta": {
            "schema_version": 1,
            "jax": jax.__version__,
            "classes": ["bf16_safe", "f32_required", "f64_cert", "non_float"],
            "generated_by": (
                "python -m citizensassemblies_tpu.lint --prec "
                "--update-prec-plan"
            ),
        },
        "cores": {
            r.name: measured_plan_entry(r.analysis, r._case)  # type: ignore[attr-defined]
            for r in reports
            if r.analysis is not None and hasattr(r, "_case")
        },
    }
    path.write_text(
        json.dumps(data, indent=1, sort_keys=True) + "\n", encoding="utf-8"
    )


def prec_plan_provenance(path: Optional[Path] = None) -> Dict[str, Any]:
    """Compact provenance of the committed precision plan, for bench rows —
    the same attribution contract as ``ir.budget_provenance``."""
    path = path or PLAN_PATH
    if not path.exists():
        return {"file": path.name, "missing": True}
    raw = path.read_bytes()
    data = json.loads(raw.decode("utf-8"))
    cores = data.get("cores", {})
    return {
        "file": path.name,
        "sha256": hashlib.sha256(raw).hexdigest()[:12],
        "cores": len(cores),
        "demoted": sum(1 for c in cores.values() if c.get("demote_args")),
        "jax": data.get("_meta", {}).get("jax"),
    }


# --- the pass ----------------------------------------------------------------


def run_prec_checks(
    entries: Optional[Sequence[CoreEntry]] = None,
    plan_path: Optional[Path] = None,
    update_plan: bool = False,
) -> PrecReport:
    """Certify every registered core (or ``entries``) against the committed
    precision plan. ``update_plan=True`` re-certifies and REWRITES the plan
    (the deliberate ratchet move); P2 violations are then dropped — the new
    plan is the certification — while P1/P3 still fail."""
    plan_path = Path(plan_path) if plan_path is not None else PLAN_PATH
    entries = list(entries) if entries is not None else collect()
    plan = load_prec_plan(plan_path)

    reports: List[PrecCoreReport] = []
    for e in entries:
        reports.append(
            verify_prec_core(e, plan.get(e.name), update_plan=update_plan)
        )

    if update_plan:
        write_prec_plan(plan_path, reports)
        for rep in reports:
            rep.violations = [v for v in rep.violations if v.rule != "P2"]
    else:
        known = {e.name for e in entries}
        for name in sorted(set(plan) - known):
            reports.append(
                PrecCoreReport(
                    name=name,
                    path=str(plan_path.name),
                    line=1,
                    violations=[
                        Violation(
                            path=str(plan_path.name), line=1, col=0,
                            rule="P2", name="stale-plan-entry",
                            message=(
                                f"[{name}] precision-plan entry has no "
                                "registered core — remove it via "
                                "--update-prec-plan"
                            ),
                        )
                    ],
                )
            )

    return PrecReport(
        cores=reports, plan_path=str(plan_path), updated=update_plan
    )


def prec_plan_diff(report: PrecReport) -> Dict[str, Any]:
    """Measured-vs-plan comparison for the CI build artifact
    (``PRECISION_PLAN_DIFF.json``), with the per-core traffic table — the
    HBM-reduction evidence rows the acceptance gate reads."""
    plan = load_prec_plan(Path(report.plan_path))
    cores: Dict[str, Any] = {}
    traffic: Dict[str, Any] = {}
    for rep in report.cores:
        entry: Dict[str, Any] = {"status": "PASS" if rep.ok else "FAIL"}
        if rep.analysis is not None:
            entry["measured"] = {
                "jaxpr_sha": rep.analysis.jaxpr_sha,
                "classes": rep.analysis.classes,
                "n_vars": rep.analysis.n_vars,
                "certified_demote": rep.analysis.certified_demote,
            }
            committed = plan.get(rep.name)
            if committed:
                entry["plan"] = committed
        cores[rep.name] = entry
        if rep.traffic is not None and rep.applied_demote:
            traffic[rep.name] = {
                **rep.traffic, "demote_args": rep.applied_demote,
            }
    big = sum(
        1 for t in traffic.values() if t.get("reduction_pct", 0) >= 25.0
    )
    return {
        "plan_file": report.plan_path,
        "provenance": prec_plan_provenance(Path(report.plan_path)),
        "traffic": traffic,
        "cores_over_25pct": big,
        "waiver": (
            "operand-bytes model at the jaxpr level; XLA:CPU legalizes bf16 "
            "through f32 converts, so the compiled CPU cost model would show "
            "an increase — the bytes win is realized on TPU/GPU HBM"
        ),
        "cores": cores,
    }


def render_prec_report(report: PrecReport) -> str:
    """graftlint-style text: violations in file:line form, then per-core
    PASS/FAIL lines, then the summary tail."""
    lines = [v.render() for v in report.violations]
    for rep in sorted(report.cores, key=lambda r: r.name):
        status = "PASS" if rep.ok else "FAIL"
        extra = ""
        if rep.analysis is not None:
            c = rep.analysis.classes
            extra = (
                f" (bf16_safe={c['bf16_safe']} f32_required={c['f32_required']}"
                f" f64_cert={c['f64_cert']}"
            )
            if rep.applied_demote:
                extra += (
                    f", demoted args {rep.applied_demote}"
                    f" -{rep.traffic['reduction_pct']}% bytes"
                )
            extra += ")"
        lines.append(f"{rep.path}:{rep.line}: {status} [{rep.name}]{extra}")
    n_fail = sum(1 for r in report.cores if not r.ok)
    n_dem = sum(1 for r in report.cores if r.applied_demote)
    lines.append(
        f"graftgrade: {len(report.cores)} core(s) certified, {n_dem} demoted, "
        f"{n_fail} failing, plan={report.plan_path}"
        + (" (updated)" if report.updated else "")
    )
    return "\n".join(lines)


def prec_report_as_json(report: PrecReport) -> Dict[str, Any]:
    """Stable JSON schema shared with the AST/IR/SPMD passes; folds the S3
    ``cert_isolated`` verdicts in so the scope-level and compiled-truth
    views cannot drift apart."""
    return {
        "schema_version": 1,
        "pass": "prec",
        "ok": report.ok,
        "plan": report.plan_path,
        "updated": report.updated,
        "cores": [
            {
                "core": rep.name,
                "path": rep.path,
                "line": rep.line,
                "status": "PASS" if rep.ok else "FAIL",
                "classes": rep.analysis.classes if rep.analysis else None,
                "demote_args": rep.applied_demote,
                "traffic": rep.traffic,
                "census": rep.census,
                "cert_isolated": rep.cert_isolated,
            }
            for rep in sorted(report.cores, key=lambda r: r.name)
        ],
        "violations": [dataclasses.asdict(v) for v in report.violations],
    }
