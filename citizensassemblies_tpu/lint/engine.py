"""graftlint rule engine: module loading, suppression, rule dispatch.

A rule is an object with ``rule_id`` (``"R1"``), ``name`` (kebab-case slug)
and ``description``, plus either

* ``check_module(module) -> [Violation]`` — per-file AST rules, or
* ``check_package(modules) -> [Violation]`` — cross-file rules (R6 needs the
  whole package plus README to judge a config knob).

Suppression syntax (the acceptance contract requires a *reason*):

* ``# graftlint: disable=R1 -- reason``       suppress R1 on this line and
  the next (so the comment may sit on its own line above a long statement);
* ``# graftlint: disable=R1,R4 -- reason``    several rules at once;
* ``# graftlint: disable-file=R6 -- reason``  whole-file suppression.

Directives are parsed from real COMMENT tokens (``tokenize``), so a
directive spelled inside a string literal — a lint self-test fixture, a
docstring example like the ones above — is inert. Two directive hygiene
checks ride the engine itself (both R0): a disable *without a reason*, and
an *unused* disable that matches no finding (ruff's unused-noqa, so stale
suppressions cannot accumulate as the rules or the code improve).
"""

from __future__ import annotations

import ast
import dataclasses
import io
import re
import tokenize
from pathlib import Path
from typing import Iterable, List, Optional, Sequence, Set, Tuple


@dataclasses.dataclass(frozen=True)
class Violation:
    path: str
    line: int
    col: int
    rule: str  # "R1"
    name: str  # "host-sync-in-jit"
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col}: {self.rule} [{self.name}] {self.message}"


@dataclasses.dataclass
class ModuleSource:
    """One parsed python file plus its raw lines (for suppression scanning)."""

    path: Path
    rel: str  # path as reported in violations
    text: str
    lines: List[str]
    tree: ast.Module


@dataclasses.dataclass
class LintReport:
    violations: List[Violation]
    suppressed: int
    files: int

    @property
    def ok(self) -> bool:
        return not self.violations


_SUPPRESS_RE = re.compile(
    r"#\s*graftlint:\s*(disable(?:-file)?)\s*=\s*([A-Z][0-9]+(?:\s*,\s*[A-Z][0-9]+)*)"
    r"(?:\s*--\s*(\S.*))?"
)


@dataclasses.dataclass
class _Directive:
    """One parsed ``# graftlint: disable…`` comment."""

    line: int
    rules: Set[str]
    file_wide: bool
    has_reason: bool
    text: str  # "disable" / "disable-file", for messages
    used: Set[str] = dataclasses.field(default_factory=set)


@dataclasses.dataclass
class _Suppressions:
    directives: List[_Directive]

    def covers(self, rule: str, line: int) -> bool:
        """Does any directive suppress ``rule`` at ``line``? Marks the
        matching directives used, which is what the unused-suppression
        check reads afterwards."""
        hit = False
        for d in self.directives:
            if rule not in d.rules:
                continue
            # a line directive covers its own line and the next one, so it
            # can annotate a long statement from the line above
            if d.file_wide or line in (d.line, d.line + 1):
                d.used.add(rule)
                hit = True
        return hit


def _comment_tokens(text: str) -> List[Tuple[int, str]]:
    """(line, comment_text) for every real COMMENT token. Tokenizing keeps
    directives inside string literals inert; on files tokenize cannot digest
    (rare encoding edge cases) fall back to raw line scanning."""
    try:
        return [
            (tok.start[0], tok.string)
            for tok in tokenize.generate_tokens(io.StringIO(text).readline)
            if tok.type == tokenize.COMMENT
        ]
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return list(enumerate(text.splitlines(), start=1))


def _parse_suppressions(text: str) -> _Suppressions:
    directives: List[_Directive] = []
    for line, comment in _comment_tokens(text):
        m = _SUPPRESS_RE.search(comment)
        if not m:
            continue
        kind, rule_list, reason = m.group(1), m.group(2), m.group(3)
        directives.append(
            _Directive(
                line=line,
                rules={r.strip() for r in rule_list.split(",")},
                file_wide=kind == "disable-file",
                has_reason=bool(reason),
                text=kind,
            )
        )
    return _Suppressions(directives=directives)


def load_module(path: Path, root: Optional[Path] = None) -> Optional[ModuleSource]:
    """Parse one file; returns None for unparsable sources (reported upstream)."""
    text = path.read_text(encoding="utf-8")
    try:
        tree = ast.parse(text, filename=str(path))
    except SyntaxError:
        return None
    try:
        rel = str(path.relative_to(root)) if root is not None else str(path)
    except ValueError:
        rel = str(path)
    return ModuleSource(
        path=path, rel=rel, text=text, lines=text.splitlines(), tree=tree
    )


def iter_python_files(paths: Iterable[Path]) -> List[Path]:
    out: List[Path] = []
    for p in paths:
        if p.is_dir():
            out.extend(sorted(p.rglob("*.py")))
        elif p.suffix == ".py":
            out.append(p)
    # dedupe, keep order
    seen: Set[Path] = set()
    uniq = []
    for p in out:
        rp = p.resolve()
        if rp not in seen:
            seen.add(rp)
            uniq.append(p)
    return uniq


def all_rules():
    """The registered rule set, R1..R13 (R0 is emitted by the engine itself)."""
    from citizensassemblies_tpu.lint.config_rule import ConfigKnobRule
    from citizensassemblies_tpu.lint.rules import (
        CoreSpanRule,
        DonatedBufferReuseRule,
        DtypeDisciplineRule,
        DtypeLiteralHygieneRule,
        FaultSiteRule,
        HostSyncInJitRule,
        JitConstructionRule,
        MeshHygieneRule,
        MetricHygieneRule,
        ShardingSpecHygieneRule,
        ThreadDisciplineRule,
        TracerBranchRule,
    )

    return [
        HostSyncInJitRule(),
        JitConstructionRule(),
        DonatedBufferReuseRule(),
        DtypeDisciplineRule(),
        TracerBranchRule(),
        ConfigKnobRule(),
        ThreadDisciplineRule(),
        CoreSpanRule(),
        FaultSiteRule(),
        MeshHygieneRule(),
        MetricHygieneRule(),
        ShardingSpecHygieneRule(),
        DtypeLiteralHygieneRule(),
    ]


def lint_paths(
    paths: Sequence[Path],
    rules=None,
    readme: Optional[Path] = None,
    root: Optional[Path] = None,
) -> LintReport:
    """Lint every python file under ``paths`` with the full rule set."""
    rules = rules if rules is not None else all_rules()
    root = root or Path.cwd()
    files = iter_python_files([Path(p) for p in paths])
    modules: List[ModuleSource] = []
    raw: List[Violation] = []
    for f in files:
        mod = load_module(f, root=root)
        if mod is None:
            raw.append(
                Violation(
                    path=str(f), line=1, col=0, rule="R0",
                    name="unparsable", message="file does not parse",
                )
            )
            continue
        modules.append(mod)

    for rule in rules:
        if hasattr(rule, "check_package"):
            raw.extend(rule.check_package(modules, readme=readme))
        else:
            for mod in modules:
                raw.extend(rule.check_module(mod))

    # apply suppressions + report directive hygiene (missing reason, unused)
    sup_by_rel = {m.rel: _parse_suppressions(m.text) for m in modules}
    kept: List[Violation] = []
    suppressed = 0
    for v in sorted(raw, key=lambda v: (v.path, v.line, v.col, v.rule)):
        sup = sup_by_rel.get(v.path)
        if sup is not None and sup.covers(v.rule, v.line):
            suppressed += 1
            continue
        kept.append(v)
    for m in modules:
        for d in sup_by_rel[m.rel].directives:
            if not d.has_reason:
                kept.append(
                    Violation(
                        path=m.rel, line=d.line, col=0, rule="R0",
                        name="suppression-without-reason",
                        message=(
                            f"'graftlint: {d.text}=' needs a reason "
                            "(append ' -- why this is safe')"
                        ),
                    )
                )
            for rule in sorted(d.rules - d.used):
                kept.append(
                    Violation(
                        path=m.rel, line=d.line, col=0, rule="R0",
                        name="unused-suppression",
                        message=(
                            f"'graftlint: {d.text}={rule}' suppresses no "
                            "finding — remove the stale directive (mirrors "
                            "ruff's unused-noqa)"
                        ),
                    )
                )
    kept.sort(key=lambda v: (v.path, v.line, v.col, v.rule))
    return LintReport(violations=kept, suppressed=suppressed, files=len(files))


def render_report(report: LintReport) -> str:
    lines = [v.render() for v in report.violations]
    tail = (
        f"graftlint: {len(report.violations)} violation(s), "
        f"{report.suppressed} suppressed, {report.files} file(s) checked"
    )
    return "\n".join(lines + [tail])
