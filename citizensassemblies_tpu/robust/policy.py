"""Deadline, retry and degradation policy for the serving stack.

Restarted first-order solvers need divergence/stall detection and principled
restarts to be dependable (Applegate et al., PDLP); a *service* over them
additionally needs bounded wall-clock and a principled answer to "what do we
turn off when a fast path keeps failing". This module is that answer:

* :class:`Deadline` — a per-request monotonic budget threaded through
  ``RequestContext``. The CG round loop checks it ONCE per round at the
  round's existing single host sync point (a host clock read — no new
  host↔device syncs), so a request can never grind past its deadline inside
  the face loop; expiry raises :class:`DeadlineExceeded` carrying a partial
  audit fragment instead of hanging.
* :class:`RetryBudget` — counted exponential-backoff retries for transient
  faults (injected or real backend failures). The budget is per request;
  exhaustion re-raises the fault.
* :class:`DegradationLadder` — the ORDERED fallback chain walked one rung
  per retry: device pricing → host MILP, ELL → dense, batched → serial,
  fused screen → host screen. Every rung lands on a gate whose off-position
  is pinned bit-identical by the existing test suite, so a degraded request
  is *slower, not different* — and its result still passes the same 1e-3
  L∞ arithmetic audit.

Nothing here imports jax.
"""

from __future__ import annotations

import time
from typing import Any, Dict, List, Optional, Tuple

from citizensassemblies_tpu.utils.config import Config


class DeadlineExceeded(RuntimeError):
    """The request's deadline expired. ``partial`` carries whatever audit
    fragment the raising layer could assemble (best-so-far ε, round count),
    so the graceful rejection ships evidence instead of a bare timeout."""

    def __init__(self, message: str, partial: Optional[Dict[str, Any]] = None):
        super().__init__(message)
        self.partial = partial or {}


class Deadline:
    """Monotonic per-request wall-clock budget."""

    def __init__(self, seconds: float):
        self.seconds = float(seconds)
        self.t0 = time.monotonic()

    def elapsed(self) -> float:
        return time.monotonic() - self.t0

    def remaining(self) -> float:
        return self.seconds - self.elapsed()

    @property
    def expired(self) -> bool:
        return self.remaining() <= 0.0

    def check(
        self, where: str, log=None, partial: Optional[Dict[str, Any]] = None
    ) -> None:
        """Raise :class:`DeadlineExceeded` when expired; counts the trip on
        ``log``. A pure host clock read — safe at any frequency, and the CG
        loop calls it once per round so no new host syncs appear."""
        if not self.expired:
            return
        if log is not None:
            log.count("deadline_exceeded")
        raise DeadlineExceeded(
            f"deadline of {self.seconds:.1f}s exceeded at {where} "
            f"({self.elapsed():.1f}s elapsed)",
            partial=partial,
        )


class RetryBudget:
    """Counted exponential-backoff retries for transient faults."""

    def __init__(self, attempts: int = 2, backoff_s: float = 0.05):
        self.attempts = max(int(attempts), 0)
        self.backoff_s = max(float(backoff_s), 0.0)
        self.used = 0

    @property
    def left(self) -> int:
        return self.attempts - self.used

    def take(self) -> Optional[float]:
        """Consume one retry; returns the backoff delay (exponential in the
        retries already used) or None when the budget is exhausted."""
        if self.used >= self.attempts:
            return None
        delay = self.backoff_s * (2.0 ** self.used)
        self.used += 1
        return delay


#: the certified fallback chain, in order: each rung is a Config gate whose
#: off-position runs a pinned bit-identical (or certified-equivalent) path
DEGRADATION_LADDER: Tuple[Tuple[str, Dict[str, Any]], ...] = (
    ("megakernel_to_chained", {"pdhg_megakernel": False}),
    ("device_pricing_host_milp", {"decomp_device_pricing": False}),
    ("ell_to_dense", {"sparse_ops": False}),
    ("batched_to_serial", {"lp_batch": False}),
    ("fused_screen_to_host", {"decomp_batched_expand": False}),
    ("mesh_to_single_device", {"dist_mesh": False}),
)


class DegradationLadder:
    """Walk the certified fallback chain one rung per transient fault.

    Each :meth:`degrade` call returns a Config with the next rung's gate
    forced off (cumulatively — rung 2 keeps rung 1's downgrade). Past the
    last rung the config is returned unchanged: the bottom of the ladder is
    the all-serial all-host path, which either works or the fault is not
    something a fallback fixes.
    """

    def __init__(self):
        self.steps: List[str] = []

    @property
    def position(self) -> int:
        return len(self.steps)

    @property
    def exhausted(self) -> bool:
        return self.position >= len(DEGRADATION_LADDER)

    def degrade(self, cfg: Config, log=None) -> Config:
        if self.exhausted:
            return cfg
        name, patch = DEGRADATION_LADDER[self.position]
        self.steps.append(name)
        if log is not None:
            log.count(f"robust_degrade_{name}")
            log.count("robust_degrade_steps")
        return cfg.replace(**patch)
