"""Seeded, config-gated fault injection for chaos runs.

Every fault site is a named knob consulted at a hot boundary::

    from citizensassemblies_tpu.robust import inject
    if inject.site("pdhg_nan", log):
        x0[0] = np.nan  # poison the lane; the sentinel must quarantine it

Sites are registered in :data:`FAULT_SITES` (graftlint R9 additionally
requires every ``inject.site(...)`` literal to be documented in the README
catalogue, the same enforcement shape as R8's span coverage). A chaos run is
configured by ``Config.fault_sites`` — a spec string
``"pdhg_nan:0.1,oracle_raise:0.05"`` of per-site firing rates — plus
``Config.fault_seed``. Firing decisions are **deterministic**: the n-th
consultation of a site fires iff ``crc(seed, site, n)`` maps below the rate,
so the same spec + seed reproduces the identical fault schedule across
processes and machines (no process-salted ``hash``, no global RNG state).

The injector is ambient: the service installs one per request on its
``RequestContext``; offline harnesses (``bench.py --chaos``, tests) install
a process default via :func:`use_injector`. With no injector installed —
the production default, ``fault_sites=""`` — :func:`site` is a dict lookup
and a ``None`` check: zero allocation, no RNG, nothing to misfire.

Nothing here imports jax; the module must stay importable from the lint
tooling.
"""

from __future__ import annotations

import hashlib
import threading
from contextlib import contextmanager
from typing import Dict, Optional

#: the registry: site name → where it fires and what recovery it exercises.
#: graftlint R9 checks every ``inject.site("<name>")`` literal against the
#: README "Fault injection sites" catalogue.
FAULT_SITES: Dict[str, str] = {
    "pdhg_nan": (
        "poisons a PDHG warm start with NaN (serial wrapper or one batched "
        "lane) — exercises the in-loop numerical sentinel + float64 host "
        "re-solve quarantine"
    ),
    "qp_nan": (
        "poisons the fused L2 stage's donor iterate — exercises the QP "
        "sentinel and the serial float64 fallback of solve_final_primal_l2"
    ),
    "oracle_raise": (
        "anchor-oracle backend (native/HiGHS) failure — exercises the "
        "retry-once-then-skip policy (anchors are heuristic columns)"
    ),
    "device_dispatch": (
        "device-pricing dispatch raises — exercises the device→host-MILP "
        "rung of the degradation ladder"
    ),
    "batcher_leader_death": (
        "cross-request batcher leader dies after claiming a group, before "
        "dispatch — exercises the follower watchdog / re-election"
    ),
    "warm_slot_corrupt": (
        "a loaded warm-start slot is NaN-corrupted — exercises lane "
        "quarantine (a corrupt warm start must not poison the fleet)"
    ),
    "worker_crash": (
        "the request worker crashes at execution start — exercises the "
        "service retry budget + degradation ladder"
    ),
    "queue_stall": (
        "artificial pre-execution stall — exercises deadline accounting "
        "and graceful DeadlineExceeded rejection"
    ),
    "face_abort": (
        "kills the face-decomposition loop mid-round — exercises the "
        "crash-consistent checkpoint/resume path"
    ),
    "dist_collective": (
        "graftpod mesh handout fails (collective init / topology build) — "
        "exercises the mesh→single-device rung of the degradation ladder"
    ),
}


class FaultInjected(RuntimeError):
    """A deliberately injected, *transient* fault. The service retry policy
    treats it (and real transient backend errors) as retryable."""

    def __init__(self, site: str):
        super().__init__(f"injected fault at site '{site}'")
        self.site = site


def _hash_unit(seed: int, site: str, n: int) -> float:
    """Deterministic uniform value in [0, 1) for consultation ``n`` of
    ``site`` under ``seed`` — blake2b, not ``hash()`` (salted per process)
    and not crc32 (linear: consecutive consults would differ by a FIXED
    xor, correlating the schedule and making some joint fire patterns
    impossible)."""
    digest = hashlib.blake2b(
        f"{seed}:{site}:{n}".encode(), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big") / 18446744073709551616.0


class FaultInjector:
    """Parsed ``fault_sites`` spec + per-site consultation counters.

    Thread-safe: several worker threads of one request (anchor pricer,
    batcher leader) consult sites concurrently; the counter increment is the
    only shared state and rides one lock.
    """

    def __init__(self, spec: str, seed: int = 0):
        self.seed = int(seed)
        self.spec = spec or ""
        self._rates: Dict[str, float] = {}
        for part in self.spec.split(","):
            part = part.strip()
            if not part:
                continue
            name, _, rate = part.partition(":")
            name = name.strip()
            if name not in FAULT_SITES:
                raise ValueError(
                    f"unknown fault site {name!r} (known: {sorted(FAULT_SITES)})"
                )
            self._rates[name] = min(max(float(rate or 1.0), 0.0), 1.0)
        self._lock = threading.Lock()
        self._consulted: Dict[str, int] = {}
        self._fired: Dict[str, int] = {}

    def fire(self, site: str) -> bool:
        """Deterministically decide whether this consultation of ``site``
        fires. Unknown sites are a programming error (R9 keeps the literals
        honest; this keeps the runtime honest)."""
        if site not in FAULT_SITES:
            raise ValueError(f"unknown fault site {site!r}")
        rate = self._rates.get(site)
        if rate is None or rate <= 0.0:
            return False
        with self._lock:
            n = self._consulted.get(site, 0)
            self._consulted[site] = n + 1
            hit = _hash_unit(self.seed, site, n) < rate
            if hit:
                self._fired[site] = self._fired.get(site, 0) + 1
        return hit

    def stats(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            return {
                "consulted": dict(self._consulted),
                "fired": dict(self._fired),
            }


#: process-default injector for offline harnesses (bench --chaos, tests);
#: requests under a RequestContext carry their own and never read this
_DEFAULT: Optional[FaultInjector] = None


def install_injector(inj: Optional[FaultInjector]) -> None:
    global _DEFAULT
    _DEFAULT = inj


@contextmanager
def use_injector(inj: Optional[FaultInjector]):
    """Install ``inj`` as the process-default injector for the scope."""
    global _DEFAULT
    prev, _DEFAULT = _DEFAULT, inj
    try:
        yield inj
    finally:
        _DEFAULT = prev


def active_injector() -> Optional[FaultInjector]:
    """The calling thread's injector: the ambient RequestContext's when one
    is active, else the process default (offline chaos harness), else None
    (production: injection compiled out to a None check)."""
    from citizensassemblies_tpu.service.context import current_context

    ctx = current_context()
    if ctx is not None and getattr(ctx, "injector", None) is not None:
        return ctx.injector
    return _DEFAULT


def site(name: str, log=None, inj: Optional[FaultInjector] = None) -> bool:
    """Consult fault site ``name``; counts ``fault_<name>`` on ``log`` when
    it fires. The call sites pass a string LITERAL (graftlint R9). ``inj``
    overrides the ambient lookup — worker threads that outlive their
    request's ContextVar scope (the anchor pricer) capture the injector at
    construction and pass it explicitly."""
    if inj is None:
        inj = active_injector()
    if inj is None:
        return False
    if inj.fire(name):
        if log is not None:
            log.count(f"fault_{name}")
        return True
    return False


def raise_if(name: str, log=None, inj: Optional[FaultInjector] = None) -> None:
    """Consult ``name`` and raise :class:`FaultInjected` when it fires —
    for sites whose real-world analog is an exception, not a corruption."""
    if site(name, log, inj=inj):
        raise FaultInjected(name)
