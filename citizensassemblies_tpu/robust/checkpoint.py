"""Crash-consistent checkpointing of the face-decomposition CG loop.

The PR 2 checkpoint layer (``utils/checkpoint``) snapshots the *outer*
column-generation state at round boundaries; a killed request inside the
face loop still restarted the whole decomposition. Here the face loop's own
certified state — the portfolio columns, the current mixture and its
arithmetic ε (the acceptance certificate is ``‖M p − v‖∞``, so the snapshot
is certified by construction, not by trusting a solver) — is saved every N
rounds (``Config.robust_checkpoint_every``) with the same atomic
tmp-then-rename discipline, and :func:`load_face_state` resumes only into
the identical (reduction, profile, acceptance bar) via a content
fingerprint. A resumed run re-enters the round loop with the checkpointed
hull and warm mixture: it converges to the same contract band as the
uninterrupted run (pinned across seeds by ``tests/test_robust.py``).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
from pathlib import Path
from typing import Optional, Union

import numpy as np


@dataclasses.dataclass
class FaceCGState:
    """The face loop's certified state at a round boundary."""

    compositions: np.ndarray  # int16/int32 [C, T]
    probabilities: np.ndarray  # float64 [C] — the mixture p (certified)
    eps: float  # its arithmetic residual ‖M p − v‖∞ at save time
    round: int
    fingerprint: str = ""


def face_fingerprint(reduction, v: np.ndarray, accept: float) -> str:
    """Digest of everything that pins the face problem: the type reduction's
    structure (features, quotas, sizes, k), the target profile and the
    acceptance bar. A checkpoint from any other problem must not resume."""
    h = hashlib.sha256()
    h.update(np.asarray(reduction.type_feature, dtype=np.int64).tobytes())
    h.update(np.asarray(reduction.qmin, dtype=np.int64).tobytes())
    h.update(np.asarray(reduction.qmax, dtype=np.int64).tobytes())
    h.update(np.asarray(reduction.msize, dtype=np.int64).tobytes())
    h.update(str(int(reduction.k)).encode())
    h.update(np.asarray(v, dtype=np.float64).tobytes())
    h.update(repr(float(accept)).encode())
    return h.hexdigest()


def save_face_state(path: Union[str, Path], state: FaceCGState) -> None:
    """Atomic write (tmp + rename): a crash mid-save never corrupts the
    previous checkpoint — the crash-consistency half of the contract."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_suffix(path.suffix + ".tmp")
    with open(tmp, "wb") as fh:
        np.savez_compressed(
            fh,
            kind=np.asarray([2], dtype=np.int8),  # face-state marker
            compositions=state.compositions.astype(np.int32),
            probabilities=state.probabilities.astype(np.float64),
            eps=np.asarray([state.eps], dtype=np.float64),
            round=np.asarray([state.round], dtype=np.int64),
            fingerprint=np.frombuffer(state.fingerprint.encode(), dtype=np.uint8),
        )
    os.replace(tmp, path)


def load_face_state(
    path: Union[str, Path], T: int, fingerprint: str = ""
) -> Optional[FaceCGState]:
    """Load a face checkpoint if present and written for the same problem.
    A mismatched or corrupt file is ignored (the caller starts fresh), never
    an error — the checkpoint is an accelerant, not a dependency."""
    path = Path(path)
    if not path.exists():
        return None
    try:
        with np.load(path) as z:
            if "kind" not in z or int(z["kind"][0]) != 2:
                return None
            comps = z["compositions"]
            if comps.ndim != 2 or comps.shape[1] != T:
                return None
            stored_fp = bytes(z["fingerprint"]).decode() if "fingerprint" in z else ""
            if fingerprint and stored_fp != fingerprint:
                return None
            probs = z["probabilities"]
            if probs.shape[0] != comps.shape[0]:
                return None
            return FaceCGState(
                compositions=comps.astype(np.int32),
                probabilities=probs.astype(np.float64),
                eps=float(z["eps"][0]),
                round=int(z["round"][0]),
                fingerprint=stored_fp,
            )
    except Exception:
        return None


def clear_face_state(path: Union[str, Path]) -> None:
    Path(path).unlink(missing_ok=True)


class FaceCheckpointer:
    """The face loop's checkpoint driver: resolves the path from the config
    (``robust_checkpoint_dir`` / ``face_<fp16>.npz``), loads a matching
    snapshot on entry, saves the running-best certified state every
    ``robust_checkpoint_every`` rounds, and clears the file once the loop
    returns a certified result (a completed run must not leave a stale
    resume point for the next request of the same problem)."""

    def __init__(self, cfg, reduction, v: np.ndarray, accept: float):
        self.every = int(getattr(cfg, "robust_checkpoint_every", 0) or 0)
        ckpt_dir = str(getattr(cfg, "robust_checkpoint_dir", "") or "")
        self.enabled = self.every > 0 and bool(ckpt_dir)
        self.path: Optional[Path] = None
        self.fingerprint = ""
        self._last_saved_round = -1
        if not self.enabled:
            return
        self.fingerprint = face_fingerprint(reduction, v, accept)
        self.path = Path(ckpt_dir) / f"face_{self.fingerprint[:16]}.npz"

    def load(self, T: int) -> Optional[FaceCGState]:
        if not self.enabled:
            return None
        return load_face_state(self.path, T, self.fingerprint)

    def maybe_save(
        self, rnd: int, comps: np.ndarray, p: np.ndarray, eps: float, log=None
    ) -> bool:
        """Save at round boundaries (every N rounds, once per round). The
        state handed in is the loop's running best — already certified by
        its arithmetic residual."""
        if not self.enabled or rnd == self._last_saved_round:
            return False
        if rnd % self.every != 0:
            return False
        self._last_saved_round = rnd
        save_face_state(
            self.path,
            FaceCGState(
                compositions=np.asarray(comps),
                probabilities=np.asarray(p, dtype=np.float64),
                eps=float(eps),
                round=int(rnd),
                fingerprint=self.fingerprint,
            ),
        )
        if log is not None:
            log.count("robust_checkpoint_saved")
        return True

    def clear(self) -> None:
        if self.enabled and self.path is not None:
            clear_face_state(self.path)
