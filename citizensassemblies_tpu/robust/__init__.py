"""graftfault: seeded fault injection + the production hardening it exercises.

Three pieces, one contract:

* :mod:`~citizensassemblies_tpu.robust.inject` — a config-gated,
  seed-deterministic fault-injection registry. Hot boundaries consult named
  sites (``inject.site("pdhg_nan", log)``); a chaos run with the same
  ``Config.fault_sites`` spec and ``fault_seed`` fires the identical fault
  schedule, so every chaos finding reproduces.
* :mod:`~citizensassemblies_tpu.robust.policy` — per-request
  :class:`~citizensassemblies_tpu.robust.policy.Deadline` (checked once per
  CG round at the existing host sync point), exponential-backoff
  :class:`~citizensassemblies_tpu.robust.policy.RetryBudget` for transient
  faults, and the ordered
  :class:`~citizensassemblies_tpu.robust.policy.DegradationLadder` (device
  pricing → host MILP, ELL → dense, batched → serial, fused screen → host
  screen) the service walks between retries.
* :mod:`~citizensassemblies_tpu.robust.checkpoint` — crash-consistent face-
  decomposition checkpoints: the CG loop snapshots its certified state every
  N rounds so a killed request resumes from the last certified round.

The contract that makes all of it safe: acceptance everywhere in this stack
is the float64 *arithmetic* residual of whatever mixture comes back (the
paper's 1e-3 L∞ audit), so a degraded, retried or resumed path is certified
by the same check as the fast path — never "probably fine".
"""

from citizensassemblies_tpu.robust.inject import (
    FAULT_SITES,
    FaultInjected,
    FaultInjector,
    use_injector,
)
from citizensassemblies_tpu.robust.policy import (
    DEGRADATION_LADDER,
    Deadline,
    DeadlineExceeded,
    DegradationLadder,
    RetryBudget,
)

__all__ = [
    "FAULT_SITES",
    "FaultInjected",
    "FaultInjector",
    "use_injector",
    "DEGRADATION_LADDER",
    "Deadline",
    "DeadlineExceeded",
    "DegradationLadder",
    "RetryBudget",
]
