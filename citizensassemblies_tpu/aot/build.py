"""graftboot cache builder: record service shapes, serialize every core.

The builder's job is to produce the artifact :func:`~.store.load_store`
boots from. Coverage comes from two complementary sweeps, both recorded
through the live ``aot_seeded`` wrappers (so the cache keys are the exact
signatures the serving path will look up — no hand-maintained shape list):

1. **Manifest walk** — every ``@register_ir_core`` registration, replayed at
   its budget shapes via ``lint.registry.build_cases()``. This is the same
   shape manifest ``make check-ir`` certifies, so every core the verifier
   knows about lands in the cache, including the ELL twins and the
   two-sided households masters the flagship request may not touch.
2. **Flagship serve recording** — the coldboot request class
   (:func:`flagship_instance`) driven through a real ``SelectionService``,
   which captures the *service* shapes: the power-of-two LP bucket lattice
   ``solvers/batch_lp.py`` actually dispatches for this instance family,
   at the batch dims cross-request batching produces. The ``service``
   profile widens the sweep across more pool sizes (more lattice buckets);
   ``smoke`` keeps CI inside its minute budget.
3. **Bucket-lattice sweep** — :func:`bucket_lattice_workload` pushes one
   inert all-zero batch through every predicted LP bucket
   (:data:`COLDBOOT_LATTICE`). The SAME function is the boot-time fleet
   pre-warm, so the shapes the cache was built at and the shapes boot
   warms are one list that cannot drift.

Each unique (family, signature) is then lowered from its recorded avals,
compiled, serialized (``jax.experimental.serialize_executable``) and written
into one versioned artifact (fingerprint + content sha, see ``store.py``).
Per-entry failures — e.g. a Pallas kernel whose backend refuses
serialization — are recorded as skips, never a build abort: a partial cache
still kills most of the cold start, and the skip list names what it misses.
"""

from __future__ import annotations

import os
import time
from typing import Any, Dict, List, Optional, Tuple

from citizensassemblies_tpu.aot.store import (
    Recorder,
    install_recorder,
    install_store,
    resolve_cache_path,
    save_artifact,
)

#: the coldboot flagship request class — the builder records it and
#: ``bench.py --coldboot`` serves it, so the two stay in lockstep
COLDBOOT_SPEC: Dict[str, int] = {"n": 24, "k": 4, "n_categories": 2, "seed": 0}

#: extra pool sizes the ``service`` profile sweeps (more lattice buckets)
_SERVICE_SWEEP: Tuple[Tuple[int, int], ...] = ((32, 4), (40, 5), (48, 6))

#: the predicted serving lattice: ``(batch, m1, m2, nv)`` power-of-two LP
#: bucket shapes (``solvers/batch_lp.py`` bucketing) the flagship request
#: family dispatches at, widened to the neighbouring buckets cross-request
#: batching and quota churn reach. The builder records THIS list and the
#: boot-time fleet pre-warm replays THIS list — one shared constant is what
#: keeps build-time coverage and boot-time readiness in lockstep.
COLDBOOT_LATTICE: Tuple[Tuple[int, int, int, int], ...] = (
    (1, 8, 8, 128),
    (2, 8, 8, 128),
    (4, 8, 8, 128),
    (8, 8, 8, 128),
    (8, 8, 8, 256),
    (4, 8, 8, 256),
    (8, 16, 8, 128),
    (4, 16, 8, 128),
    (8, 16, 16, 256),
    (2, 16, 16, 256),
    (8, 8, 8, 64),
    (4, 32, 16, 256),
)

#: wider buckets only the ``service`` profile warms (bigger pools, bigger
#: caches — not worth CI's minute budget in ``smoke``)
_LATTICE_SERVICE_EXTRA: Tuple[Tuple[int, int, int, int], ...] = (
    (8, 8, 8, 512),
    (16, 16, 16, 256),
    (8, 32, 16, 512),
    (16, 8, 8, 128),
)


def lattice_points(profile: str = "smoke") -> Tuple[Tuple[int, int, int, int], ...]:
    if profile == "service":
        return COLDBOOT_LATTICE + _LATTICE_SERVICE_EXTRA
    return COLDBOOT_LATTICE


def bucket_lattice_workload(cfg=None, profile: str = "smoke") -> Dict[str, Any]:
    """Drive one inert all-zero batch through every predicted LP bucket.

    An all-zero instance's KKT residual is zero at the first convergence
    check (tol pinned to the pad tolerance), so each bucket costs one cheap
    dispatch — but forces the batch-LP core THROUGH the compiler (or the
    store) at that exact shape. Run at build time under the recorder this
    is what populates the lattice; run at boot it is the fleet pre-warm:
    with a cache the executables deserialize in milliseconds, without one
    each bucket pays its full XLA compile. Same call, same shapes, both
    sides — the coldboot bench's readiness contract.
    """
    import numpy as np

    from citizensassemblies_tpu.solvers.batch_lp import BatchLP, solve_lp_batch

    cfg = coldboot_config(cfg)
    points = lattice_points(profile)
    t0 = time.time()
    for bsz, m1, m2, nv in points:
        probs = [
            BatchLP(
                c=np.zeros(nv, np.float32),
                G=np.zeros((m1, nv), np.float32),
                h=np.zeros(m1, np.float32),
                A=np.zeros((m2, nv), np.float32),
                b=np.zeros(m2, np.float32),
                tol=1.0,
            )
            for _ in range(bsz)
        ]
        # max_iters pins the core key to the one the leximin master's
        # pricing batches dispatch (solvers/compositions.py) — the lattice
        # must warm the SERVING core family, not the cfg-default one
        solve_lp_batch(probs, cfg=cfg, defer=False, max_iters=8_192)
    return {"buckets": len(points), "seconds": round(time.time() - t0, 3)}


def coldboot_config(base=None):
    """The config both the builder and the coldboot bench child run under.

    ``lp_batch=True`` forces the batched LP engine on (its CPU auto-route
    would otherwise turn the flagship path into the unbatched solver and
    the cache would warm the wrong cores).
    """
    from citizensassemblies_tpu.utils.config import default_config

    cfg = base if base is not None else default_config()
    return cfg.replace(lp_batch=True)


def flagship_instance(seed: Optional[int] = None):
    from citizensassemblies_tpu.core.generator import random_instance

    spec = dict(COLDBOOT_SPEC)
    if seed is not None:
        spec["seed"] = seed
    return random_instance(**spec)


def _record_flagship(cfg, profile: str) -> int:
    """Drive the flagship request class through a real service instance
    (worker threads, cross-request batcher and all) so the recorder sees
    the serving-path signatures. Returns the number of requests served."""
    from citizensassemblies_tpu.core.generator import random_instance
    from citizensassemblies_tpu.service import SelectionRequest, SelectionService

    svc = SelectionService(cfg)
    specs = [(flagship_instance(), "build0")]
    if profile == "service":
        specs += [
            (random_instance(n=n, k=k, n_categories=2, seed=i), f"build{i % 3}")
            for i, (n, k) in enumerate(_SERVICE_SWEEP, start=1)
        ]
    chans = [
        svc.submit(SelectionRequest(instance=inst, tenant=tenant))
        for inst, tenant in specs
    ]
    for ch in chans:
        ch.result(timeout=1200)
    return len(specs)


def _record_manifest(rec: Recorder) -> Tuple[int, List[str]]:
    """Replay every registered IR case's budget avals into the recorder.

    Only cores whose registered ``fn`` is an ``aot_seeded`` wrapper can be
    cached (the wrapper's family string IS the serve-time lookup key);
    plain jits in the registry are reported, not failed.
    """
    from citizensassemblies_tpu.aot.store import SeededJit
    from citizensassemblies_tpu.lint.registry import build_cases

    unwrapped: List[str] = []
    recorded = 0
    for name, case in build_cases():
        if not isinstance(case.fn, SeededJit):
            unwrapped.append(name)
            continue
        rec.record(case.fn, case.args, dict(case.static))
        recorded += 1
    return recorded, unwrapped


def build_cache(
    path: Optional[str] = None, profile: str = "smoke", cfg=None
) -> Dict[str, Any]:
    """Record, compile, serialize, save. Returns the build report."""
    import jax
    from jax.experimental.serialize_executable import serialize

    cfg = coldboot_config(cfg)
    path = resolve_cache_path(cfg, path)

    # the package-level persistent XLA cache (citizensassemblies_tpu/
    # __init__.py) can hand ``compile()`` an executable persisted by an
    # EARLIER process under a different cpu runtime — its serialization
    # then references JIT'd symbols no other process can resolve ("Symbols
    # not found"). Serialized artifacts must come from THIS process's
    # compiler, so the builder opts out of the disk cache.
    try:
        jax.config.update("jax_compilation_cache_dir", None)
    except Exception:  # pragma: no cover - cache knob absent/renamed
        pass

    # a store left installed by an earlier boot in this process would serve
    # hits during recording — harmless for keys, but the build must compile
    # from the inner jits, so blind the wrappers for the duration
    install_store(None)
    rec = Recorder()
    install_recorder(rec)
    t0 = time.time()
    try:
        manifest_n, unwrapped = _record_manifest(rec)
        served = _record_flagship(cfg, profile)
        lattice = bucket_lattice_workload(cfg, profile)
    finally:
        install_recorder(None)
    record_s = time.time() - t0

    entries: List[Dict[str, Any]] = []
    skipped: List[Dict[str, str]] = []
    t1 = time.time()
    for (family, sig), spec in sorted(rec.entries.items()):
        try:
            lowered = spec["fn"].lower(*spec["lower_args"], **spec["lower_kwargs"])
            donation = lowered.as_text().count("tf.aliasing_output")
            payload, in_tree, out_tree = serialize(lowered.compile())
        except Exception as exc:  # pallas/backend refusals: skip, keep going
            skipped.append({"family": family, "sig": sig, "error": repr(exc)})
            continue
        entries.append(
            {
                "key": f"{family}|{sig}",
                "family": family,
                "sig": sig,
                "payload": payload,
                "in_tree": in_tree,
                "out_tree": out_tree,
                "args": spec["args"],
                "dyn_kwargs": spec["dyn_kwargs"],
                "static_kwargs": {
                    k: repr(v) for k, v in spec["static_kwargs"].items()
                },
                "donation": donation,
            }
        )
    compile_s = time.time() - t1

    report = {
        "profile": profile,
        "requests_served": served,
        "manifest_cores_recorded": manifest_n,
        "manifest_unwrapped": unwrapped,
        "lattice_buckets": lattice["buckets"],
        "entries": len(entries),
        "skipped": skipped,
        "families": sorted({e["family"] for e in entries}),
        "record_s": round(record_s, 3),
        "compile_serialize_s": round(compile_s, 3),
        "path": os.path.abspath(path),
    }
    report["sha"] = save_artifact(path, entries, workload=dict(report))
    return report
