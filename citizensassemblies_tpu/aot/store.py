"""graftboot executable store: AOT-serialized compiled cores, keyed by shape.

The cold-start problem this kills: a fresh process pays seconds of XLA
tracing + compilation per (core, bucket shape) before its first PDHG iterate
runs. ``lint/ir.py`` already AOT-lowers every registered core
(``lower().compile()``); this module closes the loop by SERIALIZING those
compiled executables at build time (``jax.experimental.serialize_executable``)
and loading them at boot, so the memo factories hand out programs that never
touch the compiler.

Three cooperating pieces:

* :class:`SeededJit` — the wrapper every memo factory installs around its
  jitted core (``aot_seeded``). Per call it computes a cheap shape/dtype
  signature of the operands and consults the process ``ExecStore``: hit →
  the deserialized executable runs (zero compile events, counted
  ``aot_cache_hit``); miss → the original jit runs (counted
  ``aot_cache_miss`` while a store is active). With no store installed the
  wrapper is a pure pass-through, so ``Config.aot_cache=False`` is
  bit-identical to the plain JIT path by construction. ``.lower`` delegates
  to the inner jit — the IR/SPMD verifiers keep seeing the same program.
* :class:`ExecStore` — the loaded cache: ``(family, call signature) →
  deserialized executable`` plus the hit/miss/stale counters and the
  artifact sha that land in bench rows and request audit stamps.
* :class:`Recorder` — the build-time twin: while installed, every
  ``SeededJit`` call records ``(family, inner jit, operand specs)`` so the
  builder can re-lower each unique entry AT ITS EXACT SERVICE SIGNATURE
  (weak types, donation, static values included) and serialize it. Recording
  from the real call sites is what makes the cache key honest — no
  hand-maintained shape manifest to drift.

Staleness contract: the artifact is keyed by (jax version, backend,
platform fingerprint, core family, shape/dtype/static signature, donation
signature). A global fingerprint mismatch marks every entry stale at load; a
per-entry deserialization failure or a call-time signature surprise falls
back to the plain jit, counted (``aot_cache_stale``) — never a crash.

Import-light by design: ``jax`` is imported lazily so the solver modules
(which import ``aot_seeded`` at module top) pay nothing at import time.
"""

from __future__ import annotations

import hashlib
import os
import pickle
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from citizensassemblies_tpu.utils.guards import compiling_as

#: artifact schema — bump on any layout change; a mismatched artifact is
#: treated as stale in toto (per-entry fallback, never a crash)
SCHEMA_VERSION = 1

_lock = threading.Lock()
_STORE: Optional["ExecStore"] = None
_RECORDER: Optional["Recorder"] = None


# --- call signatures ---------------------------------------------------------


def _spec_of(value: Any) -> Tuple[str, Any]:
    """One operand's cache-key spec: arrays by (shape, dtype, weak_type),
    python scalars by their aval CLASS (a weak f32 scalar compiles the same
    executable whatever its value), everything else by repr."""
    shape = getattr(value, "shape", None)
    dtype = getattr(value, "dtype", None)
    if shape is not None and dtype is not None:
        weak = bool(getattr(value, "weak_type", False))
        return ("arr", (tuple(int(d) for d in shape), str(dtype), weak))
    if isinstance(value, bool):
        return ("pybool", value)
    if isinstance(value, int):
        return ("pyint", 0)
    if isinstance(value, float):
        return ("pyfloat", 0.0)
    return ("lit", repr(value))


def _sig_token(spec: Tuple[str, Any]) -> str:
    kind, payload = spec
    if kind == "arr":
        shape, dtype, weak = payload
        return f"{dtype}{list(shape)}{'w' if weak else ''}"
    if kind == "pybool":
        return f"b{int(payload)}"
    return kind if kind in ("pyint", "pyfloat") else f"={payload}"


def call_signature(
    args: Sequence[Any],
    kwargs: Dict[str, Any],
    static_argnames: Sequence[str] = (),
) -> str:
    """The store-lookup key fragment for one call: dynamic operands by
    shape/dtype signature, static kwargs by value (a static changes the
    compiled program, so it is part of the key)."""
    parts: List[str] = []
    for a in args:
        parts.append(_sig_token(_spec_of(a)))
    for name in sorted(kwargs):
        v = kwargs[name]
        if name in static_argnames:
            parts.append(f"{name}={v!r}")
        else:
            parts.append(f"{name}:{_sig_token(_spec_of(v))}")
    return ";".join(parts)


# --- platform fingerprint ----------------------------------------------------


def platform_fingerprint() -> Dict[str, Any]:
    """The environment identity a serialized executable is only valid for:
    jax version, backend, device platform/kind/count. Loaded against a
    different fingerprint, every entry is stale (JIT fallback, counted)."""
    import jax

    dev = jax.devices()[0]
    fp = {
        "schema": SCHEMA_VERSION,
        "jax": jax.__version__,
        "backend": jax.default_backend(),
        "platform": str(getattr(dev, "platform", "?")),
        "device_kind": str(getattr(dev, "device_kind", "?")),
        "device_count": int(jax.device_count()),
    }
    if fp["backend"] == "cpu":
        # XLA:CPU's thunk runtime emits executables whose JIT'd symbols do
        # not survive cross-process deserialization ("Symbols not found");
        # CPU caches are built and loaded under the legacy runtime
        # (XLA_FLAGS=--xla_cpu_use_thunk_runtime=false, see Makefile
        # aot-cache). The runtime choice is part of the artifact identity.
        fp["cpu_runtime"] = (
            "legacy"
            if "--xla_cpu_use_thunk_runtime=false"
            in os.environ.get("XLA_FLAGS", "")
            else "thunk"
        )
    return fp


def default_cache_path() -> str:
    """Resolution order: ``CITIZENS_AOT_CACHE`` env override, else a
    per-user cache file. The backend rides the filename so a TPU build and
    a CPU build never collide."""
    env = os.environ.get("CITIZENS_AOT_CACHE", "")
    if env:
        return env
    import jax

    return os.path.join(
        os.path.expanduser("~"), ".cache", "citizensassemblies_tpu",
        f"aot_cache_{jax.default_backend()}.pkl",
    )


def resolve_cache_path(cfg=None, path: Optional[str] = None) -> str:
    if path:
        return str(path)
    cfg_path = str(getattr(cfg, "aot_cache_path", "") or "") if cfg is not None else ""
    return cfg_path or default_cache_path()


# --- the loaded store --------------------------------------------------------


class ExecStore:
    """The boot-loaded executable cache plus its serving counters.

    ``lookup`` and the counters are thread-safe (serving dispatches from
    several request workers at once); entries are immutable after load.
    """

    def __init__(self, sha: str, status: str = "ok"):
        self.sha = sha
        #: "ok" | "missing" | "corrupt" | "fingerprint_mismatch"
        self.status = status
        #: raw serialized payloads — deserialization is LAZY (first lookup),
        #: so boot pays only for the entries it actually serves and a bad
        #: payload surfaces exactly where the jit fallback lives
        self._raw: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._entries: Dict[Tuple[str, str], Any] = {}
        self._dead: set = set()
        #: per-entry operand specs (family → list of (args specs, kwargs)),
        #: what the speculative pre-warm replays with inert zero operands
        self._specs: Dict[Tuple[str, str], Dict[str, Any]] = {}
        self._clock = threading.Lock()
        self._mlock = threading.Lock()
        self.hits = 0
        self.misses = 0
        self.stale = 0
        self.prewarmed = 0

    def __len__(self) -> int:
        return len(self._raw) + sum(
            1 for k in self._entries if k not in self._raw
        )

    def families(self) -> List[str]:
        keys = set(self._raw) | set(self._entries)
        return sorted({fam for fam, _sig in keys})

    def add(self, family: str, sig: str, exe: Any, spec: Dict[str, Any]) -> None:
        """Install an already-loaded executable (tests, eager loads)."""
        self._entries[(family, sig)] = exe
        self._specs[(family, sig)] = spec

    def add_raw(self, family: str, sig: str, raw: Dict[str, Any]) -> None:
        """Install a serialized entry for lazy deserialization at first use."""
        self._raw[(family, sig)] = raw
        self._specs[(family, sig)] = {
            "args": raw.get("args", []),
            "dyn_kwargs": raw.get("dyn_kwargs", []),
        }

    def _materialize(self, key: Tuple[str, str]) -> Optional[Any]:
        exe = self._entries.get(key)
        if exe is not None:
            return exe
        raw = self._raw.get(key)
        if raw is None or key in self._dead:
            return None
        with self._mlock:
            exe = self._entries.get(key)
            if exe is not None or key in self._dead:
                return exe
            try:
                from jax.experimental.serialize_executable import (
                    deserialize_and_load,
                )

                exe = deserialize_and_load(
                    raw["payload"], raw["in_tree"], raw["out_tree"]
                )
            except Exception:
                self._dead.add(key)
                self.bump_stale()
                return None
            self._entries[key] = exe
            return exe

    def lookup(self, family: str, sig: str) -> Optional[Any]:
        exe = self._materialize((family, sig))
        with self._clock:
            if exe is not None:
                self.hits += 1
            else:
                self.misses += 1
        return exe

    def bump_stale(self, n: int = 1) -> None:
        with self._clock:
            self.stale += int(n)

    def unhit(self) -> None:
        """A looked-up executable that failed at call time: re-book the hit
        as stale (the fallback jit serves the request)."""
        with self._clock:
            self.hits -= 1
            self.stale += 1

    def stamp(self) -> Dict[str, Any]:
        """The ``aot`` block for bench rows and request audit stamps."""
        with self._clock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "stale": self.stale,
                "prewarmed": self.prewarmed,
                "entries": len(self),
                "cache_sha": self.sha,
                "status": self.status,
            }

    # --- speculative pre-warm ------------------------------------------------

    def prewarm(
        self,
        families: Optional[Sequence[str]] = None,
        nv_max: Optional[int] = None,
    ) -> int:
        """Touch loaded executables with inert all-zero operands.

        Padding lanes in this codebase are inert by construction (an
        all-zero LP instance's KKT residual is 0 at the first convergence
        check), so executing an entry on zeros costs one cheap dispatch and
        faults in every lazy buffer the first real solve would otherwise
        pay for. ``families`` filters by family-name prefix; ``nv_max``
        drops entries whose widest operand axis exceeds the predicted
        bucket dimension (the registry-fingerprint → bucket-shape map).
        Failures are ignored — pre-warming is speculative by definition.
        """
        import jax.numpy as jnp

        touched = 0
        keys = sorted(set(self._raw) | set(self._entries))
        for family, sig in keys:
            if families is not None and not any(
                family.startswith(p) for p in families
            ):
                continue
            exe = self._materialize((family, sig))
            if exe is None:
                continue
            spec = self._specs.get((family, sig)) or {}
            arg_specs = spec.get("args", [])
            if nv_max is not None:
                widest = max(
                    (max(s[1][0]) for s in arg_specs if s[0] == "arr" and s[1][0]),
                    default=0,
                )
                if widest > int(nv_max):
                    continue
            try:
                operands = []
                for kind, payload in arg_specs:
                    if kind == "arr":
                        shape, dtype, _weak = payload
                        operands.append(jnp.zeros(shape, dtype))
                    elif kind == "pybool":
                        operands.append(bool(payload))
                    elif kind == "pyint":
                        operands.append(0)
                    elif kind == "pyfloat":
                        operands.append(1.0)
                    else:  # unreplayable literal: skip the entry
                        raise TypeError(payload)
                for name, nspec in spec.get("dyn_kwargs", []):
                    kind, payload = nspec
                    if kind != "arr":
                        raise TypeError(name)
                    shape, dtype, _weak = payload
                    operands.append(jnp.zeros(shape, dtype))
                exe(*operands)
            except Exception:
                continue
            touched += 1
        with self._clock:
            self.prewarmed += touched
        return touched


def install_store(store: Optional[ExecStore]) -> None:
    """Install (or clear, with ``None``) the process-global store the
    ``SeededJit`` wrappers consult."""
    global _STORE
    with _lock:
        _STORE = store


def active_store() -> Optional[ExecStore]:
    return _STORE


# --- the seeded-jit wrapper --------------------------------------------------


def _ambient_gate_off() -> bool:
    """True when the ambient request's config hard-disables the cache
    (``Config.aot_cache=False`` must be bit-identical AND store-blind even
    while another tenant's store is installed)."""
    try:
        from citizensassemblies_tpu.service.context import current_context
    except Exception:  # pragma: no cover - service layer absent
        return False
    ctx = current_context()
    return ctx is not None and getattr(ctx.cfg, "aot_cache", None) is False


class SeededJit:
    """A memo factory's jitted core, store-seeded (see module docstring).

    ``family`` carries the core id AND its static schedule key (the factory
    builds one wrapper per key, so the family string is unique per compiled
    program family). ``static_argnames`` mirrors the inner jit's statics —
    the wrapper needs them to key static kwargs by VALUE and to drop them
    from the deserialized call (an AOT executable takes dynamic operands
    only; its statics are baked in).
    """

    __slots__ = ("family", "fn", "static_argnames")

    def __init__(self, family: str, fn: Any, static_argnames: Sequence[str] = ()):
        self.family = family
        self.fn = fn
        self.static_argnames = tuple(static_argnames)

    def __call__(self, *args, **kwargs):
        rec = _RECORDER
        if rec is not None:
            rec.record(self, args, kwargs)
        store = _STORE
        if store is not None and not _ambient_gate_off():
            sig = call_signature(args, kwargs, self.static_argnames)
            exe = store.lookup(self.family, sig)
            if exe is not None:
                dyn_kwargs = {
                    k: v for k, v in kwargs.items()
                    if k not in self.static_argnames
                }
                try:
                    with compiling_as(self.family):
                        return exe(*args, **dyn_kwargs)
                except Exception:
                    # signature surprise (donation/layout/aval drift): the
                    # plain jit serves the call — stale, never a crash
                    store.unhit()
        with compiling_as(self.family):
            return self.fn(*args, **kwargs)

    def lower(self, *args, **kwargs):
        """The IR/SPMD verifiers' entry point — always the inner jit."""
        return self.fn.lower(*args, **kwargs)


def aot_seeded(family: str, fn: Any, static_argnames: Sequence[str] = ()) -> SeededJit:
    """Wrap a freshly built jitted core for store seeding (factory exit)."""
    return SeededJit(family, fn, static_argnames)


# --- build-time recording ----------------------------------------------------


class Recorder:
    """Collects ``(family, inner jit, operand specs)`` from live SeededJit
    calls while installed — the builder's shape manifest (see build.py)."""

    def __init__(self):
        self._lock = threading.Lock()
        #: (family, sig) → {"fn", "args" specs, "static_kwargs",
        #: "dyn_kwargs", "lower_args", "lower_kwargs"}
        self.entries: Dict[Tuple[str, str], Dict[str, Any]] = {}

    def record(self, seeded: SeededJit, args, kwargs) -> None:
        import jax

        sig = call_signature(args, kwargs, seeded.static_argnames)
        key = (seeded.family, sig)
        with self._lock:
            if key in self.entries:
                return
            lower_args = []
            arg_specs = []
            for a in args:
                spec = _spec_of(a)
                arg_specs.append(spec)
                if spec[0] == "arr":
                    shape, dtype, weak = spec[1]
                    lower_args.append(
                        jax.ShapeDtypeStruct(shape, dtype, weak_type=weak)
                    )
                else:
                    lower_args.append(a)
            static_kwargs = {}
            dyn_kwargs = []
            lower_kwargs = {}
            for name, v in kwargs.items():
                if name in seeded.static_argnames:
                    static_kwargs[name] = v
                    lower_kwargs[name] = v
                else:
                    spec = _spec_of(v)
                    dyn_kwargs.append((name, spec))
                    if spec[0] == "arr":
                        shape, dtype, weak = spec[1]
                        lower_kwargs[name] = jax.ShapeDtypeStruct(
                            shape, dtype, weak_type=weak
                        )
                    else:
                        lower_kwargs[name] = v
            self.entries[key] = {
                "fn": seeded.fn,
                "args": arg_specs,
                "static_kwargs": static_kwargs,
                "dyn_kwargs": sorted(dyn_kwargs),
                "lower_args": lower_args,
                "lower_kwargs": lower_kwargs,
            }


def install_recorder(rec: Optional[Recorder]) -> None:
    global _RECORDER
    with _lock:
        _RECORDER = rec


# --- artifact save / load ----------------------------------------------------


def _artifact_sha(entries: List[Dict[str, Any]]) -> str:
    h = hashlib.sha256()
    for e in sorted(entries, key=lambda e: e["key"]):
        h.update(e["key"].encode())
        h.update(e["payload"])
    return h.hexdigest()[:12]


def save_artifact(
    path: str,
    entries: List[Dict[str, Any]],
    workload: Optional[Dict[str, Any]] = None,
) -> str:
    """Write the versioned cache artifact; returns its content sha."""
    sha = _artifact_sha(entries)
    doc = {
        "schema_version": SCHEMA_VERSION,
        "fingerprint": platform_fingerprint(),
        "sha": sha,
        "workload": dict(workload or {}),
        "entries": entries,
    }
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "wb") as fh:
        pickle.dump(doc, fh, protocol=pickle.HIGHEST_PROTOCOL)
    os.replace(tmp, path)
    return sha


def load_store(
    path: Optional[str] = None, cfg=None, require: bool = False
) -> Optional[ExecStore]:
    """Load + deserialize the cache artifact into an :class:`ExecStore`.

    Failure ladder (``require=False``): missing file → ``None``; unreadable
    or schema/fingerprint-mismatched artifact → an EMPTY store whose status
    records why (so the miss/stale counters still ride the audit stamps);
    per-entry deserialization is LAZY — a bad payload surfaces at its first
    lookup, counted stale, and the plain jit serves that call. With
    ``require=True`` (``Config.aot_cache=True``) the first two rungs raise
    instead — the fail-loud mode for fleets that must not boot cold.
    """
    path = resolve_cache_path(cfg, path)
    if not os.path.exists(path):
        if require:
            raise RuntimeError(
                f"aot_cache=True but no cache artifact at {path} — run "
                "`python -m citizensassemblies_tpu.aot build` (make aot-cache)"
            )
        return None
    try:
        with open(path, "rb") as fh:
            doc = pickle.load(fh)
        entries = doc["entries"]
        fingerprint = doc["fingerprint"]
        sha = doc["sha"]
        if doc["schema_version"] != SCHEMA_VERSION:
            raise ValueError(f"schema {doc['schema_version']} != {SCHEMA_VERSION}")
    except Exception as exc:
        if require:
            raise RuntimeError(f"aot_cache=True but {path} is unreadable: {exc}")
        return ExecStore(sha="", status="corrupt")
    mine = platform_fingerprint()
    if fingerprint != mine:
        if require:
            raise RuntimeError(
                f"aot_cache=True but {path} was built for {fingerprint}, "
                f"this process is {mine}"
            )
        store = ExecStore(sha=sha, status="fingerprint_mismatch")
        store.bump_stale(len(entries))
        return store
    store = ExecStore(sha=sha)
    for e in entries:
        store.add_raw(e["family"], e["sig"], e)
    return store
