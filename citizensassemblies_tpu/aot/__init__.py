"""graftboot: AOT-serialized executable cache — kill the cold start.

Build (``python -m citizensassemblies_tpu.aot build`` / ``make aot-cache``)
records every hot core at its service shapes and serializes the compiled
executables into a versioned artifact; :func:`boot` loads it at process
start so the memo factories hand out programs that never touch the XLA
compiler. See ``store.py`` for the serving contract (tri-state
``Config.aot_cache``, counted fallbacks, never a crash) and ``build.py``
for coverage.
"""

from citizensassemblies_tpu.aot.store import (  # noqa: F401
    ExecStore,
    Recorder,
    SeededJit,
    active_store,
    aot_seeded,
    call_signature,
    install_recorder,
    install_store,
    load_store,
    platform_fingerprint,
    resolve_cache_path,
    save_artifact,
)


def boot(cfg=None, path=None):
    """Load the cache artifact per ``Config.aot_cache`` and install it.

    * ``None`` (default) — auto: load if an artifact exists, else boot cold.
    * ``True`` — required: a missing/unreadable/mismatched artifact raises.
    * ``False`` — hard off: nothing is loaded or installed; with the
      wrappers pass-through this is bit-identical to the plain JIT path.

    Returns the installed :class:`~.store.ExecStore` (or ``None``).
    """
    mode = getattr(cfg, "aot_cache", None) if cfg is not None else None
    if mode is False:
        return None
    store = load_store(path=path, cfg=cfg, require=(mode is True))
    if store is not None:
        install_store(store)
    return store
