"""CLI: ``python -m citizensassemblies_tpu.aot build`` (see ``make aot-cache``).

Prints the build report as one JSON document; exit 0 when at least one
entry serialized, 2 when the cache came out empty (nothing to boot from).
"""

from __future__ import annotations

import argparse
import json
import sys


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(prog="python -m citizensassemblies_tpu.aot")
    sub = parser.add_subparsers(dest="cmd", required=True)
    b = sub.add_parser("build", help="record service shapes and serialize the cache")
    b.add_argument(
        "--out", default=None,
        help="artifact path (default: CITIZENS_AOT_CACHE or the per-user cache)",
    )
    b.add_argument(
        "--profile", choices=("smoke", "service"), default="smoke",
        help="shape coverage: smoke = manifest + flagship (CI); "
        "service = + the wider pool-size sweep",
    )
    args = parser.parse_args(argv)

    from citizensassemblies_tpu.aot.build import build_cache

    report = build_cache(path=args.out, profile=args.profile)
    json.dump(report, sys.stdout, indent=2, default=repr)
    print()
    return 0 if report["entries"] else 2


if __name__ == "__main__":
    sys.exit(main())
