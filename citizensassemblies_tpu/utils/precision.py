"""Central mixed-precision policy (graftgrade runtime half).

Every dtype decision the bf16 lowering can influence is routed through this
module — graftlint R13 (``dtype-literal-hygiene``) holds the rest of the
solver/kernel hot paths to that: 16-bit dtype literals live ONLY here, and
operand-derived dtypes (``x.dtype`` flowing into an iterate allocation) must
pass through :func:`iterate_dtype` so a demoted bf16 operand can never drag
the PDHG/QP iterates, norms or KKT arithmetic below float32.

The lowering itself is OPERAND demotion, not compute demotion: the committed
``PRECISION_PLAN.json`` (ratcheted by ``citizensassemblies_tpu.lint --prec``)
names, per registered core, which read-only operator arguments are certified
``bf16_safe``. :func:`demote_operator` applies exactly that plan — gated by
the tri-state ``Config.mixed_precision``, and only when the concrete array
round-trips bf16→f32 losslessly (composition/constraint matrices here are
small-integer valued, exact in bf16's 8-bit mantissa; a lossy operand is
shipped at f32 and counted ``mp_lossy_skip`` instead). Matvec accumulation
stays f32 (``preferred_element_type`` on the dot, jnp type promotion on the
scaled ELL values), certification/audit arithmetic stays f64-untouched, and
the PR 9 sentinel → float64 host re-solve ladder backstops the runtime.
"""

from __future__ import annotations

import functools
import json
from pathlib import Path
from typing import Any, Optional

import numpy as np

#: the committed, ratcheted plan artifact (repo root, next to
#: ANALYSIS_BUDGET.json / SPMD_BUDGET.json). Regenerated deliberately via
#: ``make update-prec-plan``; ``make check-prec`` fails on drift.
PLAN_PATH = Path(__file__).resolve().parent.parent.parent / "PRECISION_PLAN.json"

#: the ONLY 16-bit dtype literals in the hot-path packages (R13's anchor).
#: Kept as strings so importing this module never imports jax; resolved
#: lazily by :func:`demote_dtype`.
_DEMOTE_NAME = "bfloat16"
_HALF_NAMES = ("bfloat16", "float16")


def demote_dtype():
    """The storage dtype demoted operands use (``jnp.bfloat16``)."""
    import jax.numpy as jnp

    return jnp.bfloat16


def is_half_dtype(dtype: Any) -> bool:
    """True for the 16-bit floating dtypes (bfloat16/float16)."""
    try:
        return np.dtype(dtype).name in _HALF_NAMES
    except TypeError:
        return False


def iterate_dtype(dtype: Any):
    """Floor an operand-derived dtype at float32 for iterate/scaling use.

    The hot cores derive working dtypes from their operands (``f32 =
    val.dtype``-style); under operand demotion that inference would silently
    make the PDHG/QP iterates, Ruiz scalings, power-iteration vectors and
    KKT residuals bf16 — convergence-fatal at ``pdhg_tol=1e-6``, two orders
    below bf16 resolution — or trip a ``while``/``fori`` carry dtype
    mismatch at trace time. This is the single sanctioned mapping: 16-bit
    in, float32 out; anything at or above float32 passes through unchanged.
    """
    return np.dtype("float32") if is_half_dtype(dtype) else np.dtype(dtype)


def mixed_precision_enabled(cfg: Optional[Any]) -> bool:
    """Resolve the tri-state ``Config.mixed_precision`` gate.

    ``False`` ⇒ hard off, bit-identical to the pre-graftgrade build (pinned
    by test). ``None`` (auto) ⇒ engage on accelerator backends only — the
    same routing posture as ``lp_batch``/``decomp_device_pricing``: on CPU
    the XLA legalizer re-materializes f32 converts around every bf16
    operand, so the bytes win is a TPU/GPU phenomenon (the README records
    the CPU-regime waiver). ``True`` forces engagement everywhere — the CPU
    test/CI route, where demotion remains *correct* (lossless round-trip)
    just not *profitable*.
    """
    mode = getattr(cfg, "mixed_precision", None) if cfg is not None else None
    if mode is not None:
        return bool(mode)
    import jax

    return jax.default_backend() not in ("cpu",)


@functools.lru_cache(maxsize=1)
def _plan_demotable() -> dict:
    """``{core name: tuple(demoted arg indices)}`` from the committed plan.

    Missing or unreadable plan ⇒ empty mapping: with no certified plan the
    runtime demotes NOTHING — the gate can only apply what graftgrade has
    actually committed to ``PRECISION_PLAN.json``.
    """
    try:
        data = json.loads(PLAN_PATH.read_text())
    except (OSError, ValueError):
        return {}
    out = {}
    for name, entry in data.get("cores", {}).items():
        args = tuple(int(i) for i in entry.get("demote_args", ()))
        if args:
            out[name] = args
    return out


def plan_demote_args(core: str) -> tuple:
    """The committed plan's certified demotable arg indices for ``core``."""
    return _plan_demotable().get(core, ())


def demote_operator(
    arr: Any,
    cfg: Optional[Any],
    *,
    core: str,
    arg: Optional[int] = None,
    log: Optional[Any] = None,
):
    """Demote one read-only operator array to bf16 under the committed plan.

    Returns ``arr`` unchanged unless ALL of: the ``mixed_precision`` gate
    resolves on, ``core`` has a certified ``demote_args`` entry in the
    committed plan (containing ``arg`` when given), the array is float32,
    and the bf16 round-trip is bit-exact. A gate-on but lossy operand is
    kept at f32 and counted (``mp_lossy_skip``) — the contract never rides
    on rounding luck, so engaged-vs-off stays bit-identical by construction.
    """
    if not mixed_precision_enabled(cfg):
        return arr
    certified = plan_demote_args(core)
    if not certified or (arg is not None and int(arg) not in certified):
        return arr
    import jax.numpy as jnp

    a = jnp.asarray(arr)
    if a.dtype != jnp.float32:
        return arr
    a16 = a.astype(demote_dtype())
    if bool(jnp.all(a16.astype(jnp.float32) == a)):
        if log is not None:
            log.count("mp_demoted_operands")
        return a16
    if log is not None:
        log.count("mp_lossy_skip")
    return arr
