"""Bounded LRU memo for module-level jit/shard_map caches.

The repo memoizes built callables at module level so repeat calls re-enter
compiled executables instead of re-lowering (graftlint R2): the chain-parallel
MC wrappers (``parallel/mc.py``), the batched LP engine's per-schedule cores
(``solvers/batch_lp.py``), the fused L2 cores (``solvers/qp.py``) and the
mesh-keyed sharded PDHG programs (``parallel/solver.py``). Plain dicts there
are unbounded: a long bench session that recreates meshes, or a sweep over
iteration schedules, accretes executables (and the device buffers their
constants pin) forever. :class:`LRU` bounds each cache with
least-recently-used eviction and counts every eviction into one module
counter, so cache pressure is observable (``memo_evictions()`` — bench
evidence rows record it).
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Iterator, Optional

#: process-wide eviction count across every LRU memo (observability only)
_EVICTIONS = 0


def memo_evictions() -> int:
    """Total LRU memo evictions since process start, across all caches."""
    return _EVICTIONS


class LRU:
    """A small ordered cache with least-recently-used eviction.

    Drop-in for the dict operations the memo sites use (``get``, item
    assignment, ``in``, ``len``, ``clear``, iteration over keys). A hit
    refreshes recency; an insert beyond ``cap`` evicts the oldest entry and
    bumps the global eviction counter.
    """

    def __init__(self, cap: int, name: str = ""):
        self.cap = max(int(cap), 1)
        self.name = name
        self._d: "OrderedDict[Any, Any]" = OrderedDict()
        self.evictions = 0

    def get(self, key, default: Optional[Any] = None):
        try:
            self._d.move_to_end(key)
        except KeyError:
            return default
        return self._d[key]

    def __getitem__(self, key):
        self._d.move_to_end(key)
        return self._d[key]

    def __setitem__(self, key, value) -> None:
        global _EVICTIONS
        if key in self._d:
            self._d.move_to_end(key)
        self._d[key] = value
        while len(self._d) > self.cap:
            self._d.popitem(last=False)
            self.evictions += 1
            _EVICTIONS += 1

    def __contains__(self, key) -> bool:
        return key in self._d

    def __len__(self) -> int:
        return len(self._d)

    def __iter__(self) -> Iterator:
        return iter(list(self._d))

    def clear(self) -> None:
        self._d.clear()
