"""Bounded LRU memo for module-level jit/shard_map caches.

The repo memoizes built callables at module level so repeat calls re-enter
compiled executables instead of re-lowering (graftlint R2): the chain-parallel
MC wrappers (``parallel/mc.py``), the batched LP engine's per-schedule cores
(``solvers/batch_lp.py``), the fused L2 cores (``solvers/qp.py``) and the
mesh-keyed sharded PDHG programs (``parallel/solver.py``). Plain dicts there
are unbounded: a long bench session that recreates meshes, or a sweep over
iteration schedules, accretes executables (and the device buffers their
constants pin) forever. :class:`LRU` bounds each cache with
least-recently-used eviction and counts every eviction into one module
counter, so cache pressure is observable (``memo_evictions()`` — bench
evidence rows record it).

Eviction attribution: every LRU entry carries an OWNER (default: the cache's
own name), and evictions are counted both process-wide and per owner
(:func:`memo_evictions_by_owner`). The serving layer
(``citizensassemblies_tpu/service``) caps each tenant's session state —
warm-start slots, packed ELL operands, result memos — in tenant-owned LRUs
and inserts with ``owner="tenant:<name>"``, so when a cache cycles under
memory pressure the per-request audit stamp can say WHICH tenant's entries
were evicted instead of reporting one opaque process-wide number. Counters
are lock-guarded: concurrent requests evict from shared caches on their own
worker threads.
"""

from __future__ import annotations

import threading
import weakref
from collections import OrderedDict
from typing import Any, Dict, Iterator, List, Optional

#: guards the module-wide eviction counters (service worker threads evict
#: concurrently); LRU instances reuse it — evictions are rare enough that a
#: single shared lock is simpler than per-cache locks and never hot
_EVICTION_LOCK = threading.Lock()

#: process-wide eviction count across every LRU memo (observability only)
_EVICTIONS = 0

#: eviction counts split by the evicted ENTRY's owner (cache name, or the
#: ``tenant:<name>`` owner tag the serving layer inserts with)
_EVICTIONS_BY_OWNER: Dict[str, int] = {}


def memo_evictions() -> int:
    """Total LRU memo evictions since process start, across all caches."""
    return _EVICTIONS


def memo_evictions_by_owner() -> Dict[str, int]:
    """Eviction counts keyed by the evicted entry's owner — the per-tenant
    attribution the service's audit stamps report (a copy; safe to hold)."""
    with _EVICTION_LOCK:
        return dict(_EVICTIONS_BY_OWNER)


#: weak registry of every live LRU — the graftscope memory ledger walks it
#: to attribute resident cache bytes per owner (``obs/memory.py``). Weak so
#: a dropped cache (a torn-down tenant session) leaves no ghost entry.
_INSTANCES: "weakref.WeakSet[LRU]" = weakref.WeakSet()


def live_caches() -> List["LRU"]:
    """Every LRU currently alive in the process (a snapshot copy)."""
    with _EVICTION_LOCK:
        return list(_INSTANCES)


class LRU:
    """A small ordered cache with least-recently-used eviction.

    Drop-in for the dict operations the memo sites use (``get``, item
    assignment, ``in``, ``len``, ``clear``, iteration over keys). A hit
    refreshes recency; an insert beyond ``cap`` evicts the oldest entry and
    bumps the global eviction counter — attributed to the evicted entry's
    owner (:meth:`put`), or to the cache's name when none was given.
    """

    def __init__(self, cap: int, name: str = ""):
        self.cap = max(int(cap), 1)
        self.name = name
        self._d: "OrderedDict[Any, Any]" = OrderedDict()
        self._owners: Dict[Any, str] = {}
        self.evictions = 0
        with _EVICTION_LOCK:
            _INSTANCES.add(self)

    def get(self, key, default: Optional[Any] = None):
        try:
            self._d.move_to_end(key)
        except KeyError:
            return default
        return self._d[key]

    def __getitem__(self, key):
        self._d.move_to_end(key)
        return self._d[key]

    def put(self, key, value, owner: Optional[str] = None) -> None:
        """Insert with an explicit OWNER attribution for eviction accounting
        (the service inserts tenant session state with ``tenant:<name>``).
        ``lru[key] = value`` is equivalent with ``owner=None`` — the eviction
        then counts against the cache's own name."""
        global _EVICTIONS
        if key in self._d:
            self._d.move_to_end(key)
        self._d[key] = value
        if owner is not None:
            self._owners[key] = owner
        else:
            self._owners.pop(key, None)
        while len(self._d) > self.cap:
            old_key, _ = self._d.popitem(last=False)
            old_owner = self._owners.pop(old_key, None) or self.name or "unnamed"
            self.evictions += 1
            with _EVICTION_LOCK:
                _EVICTIONS += 1
                _EVICTIONS_BY_OWNER[old_owner] = (
                    _EVICTIONS_BY_OWNER.get(old_owner, 0) + 1
                )

    def __setitem__(self, key, value) -> None:
        self.put(key, value)

    def __contains__(self, key) -> bool:
        return key in self._d

    def __len__(self) -> int:
        return len(self._d)

    def __iter__(self) -> Iterator:
        return iter(list(self._d))

    def pop(self, key, default: Optional[Any] = None):
        """Remove and return one entry WITHOUT counting an eviction — a
        deliberate removal (request-state rollback) is not cache pressure."""
        self._owners.pop(key, None)
        return self._d.pop(key, default)

    def clear(self) -> None:
        self._d.clear()
        self._owners.clear()
