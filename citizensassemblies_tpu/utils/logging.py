"""In-band log channel + tee logging.

The reference's algorithms accumulate human-readable progress into
``output_lines: List[str]`` (``leximin.py:54-56,429``) which the analysis layer
returns alongside results, and ``analyze_instance`` tees console output into
``analysis/<instance>_<k>_statistics.txt`` via a ``log()`` closure
(``analysis.py:552-556``). ``RunLog`` preserves both behaviors behind one object.
"""

from __future__ import annotations

import sys
import time
from contextlib import contextmanager
from pathlib import Path
from typing import IO, List, Optional


class RunLog:
    """Collects algorithm output lines; optionally echoes to stdout and a file."""

    def __init__(self, echo: bool = True, file: Optional[IO[str]] = None):
        self.lines: List[str] = []
        self.echo = echo
        self.file = file
        self._timers: dict[str, float] = {}
        self._counters: dict[str, int] = {}

    def emit(self, message: str) -> str:
        """Record a line (the reference's ``_print`` at ``leximin.py:54-56``)."""
        self.lines.append(message)
        if self.echo:
            print(message)
        if self.file is not None:
            self.file.write(message + "\n")
        return message

    def log(self, *info) -> None:
        """Tab-joined tee write (the reference's ``log`` at ``analysis.py:554-556``)."""
        msg = "\t".join(str(m) for m in info)
        if self.echo:
            print(*info)
        if self.file is not None:
            self.file.write(msg + "\n")
        self.lines.append(msg)

    @contextmanager
    def timer(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            self._timers[name] = self._timers.get(name, 0.0) + time.perf_counter() - t0

    @property
    def timers(self) -> dict:
        return dict(self._timers)

    def count(self, name: str, inc: int = 1) -> None:
        """Accumulate a named event counter (e.g. warm-start hits, overlap
        harvests) — the discrete sibling of :meth:`timer`, rendered by
        :func:`citizensassemblies_tpu.utils.profiling.format_counters`."""
        self._counters[name] = self._counters.get(name, 0) + inc

    def gauge(self, name: str, value) -> None:
        """Record a point-in-time VALUE (latest wins, no accumulation) into
        the counters channel — e.g. the measured ELL fill ratio of the last
        pack, which a bench row wants as a level, not a sum."""
        self._counters[name] = value

    @property
    def counters(self) -> dict:
        return dict(self._counters)


@contextmanager
def tee_file(path: Path, echo: bool = True):
    """Context manager yielding a RunLog that writes to ``path`` (utf-8)."""
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        yield RunLog(echo=echo, file=fh)


def progress(i: int, total: int, every: int = 100, out: IO[str] = sys.stdout) -> None:
    """Reference-style periodic progress print (``analysis.py:181-182``)."""
    if (i + 1) % every == 0:
        out.write(f"Running iteration {i + 1} out of {total}.\n")
