"""In-band log channel + tee logging.

The reference's algorithms accumulate human-readable progress into
``output_lines: List[str]`` (``leximin.py:54-56,429``) which the analysis layer
returns alongside results, and ``analyze_instance`` tees console output into
``analysis/<instance>_<k>_statistics.txt`` via a ``log()`` closure
(``analysis.py:552-556``). ``RunLog`` preserves both behaviors behind one object.

Metrics: ``count``/``gauge``/``timer`` delegate to a per-RunLog typed
:class:`~citizensassemblies_tpu.obs.metrics.MetricsRegistry` — the
grafttrace registry that also backs the service's Prometheus dump — with
BIT-COMPATIBLE accessors: :attr:`counters` and :attr:`timers` return the
same flat dicts (counters accumulate, gauges are latest-wins in the same
namespace, timers accumulate seconds) as the pre-registry dict storage did,
as defensive copies taken under the registry's mutation lock.

Tracing: when a :class:`~citizensassemblies_tpu.obs.trace.Tracer` is
active — ambient via ``obs.trace.use_tracer`` (the service installs one per
request through its ``RequestContext``) or attached as ``self.tracer`` (so
worker threads holding the request's log attribute correctly) — every
``timer`` scope additionally records a SPAN of the same name, which is how
the existing phase timers (``decomp_master``, ``stage_lp``, ``xmin_l2``…)
become the trace tree without touching their call sites. With no tracer the
timer path is the plain two-clock read it always was.

Thread safety: the serving layer (``citizensassemblies_tpu/service``) runs
CONCURRENT requests over solver code that mutates a RunLog's counter/timer
channels from whatever thread happens to be executing — including the
engine-level logs the cross-request batcher updates from several requests'
worker threads at once. ``dict.get``+store is not atomic under that load
(two threads read the same old value and one increment is lost), so every
mutation of ``lines`` takes the instance lock and every metrics mutation
takes the registry lock. Both are uncontended in the single-threaded
offline path (a few ns per count), and ``tests/test_service.py`` hammers
``count()`` from a pool to pin the no-lost-increments contract.
"""

from __future__ import annotations

import sys
import threading
import time
from contextlib import contextmanager
from pathlib import Path
from typing import IO, List, Optional

from citizensassemblies_tpu.obs.metrics import MetricsRegistry


class RunLog:
    """Collects algorithm output lines; optionally echoes to stdout and a file."""

    def __init__(self, echo: bool = True, file: Optional[IO[str]] = None):
        self.lines: List[str] = []
        self.echo = echo
        self.file = file
        #: typed metrics registry behind count/gauge/timer (obs.metrics)
        self.metrics = MetricsRegistry()
        #: optional grafttrace Tracer: set by the service's RequestContext
        #: (or a bench harness) so spans attribute to the owning request
        #: even from worker threads; None = no tracing from this log
        self.tracer = None
        #: guards every mutation of lines — concurrent requests in the
        #: serving layer emit into shared engine logs
        self._mutex = threading.Lock()

    def emit(self, message: str) -> str:
        """Record a line (the reference's ``_print`` at ``leximin.py:54-56``)."""
        with self._mutex:
            self.lines.append(message)
        if self.echo:
            print(message)
        if self.file is not None:
            self.file.write(message + "\n")
        return message

    def log(self, *info) -> None:
        """Tab-joined tee write (the reference's ``log`` at ``analysis.py:554-556``)."""
        msg = "\t".join(str(m) for m in info)
        if self.echo:
            print(*info)
        if self.file is not None:
            self.file.write(msg + "\n")
        with self._mutex:
            self.lines.append(msg)

    @contextmanager
    def timer(self, name: str):
        """Accumulating phase timer; records a same-named span when a tracer
        is active (``self.tracer`` or the ambient one — see module doc)."""
        from citizensassemblies_tpu.obs.trace import _resolve

        tracer = _resolve(self)
        sp = tracer.begin(name, stacked=True) if tracer is not None else None
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            if tracer is not None:
                tracer.end(sp)
            self.metrics.timer(name).observe(dt)

    @property
    def timers(self) -> dict:
        return self.metrics.flat_timers()

    def count(self, name: str, inc: int = 1) -> None:
        """Accumulate a named event counter (e.g. warm-start hits, overlap
        harvests) — the discrete sibling of :meth:`timer`, rendered by
        :func:`citizensassemblies_tpu.obs.metrics.format_counters`."""
        self.metrics.counter(name).inc(inc)

    def gauge(self, name: str, value) -> None:
        """Record a point-in-time VALUE (latest wins, no accumulation) into
        the counters channel — e.g. the measured ELL fill ratio of the last
        pack, which a bench row wants as a level, not a sum."""
        self.metrics.gauge(name).set(value)

    @property
    def counters(self) -> dict:
        return self.metrics.flat_counters()


@contextmanager
def tee_file(path: Path, echo: bool = True):
    """Context manager yielding a RunLog that writes to ``path`` (utf-8)."""
    path.parent.mkdir(parents=True, exist_ok=True)
    with open(path, "w", encoding="utf-8") as fh:
        yield RunLog(echo=echo, file=fh)


def progress(i: int, total: int, every: int = 100, out: IO[str] = sys.stdout) -> None:
    """Reference-style periodic progress print (``analysis.py:181-182``)."""
    if (i + 1) % every == 0:
        out.write(f"Running iteration {i + 1} out of {total}.\n")
