"""Mid-algorithm checkpointing of column-generation state.

The reference only memoizes *finished* runs (``analysis.py:271-327``), so a
crashed 4,000-second LEXIMIN run restarts from zero (SURVEY §5). Here the CG
state — portfolio matrix, fixed-probability vector, coverage mask, RNG key and
counters — is saved between outer rounds as one ``.npz`` and restored on the
next call, so a preempted run resumes at its last fixed tranche.

Atomic write (tmp + rename) so a crash mid-save never corrupts the previous
checkpoint.
"""

from __future__ import annotations

import dataclasses
import os
from pathlib import Path
from typing import Optional, Union

import numpy as np


@dataclasses.dataclass
class CGState:
    """Column-generation state at an outer-round boundary."""

    portfolio: np.ndarray  # bool[|C|, n]
    fixed: np.ndarray  # float64[n]; < 0 ⇒ not yet fixed
    covered: np.ndarray  # bool[n]
    key: np.ndarray  # jax PRNGKey data
    reduction_counter: int = 0
    dual_solves: int = 0
    exact_prices: int = 0
    #: hash of (instance, config, households); a checkpoint only resumes into
    #: the identical problem — see :func:`problem_fingerprint`
    fingerprint: str = ""


def problem_fingerprint(dense, cfg, households=None) -> str:
    """Digest of everything that determines the CG trajectory: incidence
    matrix, quotas, k, solver config, household groups. A checkpoint written
    under any other problem must not be resumed (same hazard class as the
    cache layer's config key)."""
    import hashlib

    h = hashlib.sha256()
    h.update(dense.A_np.astype(np.uint8).tobytes())
    h.update(dense.qmin_np.tobytes())
    h.update(dense.qmax_np.tobytes())
    h.update(str(dense.k).encode())
    h.update(repr(cfg).encode())
    if households is not None:
        h.update(np.asarray(households, dtype=np.int64).tobytes())
    return h.hexdigest()


def save_cg_state(path: Union[str, Path], state: CGState) -> None:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_suffix(path.suffix + ".tmp")
    with open(tmp, "wb") as fh:
        np.savez_compressed(
            fh,
            portfolio=state.portfolio.astype(bool),
            fixed=state.fixed.astype(np.float64),
            covered=state.covered.astype(bool),
            key=np.asarray(state.key),
            counters=np.asarray(
                [state.reduction_counter, state.dual_solves, state.exact_prices],
                dtype=np.int64,
            ),
            fingerprint=np.frombuffer(state.fingerprint.encode(), dtype=np.uint8),
        )
    os.replace(tmp, path)


def load_cg_state(
    path: Union[str, Path], n: int, fingerprint: str = ""
) -> Optional[CGState]:
    """Load a checkpoint if present and written for the *same problem*
    (matching pool size and, when given, matching :func:`problem_fingerprint`).
    A checkpoint for a different problem — or a corrupt file — is ignored, not
    an error: the caller just starts fresh."""
    path = Path(path)
    if not path.exists():
        return None
    try:
        with np.load(path) as z:
            portfolio = z["portfolio"]
            if portfolio.ndim != 2 or portfolio.shape[1] != n:
                return None
            stored_fp = bytes(z["fingerprint"]).decode() if "fingerprint" in z else ""
            if fingerprint and stored_fp != fingerprint:
                return None
            counters = z["counters"]
            return CGState(
                portfolio=portfolio.astype(bool),
                fixed=z["fixed"],
                covered=z["covered"],
                key=z["key"],
                reduction_counter=int(counters[0]),
                dual_solves=int(counters[1]),
                exact_prices=int(counters[2]),
                fingerprint=stored_fp,
            )
    except Exception:
        return None


def clear_cg_state(path: Union[str, Path]) -> None:
    Path(path).unlink(missing_ok=True)


@dataclasses.dataclass
class TypeCGState:
    """Type-space column-generation state at a decomposition-round boundary
    (the many-type LEXIMIN path, ``solvers/cg_typespace.py``)."""

    compositions: np.ndarray  # int32[C, T]
    v_relax: np.ndarray  # float64[T] relaxation-leximin targets
    coverable: np.ndarray  # bool[T]
    key: np.ndarray  # jax PRNGKey data
    round: int = 0
    fingerprint: str = ""


def save_ts_state(path: Union[str, Path], state: TypeCGState) -> None:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    tmp = path.with_suffix(path.suffix + ".tmp")
    with open(tmp, "wb") as fh:
        np.savez_compressed(
            fh,
            kind=np.asarray([1], dtype=np.int8),  # distinguishes from CGState files
            compositions=state.compositions.astype(np.int32),
            v_relax=state.v_relax.astype(np.float64),
            coverable=state.coverable.astype(bool),
            key=np.asarray(state.key),
            round=np.asarray([state.round], dtype=np.int64),
            fingerprint=np.frombuffer(state.fingerprint.encode(), dtype=np.uint8),
        )
    os.replace(tmp, path)


def load_ts_state(
    path: Union[str, Path], T: int, fingerprint: str = ""
) -> Optional[TypeCGState]:
    path = Path(path)
    if not path.exists():
        return None
    try:
        with np.load(path) as z:
            if "kind" not in z or "compositions" not in z:
                return None
            comps = z["compositions"]
            if comps.ndim != 2 or comps.shape[1] != T:
                return None
            stored_fp = bytes(z["fingerprint"]).decode() if "fingerprint" in z else ""
            if fingerprint and stored_fp != fingerprint:
                return None
            return TypeCGState(
                compositions=comps.astype(np.int32),
                v_relax=z["v_relax"],
                coverable=z["coverable"],
                key=z["key"],
                round=int(z["round"][0]),
                fingerprint=stored_fp,
            )
    except Exception:
        return None
