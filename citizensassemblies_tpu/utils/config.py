"""Typed configuration for every knob the reference hard-codes.

The reference scatters magic constants across modules (module-level ``EPS = 0.0005``
at ``leximin.py:30``/``xmin.py:32``, 10,000 Monte-Carlo iterations at
``analysis.py:288``, ``3 * n`` multiplicative-weight rounds at ``leximin.py:373``,
0.8 weight decay at ``leximin.py:259``, 0.9/0.1 smoothing at ``leximin.py:273``,
the 1e-4 fixed-probability relaxation step at ``leximin.py:412``, ``5 * n`` XMIN
expansion iterations at ``xmin.py:511``, ``3 * n`` dedup attempts at
``xmin.py:466``, Gurobi ``Method=2``/``Crossover=0`` at ``leximin.py:325-327``).
Here they are all lifted into one frozen dataclass.
"""

from __future__ import annotations

import dataclasses
from typing import Optional


@dataclasses.dataclass(frozen=True)
class Config:
    # --- numerical tolerances -------------------------------------------------
    #: numerical deviation accepted as equality when dealing with solvers
    #: (reference ``leximin.py:30``).
    eps: float = 5e-4
    #: amount by which all fixed probabilities are shaved when the dual LP
    #: becomes numerically infeasible (reference ``leximin.py:412``).
    fixed_prob_relax_step: float = 1e-4
    #: probabilities below this are treated as zero when counting the support
    #: of a distribution (reference ``analysis.py:209``).
    support_eps: float = 1e-11

    # --- LEGACY Monte-Carlo ---------------------------------------------------
    #: number of Monte-Carlo panel draws (reference ``analysis.py:288``).
    mc_iterations: int = 10_000
    #: chains drawn per device batch in the vectorized sampler.
    mc_batch: int = 2_048
    #: hard cap on resampling sweeps for rejected chains before giving up.
    mc_max_resample_rounds: int = 200

    # --- LEXIMIN column generation -------------------------------------------
    #: multiplicative-weight portfolio-seeding rounds as a multiple of n
    #: (reference ``leximin.py:373`` uses 3 * n sequential ILP solves; the TPU
    #: path replaces them with batched stochastic sampling, this knob scales
    #: the batch budget instead).
    mw_rounds_factor: int = 3
    # NOTE: the reference's MW decay (0.8, ``leximin.py:259``) and duplicate
    # smoothing (0.9/0.1, ``leximin.py:273``) have no analog here — the
    # batched-draw seeding replaced the sequential MW loop entirely, so those
    # two knobs are intentionally absent rather than carried as dead config.
    #: panels sampled per stochastic pricing batch on device.
    pricing_batch: int = 4_096
    #: cap on the batched portfolio-seeding draw (keeps the first dual LPs
    #: small; the portfolio grows by pricing only where it matters).
    seed_batch: int = 1_024
    #: violated columns added per dual LP solve.
    cg_columns_per_round: int = 16
    #: violated compositions added per stage-LP solve in type-space CG (cheap
    #: to carry: the stage LP has one row per type regardless of columns).
    cg_columns_typespace: int = 512
    #: cap on the agent-space portfolio: once reached, batched stochastic
    #: pricing stops ADDING columns and the exact oracle carries the tail
    #: (one certified column per round, exactly the reference's loop shape) —
    #: the buffer the padded dual LPs solve over stays bounded.
    max_portfolio: int = 8_192

    # --- type-space enumeration ----------------------------------------------
    #: run LEXIMIN over the full enumeration of feasible compositions when the
    #: instance has at most this many distinct agent types (agents with equal
    #: feature rows are interchangeable; the leximin allocation is unique and
    #: hence type-symmetric, so this path is exact).
    enum_max_types: int = 16
    #: abandon enumeration beyond this many feasible compositions.
    enum_cap: int = 200_000
    #: abandon enumeration beyond this many search nodes.
    enum_node_budget: int = 3_000_000
    #: panel budget when expanding a composition distribution into concrete
    #: panels (bounds both the portfolio size and, on the equidistributed
    #: path, the per-composition allocation error ≈ 1/expand_budget).
    expand_budget: int = 4_096
    #: panel cap for the greedy water-filling seed of the exact panel
    #: decomposition (``decompose_with_pricing``); mass unserved within the
    #: budget is recovered by its pricing-LP loop.
    decompose_budget: int = 16_384
    #: probe-LP tolerance certifying that a type cannot exceed the stage value.
    probe_tol: float = 1e-7
    #: panel-decomposition polish tolerance on the ENUMERATED type-space path
    #: (``models/leximin.py``): the decomposition accepts when it realizes
    #: the composition mixture's marginals within this deviation. The CG
    #: path floors it at its greedy noise scale (2e-5), and large instances
    #: at 2.5e-4 — see the tol derivation at the call site.
    decomp_tol: float = 1e-6
    #: after the pricing rounds are exhausted, still accept the relaxation
    #: profile when the residual is below this; only a larger residual — a
    #: genuine integrality gap — falls back to stage CG. Budget against the
    #: 1e-3 L∞ acceptance bar: the panel decomposition adds ≤ ~5e-5 on top
    #: of the composition-mixture ε (measured across sf_e-class runs:
    #: final L∞ ≈ ε + 3e-5..5e-5), so 6.5e-4 leaves ≥ 30 % headroom. On
    #: sf_e-class instances the face optimum hovers just above 4-5e-4 for
    #: many rounds, so a 5e-4 bar burned a third of the run's wall-clock on
    #: the last 1.5e-4 of ε that the bar does not need.
    decomp_accept: float = 6.5e-4
    #: acceptance after the face loop stalls or exhausts its rounds: a
    #: residual in (decomp_accept, decomp_accept_stalled] is still accepted —
    #: the panel-decomposition tolerance is coupled so the end-to-end L∞
    #: stays ≤ 9e-4 (see ``models/leximin.py``) — instead of paying the
    #: stage-CG fallback's minutes-long full column generation for the last
    #: ~1e-4 of ε the 1e-3 contract does not need.
    decomp_accept_stalled: float = 8e-4
    #: pricing rounds attempted for the decomposition before falling back to
    #: stage-wise column generation.
    decomp_max_rounds: int = 60
    #: the face master runs on the host LP instead of device PDHG when BOTH
    #: the type count and the column count are small: each device call pays
    #: the accelerator round-trip (through a TPU tunnel, ~0.5 s per master
    #: on a 95-type instance) but a host HiGHS solve scales with T×columns
    #: (measured ~1.9 s at 154×6000, where PDHG wins again).
    decomp_host_master_max_types: int = 384
    decomp_host_master_max_cols: int = 2_500
    #: wall-clock budget for the face-round loop: past it, a best residual
    #: already inside the stalled-acceptance band stops the loop (end-game
    #: polish still runs), bounding the tail a slow-converging hull can add
    #: — the r3 flagship showed a 150 s worst-of-3 against a 62 s median.
    decomp_time_budget_s: float = 45.0
    #: run the face loop's anchor-oracle MILP pricing in a worker thread,
    #: double-buffered against the device master: round r's anchors are
    #: SUBMITTED right after round r's duals arrive and HARVESTED at round
    #: r+1's expansion, so the MILPs execute while the device solves the
    #: next master (and while the polish/expansion run). Both settings use
    #: the same one-round-lagged schedule — False merely executes the jobs
    #: inline at the submit point — so the emitted column stream, and hence
    #: the returned portfolio, is bit-identical between the two (the
    #: regression contract ``tests/test_face_decompose.py`` pins). Anchors
    #: are heuristic columns (acceptance is the master iterate's arithmetic
    #: residual), so a one-round-stale aim costs at most an extra round
    #: while removing decomp_oracle from the critical path entirely.
    decomp_oracle_overlap: bool = True
    #: carry the master's and polish's PDHG primal/dual iterates across CG
    #: rounds and bucket growths (the saved iterate is re-padded into the
    #: new bucket) instead of cold-starting every solve. False cold-starts
    #: everything — the fallback when a warm iterate misbehaves.
    decomp_warm_start: bool = True
    #: consecutive warm-started master rounds without ε improvement before
    #: the warm iterate is dropped once (cold restart): a stalled first-order
    #: iterate can sit in a corner the fresh problem has moved away from,
    #: and restarting from zero re-equilibrates faster than escaping it.
    decomp_warm_stall_rounds: int = 3
    #: screen the neighbor-expansion move candidates in one jitted batch per
    #: round (two uint32 bitmask lanes + gathers, compiled once per pair
    #: bucket) instead of the host numpy sweep. Engaged on accelerator
    #: backends only — CPU-only runs keep the numpy sweep, where per-call
    #: dispatch/compile overhead outweighs the batching (same routing logic
    #: as the masters). Results are identical below ``per_round_cap``; above
    #: it the batched path keeps the first (mass-ordered) feasible moves
    #: where the numpy path subsamples randomly.
    decomp_batched_expand: bool = True
    #: device-resident anchor pricing for the face-decomposition loop
    #: (``solvers/device_pricing.py``): the per-round anchor batch is priced
    #: in ONE jitted device dispatch (β-ladder greedy lanes; an exact DP
    #: lane on single-category reductions) overlapped with the next master,
    #: the exact host MILP runs only for tasks the device screen misses
    #: (``decomp_oracle_device_hit``/``_miss``), and the batched move screen
    #: goes one-round-lagged so the steady-state CG round keeps a single
    #: host↔device synchronization point (``decomp_host_syncs`` ≤ 1 per
    #: round). Tri-state: ``None`` = auto (on on accelerator backends, off
    #: on CPU), ``True``/``False`` force. Off ⇒ the host anchor-MILP
    #: schedule runs bit-identically (the pre-device-pricing engine).
    decomp_device_pricing: Optional[bool] = None
    # NOTE: an earlier `decomp_multicut` knob (exact MILPs per decomposition
    # round) was absorbed into the face loop's fixed anchor schedule (one
    # dual-direction anchor + alternate-round noisy pair + up to three
    # forced-inclusion anchors, ``face_decompose.realize_profile``); it was
    # removed rather than kept as dead config.

    # --- XMIN -----------------------------------------------------------------
    #: portfolio-expansion budget as a multiple of n, counted in *distinct*
    #: panels added. The reference iterates 5n one-panel expansions
    #: (``xmin.py:511``) but its per-iteration CG re-solves add further
    #: pricing columns, so its final support exceeds 5n + seed; 8n distinct
    #: batched draws reaches the same support without the O(n) re-solves.
    #: May be fractional (e.g. 0.25 on a large pool) when a capped expansion
    #: is wanted — CI on CPU, quick-look runs.
    xmin_iterations_factor: float = 8
    #: dual-ascent iterations for the min-L2 final stage
    #: (``solvers/qp.py::solve_final_primal_l2``). 20k converges the spread
    #: on every benched instance; the knob exists because the fixed-count
    #: loop is the CPU-test bottleneck at large portfolios.
    xmin_qp_iters: int = 20_000
    #: attempts to sample a panel not already in the portfolio, as a multiple
    #: of n (reference ``xmin.py:466``).
    xmin_dedup_attempts_factor: int = 3
    #: L∞ budget for XMIN's support-maximizing blend: per-agent probabilities
    #: must stay within this of their leximin values after the spread (the
    #: framework's acceptance bar is 1e-3; the margin absorbs the leximin
    #: stage's own realization ε).
    xmin_linf_band: float = 8e-4

    # --- PDHG LP solver -------------------------------------------------------
    #: KKT tolerance for the device PDHG LP solver — 1e-6 is near the float32
    #: noise floor and two orders below the EPS=5e-4 fixing tolerance.
    pdhg_max_iters: int = 100_000
    pdhg_tol: float = 1e-6
    #: iterations per convergence check: each check costs ~12 matvecs (KKT of
    #: both the current and the averaged iterate), so checking every 64 was
    #: ~20 % of the whole solve
    pdhg_check_every: int = 128
    #: route the PDHG hot cores through the fused Pallas megakernel
    #: (``kernels/pdhg_megakernel.py``): one ``pallas_call`` per PDHG block
    #: keeps x/y and the packed ELL values VMEM-resident across
    #: ``pdhg_check_every`` iterations instead of shuttling them through HBM
    #: between every XLA op. ``None`` = auto (real accelerator backends
    #: only, and only when the kernel's estimated VMEM working set fits the
    #: budget below); ``True`` forces the fused path (interpret mode on
    #: non-TPU backends — the CPU test route); ``False`` ⇒ every consumer
    #: runs the chained ``_two_sided_iterate``/``_pdhg_body_ell`` cores
    #: bit-identically.
    pdhg_megakernel: Optional[bool] = None
    #: per-core VMEM budget (MiB) for the megakernel fit check: instances
    #: whose transposed-pack expansion + operands exceed this fall back to
    #: the chained cores instead of compiling a spilling kernel (~16 MiB
    #: physical per TPU core; the default leaves headroom for Mosaic's own
    #: scratch).
    pdhg_megakernel_vmem_mb: int = 12

    # --- batched LP/QP engine (solvers/batch_lp.py) ---------------------------
    #: fuse fleets of small independent LP/QP solves into padded, vmapped
    #: device calls (``solvers/batch_lp.py``): polish-face screening in the
    #: decomposition end-game, the fused XMIN L2 stage, the probe prescreen,
    #: and sweep-level LP fleets. ``None`` = auto (on on accelerator
    #: backends, off on CPU, where per-call dispatch overhead outweighs the
    #: batching — same routing logic as the device masters); ``True``/
    #: ``False`` force. With the engine off every call site runs its serial
    #: path bit-identically.
    lp_batch: Optional[bool] = None
    #: cap on a padded bucket dimension: shapes are rounded up to a power of
    #: two below the cap and to a multiple of the cap above it, so compiled
    #: executables stay bounded (each distinct bucket compiles once) without
    #: unbounded padding waste on large instances.
    lp_batch_bucket_max: int = 4_096
    #: batched device prescreen of per-candidate probe LPs
    #: (``solvers/compositions.py``): an approximate device solve of the
    #: whole candidate fleet witnesses clearly-loose candidates at a
    #: float64-validated face point, pruning their host LPs. The screen can
    #: only REDUCE the host-LP count — every candidate it cannot witness
    #: loose still gets its float64 host confirm, so certification soundness
    #: is unchanged.
    lp_batch_screen: bool = True

    # --- structured-sparse operator layer (solvers/sparse_ops.py) -------------
    #: route the PDHG/QP hot cores through the fixed-nnz ELL operator layer
    #: (``solvers/sparse_ops.py``): the face-decomposition master and polish,
    #: the batched polish screen, the dual leximin LP, the XMIN L2 stage and
    #: the mesh-sharded dual LP then run gather/scatter matvecs over packed
    #: ``indices/values`` arrays instead of dense GEMVs — the matrices'
    #: columns are panel compositions (≤ k nonzeros of T types), so at
    #: production shapes ≥90 % of the dense FLOPs/HBM bytes are
    #: multiply-by-zero. ``None`` = auto (on exactly when the measured fill
    #: is ≤ ``sparse_fill_cutoff``); ``True``/``False`` force. Off ⇒ every
    #: call site runs its dense path bit-identically.
    sparse_ops: Optional[bool] = None
    #: auto-routing cutoff for ``sparse_ops=None``: the ELL path engages when
    #: the measured nnz fill ratio of the packed operator is at or below
    #: this. 0.25 ≈ the break-even where gather/scatter matvec traffic
    #: (indices + values) stops beating the dense GEMV's bytes.
    sparse_fill_cutoff: float = 0.25

    # --- graftgrade certified mixed precision (utils/precision.py) -------------
    #: apply the committed ``PRECISION_PLAN.json`` bf16 operand demotion to
    #: the PDHG/QP hot cores (dense, ELL, megakernel and batched routes):
    #: read-only operator matrices certified ``bf16_safe`` by ``lint --prec``
    #: ship to the device at half width, matvec accumulation stays f32, KKT
    #: residuals and all certification/audit arithmetic stay f64-untouched,
    #: and the sentinel → float64 host re-solve ladder backstops the runtime.
    #: Tri-state: ``None`` = auto (accelerator backends only — on CPU the
    #: XLA legalizer re-upcasts around every bf16 operand, so the bytes win
    #: is waived there, see README); ``True`` forces engagement (the CPU
    #: test route — still correct, demotion only applies when the bf16
    #: round-trip is bit-exact, lossy operands stay f32 and are counted
    #: ``mp_lossy_skip``); ``False`` = hard off, bit-identical to the
    #: pre-graftgrade build (pinned by test).
    mixed_precision: Optional[bool] = None

    #: route the agent-space dual LP through the mesh-sharded device PDHG
    #: (``parallel/solver.py``) whenever more than one device is visible and
    #: the portfolio has at least this many rows — the regime where the C×n
    #: committee matrix outgrows one chip's comfortable working set and the
    #: GEMVs want the mesh (SURVEY §5 long-context analog). Below it the
    #: host/single-device solvers win on latency.
    dual_shard_min_rows: int = 4_096
    #: route the face-decomposition master through the mesh-sharded PDHG
    #: (``parallel/solver.py::solve_decomp_master_sharded``) when more than
    #: one device is visible and the problem has at least this many distinct
    #: agent TYPES — the sharded axis is the 2T constraint rows, and the
    #: master's column count is architecturally capped (~6k) while the type
    #: count grows with pool diversity; beyond one chip's comfortable row
    #: set the mesh carries it.
    master_shard_min_types: int = 4_096

    #: wall-clock budget for the agent-space CG when it runs as the FALLBACK
    #: of a type-space realization that missed the 1e-3 contract. Past the
    #: budget the certified type-space profile ships with an explicit
    #: realization-ε statement (``Distribution.contract_ok = False``) instead
    #: of grinding a possibly multi-hour CG (the independent n=800 agent-space
    #: cross-check did not finish in 3.5 h). 0 — the default — disables the
    #: budget entirely, so the out-of-contract ε-wide fallback is strictly
    #: OPT-IN (ADVICE r5 #1, second half): an operator who wants the bounded
    #: wall-clock sets a positive budget explicitly and thereby accepts that
    #: a budget expiry ships a flagged ``contract_ok=False`` result — it can
    #: no longer ship silently under a default. Explicit
    #: ``force_agent_space`` / warm-start runs are never budgeted (they have
    #: no fallback to ship).
    agent_space_budget_s: float = 0.0

    # --- selection service (citizensassemblies_tpu/service) -------------------
    #: hard cap on in-flight (admitted, not yet finished) requests per
    #: ``SelectionService``; ``submit()`` raises ``AdmissionError`` beyond it
    #: so back-pressure reaches the client instead of an unbounded queue.
    serve_queue_depth: int = 256
    #: worker threads per service — the number of requests RUNNING
    #: concurrently. More workers widen the cross-request batching window's
    #: catch (more fleets in flight to fuse) at the cost of host memory per
    #: running solve; the queue above absorbs bursts beyond it.
    serve_admission_cap: int = 8
    #: how long (milliseconds) the cross-request batcher's group leader
    #: holds a window open for OTHER requests' same-schedule LP fleets
    #: before dispatching the union. 0 disables coalescing (every fleet
    #: dispatches solo, the pre-service behavior); a few ms is enough —
    #: the window only needs to catch fleets already in flight on other
    #: worker threads, not wait for future ones.
    serve_batch_window_ms: float = 4.0
    #: per-tenant memory cap: max entries in EACH of a tenant session's LRU
    #: stores (warm-start slot stores, result memos, packed ELL operands).
    #: Evictions are counted per tenant (``memo_evictions_by_owner``) and
    #: reported on the request audit stamp.
    serve_tenant_memo_cap: int = 8

    # --- scenario models (citizensassemblies_tpu/scenarios) --------------------
    #: attendance buckets for the dropout-robust leximin: per-agent no-show
    #: probabilities are quantized into this many equal-width buckets, and
    #: the bucket becomes an extra (vacuous-quota) feature category, so the
    #: product type-space stays enumerable. More buckets = finer attendance
    #: resolution but multiplies the type count (enum_max_types gates the
    #: product; past it the model degrades to attendance-unaware leximin,
    #: stamped on the scenario audit).
    scenario_dropout_buckets: int = 4
    #: replacement policy for realized dropout evaluation: "type" fills each
    #: no-show seat with a uniformly random off-panel agent of the SAME
    #: base type (quota-preserving by construction — the replacement's
    #: feature row equals the no-show's), "naive" re-draws uniformly from
    #: ALL off-panel agents (the baseline policy; may violate quotas),
    #: "none" leaves no-show seats empty.
    scenario_replacement: str = "type"
    #: default number of successive panels R for multi-assembly scheduling
    #: (``scenarios/multi.py``) when the caller does not pass ``rounds``.
    scenario_rounds: int = 3
    #: Monte-Carlo draws for the dropout-realization evaluation kernel
    #: (``parallel/mc.py::dropout_realization_round``).
    scenario_mc_draws: int = 4_096

    # --- fault tolerance (citizensassemblies_tpu/robust) -----------------------
    #: chaos-run fault-injection spec: ``"site:rate,site:rate"`` over the
    #: sites catalogued in ``robust/inject.FAULT_SITES`` (and the README).
    #: Empty (the default) disables injection entirely — the hot-boundary
    #: consults reduce to a None check. Firing is seed-deterministic
    #: (``fault_seed``): the same spec + seed reproduces the identical
    #: fault schedule, so every chaos finding replays.
    fault_sites: str = ""
    #: seed of the deterministic fault schedule (crc-based, process-stable).
    fault_seed: int = 0
    #: numerical sentinels inside the jitted PDHG/QP ``while_loop`` carries:
    #: a lane whose KKT residual goes non-finite is FROZEN at its last
    #: finite iterate and flagged (per-lane quarantine masks, the same
    #: select pattern as the batched engine's convergence masks) instead of
    #: propagating NaN; quarantined lanes are re-solved on the serial
    #: float64 host path. Zero-fault runs are bit-identical with the
    #: sentinel on or off (pinned by test), and the static flag adds no
    #: recompiles or steady-state host syncs. False = the exact pre-sentinel
    #: jaxpr.
    robust_sentinels: bool = True
    #: snapshot the face-decomposition loop's certified state (portfolio
    #: columns, mixture, arithmetic ε) every N rounds so a killed/aborted
    #: request resumes from its last certified round instead of restarting
    #: (``robust/checkpoint.py``, atomic tmp+rename writes). 0 (default)
    #: disables face checkpointing.
    robust_checkpoint_every: int = 0
    #: directory for face-loop checkpoints (``face_<fp16>.npz``, content-
    #: fingerprinted so a snapshot only resumes into the identical
    #: problem). Empty disables face checkpointing.
    robust_checkpoint_dir: str = ""
    #: per-request wall-clock deadline (seconds), threaded through
    #: ``RequestContext`` and checked once per CG round at the round's
    #: existing host sync point. Expiry raises a graceful
    #: ``DeadlineExceeded`` rejection carrying a partial audit stamp
    #: instead of hanging. 0 (default) disables the deadline.
    serve_deadline_s: float = 0.0
    #: transient-fault retries per request (injected faults and real
    #: backend failures): each retry backs off exponentially from
    #: ``serve_retry_backoff_s`` and walks one rung down the certified
    #: degradation ladder (device pricing → host MILP, ELL → dense,
    #: batched → serial, fused screen → host screen).
    serve_retry_max: int = 2
    #: base backoff (seconds) of the exponential retry delay.
    serve_retry_backoff_s: float = 0.05
    #: cap on retained ResultChannel events per request: past it, incoming
    #: progress/metrics events are dropped AND counted
    #: (``ResultChannel.dropped``) — the terminal result + audit stamp is
    #: always retained, so a long-running request's stream cannot grow
    #: without bound.
    serve_channel_cap: int = 1024
    #: graftfleet SLO-driven load management (``obs/slo.py``
    #: ``SloLoadPolicy``): ``True`` arms the policy on a service whose SLO
    #: engine is configured (``obs_slo_spec`` non-empty) — sustained
    #: fast-window burn-rate breaches turn on admission SHEDDING (each shed
    #: submit gets a typed ``ShedRejection`` terminal event with an audit
    #: stub, counted ``graftserve_shed_total``) and walk the service-level
    #: degradation ladder one rung at a time (megakernel→chained, device
    #: pricing→host, ELL→dense); recovery re-arms (shedding off, ladder
    #: reset, counted ``graftserve_shed_rearm_total``). ``False`` (the
    #: default) keeps the SLO engine observe-only — pre-fleet behavior,
    #: bit-identical.
    serve_shed: bool = False
    #: fast-window burn rate at or above which the load policy opens
    #: (sheds + descends): burn 1.0 = consuming error budget exactly at
    #: the sustainable rate, so the default trips at 2× sustainable.
    serve_shed_burn: float = 2.0
    #: fast-window burn rate at or below which every objective must sit
    #: for the policy to RE-ARM (shedding off, ladder reset) — the
    #: hysteresis band between this and ``serve_shed_burn`` prevents
    #: flapping.
    serve_shed_recover: float = 0.5
    #: the load policy's fast evaluation window (seconds): burn rates are
    #: computed over the most recent window this long, so overload is
    #: detected (and recovery observed) at this granularity rather than
    #: the SLO engine's slower alerting windows.
    serve_shed_window_s: float = 60.0
    #: deepest degradation-ladder rung the LOAD policy may walk (the fault
    #: path's per-request ladder is not capped by this). The default stops
    #: after the three capacity rungs (megakernel→chained, device
    #: pricing→host MILP, ELL→dense) — load management trades peak
    #: throughput for stability but never silently leaves the mesh or the
    #: batched engine.
    serve_shed_max_rungs: int = 3
    #: graftdelta incremental re-certification, tri-state. ``False`` = hard
    #: off: ``revise`` requests run the plain from-scratch solver and never
    #: touch the session delta store — bit-identical to pre-delta builds
    #: (pinned by test). ``None`` (auto) = serve delta re-certification when
    #: the tenant session holds a matching base certificate (warm), fall
    #: back to from-scratch (and prime the store) when cold. ``True`` = same
    #: as auto but a cold or oversized revise counts a ``delta_fallback``
    #: loudly so operators can see missed O(edit) opportunities.
    delta_solve: Optional[bool] = None
    #: largest edit the delta path accepts, as a fraction of the pool size
    #: (``edit.magnitude / n``). Past it the screen/resume machinery would
    #: approach from-scratch cost anyway, so the service falls back
    #: bit-identically to the full solver (counted ``delta_fallback``).
    delta_max_edit_frac: float = 0.05
    #: slack consumed by the dual-sensitivity cache certificate. A cache hit
    #: (zero LP solves) is only claimed when every newly-admitted column
    #: prices at least this far below the stage support price AND the
    #: allocation drift bound from pool-size changes stays under it, with
    #: ``eps_old + 2·margin`` still inside the 1e-3 L∞ contract. Smaller =
    #: fewer cache hits, never a weaker contract.
    delta_cert_margin: float = 2.0e-4

    # --- observability (citizensassemblies_tpu/obs) ----------------------------
    #: grafttrace span tracing, tri-state. ``False`` = hard off: the span
    #: helpers and ``dispatch_span`` hooks are inert even with a tracer
    #: installed — zero overhead, runs bit-identical to pre-trace builds,
    #: the warm-rep compile bound unchanged. ``None`` (auto) = passive:
    #: spans record whenever a caller installs a Tracer
    #: (``obs.trace.use_tracer``); dispatch hooks never block, so dispatch
    #: spans measure host enqueue latency. ``True`` = the SAMPLING mode:
    #: the service creates a per-request Tracer and the dispatch hooks
    #: ``block_until_ready`` their outputs so spans measure device
    #: execution — numerically identical (a wait, not a transfer), but it
    #: serializes async pipelines, hence opt-in.
    obs_trace: Optional[bool] = None
    #: seconds between the selection service's periodic metrics snapshots
    #: (queue depth, in-flight, per-tenant evictions, batcher fusion ratio)
    #: streamed as ``("metrics", …)`` events on every open ResultChannel.
    #: 0 (the default) disables the snapshot thread entirely.
    obs_metrics_interval_s: float = 0.0
    #: per-instrument label-cardinality cap of the metrics registry: past
    #: this many distinct label sets, new ones fold into one reserved
    #: overflow series (counted) instead of growing without bound.
    obs_max_label_sets: int = 64
    #: ``bench.py --trend`` regression tolerance: a row FAILS when its
    #: latest committed value exceeds tol × the best earlier round. Sized
    #: so the committed BENCH trajectory's cross-container variance passes
    #: while an injected 2× slowdown is flagged (tests/test_obs.py pins
    #: both).
    obs_trend_tol: float = 1.75
    #: graftscope memory ledger, tri-state mirroring ``obs_trace``.
    #: ``False`` = hard off: the dispatch hook does one attribute read and
    #: never imports the ledger — zero overhead, bit-identical. ``None``
    #: (auto) = snapshots record whenever a caller installs a
    #: ``MemoryLedger`` (``obs.memory.use_ledger``), e.g. the bench around
    #: its warm flagship reps. ``True`` = the service additionally creates
    #: a per-request ledger and stamps its ``memory`` block (live bytes,
    #: HBM high watermark, per-owner cache attribution) onto the audit.
    obs_memory: Optional[bool] = None
    #: declarative serving SLOs, e.g. ``"latency_p99:20s,error_rate:0.01"``
    #: (``tenant/objective:target`` entries override per tenant). Empty
    #: (the default) disables the SLO engine entirely; non-empty makes the
    #: service evaluate every request outcome, stream breach transitions
    #: as ``("slo", …)`` channel events, and lets ``bench.py --serve``
    #: gate on the committed spec.
    obs_slo_spec: str = ""
    #: machine-balance ridge (FLOPs per byte) of the roofline verdict: a
    #: core whose arithmetic intensity sits below it is bytes-bound,
    #: above it compute-bound. The default is an honest CPU-class balance
    #: (the CI regime); on real TPU hardware set it to the part's
    #: peak-FLOPs/peak-bandwidth ratio (~240 for v4) before reading
    #: verdicts off ``bench.py --roofline``.
    obs_roofline_ridge: float = 10.0

    # --- distributed runtime (citizensassemblies_tpu/dist) ---------------------
    #: graftpod mesh gate. ``True``: shardable stages (the MC estimator's
    #: auto-distribution hook, the cross-request batcher's engine handoff)
    #: run over the process's ``dist.runtime`` topology whenever it spans
    #: more than one device. ``False`` forces the undistributed single-device
    #: paths — this is the ``mesh_to_single_device`` rung of the degradation
    #: ladder, and the bit-identity anchor the 1-device contract is pinned
    #: against.
    dist_mesh: bool = True
    #: multi-process coordinator address ("host:port"). Empty (the default)
    #: means the ``CITIZENS_DIST_*`` environment variables decide: when they
    #: are absent too, ``dist.runtime.bootstrap`` runs single-process without
    #: touching ``jax.distributed``. Set (either way) alongside
    #: ``CITIZENS_DIST_NUM_PROCESSES``/``CITIZENS_DIST_PROCESS_ID`` to join
    #: a pod.
    dist_coordinator: str = ""
    #: pre-partition engine operands into the declared-once NamedSharding
    #: specs of ``dist/partition.py`` (counted: first host upload is a
    #: ``dist_placements``, a wrong-sharding device operand is a
    #: ``dist_reshards`` — held at zero in steady state by ``bench.py
    #: --dist``). ``False`` falls back to the per-call ad-hoc layout the
    #: engine used before graftpod (kept as a diagnostic escape hatch).
    dist_prepartition: bool = True
    #: graftfleet serving-fleet size: how many serving processes the fleet
    #: router spreads tenants over (rendezvous hashing — every front end
    #: routes identically with no coordination). 0 (the default) reads the
    #: ``CITIZENS_FLEET_PROCESSES`` environment contract and falls back to
    #: the jax process count, so a pod launch needs no config edit.
    fleet_processes: int = 0
    #: offered request rate (requests/second, WHOLE fleet) of the open-loop
    #: load harness: arrivals follow a seeded Poisson process at this rate
    #: and are submitted on schedule regardless of completions — the
    #: open-loop discipline under which "sustained req/s at fixed p50/p99
    #: sojourn" is meaningful (a closed loop self-throttles and hides
    #: queueing collapse).
    fleet_offered_rate_hz: float = 250.0
    #: distinct tenants of the synthetic fleet workload; the rendezvous
    #: router maps each to its owning process, so warm slots, session
    #: EllPacks, memos and delta stores stay process-local.
    fleet_tenants: int = 8
    #: graftspmd (``lint/spmd.py``) implicit-replication threshold, bytes: a
    #: registered core argument with NO declared ``dist/partition.py`` role
    #: larger than this is flagged at mesh sizes > 1 — an implicitly
    #: replicated mega-operand costs its full footprint on every device.
    #: Declare the argument ``"replicated"`` when that IS the intended
    #: layout; the default (1 MiB) lets scalars, quota vectors and
    #: per-feature tables through.
    spmd_replicated_bytes_max: int = 1 << 20

    # --- backends -------------------------------------------------------------
    #: "jax" (TPU-first, stochastic pricing + PDHG, exact certification),
    #: "highs" (host scipy/HiGHS LPs and MILPs — the cross-check backend), or
    #: "hybrid" (TPU inner loops, host exact certification).
    backend: str = "hybrid"
    #: bypass the type-space/quotient solvers and run the agent-space CG
    #: (the reference's only mode, ``leximin.py:338-470``) even when a
    #: symmetry collapse applies. This is the independent cross-check oracle
    #: the certification tests diff the production path against — before the
    #: household quotient existed they forced agent space with singleton
    #: households, which the quotient now (correctly) collapses right back.
    force_agent_space: bool = False
    #: random seed used by solver-internal sampling (not MC estimation).
    solver_seed: int = 0

    # --- runtime guard rails --------------------------------------------------
    #: ``jax.transfer_guard`` mode wrapped around the jitted hot calls
    #: (``utils/guards.no_implicit_transfers``: the PDHG cores, the sharded
    #: solver, the batched move screen): "disallow" raises on any IMPLICIT
    #: host↔device transfer inside those scopes (a numpy array reaching a
    #: jitted call re-uploads through the TPU tunnel every invocation),
    #: "log" warns instead, "off" removes the scope. Explicit conversions
    #: (``jnp.asarray``, ``jax.device_put``) are always allowed — the fix
    #: for a violation is to materialize the operand once, outside the loop.
    transfer_guard: str = "disallow"

    # --- graftboot AOT executable cache (aot/) --------------------------------
    #: tri-state cold-start killer: boot the process from the serialized
    #: executable cache (``make aot-cache``) so the memo factories hand out
    #: pre-compiled programs. ``None`` (auto) = load the artifact if one
    #: exists, boot cold otherwise; ``True`` = required — a missing,
    #: unreadable or fingerprint-mismatched artifact raises at boot (the
    #: fleet mode where a cold boot is an incident); ``False`` = hard off,
    #: bit-identical to the plain JIT path (pinned by test). At serve time
    #: a per-entry mismatch always falls back to JIT, counted
    #: (``aot_cache_hit/miss/stale``), never a crash.
    aot_cache: Optional[bool] = None
    #: cache artifact path override; "" resolves ``CITIZENS_AOT_CACHE`` then
    #: the per-user default (``~/.cache/citizensassemblies_tpu/``, keyed by
    #: backend so CPU and TPU artifacts never collide).
    aot_cache_path: str = ""
    #: speculative bucket pre-warm on tenant admission: map the new tenant's
    #: first instance to its predicted LP bucket shapes and touch those
    #: cached executables with inert zero operands (padding lanes converge
    #: at the first KKT check, so a touch costs one cheap dispatch) before
    #: the first real solve lands. ``None`` = auto (on whenever a cache is
    #: installed); ``False`` off; ``True`` additionally warms eagerly even
    #: when the store booted empty (a no-op, kept for symmetry).
    aot_prewarm: Optional[bool] = None

    def replace(self, **kw) -> "Config":
        return dataclasses.replace(self, **kw)


def default_config() -> Config:
    return Config()
