"""Tracing / profiling (SURVEY §5).

The reference's only instrumentation is a wall-clock timing harness
(``analysis.py:625-634``) and periodic progress prints. The TPU build adds:

* :func:`profiler_trace` — wraps ``jax.profiler.trace`` so any region can be
  captured for TensorBoard/Perfetto (XLA compile + device timelines).
* :func:`annotate` — ``jax.profiler.TraceAnnotation`` context for named spans
  inside a trace.
* Per-phase wall timers live on :class:`~citizensassemblies_tpu.utils.logging.RunLog`
  (``log.timer("dual_lp")``), which the solvers use to attribute CG time to
  dual solves / pricing / exact certification; :func:`format_timers` renders
  them for the in-band output-lines channel.
"""

from __future__ import annotations

from contextlib import contextmanager, nullcontext
from typing import Dict, Optional


@contextmanager
def profiler_trace(logdir: Optional[str]):
    """Capture a jax profiler trace into ``logdir`` (no-op when ``None``)."""
    if logdir is None:
        yield
        return
    import jax

    with jax.profiler.trace(str(logdir)):
        yield


def annotate(name: str):
    """Named span inside a profiler trace (host + device timeline)."""
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover
        return nullcontext()


def format_timers(timers: Dict[str, float]) -> str:
    """One-line phase-time attribution, largest first."""
    if not timers:
        return "phase times: (none recorded)"
    parts = [
        f"{name} {secs:.2f}s"
        for name, secs in sorted(timers.items(), key=lambda kv: -kv[1])
    ]
    return "phase times: " + ", ".join(parts)


def format_counters(counters: Dict[str, int]) -> str:
    """One-line phase-event attribution (warm-start hits, overlap harvests,
    cold restarts — the pipelined decomposition's counterpart to the wall
    timers), largest first."""
    if not counters:
        return "phase counters: (none recorded)"
    parts = [
        f"{name} {cnt}"
        for name, cnt in sorted(counters.items(), key=lambda kv: -kv[1])
    ]
    return "phase counters: " + ", ".join(parts)
