"""Thin compatibility shim over ``citizensassemblies_tpu.obs`` (SURVEY §5).

The tracing/metrics layer moved into the unified observability package:

* span tracing (nested spans, Chrome/Perfetto export) — ``obs.trace``;
* ``format_timers``/``format_counters`` — ``obs.metrics`` (the registry
  that now backs ``RunLog``'s channels);
* device-dispatch timing hooks — ``obs.hooks.dispatch_span``.

This module keeps the historical import surface stable (the in-band bench
output format depends on the renderers) plus the two jax-profiler wrappers
that predate grafttrace — ``profiler_trace`` captures a full XLA timeline
for TensorBoard/Perfetto where grafttrace's host spans are not enough, and
``annotate`` names regions inside such a capture.
"""

from __future__ import annotations

from contextlib import contextmanager, nullcontext
from typing import Optional

# absorbed into the obs package; re-exported for existing imports
from citizensassemblies_tpu.obs.metrics import (  # noqa: F401
    format_counters,
    format_timers,
)


@contextmanager
def profiler_trace(logdir: Optional[str]):
    """Capture a jax profiler trace into ``logdir`` (no-op when ``None``)."""
    if logdir is None:
        yield
        return
    import jax

    with jax.profiler.trace(str(logdir)):
        yield


def annotate(name: str):
    """Named span inside a profiler trace (host + device timeline)."""
    try:
        import jax

        return jax.profiler.TraceAnnotation(name)
    except Exception:  # pragma: no cover
        return nullcontext()
