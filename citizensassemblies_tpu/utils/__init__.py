from citizensassemblies_tpu.utils.config import Config, default_config  # noqa: F401
from citizensassemblies_tpu.utils.logging import RunLog  # noqa: F401
