"""Runtime guard rails for the JAX invariants the flagship speedups rest on.

The static side of this contract lives in ``citizensassemblies_tpu.lint``
(graftlint): host-sync calls must not be reachable from jitted code, jits must
not be constructed per call, donated buffers must not be reused. Static
analysis cannot see *dynamic* regressions though — a shape drifting out of its
padding bucket recompiles the PDHG core every CG round, and a numpy array
sneaking into a jitted call re-uploads it through the TPU tunnel per
invocation. The two guards here catch exactly those at runtime:

* :class:`CompilationGuard` — counts XLA compilations inside a scope via the
  ``jax.monitoring`` backend-compile event, optionally asserting a bound.
  Wired into ``face_decompose.realize_profile`` (the count lands in the run's
  phase counters as ``xla_compiles_decomp``) and around the bench's flagship
  reps, where steady-state reps assert ~zero recompiles.
* :func:`no_implicit_transfers` — a ``jax.transfer_guard`` scope around the
  jitted hot calls in ``lp_pdhg``, ``qp``, ``parallel/solver`` and
  ``face_decompose``. Explicit conversions (``jnp.asarray``,
  ``jax.device_put``) stay legal; an *implicit* transfer — a numpy array or a
  bare-scalar eager op reaching the device path inside the scope — raises
  (mode ``"disallow"``) or warns (``"log"``). ``Config.transfer_guard``
  selects the mode; ``"off"`` removes the scope entirely.

Both guards are deliberately import-light: ``jax`` is imported lazily so the
module (and the lint package, which never needs a device) stays importable
anywhere.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from typing import List, Optional

#: the jax.monitoring duration event emitted once per XLA backend compile
_COMPILE_EVENT_SUFFIX = "backend_compile_duration"

_lock = threading.Lock()
_active_guards: List["CompilationGuard"] = []
_listener_installed = False

#: thread-local core-label stack — backend_compile events emit synchronously
#: on the compiling thread, so the innermost ``compiling_as`` label at event
#: time names the core being compiled
_tls = threading.local()


class GuardViolation(RuntimeError):
    """A runtime guard's asserted bound was exceeded."""


def _label_stack() -> List[str]:
    stack = getattr(_tls, "labels", None)
    if stack is None:
        stack = _tls.labels = []
    return stack


def _current_label() -> Optional[str]:
    stack = getattr(_tls, "labels", None)
    return stack[-1] if stack else None


@contextmanager
def compiling_as(label: str):
    """Attribute any XLA compile fired inside the scope to ``label``.

    The solver dispatch sites wrap their core calls in this, so a
    :class:`CompilationGuard` report names the offending core
    (``by_name``) instead of just a phase total — a cold-boot gate failure
    says *which* executable missed the AOT cache.
    """
    stack = _label_stack()
    stack.append(str(label))
    try:
        yield
    finally:
        stack.pop()


def _install_listener() -> None:
    """Register the (process-global) compile-event listener once.

    ``jax.monitoring`` has no unregister API, so the listener stays installed
    and fans out to whatever guards are active at event time — a no-op when
    none are.
    """
    global _listener_installed
    with _lock:
        if _listener_installed:
            return
        import jax.monitoring

        def _on_duration(event: str, duration: float, **kw) -> None:
            if not event.endswith(_COMPILE_EVENT_SUFFIX):
                return
            label = _current_label() or "unattributed"
            with _lock:
                for guard in _active_guards:
                    guard.count += 1
                    guard.by_name[label] = guard.by_name.get(label, 0) + 1

        jax.monitoring.register_event_duration_secs_listener(_on_duration)
        _listener_installed = True


class CompilationGuard:
    """Count XLA compilations inside a ``with`` scope.

    ``log`` (a :class:`~citizensassemblies_tpu.utils.logging.RunLog`) receives
    the count as the phase counter ``xla_compiles_<name>`` on exit, so the
    number rides the same in-band channel as the warm-start/overlap counters.
    ``max_compiles`` asserts a bound: exceeding it raises
    :class:`GuardViolation` on exit (after the count is logged) — the
    bench/test form of "this phase must not recompile per round".

    Guards nest; each counts independently. The count includes *every* XLA
    compile in scope (eager ops compiling a new shape too), which is the
    honest metric — a recompile is paid wherever it comes from.
    """

    def __init__(
        self,
        name: str = "phase",
        log=None,
        max_compiles: Optional[int] = None,
    ):
        self.name = name
        self.log = log
        self.max_compiles = max_compiles
        self.count = 0
        #: compiles attributed per core label (``compiling_as`` scopes);
        #: compiles outside any label land under "unattributed"
        self.by_name: dict = {}

    def __enter__(self) -> "CompilationGuard":
        _install_listener()
        self.count = 0
        self.by_name = {}
        with _lock:
            _active_guards.append(self)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        with _lock:
            try:
                _active_guards.remove(self)
            except ValueError:  # pragma: no cover - double exit
                pass
        if self.log is not None and self.count:
            self.log.count(f"xla_compiles_{self.name}", self.count)
        if (
            exc_type is None
            and self.max_compiles is not None
            and self.count > self.max_compiles
        ):
            blame = ", ".join(
                f"{k}={v}"
                for k, v in sorted(self.by_name.items(), key=lambda kv: -kv[1])
            )
            raise GuardViolation(
                f"{self.name}: {self.count} XLA compilations inside a scope "
                f"bounded at {self.max_compiles} — a shape left its padding "
                f"bucket or a jit is being rebuilt per call"
                + (f" (by core: {blame})" if blame else "")
            )


def _transfer_mode(cfg) -> str:
    """Resolve the transfer-guard mode from a Config (default: disallow)."""
    if cfg is None:
        return "disallow"
    return str(getattr(cfg, "transfer_guard", "disallow"))


@contextmanager
def no_implicit_transfers(cfg=None, mode: Optional[str] = None):
    """``jax.transfer_guard`` scope for a jitted hot call.

    Inside the scope, *implicit* host↔device transfers — a numpy array passed
    straight into a jitted call (re-uploaded through the TPU tunnel every
    invocation), a bare python scalar promoted by an eager op — raise
    (``"disallow"``) or warn (``"log"``). Explicit ``jnp.asarray`` /
    ``jax.device_put`` conversions remain legal, so the fix for a violation
    is always "materialize the operand once, outside the loop".

    ``mode`` overrides; otherwise ``cfg.transfer_guard`` decides, and
    ``"off"`` makes the whole context a no-op (the escape hatch for backends
    whose dispatch path transfers internally).
    """
    resolved = mode if mode is not None else _transfer_mode(cfg)
    if resolved in ("off", "", None):
        yield
        return
    import jax

    with jax.transfer_guard(resolved):
        yield
