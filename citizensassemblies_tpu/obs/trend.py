"""Bench trend gating: per-row deltas over the committed BENCH trajectory.

The repo commits one evidence artifact per PR round (``BENCH_r*.json``
offline rows, ``BENCH_serve_r*.json`` serving rows). Until now a perf
regression only surfaced when a human re-read those files; ``bench.py
--trend`` turns the trajectory into a GATE: for every named row whose
``seconds`` appears in ≥ 2 rounds, the latest value is compared against the
best (minimum) of the earlier rounds, and a ratio beyond the tolerance
fails the process — wired into CI after the smokes.

Robust parsing, because the committed artifacts are heterogeneous:

* ``BENCH_r*.json`` are driver wrappers ``{"n", "cmd", "rc", "tail",
  "parsed"}`` where ``parsed`` may be ``null`` and ``tail`` is a truncated
  window of the bench's output — rows are recovered by regex over whichever
  source is available (``"<row>": {"seconds": X``);
* ``BENCH_serve_r*.json`` are raw result lines ``{"metric", "value",
  "detail": {...}}`` — the serve wall-clock and latency quantiles become
  synthetic rows (``serve_wall_s``, ``serve_p50_s``, ``serve_p99_s``);
* ``BENCH_detail_r*.json`` (complete per-round results, when committed)
  parse directly.

Gate semantics (deliberately regression-only — improvements never fail):

* rows with a single data point are recorded as ``insufficient`` and never
  gate (a brand-new row family must land once before it is protected);
* rows whose latest value is under ``min_seconds`` never gate — sub-second
  rows are dispatch-floor noise (the bench's own ``floor_note``);
* a row fails when ``latest > tol × min(previous rounds)``. The default
  tolerance (``Config.obs_trend_tol``) leaves headroom for the committed
  trajectory's cross-container variance while flagging a 2× slowdown —
  both pinned by ``tests/test_obs.py``.
"""

from __future__ import annotations

import dataclasses
import json
import re
from pathlib import Path
from typing import Dict, List, Optional, Tuple

#: ``"row_name": {"seconds": 12.3`` anywhere in a (possibly truncated) JSON
#: fragment — the recovery parser for driver tails with ``parsed: null``
_ROW_RE = re.compile(r'"([A-Za-z0-9_]+)"\s*:\s*\{\s*"seconds"\s*:\s*([0-9.]+)')

_OFFLINE_RE = re.compile(r"BENCH_r(\d+)\.json$")
_DETAIL_RE = re.compile(r"BENCH_detail_r(\d+)\.json$")
_SERVE_RE = re.compile(r"BENCH_serve_r(\d+)\.json$")
_KERNELS_RE = re.compile(r"BENCH_kernels_r(\d+)\.json$")
_ROOFLINE_RE = re.compile(r"ROOFLINE_r(\d+)\.json$")
_CHURN_RE = re.compile(r"BENCH_churn_r(\d+)\.json$")
_COLDBOOT_RE = re.compile(r"BENCH_coldboot_r(\d+)\.json$")
_FLEET_RE = re.compile(r"BENCH_fleet_r(\d+)\.json$")


@dataclasses.dataclass
class TrendRow:
    """One row's trajectory and verdict."""

    name: str
    points: List[Tuple[int, float]]  # (round, seconds), round-ascending
    status: str  # "ok" | "regression" | "insufficient" | "floor"
    latest: Optional[float] = None
    best_prior: Optional[float] = None
    ratio: Optional[float] = None


@dataclasses.dataclass
class TrendReport:
    rows: List[TrendRow]
    tol: float
    min_seconds: float
    rounds_seen: List[int]

    @property
    def failures(self) -> List[TrendRow]:
        return [r for r in self.rows if r.status == "regression"]

    @property
    def ok(self) -> bool:
        return not self.failures

    def as_json(self) -> dict:
        return {
            "trend_ok": self.ok,
            "tol": self.tol,
            "min_seconds": self.min_seconds,
            "rounds_seen": self.rounds_seen,
            "schema_version": 1,
            "rows": [
                {
                    "name": r.name,
                    "status": r.status,
                    "points": [[rd, v] for rd, v in r.points],
                    "latest": r.latest,
                    "best_prior": r.best_prior,
                    "ratio": r.ratio,
                }
                for r in self.rows
            ],
            "failures": [r.name for r in self.failures],
        }


def _rows_from_text(text: str) -> Dict[str, float]:
    """Regex row recovery over an arbitrary (possibly truncated) fragment.
    Last occurrence wins, matching JSON's duplicate-key behavior."""
    out: Dict[str, float] = {}
    for m in _ROW_RE.finditer(text):
        out[m.group(1)] = float(m.group(2))
    return out


def _load_offline(path: Path) -> Dict[str, float]:
    try:
        doc = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return {}
    if not isinstance(doc, dict):
        return {}
    if isinstance(doc.get("detail"), dict):  # a BENCH_detail/raw result file
        return _rows_from_text(json.dumps(doc["detail"]))
    parsed = doc.get("parsed")
    if isinstance(parsed, dict):
        return _rows_from_text(json.dumps(parsed.get("detail", parsed)))
    tail = doc.get("tail")
    if isinstance(tail, str):
        return _rows_from_text(tail)
    return {}


def _load_serve(path: Path) -> Dict[str, float]:
    try:
        doc = json.loads(path.read_text(encoding="utf-8"))
    except (OSError, ValueError):
        return {}
    if not isinstance(doc, dict):
        return {}
    if "tail" in doc and not isinstance(doc.get("parsed"), dict):
        # driver-wrapped serve row: recover what the window kept
        text = doc["tail"] if isinstance(doc.get("tail"), str) else ""
        rows = {}
        m = re.search(r'"p50_latency_s"\s*:\s*([0-9.]+)', text)
        if m:
            rows["serve_p50_s"] = float(m.group(1))
        m = re.search(r'"p99_latency_s"\s*:\s*([0-9.]+)', text)
        if m:
            rows["serve_p99_s"] = float(m.group(1))
        return rows
    if isinstance(doc.get("parsed"), dict):
        doc = doc["parsed"]
    detail = doc.get("detail", {}) if isinstance(doc.get("detail"), dict) else {}
    rows: Dict[str, float] = {}
    if isinstance(doc.get("value"), (int, float)):
        rows["serve_wall_s"] = float(doc["value"])
    for src, dst in (
        ("p50_latency_s", "serve_p50_s"),
        ("p99_latency_s", "serve_p99_s"),
    ):
        if isinstance(detail.get(src), (int, float)):
            rows[dst] = float(detail[src])
    return rows


def collect_series(root) -> Tuple[Dict[str, List[Tuple[int, float]]], List[int]]:
    """Scan ``root`` for the committed BENCH artifacts and assemble
    per-row ``[(round, seconds), …]`` series (round-ascending). A
    ``BENCH_detail_rNN.json`` supersedes the driver wrapper of the same
    round (it is the complete, untruncated result)."""
    root = Path(root)
    by_round: Dict[int, Dict[str, float]] = {}
    detail_rounds: set = set()
    for path in sorted(root.glob("BENCH_detail_r*.json")):
        m = _DETAIL_RE.search(path.name)
        if m:
            rows = _load_offline(path)
            if rows:
                rnd = int(m.group(1))
                by_round.setdefault(rnd, {}).update(rows)
                detail_rounds.add(rnd)
    for path in sorted(root.glob("BENCH_r*.json")):
        m = _OFFLINE_RE.search(path.name)
        if m and int(m.group(1)) not in detail_rounds:
            rows = _load_offline(path)
            if rows:
                by_round.setdefault(int(m.group(1)), {}).update(rows)
    for path in sorted(root.glob("BENCH_serve_r*.json")):
        m = _SERVE_RE.search(path.name)
        if m:
            rows = _load_serve(path)
            if rows:
                by_round.setdefault(int(m.group(1)), {}).update(rows)
    for path in sorted(root.glob("BENCH_kernels_r*.json")):
        # kernel microbench family (bench.py --kernels): same
        # {"detail": {row: {"seconds": …}}} schema as the detail files
        m = _KERNELS_RE.search(path.name)
        if m:
            rows = _load_offline(path)
            if rows:
                by_round.setdefault(int(m.group(1)), {}).update(rows)
    for path in sorted(root.glob("ROOFLINE_r*.json")):
        # graftscope roofline family (bench.py --roofline): per-core
        # dispatch seconds under {"detail": {"roofline_<core>": …}}
        m = _ROOFLINE_RE.search(path.name)
        if m:
            rows = _load_offline(path)
            if rows:
                by_round.setdefault(int(m.group(1)), {}).update(rows)
    for path in sorted(root.glob("BENCH_churn_r*.json")):
        # graftdelta churn family (bench.py --churn): per-edit-class delta
        # medians + the sampled from-scratch arm, same detail schema
        m = _CHURN_RE.search(path.name)
        if m:
            rows = _load_offline(path)
            if rows:
                by_round.setdefault(int(m.group(1)), {}).update(rows)
    for path in sorted(root.glob("BENCH_coldboot_r*.json")):
        # graftboot coldboot family (bench.py --coldboot): fresh-process
        # boot-to-first-certified-result wall clock, cached vs uncached
        m = _COLDBOOT_RE.search(path.name)
        if m:
            rows = _load_offline(path)
            if rows:
                by_round.setdefault(int(m.group(1)), {}).update(rows)
    for path in sorted(root.glob("BENCH_fleet_r*.json")):
        # graftfleet family (bench.py --fleet): open-loop fleet drive /
        # serial-reference / whole-harness wall clocks, same detail schema
        m = _FLEET_RE.search(path.name)
        if m:
            rows = _load_offline(path)
            if rows:
                by_round.setdefault(int(m.group(1)), {}).update(rows)
    series: Dict[str, List[Tuple[int, float]]] = {}
    for rnd in sorted(by_round):
        for name, value in by_round[rnd].items():
            series.setdefault(name, []).append((rnd, value))
    return series, sorted(by_round)


def trend_gate(
    root,
    tol: Optional[float] = None,
    min_seconds: float = 1.0,
) -> TrendReport:
    """Run the gate over the committed series under ``root``.

    ``tol`` defaults to ``Config.obs_trend_tol`` — the single knob shared
    with the README table (R6)."""
    if tol is None:
        from citizensassemblies_tpu.utils.config import default_config

        tol = float(default_config().obs_trend_tol)
    series, rounds = collect_series(root)
    rows: List[TrendRow] = []
    for name in sorted(series):
        points = series[name]
        if len(points) < 2:
            rows.append(TrendRow(name=name, points=points, status="insufficient"))
            continue
        latest = points[-1][1]
        best_prior = min(v for _r, v in points[:-1])
        ratio = latest / max(best_prior, 1e-9)
        if latest < min_seconds:
            status = "floor"
        elif latest > tol * best_prior:
            status = "regression"
        else:
            status = "ok"
        rows.append(
            TrendRow(
                name=name,
                points=points,
                status=status,
                latest=latest,
                best_prior=best_prior,
                ratio=round(ratio, 3),
            )
        )
    return TrendReport(rows=rows, tol=tol, min_seconds=min_seconds, rounds_seen=rounds)
