"""graftscope memory ledger: per-phase device-memory accounting.

The ledger answers the question the span tracer cannot: not *when* a phase
ran but *what it left resident*. Three sources, all read-only:

* ``jax.live_arrays()`` — every live device array's ``nbytes``, the
  backend-independent number (works on the CPU CI backend where
  ``memory_stats`` is absent);
* ``device.memory_stats()`` — allocator truth (``bytes_in_use``,
  ``peak_bytes_in_use``) on backends that expose it (TPU/GPU); the per-run
  HBM high watermark is the max over both sources;
* the :class:`~citizensassemblies_tpu.utils.memo.LRU` instance registry —
  every bounded cache in the process (tenant warm slots, ELL packs, result
  memos, jit memo tables), walked shallowly to attribute resident bytes to
  the owning subsystem or tenant.

Tri-stated by ``Config.obs_memory`` exactly like ``obs_trace``:

* ``False`` — hard off: the dispatch hook does one attribute read and
  never touches this module; bit-identical, zero allocation;
* ``None`` (auto) — snapshots record whenever a caller installs a ledger
  (:func:`use_ledger`), e.g. the bench around its warm flagship reps;
* ``True`` — the service additionally creates a per-request ledger and
  stamps its summary (``memory`` block) onto the request audit.

Snapshots are pure observation — no transfers, no deletes, no numerics —
which is what keeps the obs-off/on bitwise-identity contract testable.
"""

from __future__ import annotations

import time
from contextlib import contextmanager
from contextvars import ContextVar
from typing import Any, Dict, List, Optional

MEMORY_SCHEMA_VERSION = 1

_AMBIENT: ContextVar[Optional["MemoryLedger"]] = ContextVar(
    "citizens_memory_ledger", default=None
)


def ambient_ledger() -> Optional["MemoryLedger"]:
    """The ledger installed on this (thread's) context, if any."""
    return _AMBIENT.get()


@contextmanager
def use_ledger(ledger: Optional["MemoryLedger"]):
    """Install ``ledger`` as the ambient snapshot target for the block."""
    token = _AMBIENT.set(ledger)
    try:
        yield ledger
    finally:
        _AMBIENT.reset(token)


def ledger_enabled(cfg) -> bool:
    """The dispatch-hook gate: ``obs_memory`` hard-off wins over an
    installed ledger (mirrors the ``obs_trace`` contract)."""
    return cfg is None or getattr(cfg, "obs_memory", None) is not False


def live_array_bytes() -> Dict[str, int]:
    """Total bytes and count of live jax arrays (skips deleted handles)."""
    import jax

    total = 0
    count = 0
    for arr in jax.live_arrays():
        try:
            if arr.is_deleted():
                continue
            total += int(arr.nbytes)
            count += 1
        except Exception:  # noqa: BLE001 - a dying handle is not an error
            continue
    return {"live_bytes": total, "live_arrays": count}


def device_memory_stats() -> Dict[str, int]:
    """Summed allocator stats across local devices; ``{}`` on backends
    (CPU) that expose none — callers treat the keys as optional."""
    import jax

    in_use = 0
    peak = 0
    seen = False
    for dev in jax.local_devices():
        try:
            stats = dev.memory_stats()
        except Exception:  # noqa: BLE001 - backend without allocator stats
            stats = None
        if not stats:
            continue
        seen = True
        in_use += int(stats.get("bytes_in_use", 0))
        peak += int(stats.get("peak_bytes_in_use", stats.get("bytes_in_use", 0)))
    return {"hbm_bytes_in_use": in_use, "hbm_peak_bytes": peak} if seen else {}


def _shallow_nbytes(value: Any, depth: int = 3) -> int:
    """Bytes held by arrays reachable from ``value`` within ``depth`` hops
    through containers/dataclass fields. Shallow on purpose: cache entries
    are small pytrees (packs, warm slots, result records), and a bounded
    walk cannot be wedged by cyclic or exotic objects."""
    if value is None or depth < 0:
        return 0
    nbytes = getattr(value, "nbytes", None)
    if isinstance(nbytes, int):
        return nbytes
    if isinstance(value, dict):
        return sum(_shallow_nbytes(v, depth - 1) for v in value.values())
    if isinstance(value, (list, tuple)):
        return sum(_shallow_nbytes(v, depth - 1) for v in value)
    fields = getattr(value, "__dict__", None)
    if isinstance(fields, dict):
        return sum(_shallow_nbytes(v, depth - 1) for v in fields.values())
    return 0


def owner_attribution() -> Dict[str, int]:
    """Resident bytes per owning subsystem, from the LRU instance registry.

    Keys are the LRU entry owners (``tenant:<name>`` for session state) or
    the cache's own name; values are the shallow byte totals of the cached
    entries. This is attribution of the *cached* population — the working
    set a request allocates and frees inside one solve shows up in the
    snapshot deltas instead.
    """
    from citizensassemblies_tpu.utils.memo import live_caches

    by_owner: Dict[str, int] = {}
    for cache in live_caches():
        try:
            items = [(k, cache._d[k]) for k in list(cache._d)]
        except Exception:  # noqa: BLE001 - cache mutating under us
            continue
        for key, entry in items:
            owner = cache._owners.get(key) or cache.name or "unnamed"
            by_owner[owner] = by_owner.get(owner, 0) + _shallow_nbytes(entry)
    return by_owner


class MemoryLedger:
    """Per-run (or per-request) accountant of device-memory snapshots.

    ``snapshot(phase)`` records one row; :meth:`stamp` summarizes the run
    for audit/bench blocks; :meth:`series` exposes the live-bytes
    trajectory for the leak sentinel.
    """

    def __init__(self, name: str = "run", attribute_owners: bool = True):
        self.name = name
        self.attribute_owners = attribute_owners
        self.records: List[Dict[str, Any]] = []
        self.high_watermark_bytes = 0
        self._t0 = time.perf_counter()

    def snapshot(self, phase: str) -> Dict[str, Any]:
        rec: Dict[str, Any] = {
            "phase": phase,
            "t_s": round(time.perf_counter() - self._t0, 6),
        }
        rec.update(live_array_bytes())
        rec.update(device_memory_stats())
        resident = max(rec["live_bytes"], rec.get("hbm_bytes_in_use", 0))
        peak = max(resident, rec.get("hbm_peak_bytes", 0))
        if peak > self.high_watermark_bytes:
            self.high_watermark_bytes = peak
        self.records.append(rec)
        return rec

    def series(self, phase: Optional[str] = None) -> List[int]:
        """Live-byte trajectory, optionally filtered to one phase name."""
        return [
            r["live_bytes"]
            for r in self.records
            if phase is None or r["phase"] == phase
        ]

    def stamp(self) -> Dict[str, Any]:
        """The ``memory`` block for bench rows and service audit stamps."""
        out: Dict[str, Any] = {
            "schema_version": MEMORY_SCHEMA_VERSION,
            "ledger": self.name,
            "snapshots": len(self.records),
            "high_watermark_bytes": self.high_watermark_bytes,
        }
        if self.records:
            last = self.records[-1]
            out["live_bytes_last"] = last["live_bytes"]
            out["live_arrays_last"] = last["live_arrays"]
            if "hbm_bytes_in_use" in last:
                out["hbm_bytes_in_use"] = last["hbm_bytes_in_use"]
        if self.attribute_owners:
            owners = owner_attribution()
            out["owners"] = {
                k: owners[k] for k in sorted(owners, key=owners.get, reverse=True)
            }
        return out


def leak_verdict(series: List[int]) -> bool:
    """True (leak) when live bytes grew STRICTLY monotonically across ≥ 3
    warm repetitions — a warm rep re-entering compiled code should reach a
    steady state; unbroken growth means something accretes per call. One
    flat or descending step anywhere clears the verdict (caches settling
    on their cap plateau are not leaks)."""
    if len(series) < 3:
        return False
    return all(b > a for a, b in zip(series, series[1:]))
