"""graftscope SLO engine: declarative objectives over the serving metrics.

graftserve reports raw p50/p99 and failure counters; an operator needs the
next layer: "is tenant X inside its latency objective, and how fast is it
burning error budget?" This module turns ``Config.obs_slo_spec`` — a
one-line declarative spec like ``latency_p99:20s,error_rate:0.01`` — into
that evaluation:

* **objectives** — ``latency_pNN:<seconds>`` (the NN-th percentile of
  request sojourn must stay under the target) and ``error_rate:<frac>``
  (the failure fraction must stay under the target). A ``tenant/``-prefixed
  entry (``civic/latency_p99:5s``) overrides the global objective for that
  tenant; every tenant is additionally evaluated against the global
  entries, so per-tenant SLOs need no per-tenant spec lines.
* **multi-window burn rate** — for each objective and each window (1 min /
  5 min / 1 h by default), the ratio of observed badness to the budget the
  objective allows: error burn = observed error rate / target rate;
  latency burn = fraction of requests over the latency target / allowed
  tail fraction (1% for p99). Burn > 1 means the budget is being consumed
  faster than sustainable over that window — the standard multi-window
  alerting shape, computed here rather than in an external system.
* **breaches** — an objective whose full-window observation violates its
  target. The service streams each breach transition as a ``("slo", …)``
  event into every open ResultChannel and counts it
  (``graftserve_slo_breach_total``).

The engine is stdlib-only and lock-guarded (service worker threads record
completions concurrently); the event history is bounded by the largest
window, so a long-lived service cannot grow it without bound.
"""

from __future__ import annotations

import dataclasses
import re
import threading
import time
from typing import Any, Dict, List, Optional, Tuple

SLO_SCHEMA_VERSION = 1

#: default burn-rate windows (seconds): fast / medium / slow
DEFAULT_WINDOWS: Tuple[float, ...] = (60.0, 300.0, 3600.0)

_LATENCY_RE = re.compile(r"^latency_p(\d{1,2})$")


def _parse_target(objective: str, raw: str) -> float:
    """Target value with unit handling: ``20s``/``150ms`` for latency
    objectives, a bare fraction for rates."""
    raw = raw.strip()
    if raw.endswith("ms"):
        return float(raw[:-2]) / 1e3
    if raw.endswith("s"):
        return float(raw[:-1])
    return float(raw)


def parse_slo_spec(spec: str) -> Dict[Optional[str], Dict[str, float]]:
    """``"latency_p99:20s,error_rate:0.01,civic/latency_p99:5s"`` →
    ``{None: {...global...}, "civic": {...overrides...}}``. Raises
    ``ValueError`` on malformed entries — a typo'd SLO spec must fail the
    service at construction, not silently never gate."""
    out: Dict[Optional[str], Dict[str, float]] = {}
    for entry in spec.split(","):
        entry = entry.strip()
        if not entry:
            continue
        if ":" not in entry:
            raise ValueError(f"SLO entry {entry!r} has no ':<target>'")
        name, raw = entry.split(":", 1)
        tenant: Optional[str] = None
        if "/" in name:
            tenant, name = name.split("/", 1)
        name = name.strip()
        if name != "error_rate" and not _LATENCY_RE.match(name):
            raise ValueError(
                f"unknown SLO objective {name!r} (want latency_pNN or error_rate)"
            )
        out.setdefault(tenant, {})[name] = _parse_target(name, raw)
    return out


def _percentile(values: List[float], q: float) -> float:
    """Nearest-rank percentile (q in [0, 100]) — the conservative estimator
    for small serving samples; matches the bench's quantile convention."""
    if not values:
        return 0.0
    ordered = sorted(values)
    rank = max(0, min(len(ordered) - 1, int(round(q / 100.0 * (len(ordered) - 1)))))
    return ordered[rank]


@dataclasses.dataclass
class SloEvent:
    t: float
    tenant: str
    latency_s: float
    ok: bool


class SloEngine:
    """Evaluates a parsed spec over a bounded stream of request outcomes."""

    def __init__(
        self,
        spec: str,
        windows: Tuple[float, ...] = DEFAULT_WINDOWS,
        clock=time.monotonic,
    ):
        self.spec = parse_slo_spec(spec)
        self.windows = tuple(sorted(windows))
        self._clock = clock
        self._events: List[SloEvent] = []
        self._lock = threading.Lock()
        self._breached: set = set()  # (tenant, objective) currently breaching

    def record(self, tenant: str, latency_s: float, ok: bool) -> None:
        """One terminal request outcome (success, failure, or deadline)."""
        now = self._clock()
        horizon = now - self.windows[-1]
        with self._lock:
            self._events.append(
                SloEvent(t=now, tenant=tenant, latency_s=float(latency_s), ok=ok)
            )
            # trim anything older than the slowest window (bounded history)
            if self._events and self._events[0].t < horizon:
                self._events = [e for e in self._events if e.t >= horizon]

    def _objectives_for(self, tenant: str) -> Dict[str, float]:
        merged = dict(self.spec.get(None, {}))
        merged.update(self.spec.get(tenant, {}))
        return merged

    @staticmethod
    def _observe(
        events: List[SloEvent], objective: str, target: float
    ) -> Tuple[float, float]:
        """(observed value, burn rate) of one objective over ``events``."""
        if objective == "error_rate":
            observed = sum(1 for e in events if not e.ok) / max(len(events), 1)
            return observed, observed / max(target, 1e-12)
        q = float(_LATENCY_RE.match(objective).group(1))
        lat = [e.latency_s for e in events]
        observed = _percentile(lat, q)
        allowed_tail = max(1.0 - q / 100.0, 1e-12)
        over = sum(1 for v in lat if v > target) / max(len(lat), 1)
        return observed, over / allowed_tail

    def evaluate(self) -> Dict[str, Any]:
        """The full SLO report: per tenant × objective, the full-history
        observation, per-window burn rates, and the breach verdict."""
        with self._lock:
            events = list(self._events)
        now = self._clock()
        tenants = sorted({e.tenant for e in events})
        report: Dict[str, Any] = {
            "schema_version": SLO_SCHEMA_VERSION,
            "spec": {
                (t if t is not None else "*"): dict(objs)
                for t, objs in self.spec.items()
            },
            "windows_s": list(self.windows),
            "events": len(events),
            "tenants": {},
            "breaches": [],
        }
        for tenant in tenants:
            tenant_events = [e for e in events if e.tenant == tenant]
            objectives = self._objectives_for(tenant)
            tenant_block: Dict[str, Any] = {}
            for objective, target in sorted(objectives.items()):
                observed, _burn = self._observe(tenant_events, objective, target)
                burns = {}
                for win in self.windows:
                    recent = [e for e in tenant_events if e.t >= now - win]
                    if recent:
                        _obs, burn = self._observe(recent, objective, target)
                        burns[f"{int(win)}s"] = round(burn, 4)
                ok = observed <= target
                tenant_block[objective] = {
                    "target": target,
                    "observed": round(observed, 6),
                    "ok": ok,
                    "burn_rates": burns,
                }
                if not ok:
                    report["breaches"].append(
                        {
                            "tenant": tenant,
                            "objective": objective,
                            "target": target,
                            "observed": round(observed, 6),
                            "burn_rates": burns,
                        }
                    )
            report["tenants"][tenant] = tenant_block
        report["slo_ok"] = not report["breaches"]
        return report

    def new_breaches(self) -> List[Dict[str, Any]]:
        """Breaches that TRANSITIONED since the last call — what the service
        streams as ``("slo", …)`` events (steady-state breaching does not
        re-emit every request; recovery re-arms the transition)."""
        report = self.evaluate()
        current = {(b["tenant"], b["objective"]): b for b in report["breaches"]}
        with self._lock:
            fresh = [current[k] for k in sorted(current) if k not in self._breached]
            self._breached = set(current)
        return fresh

    def window_burns(self, window_s: float) -> Dict[Tuple[str, str], float]:
        """Burn rate of every tenant × objective over the last ``window_s``
        seconds only — the fast signal the load-management policy keys on.
        An empty window (no events) yields an empty dict: burns age out with
        their events, so a fully-shedding service can still observe recovery
        without needing fresh terminal outcomes."""
        now = self._clock()
        with self._lock:
            events = [e for e in self._events if e.t >= now - window_s]
        out: Dict[Tuple[str, str], float] = {}
        for tenant in sorted({e.tenant for e in events}):
            tenant_events = [e for e in events if e.tenant == tenant]
            for objective, target in sorted(self._objectives_for(tenant).items()):
                _obs, burn = self._observe(tenant_events, objective, target)
                out[(tenant, objective)] = round(burn, 4)
        return out


class SloLoadPolicy:
    """graftfleet load management: the SLO engine closed into an actuator.

    PR 15 made the engine *observe-only* — breaches stream as events and an
    operator reacts. A fleet under open-loop load cannot wait for an
    operator: offered rate does not slow down because the service is
    drowning. This policy closes the loop with the two levers the stack
    already certifies:

    * **admission shedding** — while the fast-window burn rate of any
      tenant × objective sits at/above ``serve_shed_burn``, new submissions
      are rejected with a typed ``("error", {"kind": "ShedRejection", …})``
      terminal event carrying an audit stub (tenant, burn, rung,
      timestamp), counted ``graftserve_shed_total``. Shedding load is the
      only move that helps a queue whose arrival rate exceeds service rate.
    * **degradation-ladder descent** — each sustained breach interval walks
      the service-level ladder one rung (megakernel→chained, device
      pricing→host, ELL→dense by default: ``serve_shed_max_rungs=3`` stops
      before the rungs that change the batching/mesh execution shape), so
      surviving requests run the cheaper certified path. Rungs are applied
      to the *service* config for every admitted request, independently of
      the per-request retry ladder.

    Recovery RE-ARMS: when every fast-window burn falls to/below
    ``serve_shed_recover`` (hysteresis band below the shed threshold — or
    the window empties entirely), shedding switches off, the ladder resets
    to rung 0, and the transition is counted
    ``graftserve_shed_rearm_total``. All state transitions happen inside
    :meth:`update`, which both the submit path and the completion path
    call, so recovery does not require fresh terminal outcomes.

    Thread-safe; stdlib-only except a lazy import of the degradation ladder
    table when a rung is actually applied.
    """

    def __init__(self, engine: SloEngine, cfg, clock=time.monotonic):
        self.engine = engine
        self.burn_open = float(getattr(cfg, "serve_shed_burn", 2.0))
        self.burn_close = float(getattr(cfg, "serve_shed_recover", 0.5))
        self.window_s = float(getattr(cfg, "serve_shed_window_s", 60.0))
        self.max_rungs = int(getattr(cfg, "serve_shed_max_rungs", 3))
        #: a sustained breach descends one further rung per cooldown, so a
        #: single burst cannot slam the ladder to the bottom instantly
        self.cooldown_s = max(self.window_s / 4.0, 1e-6)
        self._clock = clock
        self._lock = threading.Lock()
        self.shedding = False
        self.rung = 0
        self.worst_burn = 0.0
        self.shed_total = 0
        self.rearm_total = 0
        self.descend_total = 0
        self._last_descent: Optional[float] = None

    def update(self) -> float:
        """Evaluate the fast window and run the state machine; returns the
        worst observed burn. Called on every submit and every completion."""
        burns = self.engine.window_burns(self.window_s)
        worst = max(burns.values()) if burns else 0.0
        now = self._clock()
        with self._lock:
            self.worst_burn = worst
            if worst >= self.burn_open:
                if not self.shedding:
                    self.shedding = True
                    self._descend(now)
                elif (
                    self._last_descent is not None
                    and now - self._last_descent >= self.cooldown_s
                ):
                    self._descend(now)
            elif worst <= self.burn_close and self.shedding:
                self.shedding = False
                self.rung = 0
                self._last_descent = None
                self.rearm_total += 1
        return worst

    def _descend(self, now: float) -> None:
        if self.rung < self.max_rungs:
            self.rung += 1
            self.descend_total += 1
        self._last_descent = now

    def shed(self, tenant: str, request_id: str) -> Dict[str, Any]:
        """Count one shed admission and return its audit stub — the typed
        rejection ships evidence of WHY, not a bare refusal."""
        with self._lock:
            self.shed_total += 1
            return {
                "tenant": tenant,
                "request_id": request_id,
                "worst_burn": round(self.worst_burn, 4),
                "burn_threshold": self.burn_open,
                "rung": self.rung,
                "window_s": self.window_s,
                "t": self._clock(),
            }

    def degraded(self, cfg, log=None):
        """``cfg`` with the policy's current rungs applied (cumulative, in
        ladder order). Rung 0 returns ``cfg`` unchanged — the armed-but-idle
        policy is bit-identical to no policy."""
        with self._lock:
            rung = self.rung
        if rung <= 0:
            return cfg
        from citizensassemblies_tpu.robust.policy import DegradationLadder

        ladder = DegradationLadder()
        for _ in range(rung):
            cfg = ladder.degrade(cfg, log)
        return cfg

    def stamp(self) -> Dict[str, Any]:
        """Policy state snapshot for reports and the fleet rollup."""
        with self._lock:
            return {
                "shedding": self.shedding,
                "rung": self.rung,
                "worst_burn": round(self.worst_burn, 4),
                "shed_total": self.shed_total,
                "rearm_total": self.rearm_total,
                "descend_total": self.descend_total,
            }
