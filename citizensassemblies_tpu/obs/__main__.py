"""graftscope trace CLI: offline analysis of exported Chrome traces.

``python -m citizensassemblies_tpu.obs <trace.json>`` reads the trace
documents the repo already exports (``export_chrome_trace`` — the
``artifacts/trace_*.json`` smoke/CI artifacts) and answers the questions a
trace viewer makes you eyeball:

* **critical path** — from the heaviest root span, descend into the
  largest child at every level: the chain of spans that bounds the run's
  wall time, with each hop's share of its parent;
* **self time** — per span-name aggregation of exclusive time (duration
  minus the union of child intervals): where the time actually went, not
  which phase happened to be on the stack;
* **fusion timeline** — the cross-request batcher view: overlapping
  ``batch_window`` spans from different request lanes (pids) are the
  windows in which requests actually fused into one dispatch;
* ``--diff A B`` — phase-by-phase self-time comparison of two traces: the
  trend gate says *that* a row regressed, the diff says *which phase* grew.

Stdlib-only (no jax): the CLI must run on a laptop against a CI artifact.
``--json`` emits the full analysis as one machine-readable document.
"""

from __future__ import annotations

import argparse
import json
import sys
from typing import Any, Dict, List, Optional, Tuple


def _load_spans(path: str) -> Tuple[List[dict], Dict[int, str]]:
    """(spans, pid→lane-name) from one exported trace document. Spans keep
    the export's µs clock: ``{pid, tid, name, ts, dur, span_id, parent_id}``."""
    with open(path, "r", encoding="utf-8") as fh:
        doc = json.load(fh)
    events = doc.get("traceEvents", []) if isinstance(doc, dict) else []
    lanes: Dict[int, str] = {}
    spans: List[dict] = []
    for ev in events:
        if not isinstance(ev, dict):
            continue
        if ev.get("ph") == "M" and ev.get("name") == "process_name":
            lanes[ev.get("pid", 0)] = ev.get("args", {}).get("name", "?")
        elif ev.get("ph") == "X":
            args = ev.get("args", {}) or {}
            spans.append(
                {
                    "pid": ev.get("pid", 0),
                    "tid": ev.get("tid", 0),
                    "name": ev.get("name", "?"),
                    "ts": float(ev.get("ts", 0.0)),
                    "dur": float(ev.get("dur", 0.0)),
                    "span_id": args.get("span_id"),
                    "parent_id": args.get("parent_id"),
                }
            )
    return spans, lanes


def _children_index(spans: List[dict]) -> Dict[Tuple[int, Any], List[dict]]:
    """``(pid, parent span_id) → children`` — span ids are per-tracer, so
    the pid is part of the key."""
    index: Dict[Tuple[int, Any], List[dict]] = {}
    for sp in spans:
        if sp["parent_id"] is not None:
            index.setdefault((sp["pid"], sp["parent_id"]), []).append(sp)
    return index


def _union_us(intervals: List[Tuple[float, float]]) -> float:
    if not intervals:
        return 0.0
    intervals = sorted(intervals)
    total = 0.0
    lo, hi = intervals[0]
    for a, b in intervals[1:]:
        if a > hi:
            total += hi - lo
            lo, hi = a, b
        else:
            hi = max(hi, b)
    return total + (hi - lo)


def critical_path(spans: List[dict]) -> List[dict]:
    """Heaviest-descent chain from the longest root span: at each node,
    follow the child with the largest duration. Each hop carries its share
    of the parent; the residual (parent minus heaviest child) is that
    level's self + sibling time."""
    roots = [s for s in spans if s["parent_id"] is None]
    if not roots:
        return []
    index = _children_index(spans)
    node = max(roots, key=lambda s: s["dur"])
    path = []
    parent_dur: Optional[float] = None
    while node is not None:
        path.append(
            {
                "name": node["name"],
                "pid": node["pid"],
                "dur_ms": node["dur"] / 1e3,
                "of_parent": (
                    node["dur"] / parent_dur if parent_dur else 1.0
                ),
            }
        )
        parent_dur = node["dur"] or None
        kids = index.get((node["pid"], node["span_id"]), [])
        node = max(kids, key=lambda s: s["dur"]) if kids else None
    return path


def self_times(spans: List[dict]) -> Dict[str, Dict[str, float]]:
    """Per-name aggregation: count, total duration, exclusive (self) time
    in milliseconds."""
    index = _children_index(spans)
    out: Dict[str, Dict[str, float]] = {}
    for sp in spans:
        kids = index.get((sp["pid"], sp["span_id"]), [])
        covered = _union_us(
            [
                (
                    max(k["ts"], sp["ts"]),
                    min(k["ts"] + k["dur"], sp["ts"] + sp["dur"]),
                )
                for k in kids
                if k["ts"] + k["dur"] > sp["ts"] and k["ts"] < sp["ts"] + sp["dur"]
            ]
        )
        agg = out.setdefault(sp["name"], {"count": 0, "total_ms": 0.0, "self_ms": 0.0})
        agg["count"] += 1
        agg["total_ms"] += sp["dur"] / 1e3
        agg["self_ms"] += max(sp["dur"] - covered, 0.0) / 1e3
    for agg in out.values():
        agg["total_ms"] = round(agg["total_ms"], 3)
        agg["self_ms"] = round(agg["self_ms"], 3)
    return out


def fusion_timeline(
    spans: List[dict], lanes: Dict[int, str], window_name: str = "batch_window"
) -> List[dict]:
    """Clusters of overlapping ``batch_window`` spans across request lanes.
    A cluster spanning ≥ 2 pids is a window in which the cross-request
    batcher actually fused work; single-lane clusters are windows that
    closed alone (the fusion-miss diagnostic)."""
    windows = sorted(
        (s for s in spans if s["name"] == window_name), key=lambda s: s["ts"]
    )
    clusters: List[dict] = []
    for sp in windows:
        end = sp["ts"] + sp["dur"]
        if clusters and sp["ts"] <= clusters[-1]["_end"]:
            cl = clusters[-1]
            cl["_end"] = max(cl["_end"], end)
            cl["lanes"].add(sp["pid"])
            cl["spans"] += 1
        else:
            clusters.append(
                {"_start": sp["ts"], "_end": end, "lanes": {sp["pid"]}, "spans": 1}
            )
    out = []
    for cl in clusters:
        out.append(
            {
                "start_ms": round(cl["_start"] / 1e3, 3),
                "dur_ms": round((cl["_end"] - cl["_start"]) / 1e3, 3),
                "spans": cl["spans"],
                "requests": sorted(lanes.get(p, str(p)) for p in cl["lanes"]),
                "fused": len(cl["lanes"]) >= 2,
            }
        )
    return out


def analyze(path: str) -> Dict[str, Any]:
    spans, lanes = _load_spans(path)
    return {
        "trace": path,
        "spans": len(spans),
        "lanes": len(lanes),
        "critical_path": critical_path(spans),
        "self_times": self_times(spans),
        "fusion_timeline": fusion_timeline(spans, lanes),
    }


def diff(path_a: str, path_b: str) -> Dict[str, Any]:
    """Phase-by-phase self-time comparison (B relative to A)."""
    a = self_times(_load_spans(path_a)[0])
    b = self_times(_load_spans(path_b)[0])
    rows = {}
    for name in sorted(set(a) | set(b)):
        sa = a.get(name, {}).get("self_ms", 0.0)
        sb = b.get(name, {}).get("self_ms", 0.0)
        rows[name] = {
            "a_self_ms": sa,
            "b_self_ms": sb,
            "delta_ms": round(sb - sa, 3),
            "ratio": round(sb / sa, 3) if sa > 0 else None,
        }
    return {"a": path_a, "b": path_b, "phases": rows}


def _print_report(report: Dict[str, Any], limit: int) -> None:
    print(f"trace: {report['trace']}  ({report['spans']} spans, "
          f"{report['lanes']} lanes)")
    print("\ncritical path (heaviest descent):")
    for i, hop in enumerate(report["critical_path"]):
        share = f"{hop['of_parent'] * 100.0:5.1f}%"
        print(f"  {'  ' * i}{hop['name']}  {hop['dur_ms']:.3f} ms  ({share} of parent)")
    ranked = sorted(
        report["self_times"].items(), key=lambda kv: kv[1]["self_ms"], reverse=True
    )
    print(f"\nself time by phase (top {limit}):")
    print(f"  {'phase':40s} {'count':>6s} {'total ms':>10s} {'self ms':>10s}")
    for name, agg in ranked[:limit]:
        print(
            f"  {name:40s} {agg['count']:6d} {agg['total_ms']:10.3f} "
            f"{agg['self_ms']:10.3f}"
        )
    fusion = report["fusion_timeline"]
    if fusion:
        fused = sum(1 for f in fusion if f["fused"])
        print(f"\nbatcher windows: {len(fusion)} ({fused} fused ≥2 requests)")
        for f in fusion:
            tag = "FUSED" if f["fused"] else "alone"
            print(
                f"  +{f['start_ms']:.1f} ms  {f['dur_ms']:.1f} ms  {tag}  "
                f"{', '.join(f['requests'])}"
            )


def _print_diff(report: Dict[str, Any], limit: int) -> None:
    print(f"diff: {report['a']}  →  {report['b']}  (self time per phase)")
    rows = sorted(
        report["phases"].items(),
        key=lambda kv: abs(kv[1]["delta_ms"]),
        reverse=True,
    )
    print(f"  {'phase':40s} {'A ms':>10s} {'B ms':>10s} {'Δ ms':>10s} {'ratio':>7s}")
    for name, row in rows[:limit]:
        ratio = f"{row['ratio']:.2f}" if row["ratio"] is not None else "new"
        print(
            f"  {name:40s} {row['a_self_ms']:10.3f} {row['b_self_ms']:10.3f} "
            f"{row['delta_ms']:+10.3f} {ratio:>7s}"
        )


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m citizensassemblies_tpu.obs",
        description="offline analyzer for exported grafttrace Chrome traces",
    )
    parser.add_argument("trace", help="trace JSON (export_chrome_trace output)")
    parser.add_argument(
        "--diff", metavar="OTHER", default=None,
        help="compare TRACE against OTHER phase-by-phase (self time)",
    )
    parser.add_argument("--json", action="store_true", help="emit JSON")
    parser.add_argument("--limit", type=int, default=20, help="table row cap")
    ns = parser.parse_args(argv)
    if ns.diff is not None:
        report = diff(ns.trace, ns.diff)
        if ns.json:
            print(json.dumps(report, indent=1))
        else:
            _print_diff(report, ns.limit)
    else:
        report = analyze(ns.trace)
        if ns.json:
            print(json.dumps(report, indent=1))
        else:
            _print_report(report, ns.limit)
    return 0


if __name__ == "__main__":
    sys.exit(main())
