"""grafttrace device-dispatch hooks: span-wrap the jitted hot calls.

Every hot core's public entry point wraps its device dispatch in
:func:`dispatch_span` (graftlint R8 checks the wiring against the IR-core
manifest). The hook is tri-stated by ``Config.obs_trace``:

* ``False`` — hard off: inert even with a tracer installed (one attribute
  read), bit-identical, zero allocation on the shared scope;
* ``None`` (auto) — a span records whenever a tracer is ambient (or rides
  the given ``log``), measuring the HOST-side dispatch window only: the
  call may return an unrealized device array, so the span is enqueue
  latency, which is the honest number for pipelined callers;
* ``True`` (the sampling mode, carried by ``Tracer.sample_device``) — the
  hook additionally ``jax.block_until_ready``-s whatever the caller stored
  in ``scope.out``, so the span measures device EXECUTION. Blocking is a
  wait, not a transfer — numerics, counters and guard semantics are
  untouched (the obs-on/off bit-identity test pins it) — but it serializes
  async pipelines, which is why it is opt-in.

Usage::

    with dispatch_span("lp_pdhg.pdhg_core", cfg=cfg, log=log, nv=nv) as ds:
        out = core(*operands)
        ds.out = out
"""

from __future__ import annotations

from contextlib import contextmanager

from citizensassemblies_tpu.obs.trace import _resolve


class DispatchScope:
    """Mutable slot the caller parks its device outputs in; the hook blocks
    on them at scope exit in sampling mode."""

    __slots__ = ("out",)

    def __init__(self):
        self.out = None


#: shared inert scope handed out when tracing is off — callers only ever
#: WRITE ``.out`` (never read), so sharing it across threads is harmless
#: and keeps the off path allocation-free
_INERT = DispatchScope()


@contextmanager
def dispatch_span(name: str, cfg=None, log=None, **attrs):
    # graftscope memory ledger: snapshot at the span boundary whenever a
    # ledger is ambient and ``obs_memory`` is not hard-off. Resolution is
    # one ContextVar read; the hard-off path is one attribute read.
    led = None
    if cfg is None or getattr(cfg, "obs_memory", None) is not False:
        from citizensassemblies_tpu.obs.memory import ambient_ledger

        led = ambient_ledger()
    if cfg is not None and getattr(cfg, "obs_trace", None) is False:
        yield _INERT
        if led is not None:
            led.snapshot(name)
        return
    tr = _resolve(log)
    if tr is None:
        if led is None:
            yield _INERT
            return
        yield _INERT
        led.snapshot(name)
        return
    scope = DispatchScope()
    # pod runs: every span carries its process index so merged multi-host
    # trace files separate into per-host lanes (0 on single-process runs;
    # lazy import keeps the obs layer free of a hard dist dependency)
    from citizensassemblies_tpu.dist.runtime import host_lane

    attrs.setdefault("host", host_lane())
    with tr.span(name, kind="dispatch", **attrs) as sp:
        yield scope
        if tr.sample_device and scope.out is not None:
            import jax

            jax.block_until_ready(scope.out)
            if sp is not None:
                sp.attrs["sampled"] = True
    if led is not None:
        led.snapshot(name)
