"""grafttrace typed metrics registry: Counter/Gauge/Timer/Histogram + labels.

Unifies the repo's three ad-hoc metric shapes — ``RunLog`` counter/timer
dicts, the hand-stamped bench gauges (``decomp_host_syncs``,
``lp_batch_*``, ``oracle_backend_*``) and ``utils/profiling``'s formatting
helpers — behind one registry with typed instruments and optional label
sets (tenant, phase, bucket shape).

Bit-compatibility contract: ``RunLog.count``/``gauge``/``timer`` delegate
here, and :meth:`MetricsRegistry.flat_counters` / :meth:`flat_timers`
reproduce the OLD dict semantics exactly —

* counters accumulate (``get + inc``), gauges are latest-wins, and the two
  share one value namespace (the old code kept both in ``_counters``, so a
  gauge write to a counter's name replaces it, and a later ``count`` on
  that name increments from the gauge value);
* timers live in their own namespace and accumulate float seconds;
* both accessors return DEFENSIVE COPIES taken under the registry lock
  (concurrent service requests count into shared engine logs — the
  no-lost-increment contract ``tests/test_service.py`` hammers).

Label cardinality is CAPPED per instrument (``max_label_sets``, wired to
``Config.obs_max_label_sets`` by the service): past the cap, new label sets
fold into a reserved overflow series instead of growing without bound — a
misbehaving label (request id, say) degrades to one series plus a visible
``label_overflow`` count, never an OOM.

Stdlib-only: importable from the lint tooling and every host-only path.
"""

from __future__ import annotations

import threading
from contextlib import contextmanager
from time import perf_counter
from typing import Any, Dict, List, Tuple

#: the reserved label set absorbing series beyond the cardinality cap
OVERFLOW_LABELS: Tuple[Tuple[str, str], ...] = (("overflow", "true"),)

#: default per-instrument label-set cap (Config.obs_max_label_sets mirrors
#: this default; the service passes its configured value through)
DEFAULT_MAX_LABEL_SETS = 64

#: default histogram bucket boundaries (seconds-flavored; override per
#: instrument) — cumulative counts render Prometheus-style with +Inf
DEFAULT_BUCKETS = (0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0, 60.0)

_VALUE_KINDS = ("counter", "gauge")


class _Instrument:
    """One named instrument: a family of label-keyed series.

    ``kind`` ∈ counter|gauge|timer|histogram. Counter and gauge instruments
    of the same name share storage through the registry's value namespace —
    see the bit-compatibility contract in the module docstring.
    """

    __slots__ = ("registry", "kind", "name", "help", "labelnames", "buckets")

    def __init__(self, registry, kind, name, help="", labelnames=(), buckets=None):
        self.registry = registry
        self.kind = kind
        self.name = name
        self.help = help
        self.labelnames = tuple(labelnames)
        self.buckets = tuple(buckets) if buckets else DEFAULT_BUCKETS

    def labels(self, **kv) -> "_Bound":
        if set(kv) != set(self.labelnames):
            raise ValueError(
                f"{self.name}: labels {sorted(kv)} != declared "
                f"{sorted(self.labelnames)}"
            )
        key = tuple((k, str(kv[k])) for k in self.labelnames)
        return _Bound(self, self.registry._admit(self, key))

    # unlabeled shortcut (the RunLog delegation path)
    def _bound(self) -> "_Bound":
        return _Bound(self, ())

    def inc(self, v: float = 1) -> None:
        self._bound().inc(v)

    def set(self, v) -> None:
        self._bound().set(v)

    def observe(self, v: float) -> None:
        self._bound().observe(v)

    def time(self):
        return self._bound().time()


class _Bound:
    """An instrument bound to one label set."""

    __slots__ = ("inst", "key")

    def __init__(self, inst: _Instrument, key: Tuple[Tuple[str, str], ...]):
        self.inst = inst
        self.key = key

    def inc(self, v: float = 1) -> None:
        if self.inst.kind != "counter":
            raise TypeError(f"{self.inst.name} is a {self.inst.kind}, not a counter")
        self.inst.registry._add_value(self.inst, self.key, v, kind="counter")

    def set(self, v) -> None:
        if self.inst.kind != "gauge":
            raise TypeError(f"{self.inst.name} is a {self.inst.kind}, not a gauge")
        self.inst.registry._set_value(self.inst, self.key, v, kind="gauge")

    def observe(self, v: float) -> None:
        reg = self.inst.registry
        if self.inst.kind == "timer":
            reg._add_timer(self.inst, self.key, float(v))
        elif self.inst.kind == "histogram":
            reg._observe_hist(self.inst, self.key, float(v))
        else:
            raise TypeError(f"{self.inst.name} is a {self.inst.kind}")

    @contextmanager
    def time(self):
        t0 = perf_counter()
        try:
            yield
        finally:
            self.observe(perf_counter() - t0)


class MetricsRegistry:
    """Thread-safe registry of typed instruments; one per ``RunLog`` (the
    request-scoped channel) and one per ``SelectionService`` (the fleet
    channel rendered by :meth:`render_prometheus`)."""

    def __init__(self, max_label_sets: int = DEFAULT_MAX_LABEL_SETS):
        self.max_label_sets = max(int(max_label_sets), 1)
        self._lock = threading.Lock()
        self._instruments: Dict[Tuple[str, str], _Instrument] = {}
        #: counter/gauge shared value namespace: {(name, labelkey): value}
        self._values: Dict[Tuple[str, tuple], Any] = {}
        #: which kind last wrote a value key (flat render + inc semantics)
        self._value_kind: Dict[Tuple[str, tuple], str] = {}
        self._timers: Dict[Tuple[str, tuple], float] = {}
        #: {(name, labelkey): (bucket_counts list, count, sum)}
        self._hists: Dict[Tuple[str, tuple], list] = {}
        #: distinct label sets seen per instrument name (cardinality cap)
        self._label_sets: Dict[str, set] = {}
        self.label_overflow = 0

    # --- instrument constructors -------------------------------------------

    def _get(self, kind: str, name: str, help="", labelnames=(), buckets=None):
        group = "value" if kind in _VALUE_KINDS else kind
        with self._lock:
            inst = self._instruments.get((group, name))
            if inst is None:
                inst = _Instrument(self, kind, name, help, labelnames, buckets)
                self._instruments[(group, name)] = inst
            elif inst.kind != kind:
                # counter↔gauge retype mirrors the old one-dict semantics:
                # the storage survives, the declared kind follows the caller
                inst.kind = kind  # type: ignore[misc]
            return inst

    def counter(self, name: str, help: str = "", labelnames=()) -> _Instrument:
        return self._get("counter", name, help, labelnames)

    def gauge(self, name: str, help: str = "", labelnames=()) -> _Instrument:
        return self._get("gauge", name, help, labelnames)

    def timer(self, name: str, help: str = "", labelnames=()) -> _Instrument:
        return self._get("timer", name, help, labelnames)

    def histogram(
        self, name: str, help: str = "", labelnames=(), buckets=None
    ) -> _Instrument:
        return self._get("histogram", name, help, labelnames, buckets)

    # --- storage (all under the lock) --------------------------------------

    def _admit(self, inst: _Instrument, key: tuple) -> tuple:
        """Cardinality cap: a NEW label set beyond ``max_label_sets`` folds
        into the reserved overflow series (counted, never unbounded)."""
        if not key:
            return key
        with self._lock:
            seen = self._label_sets.setdefault(inst.name, set())
            if key in seen:
                return key
            if len(seen) >= self.max_label_sets:
                self.label_overflow += 1
                seen.add(OVERFLOW_LABELS)
                return OVERFLOW_LABELS
            seen.add(key)
            return key

    def _add_value(self, inst, key, v, kind):
        k = (inst.name, key)
        with self._lock:
            self._values[k] = self._values.get(k, 0) + v
            self._value_kind[k] = kind

    def _set_value(self, inst, key, v, kind):
        k = (inst.name, key)
        with self._lock:
            self._values[k] = v
            self._value_kind[k] = kind

    def _add_timer(self, inst, key, dt):
        k = (inst.name, key)
        with self._lock:
            self._timers[k] = self._timers.get(k, 0.0) + dt

    def _observe_hist(self, inst, key, v):
        k = (inst.name, key)
        with self._lock:
            rec = self._hists.get(k)
            if rec is None:
                rec = [[0] * (len(inst.buckets) + 1), 0, 0.0]
                self._hists[k] = rec
            counts, _n, _s = rec
            for i, edge in enumerate(inst.buckets):
                if v <= edge:
                    counts[i] += 1
                    break
            else:
                counts[-1] += 1
            rec[1] += 1
            rec[2] += v

    # --- flat (RunLog bit-compat) accessors --------------------------------

    @staticmethod
    def _flat_name(name: str, key: tuple) -> str:
        if not key:
            return name
        return name + "{" + ",".join(f'{k}="{v}"' for k, v in key) + "}"

    def flat_counters(self) -> Dict[str, Any]:
        """The old ``RunLog._counters`` dict: counters AND gauges, one flat
        namespace, labeled series rendered ``name{k="v"}``. A defensive
        copy under the lock."""
        with self._lock:
            return {
                self._flat_name(name, key): value
                for (name, key), value in self._values.items()
            }

    def flat_timers(self) -> Dict[str, float]:
        """The old ``RunLog._timers`` dict (defensive copy under the lock)."""
        with self._lock:
            return {
                self._flat_name(name, key): value
                for (name, key), value in self._timers.items()
            }

    # --- snapshot / prometheus rendering ------------------------------------

    def snapshot(self) -> Dict[str, Any]:
        """Structured snapshot (the service's periodic ``("metrics", …)``
        progress event payload)."""
        with self._lock:
            values = {
                self._flat_name(n, k): v for (n, k), v in self._values.items()
            }
            kinds = {
                self._flat_name(n, k): kind
                for (n, k), kind in self._value_kind.items()
            }
            timers = {
                self._flat_name(n, k): v for (n, k), v in self._timers.items()
            }
            hists = {
                self._flat_name(n, k): {"count": rec[1], "sum": rec[2]}
                for (n, k), rec in self._hists.items()
            }
            overflow = self.label_overflow
        return {
            "schema_version": 1,
            "counters": {n: v for n, v in values.items() if kinds.get(n) == "counter"},
            "gauges": {n: v for n, v in values.items() if kinds.get(n) == "gauge"},
            "timers": timers,
            "histograms": hists,
            "label_overflow": overflow,
        }

    def render_prometheus(self) -> str:
        """Prometheus text exposition of every series — the fleet bench's
        scrape-style dump (``SelectionService.metrics_text``)."""
        lines: List[str] = []
        with self._lock:
            insts = dict(self._instruments)
            values = dict(self._values)
            kinds = dict(self._value_kind)
            timers = dict(self._timers)
            hists = {k: (list(v[0]), v[1], v[2]) for k, v in self._hists.items()}
            overflow = self.label_overflow
        emitted = set()

        def _head(name: str, kind: str, help_: str):
            if name in emitted:
                return
            emitted.add(name)
            if help_:
                lines.append(f"# HELP {_sanitize(name)} {help_}")
            lines.append(f"# TYPE {_sanitize(name)} {kind}")

        for (group, name), inst in sorted(insts.items()):
            if group == "value":
                kind = "counter" if inst.kind == "counter" else "gauge"
                for (vname, key), v in sorted(
                    (kv for kv in values.items() if kv[0][0] == name),
                    key=lambda kv: kv[0][1],
                ):
                    _head(name, kinds.get((vname, key), kind), inst.help)
                    lines.append(
                        f"{_sanitize(name)}{_labels(key)} {_num(v)}"
                    )
            elif group == "timer":
                for (tname, key), v in sorted(
                    (kv for kv in timers.items() if kv[0][0] == name),
                    key=lambda kv: kv[0][1],
                ):
                    _head(name + "_seconds_total", "counter", inst.help)
                    lines.append(
                        f"{_sanitize(name)}_seconds_total{_labels(key)} {_num(v)}"
                    )
            elif group == "histogram":
                for (hname, key), (counts, n, s) in sorted(
                    (kv for kv in hists.items() if kv[0][0] == name),
                    key=lambda kv: kv[0][1],
                ):
                    _head(name, "histogram", inst.help)
                    cum = 0
                    for edge, c in zip(inst.buckets, counts):
                        cum += c
                        lines.append(
                            f"{_sanitize(name)}_bucket"
                            f"{_labels(key + (('le', repr(float(edge))),))} {cum}"
                        )
                    lines.append(
                        f"{_sanitize(name)}_bucket"
                        f"{_labels(key + (('le', '+Inf'),))} {n}"
                    )
                    lines.append(f"{_sanitize(name)}_count{_labels(key)} {n}")
                    lines.append(f"{_sanitize(name)}_sum{_labels(key)} {_num(s)}")
        if overflow:
            lines.append("# TYPE grafttrace_label_overflow_total counter")
            lines.append(f"grafttrace_label_overflow_total {overflow}")
        return "\n".join(lines) + ("\n" if lines else "")


def _sanitize(name: str) -> str:
    return "".join(c if (c.isalnum() or c == "_") else "_" for c in name)


def _labels(key: tuple) -> str:
    if not key:
        return ""
    return "{" + ",".join(f'{k}="{v}"' for k, v in key) + "}"


def _num(v) -> str:
    try:
        f = float(v)
    except (TypeError, ValueError):
        return "0"
    return repr(int(f)) if f == int(f) else repr(f)


# --- in-band rendering (absorbed from utils/profiling) -----------------------


def format_timers(timers: Dict[str, float]) -> str:
    """One-line phase-time attribution, largest first."""
    if not timers:
        return "phase times: (none recorded)"
    parts = [
        f"{name} {secs:.2f}s"
        for name, secs in sorted(timers.items(), key=lambda kv: -kv[1])
    ]
    return "phase times: " + ", ".join(parts)


def format_counters(counters: Dict[str, int]) -> str:
    """One-line phase-event attribution (warm-start hits, overlap harvests,
    cold restarts — the pipelined decomposition's counterpart to the wall
    timers), largest first."""
    if not counters:
        return "phase counters: (none recorded)"
    parts = [
        f"{name} {cnt}"
        for name, cnt in sorted(counters.items(), key=lambda kv: -kv[1])
    ]
    return "phase counters: " + ", ".join(parts)
