"""graftscope roofline attribution: join measured spans to static budgets.

graftcheck-IR (``lint/ir.py``) knows the FLOPs and bytes every registered
core's compiled program touches (``ANALYSIS_BUDGET.json``); grafttrace
knows how long each dispatch took (``dispatch_span`` wall time, device-
sampled). Neither alone says whether a core runs at a sensible fraction of
the machine — joined, they do: achieved FLOP/s, achieved B/s, and the
arithmetic intensity that places each core on the roofline, with a
bytes-bound/compute-bound verdict against the machine-balance ridge
(``Config.obs_roofline_ridge``, FLOPs per byte). PDHG-style solvers are
memory-bound by construction (PAPERS.md: PDLP throughput tracks memory
bandwidth), so the verdict names the resource a future kernel PR must
actually move.

The join is exact by construction: graftlint R8 pins every registered
core's ``dispatch_span`` name to its manifest name, and the microbench
(``bench.py --roofline``) executes each core at the SAME representative
shapes its budget was measured at — so budget FLOPs over measured seconds
is a true rate, not a shape-mismatched estimate. A dispatch span whose
name has no budget entry is a JOIN MISS and fails the smoke: the span
fired from a core the static layer cannot see.

Stdlib-only module: the jax-touching microbench lives in ``bench.py``;
this file only aggregates spans and does arithmetic, so the trace CLI and
tests run without a backend.
"""

from __future__ import annotations

import dataclasses
from pathlib import Path
from typing import Any, Dict, List, Optional, Sequence

ROOFLINE_SCHEMA_VERSION = 1


@dataclasses.dataclass
class RooflineRow:
    """One core's placement on the roofline for one run."""

    core: str
    calls: int
    seconds: float  # summed device-sampled wall time across calls
    flops: float  # per-call, from the committed budget
    bytes: float  # per-call, from the committed budget
    achieved_gflops_s: float
    achieved_gbytes_s: float
    intensity_flops_per_byte: float
    bound: str  # "bytes-bound" | "compute-bound"
    sampled: bool  # True when every call blocked on its outputs

    @property
    def finite(self) -> bool:
        return (
            self.seconds > 0.0
            and self.achieved_gflops_s >= 0.0
            and self.achieved_gbytes_s >= 0.0
            and self.achieved_gflops_s == self.achieved_gflops_s  # not NaN
        )


@dataclasses.dataclass
class RooflineReport:
    rows: List[RooflineRow]
    misses: List[str]  # dispatch-span names with no budget entry
    unexecuted: List[str]  # budgeted cores that never fired (informational)
    ridge_flops_per_byte: float
    budget_provenance: Dict[str, Any]

    @property
    def ok(self) -> bool:
        return not self.misses and all(r.finite for r in self.rows)

    def as_json(self) -> dict:
        return {
            "schema_version": ROOFLINE_SCHEMA_VERSION,
            "roofline_ok": self.ok,
            "ridge_flops_per_byte": self.ridge_flops_per_byte,
            "budget": self.budget_provenance,
            "misses": list(self.misses),
            "unexecuted": list(self.unexecuted),
            "rows": {
                r.core: {
                    "calls": r.calls,
                    "seconds": r.seconds,
                    "flops_per_call": r.flops,
                    "bytes_per_call": r.bytes,
                    "achieved_gflops_s": r.achieved_gflops_s,
                    "achieved_gbytes_s": r.achieved_gbytes_s,
                    "intensity_flops_per_byte": r.intensity_flops_per_byte,
                    "bound": r.bound,
                    "sampled": r.sampled,
                }
                for r in self.rows
            },
        }

    def trend_detail(self) -> Dict[str, Dict[str, float]]:
        """``{"roofline_<core>": {"seconds": …}}`` rows for the committed
        ``ROOFLINE_r*.json`` family — the trend loader's ``_ROW_RE`` only
        admits ``[A-Za-z0-9_]`` names, so core dots become underscores."""
        return {
            "roofline_" + r.core.replace(".", "_"): {
                "seconds": round(r.seconds, 6)
            }
            for r in self.rows
        }


def dispatch_totals(tracers: Sequence) -> Dict[str, Dict[str, Any]]:
    """Aggregate ``kind="dispatch"`` spans by name across tracers:
    ``{name: {"calls", "seconds", "sampled"}}``. ``sampled`` stays True
    only if every call blocked on device outputs (``sampled`` span attr) —
    an unsampled call means the span timed host enqueue, not execution."""
    out: Dict[str, Dict[str, Any]] = {}
    for tracer in tracers:
        for sp in tracer.spans():
            if sp.attrs.get("kind") != "dispatch" or sp.t1 is None:
                continue
            agg = out.setdefault(
                sp.name, {"calls": 0, "seconds": 0.0, "sampled": True}
            )
            agg["calls"] += 1
            agg["seconds"] += sp.duration
            agg["sampled"] = agg["sampled"] and bool(sp.attrs.get("sampled"))
    return out


def roofline_join(
    tracers: Sequence,
    budget_path=None,
    ridge: Optional[float] = None,
) -> RooflineReport:
    """Join the tracers' dispatch spans against the committed budget."""
    from citizensassemblies_tpu.lint.ir import (
        BUDGET_PATH,
        budget_provenance,
        load_budget,
    )

    if ridge is None:
        from citizensassemblies_tpu.utils.config import default_config

        ridge = float(default_config().obs_roofline_ridge)
    path = Path(budget_path) if budget_path is not None else BUDGET_PATH
    budgets, _tol = load_budget(path)

    totals = dispatch_totals(tracers)
    rows: List[RooflineRow] = []
    misses: List[str] = []
    for name in sorted(totals):
        agg = totals[name]
        budget = budgets.get(name)
        if budget is None:
            misses.append(name)
            continue
        flops = float(budget.get("flops", 0.0))
        nbytes = float(budget.get("bytes", 0.0))
        seconds = float(agg["seconds"])
        total_flops = flops * agg["calls"]
        total_bytes = nbytes * agg["calls"]
        gflops_s = (total_flops / seconds) / 1e9 if seconds > 0 else float("nan")
        gbytes_s = (total_bytes / seconds) / 1e9 if seconds > 0 else float("nan")
        intensity = flops / nbytes if nbytes > 0 else float("inf")
        rows.append(
            RooflineRow(
                core=name,
                calls=agg["calls"],
                seconds=round(seconds, 6),
                flops=flops,
                bytes=nbytes,
                achieved_gflops_s=round(gflops_s, 4),
                achieved_gbytes_s=round(gbytes_s, 4),
                intensity_flops_per_byte=round(intensity, 4),
                bound="bytes-bound" if intensity < ridge else "compute-bound",
                sampled=bool(agg["sampled"]),
            )
        )
    unexecuted = sorted(set(budgets) - set(totals))
    return RooflineReport(
        rows=rows,
        misses=misses,
        unexecuted=unexecuted,
        ridge_flops_per_byte=float(ridge),
        budget_provenance=budget_provenance(path),
    )
