"""grafttrace span tracer: nested spans, ambient activation, Chrome export.

The stack's wall-clock attribution used to live in three disconnected
channels — ``RunLog`` phase timers, ``CompilationGuard`` counters and
hand-read bench stamps — none of which could answer "where did THIS
request's 18 seconds go" for one request among many. A :class:`Tracer`
collects **spans**: named intervals with attributes, nested per thread, and
exports them as Chrome trace-event JSON (loadable in ``chrome://tracing`` /
Perfetto / speedscope).

Activation is AMBIENT and opt-in:

* :func:`use_tracer` installs a tracer on the calling thread/task via a
  ``ContextVar`` (the same isolation contract as
  ``service.context.RequestContext`` — and the service installs a
  per-request tracer through exactly that context, so concurrent requests
  produce disjoint traces by construction);
* a ``RunLog`` may carry a ``tracer`` attribute so worker threads that hold
  the request's log (the anchor-pricing overlap thread, the cross-request
  batcher) attribute their spans to the owning request even though
  ``ContextVar`` values do not cross thread boundaries;
* with NO tracer installed every entry point here is a no-op returning
  ``None`` — one ``ContextVar.get`` per call, no allocation, which is the
  ``Config.obs_trace`` "off ⇒ zero overhead" contract.

Span trees are well-nested per thread (spans close LIFO through the
context-manager protocol); :func:`begin_span`/:func:`end_span` additionally
support OPEN intervals that tile a loop without re-indenting its body (the
face-decomposition round spans) — those attach to the current stack top as
parent but do not join the stack, so they may overlap their own children's
siblings; interval-union consumers (:func:`span_coverage`) handle that.

Nothing here imports jax — the tracer must stay importable from the lint
tooling and host-only paths.
"""

from __future__ import annotations

import dataclasses
import itertools
import threading
import time
from contextlib import contextmanager
from contextvars import ContextVar
from typing import Any, Dict, Iterable, List, Optional

#: schema version stamped into every exported trace document (and onto the
#: bench rows' ``obs`` block): bump when the event layout changes shape
TRACE_SCHEMA_VERSION = 1

#: hard cap on retained spans per tracer — a runaway loop must degrade to a
#: counted drop, not an OOM (the drop count is exported with the trace)
MAX_SPANS = 200_000

#: the ambient tracer of the calling thread/task (None = tracing off)
_AMBIENT: ContextVar[Optional["Tracer"]] = ContextVar(
    "citizens_tpu_tracer", default=None
)


@dataclasses.dataclass
class Span:
    """One named interval. ``t0``/``t1`` are ``perf_counter`` seconds on the
    owning tracer's clock; ``t1 is None`` while the span is open."""

    name: str
    span_id: int
    parent_id: Optional[int]
    t0: float
    t1: Optional[float]
    tid: int
    attrs: Dict[str, Any]

    @property
    def duration(self) -> float:
        return (self.t1 if self.t1 is not None else self.t0) - self.t0


class Tracer:
    """Collects spans for ONE run/request.

    ``sample_device=True`` marks the opt-in device-sampling mode
    (``Config.obs_trace = True``): the dispatch hooks
    (``obs.hooks.dispatch_span``) then ``block_until_ready`` their recorded
    outputs so a dispatch span measures device execution instead of async
    enqueue latency. The numerics are untouched either way — blocking is a
    wait, not a transfer — which is what the obs-off/on bit-identity test
    pins.
    """

    def __init__(
        self,
        name: str = "run",
        sample_device: bool = False,
        max_spans: int = MAX_SPANS,
    ):
        self.name = name
        self.sample_device = bool(sample_device)
        self.max_spans = int(max_spans)
        self.dropped = 0
        self._lock = threading.Lock()
        self._spans: List[Span] = []
        self._tls = threading.local()
        self._ids = itertools.count(1)
        # epoch pair: monotonic for durations, wall for absolute export ts
        self._epoch_perf = time.perf_counter()
        self._epoch_unix = time.time()

    # --- recording ----------------------------------------------------------

    def _stack(self) -> List[Span]:
        st = getattr(self._tls, "stack", None)
        if st is None:
            st = []
            self._tls.stack = st
        return st

    def begin(self, name: str, stacked: bool = True, **attrs) -> Optional[Span]:
        """Open a span. ``stacked=True`` (the context-manager path) pushes it
        so later spans on this thread nest under it; ``stacked=False`` makes
        an open interval parented at the current stack top that does NOT
        capture later spans (loop tiling)."""
        st = self._stack()
        with self._lock:
            if len(self._spans) >= self.max_spans:
                self.dropped += 1
                return None
            sp = Span(
                name=name,
                span_id=next(self._ids),
                parent_id=st[-1].span_id if st else None,
                t0=time.perf_counter(),
                t1=None,
                tid=threading.get_ident(),
                attrs=dict(attrs),
            )
            self._spans.append(sp)
        if stacked:
            st.append(sp)
        return sp

    def end(self, sp: Optional[Span]) -> None:
        """Close a span (idempotent; ``None`` is a no-op)."""
        if sp is None or sp.t1 is not None:
            return
        sp.t1 = time.perf_counter()
        st = self._stack()
        if st and st[-1] is sp:
            st.pop()

    @contextmanager
    def span(self, name: str, **attrs):
        sp = self.begin(name, stacked=True, **attrs)
        try:
            yield sp
        finally:
            self.end(sp)

    # --- reading ------------------------------------------------------------

    @property
    def span_count(self) -> int:
        with self._lock:
            return len(self._spans)

    def spans(self) -> List[Span]:
        """Snapshot of recorded spans (the Span objects themselves — treat
        as read-only; open spans have ``t1 is None``)."""
        with self._lock:
            return list(self._spans)

    def chrome_events(self, pid: int = 1) -> List[dict]:
        """Chrome trace-event list for this tracer under process id ``pid``:
        one complete ("X") event per span (open spans are exported as if
        closed now — export never mutates) plus process/thread metadata."""
        now = time.perf_counter()
        spans = self.spans()
        base_us = self._epoch_unix * 1e6 - self._epoch_perf * 1e6
        events: List[dict] = [
            {
                "ph": "M",
                "pid": pid,
                "tid": 0,
                "name": "process_name",
                "args": {"name": self.name},
            }
        ]
        tids = sorted({sp.tid for sp in spans})
        tid_map = {t: i + 1 for i, t in enumerate(tids)}
        for t, short in tid_map.items():
            events.append(
                {
                    "ph": "M",
                    "pid": pid,
                    "tid": short,
                    "name": "thread_name",
                    "args": {"name": f"thread-{t}"},
                }
            )
        for sp in spans:
            t1 = sp.t1 if sp.t1 is not None else now
            args = {k: _jsonable(v) for k, v in sp.attrs.items()}
            args["span_id"] = sp.span_id
            if sp.parent_id is not None:
                args["parent_id"] = sp.parent_id
            events.append(
                {
                    "ph": "X",
                    "pid": pid,
                    "tid": tid_map.get(sp.tid, 0),
                    "name": sp.name,
                    "cat": "grafttrace",
                    "ts": base_us + sp.t0 * 1e6,
                    "dur": max(t1 - sp.t0, 0.0) * 1e6,
                    "args": args,
                }
            )
        return events


def _jsonable(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    return str(v)


# --- ambient activation ------------------------------------------------------


def current_tracer() -> Optional[Tracer]:
    """The calling thread/task's ambient tracer (None = tracing off)."""
    return _AMBIENT.get()


def activate_tracer(tracer: Optional[Tracer]):
    """Low-level install; returns the reset token (used by
    ``service.context.use_context`` to compose with its own ContextVar)."""
    return _AMBIENT.set(tracer)


def deactivate_tracer(token) -> None:
    _AMBIENT.reset(token)


@contextmanager
def use_tracer(tracer: Optional[Tracer]):
    """Install ``tracer`` as the ambient tracer for the scope (``None`` is a
    passthrough, so callers can wrap unconditionally)."""
    if tracer is None:
        yield None
        return
    token = activate_tracer(tracer)
    try:
        yield tracer
    finally:
        deactivate_tracer(token)


def _resolve(log=None) -> Optional[Tracer]:
    """Tracer resolution shared by the span helpers: the log-carried tracer
    (worker threads) wins, else the ambient one, else None (= off)."""
    if log is not None:
        tr = getattr(log, "tracer", None)
        if tr is not None:
            return tr
    return _AMBIENT.get()


@contextmanager
def span(name: str, log=None, **attrs):
    """Ambient nested span; a no-op yielding ``None`` when tracing is off."""
    tr = _resolve(log)
    if tr is None:
        yield None
        return
    sp = tr.begin(name, stacked=True, **attrs)
    try:
        yield sp
    finally:
        tr.end(sp)


def begin_span(name: str, log=None, **attrs) -> Optional[Span]:
    """Open an UNSTACKED interval (see :meth:`Tracer.begin`); pair with
    :func:`end_span`. Returns ``None`` (and does nothing) when tracing is
    off, so callers never need their own gate."""
    tr = _resolve(log)
    if tr is None:
        return None
    return tr.begin(name, stacked=False, **attrs)


def end_span(sp: Optional[Span], log=None) -> None:
    """Close an interval from :func:`begin_span` (``None``-safe, idempotent)."""
    if sp is None:
        return
    tr = _resolve(log)
    if tr is not None:
        tr.end(sp)
    else:  # tracer uninstalled between begin and end — still stamp the close
        if sp.t1 is None:
            sp.t1 = time.perf_counter()


# --- export / validation -----------------------------------------------------


def export_chrome_trace(
    tracers: Iterable[Tracer], path: Optional[str] = None
) -> dict:
    """Merge one or more tracers into a single Chrome trace document (each
    tracer becomes one ``pid`` — the per-request process lanes of a serve
    trace). Writes JSON to ``path`` when given; returns the document."""
    events: List[dict] = []
    total_dropped = 0
    names = []
    for pid, tr in enumerate(tracers, start=1):
        events.extend(tr.chrome_events(pid=pid))
        total_dropped += tr.dropped
        names.append(tr.name)
    doc = {
        "schema_version": TRACE_SCHEMA_VERSION,
        "displayTimeUnit": "ms",
        "traceEvents": events,
        "otherData": {
            "producer": "citizensassemblies_tpu.obs",
            "tracers": names,
            "dropped_spans": total_dropped,
        },
    }
    if path is not None:
        import json

        with open(path, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
    return doc


def validate_chrome_trace(doc) -> List[str]:
    """Schema check of an exported trace document; returns the list of
    problems (empty = valid). This is the contract the CI artifacts and the
    smoke assertion rely on, pinned by ``tests/test_obs.py``."""
    problems: List[str] = []
    if not isinstance(doc, dict):
        return ["document is not an object"]
    if doc.get("schema_version") != TRACE_SCHEMA_VERSION:
        problems.append(
            f"schema_version {doc.get('schema_version')!r} != {TRACE_SCHEMA_VERSION}"
        )
    events = doc.get("traceEvents")
    if not isinstance(events, list):
        return problems + ["traceEvents is not a list"]
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"event {i} is not an object")
            continue
        ph = ev.get("ph")
        if ph not in ("X", "M"):
            problems.append(f"event {i}: unknown phase {ph!r}")
            continue
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            problems.append(f"event {i}: missing/empty name")
        if not isinstance(ev.get("pid"), int) or not isinstance(ev.get("tid"), int):
            problems.append(f"event {i}: pid/tid must be ints")
        if ph == "X":
            ts, dur = ev.get("ts"), ev.get("dur")
            if not isinstance(ts, (int, float)) or not isinstance(dur, (int, float)):
                problems.append(f"event {i}: ts/dur must be numbers")
            elif dur < 0:
                problems.append(f"event {i}: negative duration")
            if not isinstance(ev.get("args", {}), dict):
                problems.append(f"event {i}: args must be an object")
    return problems


def _union_seconds(intervals: List[tuple]) -> float:
    if not intervals:
        return 0.0
    intervals = sorted(intervals)
    total = 0.0
    cur_lo, cur_hi = intervals[0]
    for lo, hi in intervals[1:]:
        if lo > cur_hi:
            total += cur_hi - cur_lo
            cur_lo, cur_hi = lo, hi
        else:
            cur_hi = max(cur_hi, hi)
    total += cur_hi - cur_lo
    return total


def span_coverage(tracer: Tracer, root_name: str) -> float:
    """Fraction of the wall time of the first completed span named
    ``root_name`` that is covered by the union of its DIRECT children
    (clipped to the root's interval). The acceptance-criteria number: the
    face-decomposition phase must trace ≥ 0.9 here."""
    spans = tracer.spans()
    root = next(
        (s for s in spans if s.name == root_name and s.t1 is not None), None
    )
    if root is None or root.duration <= 0:
        return 0.0
    ivs = []
    for s in spans:
        if s.parent_id != root.span_id:
            continue
        t1 = s.t1 if s.t1 is not None else root.t1
        lo, hi = max(s.t0, root.t0), min(t1, root.t1)
        if hi > lo:
            ivs.append((lo, hi))
    return _union_seconds(ivs) / root.duration
