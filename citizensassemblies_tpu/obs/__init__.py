"""grafttrace: unified observability for the selection stack.

Three layers, all stdlib-importable (jax only touched lazily, and only in
the opt-in device-sampling mode):

* ``obs.trace`` — nested span tracer, ambient via ContextVar and integrated
  with the service's per-request ``RequestContext``; exports Chrome
  trace-event / Perfetto JSON per run;
* ``obs.metrics`` — typed metrics registry (Counter/Gauge/Timer/Histogram
  with label sets) that ``RunLog.count``/``gauge``/``timer`` delegate to
  bit-compatibly, plus the Prometheus text renderer and the in-band
  ``format_timers``/``format_counters`` (absorbed from ``utils/profiling``);
* ``obs.hooks`` — ``dispatch_span``, the device-dispatch timing hook every
  registered IR core's entry point wraps (graftlint R8), tri-stated by
  ``Config.obs_trace``;
* ``obs.trend`` — the ``bench.py --trend`` regression gate over the
  committed BENCH_*.json trajectory;
* ``obs.memory`` — graftscope's per-phase device-memory ledger (live-array
  bytes, HBM high watermark, per-owner cache attribution), tri-stated by
  ``Config.obs_memory``;
* ``obs.roofline`` — joins measured dispatch spans against the committed
  ``ANALYSIS_BUDGET.json`` flops/bytes for achieved-rate and
  bytes-/compute-bound attribution (``bench.py --roofline``);
* ``obs.slo`` — the declarative SLO engine (``Config.obs_slo_spec``) with
  multi-window burn rates and ``("slo", …)`` breach events;
* ``obs.catalog`` — the metric-series catalogue graftlint R11 enforces;
* ``python -m citizensassemblies_tpu.obs`` — the offline trace-analysis
  CLI (critical path, self time, fusion timeline, ``--diff``).
"""

from citizensassemblies_tpu.obs.catalog import (
    METRIC_PREFIXES,
    METRIC_SERIES,
    is_registered,
)
from citizensassemblies_tpu.obs.hooks import DispatchScope, dispatch_span
from citizensassemblies_tpu.obs.memory import (
    MemoryLedger,
    ambient_ledger,
    leak_verdict,
    owner_attribution,
    use_ledger,
)
from citizensassemblies_tpu.obs.metrics import (
    MetricsRegistry,
    format_counters,
    format_timers,
)
from citizensassemblies_tpu.obs.roofline import (
    RooflineReport,
    RooflineRow,
    dispatch_totals,
    roofline_join,
)
from citizensassemblies_tpu.obs.slo import SloEngine, parse_slo_spec
from citizensassemblies_tpu.obs.trace import (
    TRACE_SCHEMA_VERSION,
    Span,
    Tracer,
    begin_span,
    current_tracer,
    end_span,
    export_chrome_trace,
    span,
    span_coverage,
    use_tracer,
    validate_chrome_trace,
)
from citizensassemblies_tpu.obs.trend import TrendReport, collect_series, trend_gate

__all__ = [
    "DispatchScope",
    "dispatch_span",
    "MetricsRegistry",
    "format_counters",
    "format_timers",
    "TRACE_SCHEMA_VERSION",
    "Span",
    "Tracer",
    "begin_span",
    "current_tracer",
    "end_span",
    "export_chrome_trace",
    "span",
    "span_coverage",
    "use_tracer",
    "validate_chrome_trace",
    "TrendReport",
    "collect_series",
    "trend_gate",
    "METRIC_PREFIXES",
    "METRIC_SERIES",
    "is_registered",
    "MemoryLedger",
    "ambient_ledger",
    "leak_verdict",
    "owner_attribution",
    "use_ledger",
    "RooflineReport",
    "RooflineRow",
    "dispatch_totals",
    "roofline_join",
    "SloEngine",
    "parse_slo_spec",
]
