"""grafttrace: unified observability for the selection stack.

Three layers, all stdlib-importable (jax only touched lazily, and only in
the opt-in device-sampling mode):

* ``obs.trace`` — nested span tracer, ambient via ContextVar and integrated
  with the service's per-request ``RequestContext``; exports Chrome
  trace-event / Perfetto JSON per run;
* ``obs.metrics`` — typed metrics registry (Counter/Gauge/Timer/Histogram
  with label sets) that ``RunLog.count``/``gauge``/``timer`` delegate to
  bit-compatibly, plus the Prometheus text renderer and the in-band
  ``format_timers``/``format_counters`` (absorbed from ``utils/profiling``);
* ``obs.hooks`` — ``dispatch_span``, the device-dispatch timing hook every
  registered IR core's entry point wraps (graftlint R8), tri-stated by
  ``Config.obs_trace``;
* ``obs.trend`` — the ``bench.py --trend`` regression gate over the
  committed BENCH_*.json trajectory.
"""

from citizensassemblies_tpu.obs.hooks import DispatchScope, dispatch_span
from citizensassemblies_tpu.obs.metrics import (
    MetricsRegistry,
    format_counters,
    format_timers,
)
from citizensassemblies_tpu.obs.trace import (
    TRACE_SCHEMA_VERSION,
    Span,
    Tracer,
    begin_span,
    current_tracer,
    end_span,
    export_chrome_trace,
    span,
    span_coverage,
    use_tracer,
    validate_chrome_trace,
)
from citizensassemblies_tpu.obs.trend import TrendReport, collect_series, trend_gate

__all__ = [
    "DispatchScope",
    "dispatch_span",
    "MetricsRegistry",
    "format_counters",
    "format_timers",
    "TRACE_SCHEMA_VERSION",
    "Span",
    "Tracer",
    "begin_span",
    "current_tracer",
    "end_span",
    "export_chrome_trace",
    "span",
    "span_coverage",
    "use_tracer",
    "validate_chrome_trace",
    "TrendReport",
    "collect_series",
    "trend_gate",
]
