"""graftscope metric catalogue: the single registry of metric series names.

Every ``log.count``/``log.gauge``/``log.timer`` and metrics-registry
``counter``/``gauge``/``histogram`` name literal used anywhere in the
package must appear here (or start with a registered dynamic prefix) —
graftlint R11 ``metric-hygiene`` enforces it statically. The failure mode
this kills: a typo'd counter name silently creates a brand-new series, the
dashboards keep reading the old (now frozen) one, and the regression goes
unobserved. With the catalogue, the typo is a lint error at the call site.

The catalogue is data, not behavior: nothing imports it on the hot path and
registration carries no runtime cost. The help strings double as the
documentation of record for what each series means.
"""

from __future__ import annotations

from typing import Dict, FrozenSet

#: every static metric series name → one-line meaning. Counters, gauges,
#: timers and histogram families share the namespace (the metrics registry
#: enforces type consistency per name at runtime; this catalogue only
#: enforces that the name was deliberate).
METRIC_SERIES: Dict[str, str] = {
    # --- distributed runtime (dist/) -----------------------------------
    "dist_reshards": "device-placement mismatches forcing a reshard (steady state must be 0)",
    "dist_placements": "operands placed into their declared sharding",
    "dist_mesh_hosts": "process count of the active mesh",
    "dist_mesh_devices": "device count of the active mesh",
    "dist_process_index": "this process's index in the pod",
    # --- oracle backends (native/, solvers/) ---------------------------
    "oracle_backend_highs": "anchor-oracle MILPs solved by the HiGHS backend",
    "oracle_backend_native": "anchor-oracle MILPs solved by the native branch-and-bound",
    "oracle_backend_device": "anchor-oracle pricing rounds served by the device DP kernel",
    # --- batched LP engine (solvers/batch_lp.py) -----------------------
    "lp_batch_probe_screened": "bucket members screened by the probe prescreen",
    "lp_batch_probe_pruned": "bucket members pruned before dispatch by the probe prescreen",
    "lp_batch_dispatches": "padded vmapped LP dispatches",
    "lp_batch_solves": "member LPs solved inside batched dispatches",
    "lp_batch_pad_lanes": "padding lanes wasted by shape bucketing",
    "lp_batch_warm_hits": "batched solves seeded from a warm slot",
    "lp_batch_l2_fused": "L2 polish stages fused into the batched dispatch",
    "lp_batch_polish_hit": "polish-screen lanes accepted on-device",
    "lp_batch_polish_miss": "polish-screen lanes sent back to the host path",
    "lp_batch_xreq_dispatches": "cross-request batched dispatches (graftserve batcher)",
    "lp_batch_xreq_fused": "requests fused into cross-request dispatches",
    # --- numerical sentinels (robust/) ---------------------------------
    "sentinel_poisoned": "lanes quarantined by the NaN/Inf sentinel",
    "sentinel_host_resolve": "poisoned lanes re-solved on the host",
    "sentinel_stalled": "solver lanes flagged by the stall sentinel",
    "sentinel_quarantined": "quarantined lanes excluded from a batch",
    # --- robustness / fault handling (robust/) --------------------------
    "robust_degrade_device_pricing": "degradations from device pricing to the host MILP",
    "robust_resume": "checkpoint resumes after an injected/real failure",
    "robust_host_resolve": "host re-solves after device-path failures",
    "robust_checkpoint_saved": "CG checkpoints saved by the failure policy",
    "robust_retry": "whole-stage retries by the failure policy",
    "robust_oracle_skip": "oracle rounds skipped under the degradation ladder",
    "robust_oracle_retry": "oracle retries after a backend failure",
    "robust_degrade_steps": "total rungs walked down the degradation ladder",
    "fault_queue_stall": "injected queue-stall faults fired (graftfault site)",
    # --- face-decomposition engine (solvers/face_decompose.py) ----------
    "decomp_oracle_device_hit": "pricing rounds where the device oracle's column was accepted",
    "decomp_oracle_device_miss": "pricing rounds where the device oracle found no column",
    "decomp_oracle_device_invalid": "device-oracle columns rejected by validation",
    "decomp_oracle_inline": "oracle calls run inline (overlap thread unavailable)",
    "decomp_oracle_overlap_hit": "overlapped oracle results ready when the master needed them",
    "decomp_oracle_overlap_wait": "master stalls waiting on the overlapped oracle",
    "decomp_host_syncs": "host↔device synchronizations in the decomposition loop",
    "decomp_polish_syncs": "host syncs attributable to the final polish",
    "decomp_polish_warm": "polish stages seeded from warm slots",
    "decomp_rounds": "column-generation rounds executed",
    "decomp_warm_cold_restart": "stall-triggered cold restarts of the warm PDHG state",
    "decomp_master_warm": "master solves entered warm",
    "decomp_master_cold": "master solves entered cold",
    # --- session / sparse substrate -------------------------------------
    "session_pack_hit": "tenant-session ELL pack reuses across requests",
    "sparse_fill_pct": "ELL pack fill ratio (percent, gauge)",
    "sparse_hit": "solves routed through the ELL sparse cores",
    "sparse_miss": "solves that fell back to the dense cores",
    # --- graftgrade mixed precision (utils/precision.py) -----------------
    "mp_demoted_operands": "operator matrices demoted to bf16 under the certified plan",
    "mp_lossy_skip": "plan-certified demotions skipped by the lossless round-trip check",
    # --- megakernel (kernels/pdhg_megakernel.py) -------------------------
    "megakernel_dispatches": "fused PDHG megakernel dispatches",
    "megakernel_lanes": "polish-screen lanes carried by megakernel dispatches",
    # --- serving layer (service/) ----------------------------------------
    "deadline_exceeded": "requests that ran out of deadline budget",
    "batcher_leader_reclaim": "batcher follower watchdog reclaims of a dead leader",
    "batch_window": "time a request waited for the cross-request batch window (timer)",
    "graftserve_admission_rejected_total": "requests rejected at admission (queue full)",
    "graftserve_shutdown_rejected_total": "requests rejected during drain/shutdown",
    "graftserve_requests_total": "completed requests, by tenant and algorithm",
    "graftserve_request_seconds": "request latency histogram (worker pickup → result)",
    "graftserve_deadline_total": "deadline-exceeded requests, by tenant",
    "graftserve_failed_total": "failed requests, by tenant",
    "graftserve_in_flight": "requests admitted and not yet finished",
    "graftserve_queue_depth": "requests waiting for a worker",
    "graftserve_batcher_fusion_ratio": "fraction of batched dispatches that fused ≥2 requests",
    "graftserve_batcher_solves_per_dispatch": "member solves per cross-request dispatch",
    "graftserve_tenant_evictions": "session-LRU evictions, by owning tenant",
    "graftserve_slo_breach_total": "SLO objective breaches streamed to channels, by tenant and objective",
    # --- graftfleet load management + fleet serving (service/fleet.py) ----
    "graftserve_shed_total": "submissions shed by the SLO load-management policy, by tenant",
    "graftserve_shed_active": "1 while the load policy is shedding admissions (gauge)",
    "graftserve_shed_rearm_total": "load-policy recovery re-arms (cumulative gauge)",
    "graftserve_degrade_rung": "current service-level degradation-ladder rung (gauge)",
    "graftserve_shed_burn_worst": "worst fast-window SLO burn at the last policy update (gauge)",
    # --- graftdelta incremental re-certification (solvers/delta.py) ------
    "delta_cache_hit": "edits served by the sensitivity cache certificate (zero LP solves)",
    "delta_resume": "edits served by a warm ladder resume from a stored stage certificate",
    "delta_resume_stages": "ladder stages actually re-run across warm resumes",
    "delta_full_ladder": "edits that re-ran the full ladder over the screened hull",
    "delta_fallback": "revise requests served from-scratch (cold session, oversized or inconsistent edit)",
    "delta_new_columns": "columns admitted by incremental region enumeration",
    "delta_screen_drop": "columns pruned by the feasibility screen",
    "delta_screen_flag": "near-margin columns re-priced on host in float64",
    "delta_recertify": "whole delta re-certification step (timer)",
    "delta_screen": "batched dual screening dispatch (timer)",
    # --- graftscope memory ledger (obs/memory.py) ------------------------
    "mem_live_bytes": "bytes held by live jax arrays at the last ledger snapshot",
    "mem_hbm_peak_bytes": "device-memory high watermark over the ledger's window",
    # --- graftboot AOT executable cache (aot/) ---------------------------
    "aot_cache_hit": "core dispatches served by a boot-loaded AOT executable (zero compiles)",
    "aot_cache_miss": "core dispatches at signatures the cache artifact does not hold",
    "aot_cache_stale": "cache entries invalidated at load or at first use (fingerprint, payload, call surprise)",
    "aot_prewarmed": "cached executables touched by speculative pre-warming (boot fleet + tenant admission)",
    # --- solver phase timers ---------------------------------------------
    "relax_leximin": "leximin relaxation phase (timer)",
    "inject": "fault-injection bookkeeping phase (timer)",
    "decomp": "face-decomposition engine phase (timer)",
    "relaxation": "LP relaxation phase (timer)",
    "stage_lp": "per-stage LP solve (timer)",
    "stochastic_pricing": "stochastic pricing pass (timer)",
    "exact_oracle": "exact anchor-oracle MILP (timer)",
    "sparse_pack": "ELL operand packing (timer)",
    "l2_fused": "fused L2 polish stage (timer)",
    "l2_eps_pdhg": "L2 epsilon-polish via PDHG (timer)",
    "l2_eps_lp": "L2 epsilon-polish via LP (timer)",
    "l2_dual_ascent": "L2 dual-ascent QP solve (timer)",
    "decomp_polish_screen": "batched polish prescreen (timer)",
    "decomp_expand": "column expansion phase (timer)",
    "decomp_master": "restricted-master solve (timer)",
    "decomp_polish": "final polish phase (timer)",
    "decomp_oracle": "anchor-oracle pricing phase (timer)",
    "scenario_leximin": "scenario-model leximin phase (timer)",
    "scenario_decompose": "scenario-model decomposition phase (timer)",
    "scenario_fleet": "scenario R-fold LP fleet phase (timer)",
    "typespace_lp": "type-space LP solve (timer)",
    "typespace_cg": "type-space column generation (timer)",
    "final_stage": "final allocation stage (timer)",
    "dual_lp": "dual LP solve (timer)",
    "xmin_draws": "XMIN committee draws (timer)",
    "xmin_dedup": "XMIN committee dedup (timer)",
    "xmin_l2": "XMIN L2 projection (timer)",
}

#: dynamic name families: a metric name built in an f-string passes R11 when
#: its literal leading fragment is one of these prefixes. Each prefix is a
#: deliberate per-key family (fault sites, ladder rungs, schedule buckets),
#: bounded by the corresponding registry rather than by this catalogue.
METRIC_PREFIXES: FrozenSet[str] = frozenset(
    {
        "fault_",  # robust/inject.py: one counter per registered fault site
        "robust_degrade_",  # robust/policy.py: one counter per ladder rung
        "lp_batch_compiles_",  # solvers/batch_lp.py: per-schedule compile counts
        "xla_compiles_",  # utils/guards.py: per-guard compile counts
    }
)


def is_registered(name: str) -> bool:
    """True when ``name`` is a catalogued series or a registered-prefix
    family member — the runtime twin of graftlint R11's static check."""
    return name in METRIC_SERIES or any(
        name.startswith(p) for p in METRIC_PREFIXES
    )
