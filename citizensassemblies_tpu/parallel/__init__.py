from citizensassemblies_tpu.parallel.mesh import make_mesh  # noqa: F401
from citizensassemblies_tpu.parallel.mc import (  # noqa: F401
    distributed_allocation,
    distributed_mc_round,
)
