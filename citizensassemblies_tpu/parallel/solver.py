"""Mesh-sharded PDHG for the dual leximin LP.

At reference scale one chip holds the whole portfolio, but the framework's
scaling axis is the portfolio/pool size (SURVEY §5 "long-context analog"):
the dual LP's constraint matrix is the C×n committee matrix, and at large C
its two GEMVs per PDHG iteration are the memory-bound hot loop. Here they
run under ``shard_map`` with the portfolio rows laid out over the mesh
(both mesh axes flattened into one row-parallel axis):

* ``G x̄`` needs only local rows — no communication;
* ``Gᵀ λ`` is a local [rows_local, n]ᵀ @ [rows_local] GEMV followed by one
  ``psum`` over the mesh — the collective rides ICI.

The primal iterate ``x`` and the equality dual ``μ`` stay replicated (they
are n+1-sized — tiny); every device therefore computes identical updates
from the psum-reduced gradient, so the sharded solve is deterministic and
device-count-invariant. Scalings (Ruiz) and the step size are computed on
host once per solve; convergence is checked between jitted blocks.

Exactness contract: same as the single-device PDHG — callers treat a
non-converged result as "fall back to host HiGHS".
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from citizensassemblies_tpu.solvers.highs_backend import DualSolution
from citizensassemblies_tpu.utils.config import Config, default_config


def _ruiz_host(K: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """Host view of the shared Ruiz equilibration (``lp_pdhg._ruiz_equilibrate``)."""
    from citizensassemblies_tpu.solvers.lp_pdhg import _ruiz_equilibrate

    d_r, d_c = _ruiz_equilibrate(jnp.asarray(K, jnp.float32))
    return np.asarray(d_r, np.float64), np.asarray(d_c, np.float64)


def solve_dual_lp_pdhg_sharded(
    P_mat: np.ndarray,
    fixed: np.ndarray,
    mesh: Mesh,
    cfg: Optional[Config] = None,
    tol: Optional[float] = None,
    max_blocks: int = 60,
    block_iters: int = 512,
) -> DualSolution:
    """Dual leximin LP (``leximin.py:300-328``) with mesh-sharded GEMVs.

    Variables ``z = [y (n), ŷ]``; ``min ŷ − Σ fixedᵢ yᵢ`` s.t.
    ``P y − ŷ·1 ≤ 0``, ``Σ_unfixed y = 1``, ``z ≥ 0``. Returns the standard
    :class:`DualSolution` (``ok=False`` ⇒ use the host fallback).
    """
    cfg = cfg or default_config()
    tol = float(cfg.pdhg_tol if tol is None else tol)
    P_mat = np.asarray(P_mat, dtype=np.float64)
    C, n = P_mat.shape
    ndev = mesh.devices.size
    fixed = np.asarray(fixed, dtype=np.float64)
    unfixed = fixed < 0
    fixed_vals = np.where(unfixed, 0.0, fixed)

    # pad rows to a device multiple; a zero row adds ŷ ≥ 0 (already implied)
    rows = -(-C // ndev) * ndev
    G = np.zeros((rows, n + 1))
    G[:C, :n] = P_mat
    G[:, n] = -1.0
    h = np.zeros(rows)
    A = np.concatenate([unfixed.astype(np.float64), [0.0]])[None, :]
    b = np.array([1.0])
    c = np.concatenate([-fixed_vals, [1.0]])

    K = np.concatenate([G, A], axis=0)
    d_r, d_c = _ruiz_host(K)
    Ks = K * d_r[:, None] * d_c[None, :]
    cs = c * d_c
    hs = h * d_r[:rows]
    bs = b * d_r[rows:]
    Gs = Ks[:rows]
    As = Ks[rows:]
    # ‖K‖₂ by host power iteration
    x = np.random.default_rng(0).standard_normal(n + 1)
    for _ in range(20):
        x = Ks.T @ (Ks @ x)
        x /= np.linalg.norm(x) + 1e-30
    norm = float(np.linalg.norm(Ks @ x))
    tau = sigma = 0.9 / max(norm, 1e-12)
    scale = 1.0 + float(np.linalg.norm(cs) + np.linalg.norm(hs) + np.linalg.norm(bs))

    axes = mesh.axis_names  # both flattened into one row-parallel axis

    @partial(
        jax.shard_map,
        mesh=mesh,
        in_specs=(P(axes), P(axes), P(), P()),
        out_specs=(P(), P(axes), P()),
        check_vma=False,
    )
    def block(G_l, lam_l, x, mu):
        G_l = G_l.astype(jnp.float32)
        h_l = jnp.zeros(G_l.shape[0], jnp.float32)  # hs is all zeros by construction

        def one_iter(carry, _):
            x, lam_l, mu = carry
            gT = jax.lax.psum(G_l.T @ lam_l, axes)
            grad = cs_d + gT + As_d[0] * mu[0]
            x_new = jnp.maximum(x - tau * grad, 0.0)
            xb = 2.0 * x_new - x
            lam_l = jnp.maximum(lam_l + sigma * (G_l @ xb - h_l), 0.0)
            mu = mu + sigma * (As_d @ xb - bs_d)
            return (x_new, lam_l, mu), None

        (x, lam_l, mu), _ = jax.lax.scan(
            one_iter, (x, lam_l, mu), None, length=block_iters
        )
        return x, lam_l, mu

    cs_d = jnp.asarray(cs, jnp.float32)
    As_d = jnp.asarray(As, jnp.float32)
    bs_d = jnp.asarray(bs, jnp.float32)
    tau = jnp.float32(tau)
    sigma = jnp.float32(sigma)

    x = np.zeros(n + 1, dtype=np.float32)
    lam = np.zeros(rows, dtype=np.float32)
    mu = np.zeros(1, dtype=np.float32)
    Gs_dev = jnp.asarray(Gs.astype(np.float32))  # upload the matrix once
    res = np.inf
    it = 0
    for _ in range(max_blocks):
        x, lam, mu = block(Gs_dev, jnp.asarray(lam), jnp.asarray(x), jnp.asarray(mu))
        x, lam, mu = np.asarray(x), np.asarray(lam), np.asarray(mu)
        it += block_iters
        # host KKT residual (same combined form as the single-device core)
        primal = max(
            float(np.maximum(Gs @ x - hs, 0.0).max(initial=0.0)),
            float(np.abs(As @ x - bs).max(initial=0.0)),
        )
        dual = float(np.maximum(-(cs + Gs.T @ lam + As.T @ mu), 0.0).max(initial=0.0))
        gap = abs(float(cs @ x + hs @ lam + bs @ mu))
        res = (primal + dual + gap / scale) / 1.0
        if res <= tol * 4.0:
            break

    # unscale
    z = x * d_c
    y = z[:n].astype(np.float64)
    yhat = float(z[n])
    objective = float(c @ (x * d_c))
    return DualSolution(ok=bool(res <= tol * 4.0), y=y, yhat=yhat, objective=objective)
