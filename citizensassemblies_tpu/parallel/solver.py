"""Mesh-sharded PDHG for the dual leximin LP — fully device-resident.

At reference scale one chip holds the whole portfolio, but the framework's
scaling axis is the portfolio/pool size (SURVEY §5 "long-context analog"):
the dual LP's constraint matrix is the C×n committee matrix, and at large C
its two GEMVs per PDHG iteration are the memory-bound hot loop. Here the
*entire solve* — Ruiz equilibration, the ‖K‖ power estimate, the PDHG
iteration loop and its KKT residual checks — runs in one jitted
``shard_map`` with the portfolio rows laid out over the mesh (both mesh
axes flattened into one row-parallel axis):

* ``G x̄`` needs only local rows — no communication;
* ``Gᵀ λ`` is a local [rows_local, n]ᵀ @ [rows_local] GEMV followed by one
  ``psum`` over the mesh — the collective rides ICI;
* column norms (Ruiz) and the dual-infeasibility reduction are ``pmax`` /
  ``psum`` reductions of local partials.

The host never touches the scaled matrix: it uploads the raw row shards
once and receives scalars (residual, objective) plus the solution vectors.
The primal iterate ``x`` and the equality dual ``μ`` stay replicated (they
are n+1-sized — tiny); every device computes identical updates from the
psum-reduced gradient, so the sharded solve is deterministic and
device-count-invariant.

Production routing: ``find_distribution_leximin`` dispatches its dual solve
here (``models/leximin.py``) whenever more than one device is visible and
the portfolio has at least ``cfg.dual_shard_min_rows`` rows — the same LP
otherwise solved by host HiGHS or single-device PDHG, so the fallback
contract is unchanged (non-converged ⇒ host HiGHS).

Exactness contract: same as the single-device PDHG — callers treat a
non-converged result as "fall back to host HiGHS".
"""

from __future__ import annotations

from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from citizensassemblies_tpu.dist import partition as dist_partition
from citizensassemblies_tpu.lint.registry import IRCase, register_ir_core, register_spmd_core
from citizensassemblies_tpu.obs.hooks import dispatch_span
from citizensassemblies_tpu.parallel.mesh import shard_map_compat
from citizensassemblies_tpu.solvers.highs_backend import DualSolution
from citizensassemblies_tpu.utils.config import Config, default_config
from citizensassemblies_tpu.utils.memo import LRU


def _sharded_core(mesh: Mesh, axes, block_iters: int, max_blocks: int):
    """Build the jitted, mesh-sharded PDHG solve for the dual-LP shape.

    Everything runs on device inside one ``shard_map``: inputs are the raw
    (unscaled) local row block ``G_l`` and the replicated problem vectors;
    outputs are the solution and the final residual. Shapes are
    (rows_local, n+1) per device.
    """

    @partial(
        shard_map_compat,
        mesh=mesh,
        in_specs=(P(axes, None), P(axes), P(), P(), P(), P()),
        out_specs=(P(), P(axes), P(), P()),
    )
    def solve(G_l, h_l, c, a_row, b, tol):
        f32 = jnp.float32
        G_l = G_l.astype(f32)
        h_l = h_l.astype(f32)  # local slice of the inequality offsets
        c = c.astype(f32)
        a_row = a_row.astype(f32)  # single equality row, replicated
        nv = c.shape[0]

        # ---- Ruiz equilibration, device-resident ------------------------
        # row scalings are local; column norms need the cross-device max.
        # The equality row keeps scale 1 (it is already unit-normed by
        # construction); all-zero padding rows/columns keep scale 1 too.
        def ruiz_body(_, carry):
            d_r_l, d_c = carry
            S = d_r_l[:, None] * G_l * d_c[None, :]
            rmax = jnp.max(jnp.abs(S), axis=1)
            cmax_l = jnp.max(jnp.abs(S), axis=0)
            cmax = jax.lax.pmax(cmax_l, axes)
            cmax = jnp.maximum(cmax, jnp.abs(a_row) * d_c)
            rn = jnp.where(rmax > 0, jnp.sqrt(jnp.maximum(rmax, 1e-10)), 1.0)
            cn = jnp.where(cmax > 0, jnp.sqrt(jnp.maximum(cmax, 1e-10)), 1.0)
            return d_r_l / rn, d_c / cn

        d_r_l, d_c = jax.lax.fori_loop(
            0, 8, ruiz_body,
            (jnp.ones(G_l.shape[0], f32), jnp.ones(nv, f32)),
        )
        Gs_l = d_r_l[:, None] * G_l * d_c[None, :]
        hs_l = h_l * d_r_l
        cs = c * d_c
        as_row = a_row * d_c
        bs = b.astype(f32)

        # ---- ‖K‖₂ power estimate, psum-reduced --------------------------
        def pow_body(_, v):
            u_l = Gs_l @ v
            w = jax.lax.psum(Gs_l.T @ u_l, axes) + as_row * (as_row @ v)
            return w / (jnp.linalg.norm(w) + 1e-12)

        v = jax.lax.fori_loop(
            0, 24, pow_body, jnp.ones(nv, f32) / jnp.sqrt(nv * 1.0)
        )
        u_l = Gs_l @ v
        norm = jnp.sqrt(
            jnp.linalg.norm(
                jax.lax.psum(Gs_l.T @ u_l, axes) + as_row * (as_row @ v)
            )
            + 1e-12
        )
        tau = 0.9 / norm
        sigma = 0.9 / norm
        cnorm = jnp.linalg.norm(cs)
        hnorm = jnp.sqrt(jax.lax.psum(jnp.sum(hs_l**2), axes))
        scale = 1.0 + cnorm + hnorm + jnp.abs(bs[0])

        def kkt(x, lam_l, mu):
            pri_l = jnp.sum(jnp.maximum(Gs_l @ x - hs_l, 0.0) ** 2)
            pri = jnp.sqrt(jax.lax.psum(pri_l, axes) + (as_row @ x - bs[0]) ** 2)
            grad = cs + jax.lax.psum(Gs_l.T @ lam_l, axes) + as_row * mu[0]
            dua = jnp.linalg.norm(jnp.minimum(grad, 0.0))
            pobj = cs @ x
            dobj = -jax.lax.psum(lam_l @ hs_l, axes) - mu[0] * bs[0]
            gap = jnp.abs(pobj - dobj)
            return (pri + dua) / scale + gap / (1.0 + jnp.abs(pobj) + jnp.abs(dobj))

        def one_iter(carry, _):
            x, lam_l, mu, xs, ls, ms = carry
            grad = cs + jax.lax.psum(Gs_l.T @ lam_l, axes) + as_row * mu[0]
            x_new = jnp.maximum(x - tau * grad, 0.0)
            xb = 2.0 * x_new - x
            lam_l = jnp.maximum(lam_l + sigma * (Gs_l @ xb - hs_l), 0.0)
            mu = mu + sigma * (jnp.array([as_row @ xb]) - bs)
            return (x_new, lam_l, mu, xs + x_new, ls + lam_l, ms + mu), None

        def block(state):
            x, lam_l, mu, xa, la, ma, it, res = state
            zero = (jnp.zeros_like(x), jnp.zeros_like(lam_l), jnp.zeros_like(mu))
            (x, lam_l, mu, xs, ls, ms), _ = jax.lax.scan(
                one_iter, (x, lam_l, mu) + zero, None, length=block_iters
            )
            inv = 1.0 / block_iters
            xa = (xa + xs * inv) * 0.5
            la = (la + ls * inv) * 0.5
            ma = (ma + ms * inv) * 0.5
            r_cur = kkt(x, lam_l, mu)
            r_avg = kkt(xa, la, ma)
            better = r_avg < r_cur
            x = jnp.where(better, xa, x)
            lam_l = jnp.where(better, la, lam_l)
            mu = jnp.where(better, ma, mu)
            return (x, lam_l, mu, xa, la, ma, it + 1, jnp.minimum(r_cur, r_avg))

        def cond(state):
            *_, it, res = state
            return (res > tol[0]) & (it < max_blocks)

        x0 = jnp.zeros(nv, f32)
        lam0 = jnp.zeros(G_l.shape[0], f32)
        mu0 = jnp.zeros(1, f32)
        state = (x0, lam0, mu0, x0, lam0, mu0, jnp.int32(0), jnp.float32(jnp.inf))
        x, lam_l, mu, _, _, _, _it, res = jax.lax.while_loop(cond, block, state)
        # unscale on device; λ rescaling is local to each shard
        return x * d_c, lam_l * d_r_l, mu, jnp.array([res])

    return solve


def _sharded_core_ell(mesh: Mesh, axes, block_iters: int, max_blocks: int):
    """The mesh-sharded dual-LP PDHG on the ELL rep of the row block.

    Same solve as :func:`_sharded_core` with the local inequality rows
    supplied as packed ELL indices/values (``solvers/sparse_ops`` row form:
    one packed row per portfolio panel, minor axis = the nv variables):
    ``Gs_l @ x`` is a local per-row gather sum, ``Gs_lᵀ λ`` a local
    ``segment_sum`` followed by the same one ``psum`` as the dense core,
    and the Ruiz column maxima are ``segment_max`` partials ``pmax``-reduced
    over the mesh. The tunnel ships ``rows_local × k_pad`` packed arrays
    instead of the dense ``rows_local × nv`` shard.
    """

    @partial(
        shard_map_compat,
        mesh=mesh,
        in_specs=(P(axes, None), P(axes, None), P(axes), P(), P(), P(), P()),
        out_specs=(P(), P(axes), P(), P()),
    )
    def solve(idx_l, val_l, h_l, c, a_row, b, tol):
        from citizensassemblies_tpu.solvers.sparse_ops import (
            ell_gather_mv,
            ell_scatter_mv,
        )

        f32 = jnp.float32
        val_l = val_l.astype(f32)
        h_l = h_l.astype(f32)
        c = c.astype(f32)
        a_row = a_row.astype(f32)
        nv = c.shape[0]
        absV = jnp.abs(val_l)

        # ---- Ruiz equilibration on the packed shard ---------------------
        def ruiz_body(_, carry):
            d_r_l, d_c = carry
            S = absV * d_r_l[:, None] * d_c[idx_l]
            rmax = S.max(axis=1)
            cmax_l = jnp.maximum(
                jax.ops.segment_max(
                    S.ravel(), idx_l.ravel(), num_segments=nv
                ),
                0.0,
            )
            cmax = jax.lax.pmax(cmax_l, axes)
            cmax = jnp.maximum(cmax, jnp.abs(a_row) * d_c)
            rn = jnp.where(rmax > 0, jnp.sqrt(jnp.maximum(rmax, 1e-10)), 1.0)
            cn = jnp.where(cmax > 0, jnp.sqrt(jnp.maximum(cmax, 1e-10)), 1.0)
            return d_r_l / rn, d_c / cn

        d_r_l, d_c = jax.lax.fori_loop(
            0, 8, ruiz_body,
            (jnp.ones(idx_l.shape[0], f32), jnp.ones(nv, f32)),
        )
        vals_s = val_l * d_r_l[:, None] * d_c[idx_l]
        hs_l = h_l * d_r_l
        cs = c * d_c
        as_row = a_row * d_c
        bs = b.astype(f32)

        def G_mv(x):
            return ell_gather_mv(idx_l, vals_s, x)

        def G_rmv_psum(y_l):
            return jax.lax.psum(
                ell_scatter_mv(idx_l, vals_s, y_l, nv), axes
            )

        # ---- ‖K‖₂ power estimate, psum-reduced --------------------------
        def pow_body(_, v):
            w = G_rmv_psum(G_mv(v)) + as_row * (as_row @ v)
            return w / (jnp.linalg.norm(w) + 1e-12)

        v = jax.lax.fori_loop(
            0, 24, pow_body, jnp.ones(nv, f32) / jnp.sqrt(nv * 1.0)
        )
        norm = jnp.sqrt(
            jnp.linalg.norm(G_rmv_psum(G_mv(v)) + as_row * (as_row @ v))
            + 1e-12
        )
        tau = 0.9 / norm
        sigma = 0.9 / norm
        cnorm = jnp.linalg.norm(cs)
        hnorm = jnp.sqrt(jax.lax.psum(jnp.sum(hs_l**2), axes))
        scale = 1.0 + cnorm + hnorm + jnp.abs(bs[0])

        def kkt(x, lam_l, mu):
            pri_l = jnp.sum(jnp.maximum(G_mv(x) - hs_l, 0.0) ** 2)
            pri = jnp.sqrt(jax.lax.psum(pri_l, axes) + (as_row @ x - bs[0]) ** 2)
            grad = cs + G_rmv_psum(lam_l) + as_row * mu[0]
            dua = jnp.linalg.norm(jnp.minimum(grad, 0.0))
            pobj = cs @ x
            dobj = -jax.lax.psum(lam_l @ hs_l, axes) - mu[0] * bs[0]
            gap = jnp.abs(pobj - dobj)
            return (pri + dua) / scale + gap / (1.0 + jnp.abs(pobj) + jnp.abs(dobj))

        def one_iter(carry, _):
            x, lam_l, mu, xs, ls, ms = carry
            grad = cs + G_rmv_psum(lam_l) + as_row * mu[0]
            x_new = jnp.maximum(x - tau * grad, 0.0)
            xb = 2.0 * x_new - x
            lam_l = jnp.maximum(lam_l + sigma * (G_mv(xb) - hs_l), 0.0)
            mu = mu + sigma * (jnp.array([as_row @ xb]) - bs)
            return (x_new, lam_l, mu, xs + x_new, ls + lam_l, ms + mu), None

        def block(state):
            x, lam_l, mu, xa, la, ma, it, res = state
            zero = (jnp.zeros_like(x), jnp.zeros_like(lam_l), jnp.zeros_like(mu))
            (x, lam_l, mu, xs, ls, ms), _ = jax.lax.scan(
                one_iter, (x, lam_l, mu) + zero, None, length=block_iters
            )
            inv = 1.0 / block_iters
            xa = (xa + xs * inv) * 0.5
            la = (la + ls * inv) * 0.5
            ma = (ma + ms * inv) * 0.5
            r_cur = kkt(x, lam_l, mu)
            r_avg = kkt(xa, la, ma)
            better = r_avg < r_cur
            x = jnp.where(better, xa, x)
            lam_l = jnp.where(better, la, lam_l)
            mu = jnp.where(better, ma, mu)
            return (x, lam_l, mu, xa, la, ma, it + 1, jnp.minimum(r_cur, r_avg))

        def cond(state):
            *_, it, res = state
            return (res > tol[0]) & (it < max_blocks)

        x0 = jnp.zeros(nv, f32)
        lam0 = jnp.zeros(idx_l.shape[0], f32)
        mu0 = jnp.zeros(1, f32)
        state = (x0, lam0, mu0, x0, lam0, mu0, jnp.int32(0), jnp.float32(jnp.inf))
        x, lam_l, mu, _, _, _, _it, res = jax.lax.while_loop(cond, block, state)
        return x * d_c, lam_l * d_r_l, mu, jnp.array([res])

    return solve


#: COMPILED-program cache, keyed per (mesh, variant, block schedule) and
#: LRU-bounded: recreating meshes in a long session must not accrete
#: executables (evictions land in utils.memo.memo_evictions())
_CORE_CACHE: LRU = LRU(cap=8, name="sharded_pdhg_cores")


def _get_sharded_jit(mesh: Mesh, block_iters: int, max_blocks: int):
    """The COMPILED-program cache for the sharded PDHG core, keyed per
    (mesh, block schedule) — shared by the production marshalling below and
    the IR verifier's registration, so both see the same jitted object."""
    axes = mesh.axis_names
    key = (mesh, axes, "dense", block_iters, max_blocks)
    core = _CORE_CACHE.get(key)
    if core is None:
        from citizensassemblies_tpu.aot.store import aot_seeded

        # the family string carries the mesh IDENTITY (device count + axis
        # names): a serialized executable is sharding-specific, so a cache
        # built on one mesh must miss cleanly on another
        core = aot_seeded(
            f"parallel.sharded[{len(mesh.devices.flat)}x{','.join(axes)},"
            f"{block_iters},{max_blocks}]",
            jax.jit(
                _sharded_core(mesh, axes, block_iters, max_blocks),
                donate_argnums=(1,),
            ),
        )
        _CORE_CACHE[key] = core
    return core


def _get_sharded_jit_ell(mesh: Mesh, block_iters: int, max_blocks: int):
    """ELL twin of :func:`_get_sharded_jit` (``h`` donated: it is
    shape/sharding-matched with the returned λ shard, as in the dense
    program)."""
    axes = mesh.axis_names
    key = (mesh, axes, "ell", block_iters, max_blocks)
    core = _CORE_CACHE.get(key)
    if core is None:
        from citizensassemblies_tpu.aot.store import aot_seeded

        core = aot_seeded(
            f"parallel.sharded_ell[{len(mesh.devices.flat)}x{','.join(axes)},"
            f"{block_iters},{max_blocks}]",
            jax.jit(
                _sharded_core_ell(mesh, axes, block_iters, max_blocks),
                donate_argnums=(2,),
            ),
        )
        _CORE_CACHE[key] = core
    return core


@register_ir_core("parallel.sharded_dual_lp", span="parallel.sharded_dual_lp")
def _ir_sharded_dual_lp() -> IRCase:
    """The mesh-sharded dual-LP solve on a deterministic ONE-device mesh:
    per-shard shapes must not depend on how many devices the verifying host
    happens to expose, or the committed cost budget would be
    environment-dependent."""
    S = jax.ShapeDtypeStruct
    f32 = jnp.float32
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("ir_rows",))
    rows, nv = 64, 33
    return IRCase(
        fn=_get_sharded_jit(mesh, block_iters=128, max_blocks=8),
        args=(
            S((rows, nv), f32), S((rows,), f32), S((nv,), f32),
            S((nv,), f32), S((1,), f32), S((1,), f32),
        ),
        donate_expected=1,  # h (shape/sharding-matched with the λ shard)
    )


@register_ir_core(
    "parallel.sharded_dual_lp_ell",
    dense_ref="parallel.sharded_dual_lp",
    span="parallel.sharded_dual_lp_ell",
)
def _ir_sharded_dual_lp_ell() -> IRCase:
    """The ELL twin at the dense registration's (rows, nv) shape, packed at
    k_pad = 8 slots — same one-device mesh so the budgets stay
    environment-independent and the dense→sparse delta is same-shape."""
    S = jax.ShapeDtypeStruct
    f32, i32 = jnp.float32, jnp.int32
    mesh = Mesh(np.asarray(jax.devices()[:1]), ("ir_rows",))
    rows, nv, kp = 64, 33, 8
    return IRCase(
        fn=_get_sharded_jit_ell(mesh, block_iters=128, max_blocks=8),
        args=(
            S((rows, kp), i32), S((rows, kp), f32), S((rows,), f32),
            S((nv,), f32), S((nv,), f32), S((1,), f32), S((1,), f32),
        ),
        donate_expected=1,  # h, as in the dense program
    )


@register_spmd_core(
    "parallel.sharded_dual_lp",
    loop_collectives=(
        "row-sharded GEMV: the per-iteration psum over G^T lambda IS the "
        "algorithm — each device owns a row shard, the dual ascent direction "
        "is their sum; see _sharded_core"
    ),
)
def _spmd_sharded_dual_lp(mesh) -> IRCase:
    """graftspmd build at the swept virtual mesh: same (rows, nv) problem as
    the IR registration, rows divisible by every swept size (64 / 8)."""
    S = jax.ShapeDtypeStruct
    f32 = jnp.float32
    rows, nv = 64, 33
    return IRCase(
        fn=_get_sharded_jit(mesh, block_iters=128, max_blocks=8),
        args=(
            S((rows, nv), f32), S((rows,), f32), S((nv,), f32),
            S((nv,), f32), S((1,), f32), S((1,), f32),
        ),
        arg_roles=(
            "rows", "rows", "replicated", "replicated", "replicated",
            "replicated",
        ),
        donate_expected=1,
    )


@register_spmd_core(
    "parallel.sharded_dual_lp_ell",
    loop_collectives=(
        "row-sharded ELL GEMV: same per-iteration psum as the dense twin — "
        "the reduction over row shards is the dual ascent step itself"
    ),
)
def _spmd_sharded_dual_lp_ell(mesh) -> IRCase:
    """The ELL twin's graftspmd build, packed at the registration's k_pad."""
    S = jax.ShapeDtypeStruct
    f32, i32 = jnp.float32, jnp.int32
    rows, nv, kp = 64, 33, 8
    return IRCase(
        fn=_get_sharded_jit_ell(mesh, block_iters=128, max_blocks=8),
        args=(
            S((rows, kp), i32), S((rows, kp), f32), S((rows,), f32),
            S((nv,), f32), S((nv,), f32), S((1,), f32), S((1,), f32),
        ),
        arg_roles=(
            "rows", "rows", "rows", "replicated", "replicated", "replicated",
            "replicated",
        ),
        donate_expected=1,
    )


def _run_core(
    mesh: Mesh,
    G: np.ndarray,
    h: np.ndarray,
    c: np.ndarray,
    a_row: np.ndarray,
    b: np.ndarray,
    tol: float,
    block_iters: int,
    max_blocks: int,
    cfg: Optional[Config] = None,
):
    """Shared marshalling for the sharded PDHG core: cache the COMPILED
    program per (mesh, block schedule), upload the row shards pre-partitioned,
    run. The jit wrapper (rather than an eagerly-executed shard_map) keeps one
    compiled executable per bucketed shape, and every input arrives already
    laid out in the sharding the program expects — the row shards via an
    explicit row-parallel ``NamedSharding``, the small replicated vectors via
    a replicated one — so successive masters of the same padded shape re-enter
    the executable without any host-side re-layout of the carry. ``h`` is
    donated (it is shape/sharding-matched with the returned λ shard), freeing
    its buffer for the output instead of allocating a fresh one per round."""
    core = _get_sharded_jit(mesh, block_iters, max_blocks)
    row_sharding = dist_partition.rows(mesh, 2)
    vec_sharding = dist_partition.rows(mesh, 1)
    rep_sharding = dist_partition.replicated(mesh)
    G_dev = jax.device_put(np.asarray(G, np.float32), row_sharding)
    h_dev = jax.device_put(np.asarray(h, np.float32), vec_sharding)
    c_dev = jax.device_put(np.asarray(c, np.float32), rep_sharding)
    a_dev = jax.device_put(np.asarray(a_row, np.float32), rep_sharding)
    b_dev = jax.device_put(np.asarray(b, np.float32), rep_sharding)
    tol_dev = jax.device_put(np.asarray([tol], np.float32), rep_sharding)
    # every input arrives pre-partitioned via explicit device_put above; the
    # guard makes an IMPLICIT transfer inside the sharded solve an error —
    # exactly the per-round host-side re-layout this path exists to avoid
    from citizensassemblies_tpu.utils.guards import no_implicit_transfers

    with dispatch_span(
        "parallel.sharded_dual_lp", cfg=cfg, rows=int(G.shape[0])
    ) as _ds:
        with no_implicit_transfers(cfg):
            out = core(G_dev, h_dev, c_dev, a_dev, b_dev, tol_dev)
        _ds.out = out
    return out


def _run_core_ell(
    mesh: Mesh,
    idx: np.ndarray,
    val: np.ndarray,
    h: np.ndarray,
    c: np.ndarray,
    a_row: np.ndarray,
    b: np.ndarray,
    tol: float,
    block_iters: int,
    max_blocks: int,
    cfg: Optional[Config] = None,
):
    """:func:`_run_core` for the ELL program: the packed index/value shards
    upload pre-partitioned over the row axis, everything else replicated —
    same guard, donation and executable-reuse contract."""
    core = _get_sharded_jit_ell(mesh, block_iters, max_blocks)
    row_sharding = dist_partition.rows(mesh, 2)
    vec_sharding = dist_partition.rows(mesh, 1)
    rep_sharding = dist_partition.replicated(mesh)
    idx_dev = jax.device_put(np.asarray(idx, np.int32), row_sharding)
    val_dev = jax.device_put(np.asarray(val, np.float32), row_sharding)
    h_dev = jax.device_put(np.asarray(h, np.float32), vec_sharding)
    c_dev = jax.device_put(np.asarray(c, np.float32), rep_sharding)
    a_dev = jax.device_put(np.asarray(a_row, np.float32), rep_sharding)
    b_dev = jax.device_put(np.asarray(b, np.float32), rep_sharding)
    tol_dev = jax.device_put(np.asarray([tol], np.float32), rep_sharding)
    from citizensassemblies_tpu.utils.guards import no_implicit_transfers

    with dispatch_span(
        "parallel.sharded_dual_lp_ell", cfg=cfg, rows=int(idx.shape[0])
    ) as _ds:
        with no_implicit_transfers(cfg):
            out = core(idx_dev, val_dev, h_dev, c_dev, a_dev, b_dev, tol_dev)
        _ds.out = out
    return out


def solve_dual_lp_pdhg_sharded(
    P_mat: np.ndarray,
    fixed: np.ndarray,
    mesh: Mesh,
    cfg: Optional[Config] = None,
    tol: Optional[float] = None,
    max_blocks: int = 120,
    block_iters: int = 512,
) -> DualSolution:
    """Dual leximin LP (``leximin.py:300-328``) with a mesh-sharded,
    device-resident PDHG.

    Variables ``z = [y (n), ŷ]``; ``min ŷ − Σ fixedᵢ yᵢ`` s.t.
    ``P y − ŷ·1 ≤ 0``, ``Σ_unfixed y = 1``, ``z ≥ 0``. Returns the standard
    :class:`DualSolution` (``ok=False`` ⇒ use the host fallback).
    """
    cfg = cfg or default_config()
    tol = float(cfg.pdhg_tol if tol is None else tol)
    P_mat = np.asarray(P_mat, dtype=np.float32)
    C, n = P_mat.shape
    ndev = mesh.devices.size
    fixed = np.asarray(fixed, dtype=np.float64)
    unfixed = fixed < 0
    fixed_vals = np.where(unfixed, 0.0, fixed)

    # pad rows to a device multiple; a zero row adds ŷ ≥ 0 (already implied)
    rows = -(-C // ndev) * ndev
    G = np.zeros((rows, n + 1), dtype=np.float32)
    G[:C, :n] = P_mat
    G[:, n] = -1.0
    a_row = np.concatenate([unfixed.astype(np.float64), [0.0]])
    b = np.array([1.0])
    c = np.concatenate([-fixed_vals, [1.0]])

    # sparse routing: the rows are panels (k members + the ŷ column), so
    # the fill is ≈ k/n — at the portfolio sizes that reach this path the
    # ELL shard ships and streams a small fraction of the dense bytes
    from citizensassemblies_tpu.solvers.sparse_ops import (
        ell_pack_rows,
        sparse_enabled,
    )

    fill = float(np.count_nonzero(G)) / max(G.size, 1)
    if sparse_enabled(cfg, fill):
        idx_r, val_r, _nnz = ell_pack_rows(G)
        x, lam, mu, res = _run_core_ell(
            mesh, idx_r, val_r, np.zeros(rows, dtype=np.float32), c, a_row,
            b, tol, block_iters, max_blocks, cfg=cfg,
        )
    else:
        x, lam, mu, res = _run_core(
            mesh, G, np.zeros(rows, dtype=np.float32), c, a_row, b, tol,
            block_iters, max_blocks, cfg=cfg,
        )
    x = np.asarray(x, dtype=np.float64)
    res_f = float(np.asarray(res)[0])
    y = x[:n]
    yhat = float(x[n])
    objective = float(c @ x)
    return DualSolution(ok=bool(res_f <= tol * 4.0), y=y, yhat=yhat, objective=objective)


def solve_decomp_master_sharded(
    MT: np.ndarray,
    v: np.ndarray,
    mesh: Mesh,
    cfg: Optional[Config] = None,
    tol: Optional[float] = None,
    max_blocks: int = 120,
    block_iters: int = 512,
):
    """The face-decomposition two-sided ε-LP with mesh-sharded rows.

    Same LP as ``cg_typespace._decomp_lp`` / ``face_decompose._master_pdhg``:
    variables ``[p (C), ε]``, ``min ε`` s.t. ``v − ε ≤ M p ≤ v + ε``,
    ``Σp = 1``, all ≥ 0 — the flagship solve path's heaviest recurring
    kernel, here row-sharded over the mesh (2T rows split across devices,
    psum-reduced transposed GEMVs) so pools whose type count outgrows one
    chip keep scaling. Returns ``(eps_realized, w, p_norm, eps_obj, ok)``
    with the same semantics as ``_master_pdhg`` (the arithmetic
    ``eps_realized`` is solver-independent; ``w = y_lo − y_up`` are the
    aiming duals).
    """
    cfg = cfg or default_config()
    tol = float(cfg.pdhg_tol if tol is None else tol)
    MT = np.asarray(MT, dtype=np.float64)
    T, C = MT.shape
    ndev = mesh.devices.size
    v = np.asarray(v, dtype=np.float64)

    # pad columns to a bucket so successive face rounds (whose column
    # counts differ) reuse one compiled program: a zero column has zero
    # cost/constraint coefficients, keeps Ruiz scale 1, and its variable
    # stays at its zero start
    bucket = 2048
    Cp = -(-(C + 1) // bucket) * bucket
    rows = -(-(2 * T) // ndev) * ndev
    G = np.zeros((rows, Cp), dtype=np.float32)
    G[:T, :C] = -MT
    G[T : 2 * T, :C] = MT
    G[: 2 * T, C] = -1.0
    h = np.zeros(rows, dtype=np.float32)
    h[:T] = -v
    h[T : 2 * T] = v
    a_row = np.zeros(Cp)
    a_row[:C] = 1.0
    b = np.array([1.0])
    c = np.zeros(Cp)
    c[C] = 1.0

    x, lam, mu, res = _run_core(
        mesh, G, h, c, a_row, b, tol, block_iters, max_blocks, cfg=cfg
    )
    x = np.asarray(x, dtype=np.float64)
    lam = np.asarray(lam, dtype=np.float64)
    res_f = float(np.asarray(res)[0])
    p = np.maximum(x[:C], 0.0)
    total = p.sum()
    if not np.isfinite(total) or total <= 0.0:
        return float("inf"), np.zeros(T), np.full(C, 1.0 / max(C, 1)), float("inf"), False
    p_norm = p / total
    eps_real = float(np.abs(MT @ p_norm - v).max())
    w = np.maximum(lam[:T], 0.0) - np.maximum(lam[T : 2 * T], 0.0)
    return eps_real, w, p_norm, float(x[C]), bool(res_f <= tol * 4.0)
