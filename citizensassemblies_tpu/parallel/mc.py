"""Distributed Monte-Carlo and portfolio reductions via shard_map + collectives.

Replaces the reference's sequential 10,000-iteration LEGACY loop
(``analysis.py:180-187``) with chain-parallel sampling across the device mesh:
every device draws its own batch of panels with the jitted greedy kernel, and
the per-agent selection counts plus the n×n pair co-selection matrix are
reduced with ``psum`` over the ``chains`` axis (ICI collectives — the
framework's "communication backend", cf. SURVEY.md §5 "Distributed
communication backend").

The shard_map'd callables are built once per (mesh, static-shape) key and
memoized in module-level caches: a fresh wrapper per call carries a fresh
trace identity, which defeats JAX's compile cache and re-lowers the whole
sampler every MC round (graftlint R2). The instance tensors are therefore
*arguments* of the mapped functions (replicated specs), not closure captures
— captured device arrays would be baked into the trace as constants, forcing
exactly the per-call retrace the memo exists to avoid.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh
from jax.sharding import PartitionSpec as P

from citizensassemblies_tpu.core.instance import DenseInstance
from citizensassemblies_tpu.dist import partition as dist_partition
from citizensassemblies_tpu.dist.runtime import AXIS_AGENTS, AXIS_CHAINS, CHAIN_AXES
from citizensassemblies_tpu.lint.registry import (
    IRCase,
    register_ir_core,
    register_spmd_core,
)
from citizensassemblies_tpu.models.legacy import _sample_panels_kernel, chain_keys_for
from citizensassemblies_tpu.obs.hooks import dispatch_span
from citizensassemblies_tpu.parallel.mesh import shard_map_compat
from citizensassemblies_tpu.utils.memo import LRU

# LRU-bounded (utils/memo): keys embed the Mesh object, so a session that
# recreates meshes (sweeps, dry runs, bench rows) would otherwise leak one
# set of lowered executables per mesh instance forever. Evictions are
# counted process-wide (memo_evictions()); a re-built wrapper after an
# eviction re-lowers once, exactly like a first call.
_DRAW_CACHE: LRU = LRU(cap=8, name="mc_draw")
_ROUND_CACHE: LRU = LRU(cap=8, name="mc_round")
_MATVEC_CACHE: LRU = LRU(cap=8, name="mc_matvec")
_DROPOUT_CACHE: LRU = LRU(cap=8, name="mc_dropout")
_DROPOUT_SHARD_CACHE: LRU = LRU(cap=8, name="mc_dropout_shard")

#: replacement policies of the dropout-realization kernel (scenarios/dropout):
#: "type" refills each no-show seat with a uniformly random off-panel agent of
#: the SAME base type (identical feature row, so quota-preserving by
#: construction), "naive" re-draws
#: uniformly from ALL off-panel agents (the baseline; may break quotas),
#: "none" leaves no-show seats empty.
DROPOUT_POLICIES: Tuple[str, ...] = ("type", "naive", "none")


def _draw_callable(mesh: Mesh, B_local: int, sharded_scores: bool):
    """Memoized chain-parallel draw: args ``(dense, keys, scores, households)``
    with the instance replicated and the key/score streams chain-sharded."""
    key = (mesh, B_local, sharded_scores)
    fn = _DRAW_CACHE.get(key)
    if fn is None:
        score_spec = P(CHAIN_AXES) if sharded_scores else P()

        @partial(
            shard_map_compat,
            mesh=mesh,
            in_specs=(P(), P(CHAIN_AXES), score_spec, P()),
            out_specs=(P(CHAIN_AXES), P(CHAIN_AXES)),
        )
        def fn(dense, local_keys, local_scores, households):
            return _sample_panels_kernel(
                dense,
                local_keys[0],
                B_local,
                local_scores,
                households,
                chain_keys=local_keys,
            )

        _DRAW_CACHE[key] = fn
    return fn


def distributed_sample_panels(
    dense: DenseInstance,
    key,
    batch: int,
    mesh: Mesh,
    scores=None,
    households=None,
    log=None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Chain-parallel panel draw over the mesh, bit-identical to the
    single-device kernel.

    Every chain's randomness comes from its *global* chain id
    (:func:`~citizensassemblies_tpu.models.legacy.chain_keys_for`), so device
    d simply evaluates chains ``[d·B_local, (d+1)·B_local)`` of the same
    stream the single-device kernel would produce — the production routing
    for the reference's 10k-draw estimator loop (``analysis.py:180-187``).
    Returns ``(panels int32[batch, k], ok bool[batch])``.
    """
    ndev = mesh.devices.size
    B_local = -(-batch // ndev)  # ceil
    total = B_local * ndev
    keys = chain_keys_for(key, 0, total)
    sharded_scores = (
        scores is not None and getattr(scores, "ndim", 1) == 2 and scores.shape[0] > 1
    )
    if sharded_scores and scores.shape[0] < total:
        scores = jnp.concatenate(
            [jnp.asarray(scores, jnp.float32)]
            + [jnp.zeros((total - scores.shape[0], dense.n), jnp.float32)],
            axis=0,
        )
    # a singleton-household vector is the kernel's households=None semantics,
    # so the mapped function keeps one signature either way
    hh = (
        jnp.asarray(households, jnp.int32)
        if households is not None
        else jnp.arange(dense.n, dtype=jnp.int32)
    )
    draw = _draw_callable(mesh, B_local, sharded_scores)
    # pre-partition the chain-axis key stream into the declared spec, so the
    # shard_map dispatch consumes it in place instead of resharding
    keys = dist_partition.prepartition(
        keys, dist_partition.chain_batch(mesh, ndim=keys.ndim), log=log
    )
    panels, ok = draw(
        dense,
        keys,
        scores if scores is not None else jnp.zeros((1, dense.n), jnp.float32),
        hh,
    )
    return panels[:batch], ok[:batch]


def _round_callable(mesh: Mesh, per_device_batch: int, n: int):
    """Memoized MC round: one draw + psum-reduced count/pair statistics."""
    key = (mesh, per_device_batch, n)
    fn = _ROUND_CACHE.get(key)
    if fn is None:
        # varying-axis audit off (shard_map_compat): the sampler's scan
        # carries state replicated that becomes device-varying through the
        # per-device keys
        @partial(
            shard_map_compat,
            mesh=mesh,
            in_specs=(P(), P(CHAIN_AXES)),
            out_specs=(P(CHAIN_AXES), P(CHAIN_AXES), P(), P()),
        )
        def fn(dense, local_keys):
            panels, ok = _sample_panels_kernel(dense, local_keys[0], per_device_batch)
            S = jnp.zeros((per_device_batch, n), dtype=jnp.float32)
            S = S.at[jnp.arange(per_device_batch)[:, None], panels].set(1.0)
            S = S * ok[:, None].astype(jnp.float32)
            counts = jax.lax.psum(jnp.sum(S, axis=0), CHAIN_AXES)
            pair = jax.lax.psum(S.T @ S, CHAIN_AXES)
            pair = pair * (1.0 - jnp.eye(n, dtype=pair.dtype))
            return panels, ok, counts, pair

        _ROUND_CACHE[key] = fn
    return fn


def distributed_mc_round(
    dense: DenseInstance, key, mesh: Mesh, per_device_batch: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One chain-parallel Monte-Carlo round over the mesh.

    Each device draws ``per_device_batch`` panels; returns
    ``(panels [ndev*B, k], ok [ndev*B], counts [n], pair [n, n])`` where
    ``counts``/``pair`` are the psum-reduced selection counts and pair
    co-selection counts of all accepted panels.
    """
    ndev = mesh.devices.size
    keys = jax.random.split(key, ndev)
    round_fn = _round_callable(mesh, per_device_batch, dense.n)
    return round_fn(dense, keys)


def _matvec_callable(mesh: Mesh):
    key = mesh
    fn = _MATVEC_CACHE.get(key)
    if fn is None:

        @partial(
            shard_map_compat,
            mesh=mesh,
            in_specs=(P(AXIS_CHAINS, AXIS_AGENTS), P(AXIS_CHAINS)),
            out_specs=P(AXIS_AGENTS),
        )
        def fn(P_local, p_local):
            return jax.lax.psum(P_local.T @ p_local, AXIS_CHAINS)

        _MATVEC_CACHE[key] = fn
    return fn


def distributed_allocation(P_matrix, probs, mesh: Mesh, log=None):
    """π = Pᵀ p with the portfolio row-sharded over the ``chains`` axis and the
    agent axis sharded over ``agents`` — the layout used by the device LP
    solver at large portfolio sizes. Operands go through the declared-once
    graftpod specs: a caller that keeps its portfolio resident in the
    declared sharding pays zero placement work (``dist_reshards`` stays 0)."""
    P_sharded = dist_partition.prepartition(
        P_matrix, dist_partition.portfolio(mesh), log=log
    )
    p_sharded = dist_partition.prepartition(
        probs, dist_partition.chain_rows(mesh), log=log
    )
    return _matvec_callable(mesh)(P_sharded, p_sharded)


# --- dropout realization (scenarios/dropout) ---------------------------------
# One draw = sample a panel from the portfolio, flip per-member attendance
# coins, refill the no-show seats under a replacement policy, and check the
# realized panel against the quotas. The per-type uniform refill uses a
# segment-rank trick instead of a gather/loop: every agent gets a uniform
# priority (+2 if ineligible), one argsort over ``type·4 + priority`` orders
# each type's eligible candidates first, and a candidate is seated iff its
# rank within its type segment is below that type's no-show count — a
# uniformly random need_t-subset of the eligible candidates, with no
# data-dependent shapes anywhere in the trace.


def _dropout_realization_fn(B: int, policy: str):
    """Memoized jitted dropout-realization batch: ``B`` draws per call.

    Signature (all arrays device operands, shapes static per cache key):
    ``(Pm bool[C,n], cum f32[C], attend f32[n], type_id i32[n],
    starts i32[n] (segment start of each agent's type), A bool[n,F],
    qmin i32[F], qmax i32[F], keys u32[B,2])`` →
    ``(counts f32[n], counts_valid f32[n], quota_ok f32[B], seated f32[B])``
    where ``counts_valid`` only accrues seats on realized panels that satisfy
    ALL quotas (a quota-broken realization is a failed assembly).
    """
    key = (B, policy)
    fn = _DROPOUT_CACHE.get(key)
    if fn is not None:
        return fn
    if policy not in DROPOUT_POLICIES:
        raise ValueError(f"unknown replacement policy {policy!r} {DROPOUT_POLICIES}")

    @jax.jit
    def fn(Pm, cum, attend, type_id, starts, A, qmin, qmax, keys):
        C, n = Pm.shape

        def one_draw(k):
            kp, ka, kr = jax.random.split(k, 3)
            c = jnp.minimum(
                jnp.searchsorted(
                    cum, jax.random.uniform(kp, dtype=jnp.float32), side="right"
                ),
                C - 1,
            )
            members = Pm[c]
            shows = members & (
                jax.random.uniform(ka, (n,), dtype=jnp.float32) < attend
            )
            noshow = members & ~shows
            if policy == "none":
                final = shows
            else:
                cand = ~members
                score = jax.random.uniform(
                    kr, (n,), dtype=jnp.float32
                ) + 2.0 * (~cand).astype(jnp.float32)
                if policy == "type":
                    need = (
                        jnp.zeros(n, jnp.int32)
                        .at[type_id]
                        .add(noshow.astype(jnp.int32))
                    )
                    order = jnp.argsort(type_id.astype(jnp.float32) * 4.0 + score)
                    pos = (
                        jnp.zeros(n, jnp.int32)
                        .at[order]
                        .set(jnp.arange(n, dtype=jnp.int32))
                    )
                    refill = cand & (pos - starts < need[type_id])
                else:  # naive: one global segment, re-draw from everyone off-panel
                    order = jnp.argsort(score)
                    pos = (
                        jnp.zeros(n, jnp.int32)
                        .at[order]
                        .set(jnp.arange(n, dtype=jnp.int32))
                    )
                    refill = cand & (pos < jnp.sum(noshow))
                final = shows | refill
            fcnt = final.astype(jnp.int32) @ A.astype(jnp.int32)
            ok = jnp.all((fcnt >= qmin) & (fcnt <= qmax))
            return (
                final.astype(jnp.float32),
                ok.astype(jnp.float32),
                jnp.sum(final).astype(jnp.float32),
            )

        seated, ok, filled = jax.vmap(one_draw)(keys)
        return (
            jnp.sum(seated, axis=0),
            jnp.sum(seated * ok[:, None], axis=0),
            ok,
            filled,
        )

    _DROPOUT_CACHE[key] = fn
    return fn


def _dropout_shard_callable(mesh: Mesh, B_local: int, policy: str):
    """Chain-sharded dropout realization: per-device vmapped draws, psum'd
    counts. Instance tensors are replicated ARGUMENTS (graftlint R2)."""
    key = (mesh, B_local, policy)
    fn = _DROPOUT_SHARD_CACHE.get(key)
    if fn is None:
        body = _dropout_realization_fn(B_local, policy)

        @partial(
            shard_map_compat,
            mesh=mesh,
            in_specs=(P(), P(), P(), P(), P(), P(), P(), P(), P(CHAIN_AXES)),
            out_specs=(
                P(),
                P(),
                P(CHAIN_AXES),
                P(CHAIN_AXES),
            ),
        )
        def fn(Pm, cum, attend, type_id, starts, A, qmin, qmax, local_keys):
            counts, valid, ok, filled = body(
                Pm, cum, attend, type_id, starts, A, qmin, qmax, local_keys
            )
            return (
                jax.lax.psum(counts, CHAIN_AXES),
                jax.lax.psum(valid, CHAIN_AXES),
                ok,
                filled,
            )

        _DROPOUT_SHARD_CACHE[key] = fn
    return fn


@dataclasses.dataclass
class DropoutRealization:
    """Monte-Carlo realized-outcome estimate of a panel distribution under
    agent dropout (``scenarios/dropout``)."""

    counts: np.ndarray  # float64[n] times each agent ended up seated
    counts_valid: np.ndarray  # float64[n] seats on quota-satisfying panels only
    draws: int
    policy: str
    quota_ok_rate: float  # fraction of realized panels satisfying all quotas
    fill_rate: float  # mean realized panel size / k

    @property
    def frequencies(self) -> np.ndarray:
        """Realized per-agent seating probability estimate."""
        return self.counts / float(self.draws)

    @property
    def frequencies_valid(self) -> np.ndarray:
        """Per-agent probability of being seated on a VALID realized panel —
        a quota-broken assembly counts as a failed realization, so policies
        that refill seats by breaking quotas pay for it here."""
        return self.counts_valid / float(self.draws)


def _type_segment_starts(type_id: np.ndarray) -> np.ndarray:
    """``starts[i]`` = index of the first agent of agent i's type in the
    type-sorted order the kernel's argsort produces (types are assigned in
    first-appearance order by TypeReduction, but the segment trick only needs
    *consistent* segments, so plain bincount order works for any labeling)."""
    type_id = np.asarray(type_id, dtype=np.int32)
    T = int(type_id.max()) + 1 if type_id.size else 0
    counts = np.bincount(type_id, minlength=T)
    starts_t = np.concatenate([[0], np.cumsum(counts)[:-1]]).astype(np.int32)
    return starts_t[type_id]


def dropout_realization_round(
    P_matrix: np.ndarray,
    probs: np.ndarray,
    attendance: np.ndarray,
    type_id: np.ndarray,
    dense: DenseInstance,
    key,
    draws: int,
    policy: str = "type",
    mesh: Optional[Mesh] = None,
) -> DropoutRealization:
    """Estimate realized seating outcomes of a panel distribution under
    per-agent attendance probabilities and a replacement policy.

    ``P_matrix`` is the bool[C, n] portfolio with probabilities ``probs``;
    ``attendance`` is float[n] per-agent show-up probability; ``type_id``
    the base-type labels replacement candidates are matched on (agents with
    identical feature rows, so any same-type refill preserves quotas). With a
    ``mesh`` the draws are chain-sharded over its devices, on the same
    global key stream (:func:`chain_keys_for`), so a 1-device mesh is
    bit-identical to the plain path and an N-device mesh evaluates the
    same draws in parallel.
    """
    Pm = jnp.asarray(np.asarray(P_matrix, dtype=bool))
    p = np.clip(np.asarray(probs, dtype=np.float64), 0.0, None)
    p = p / p.sum()
    cum = jnp.asarray(np.cumsum(p), dtype=jnp.float32)
    attend = jnp.asarray(np.asarray(attendance), dtype=jnp.float32)
    tid = jnp.asarray(np.asarray(type_id), dtype=jnp.int32)
    starts = jnp.asarray(_type_segment_starts(type_id))
    A = jnp.asarray(np.asarray(dense.host.A, dtype=bool))
    qmin = jnp.asarray(np.asarray(dense.host.qmin), dtype=jnp.int32)
    qmax = jnp.asarray(np.asarray(dense.host.qmax), dtype=jnp.int32)
    with dispatch_span(
        "mc.dropout_realization", draws=int(draws), policy=policy
    ) as _ds:
        if mesh is None:
            keys = chain_keys_for(key, 0, draws)
            counts, valid, ok, filled = _dropout_realization_fn(int(draws), policy)(
                Pm, cum, attend, tid, starts, A, qmin, qmax, keys
            )
            total = int(draws)
        else:
            ndev = mesh.devices.size
            B_local = -(-int(draws) // ndev)  # ceil
            total = B_local * ndev
            keys = chain_keys_for(key, 0, total)
            counts, valid, ok, filled = _dropout_shard_callable(mesh, B_local, policy)(
                Pm, cum, attend, tid, starts, A, qmin, qmax, keys
            )
        counts = np.asarray(counts, dtype=np.float64)
        valid = np.asarray(valid, dtype=np.float64)
        ok_rate = float(np.asarray(ok, dtype=np.float64).mean())
        fill = float(np.asarray(filled, dtype=np.float64).mean()) / float(dense.k)
        _ds.out = {"draws": total, "quota_ok_rate": round(ok_rate, 4)}
    return DropoutRealization(
        counts=counts,
        counts_valid=valid,
        draws=total,
        policy=policy,
        quota_ok_rate=ok_rate,
        fill_rate=fill,
    )


@register_ir_core("mc.dropout_realization", span="mc.dropout_realization")
def _build_dropout_realization_case() -> IRCase:
    """IR case at a small representative shape: 64 draws over a 12-panel
    portfolio of 40 agents with 6 quota features, "type" policy (the
    production default — the argsort segment-refill path)."""
    C, n, F, B = 12, 40, 6, 64
    f32 = jnp.float32
    i32 = jnp.int32
    return IRCase(
        fn=_dropout_realization_fn(B, "type"),
        args=(
            jax.ShapeDtypeStruct((C, n), jnp.bool_),
            jax.ShapeDtypeStruct((C,), f32),
            jax.ShapeDtypeStruct((n,), f32),
            jax.ShapeDtypeStruct((n,), i32),
            jax.ShapeDtypeStruct((n,), i32),
            jax.ShapeDtypeStruct((n, F), jnp.bool_),
            jax.ShapeDtypeStruct((F,), i32),
            jax.ShapeDtypeStruct((F,), i32),
            jax.ShapeDtypeStruct((B, 2), jnp.uint32),
        ),
    )


@register_spmd_core("mc.dropout_realization")
def _spmd_dropout_realization(mesh) -> IRCase:
    """graftspmd build: the chain-sharded production wrapper (per-device
    vmapped draws, psum'd counts) at 8 draws per device — the global key
    batch scales with the swept mesh so every size keeps the same per-shard
    program. The bare shard_map callable has no ``.lower``; the verifier
    needs the jitted form, so this builder jits it per swept mesh (cheap,
    lint-only — the production path stays on the memoized cache)."""
    ndev = int(mesh.devices.size)
    B_local = 8
    C, n, F = 12, 40, 6
    f32 = jnp.float32
    i32 = jnp.int32
    fn = jax.jit(_dropout_shard_callable(mesh, B_local, "type"))  # graftlint: disable=R2 -- verifier-only rewrap; production dispatch uses the _DROPOUT_SHARD_CACHE memo
    return IRCase(
        fn=fn,
        args=(
            jax.ShapeDtypeStruct((C, n), jnp.bool_),
            jax.ShapeDtypeStruct((C,), f32),
            jax.ShapeDtypeStruct((n,), f32),
            jax.ShapeDtypeStruct((n,), i32),
            jax.ShapeDtypeStruct((n,), i32),
            jax.ShapeDtypeStruct((n, F), jnp.bool_),
            jax.ShapeDtypeStruct((F,), i32),
            jax.ShapeDtypeStruct((F,), i32),
            jax.ShapeDtypeStruct((B_local * ndev, 2), jnp.uint32),
        ),
        arg_roles=(
            "replicated", "replicated", "replicated", "replicated",
            "replicated", "replicated", "replicated", "replicated",
            "chain_batch",
        ),
    )
