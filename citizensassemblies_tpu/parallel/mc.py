"""Distributed Monte-Carlo and portfolio reductions via shard_map + collectives.

Replaces the reference's sequential 10,000-iteration LEGACY loop
(``analysis.py:180-187``) with chain-parallel sampling across the device mesh:
every device draws its own batch of panels with the jitted greedy kernel, and
the per-agent selection counts plus the n×n pair co-selection matrix are
reduced with ``psum`` over the ``chains`` axis (ICI collectives — the
framework's "communication backend", cf. SURVEY.md §5 "Distributed
communication backend").

The shard_map'd callables are built once per (mesh, static-shape) key and
memoized in module-level caches: a fresh wrapper per call carries a fresh
trace identity, which defeats JAX's compile cache and re-lowers the whole
sampler every MC round (graftlint R2). The instance tensors are therefore
*arguments* of the mapped functions (replicated specs), not closure captures
— captured device arrays would be baked into the trace as constants, forcing
exactly the per-call retrace the memo exists to avoid.
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from citizensassemblies_tpu.core.instance import DenseInstance
from citizensassemblies_tpu.models.legacy import _sample_panels_kernel, chain_keys_for
from citizensassemblies_tpu.parallel.mesh import shard_map_compat
from citizensassemblies_tpu.utils.memo import LRU

# LRU-bounded (utils/memo): keys embed the Mesh object, so a session that
# recreates meshes (sweeps, dry runs, bench rows) would otherwise leak one
# set of lowered executables per mesh instance forever. Evictions are
# counted process-wide (memo_evictions()); a re-built wrapper after an
# eviction re-lowers once, exactly like a first call.
_DRAW_CACHE: LRU = LRU(cap=8, name="mc_draw")
_ROUND_CACHE: LRU = LRU(cap=8, name="mc_round")
_MATVEC_CACHE: LRU = LRU(cap=8, name="mc_matvec")


def _draw_callable(mesh: Mesh, B_local: int, sharded_scores: bool):
    """Memoized chain-parallel draw: args ``(dense, keys, scores, households)``
    with the instance replicated and the key/score streams chain-sharded."""
    key = (mesh, B_local, sharded_scores)
    fn = _DRAW_CACHE.get(key)
    if fn is None:
        score_spec = P(("chains", "agents")) if sharded_scores else P()

        @partial(
            shard_map_compat,
            mesh=mesh,
            in_specs=(P(), P(("chains", "agents")), score_spec, P()),
            out_specs=(P(("chains", "agents")), P(("chains", "agents"))),
        )
        def fn(dense, local_keys, local_scores, households):
            return _sample_panels_kernel(
                dense,
                local_keys[0],
                B_local,
                local_scores,
                households,
                chain_keys=local_keys,
            )

        _DRAW_CACHE[key] = fn
    return fn


def distributed_sample_panels(
    dense: DenseInstance,
    key,
    batch: int,
    mesh: Mesh,
    scores=None,
    households=None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Chain-parallel panel draw over the mesh, bit-identical to the
    single-device kernel.

    Every chain's randomness comes from its *global* chain id
    (:func:`~citizensassemblies_tpu.models.legacy.chain_keys_for`), so device
    d simply evaluates chains ``[d·B_local, (d+1)·B_local)`` of the same
    stream the single-device kernel would produce — the production routing
    for the reference's 10k-draw estimator loop (``analysis.py:180-187``).
    Returns ``(panels int32[batch, k], ok bool[batch])``.
    """
    ndev = mesh.devices.size
    B_local = -(-batch // ndev)  # ceil
    total = B_local * ndev
    keys = chain_keys_for(key, 0, total)
    sharded_scores = (
        scores is not None and getattr(scores, "ndim", 1) == 2 and scores.shape[0] > 1
    )
    if sharded_scores and scores.shape[0] < total:
        scores = jnp.concatenate(
            [jnp.asarray(scores, jnp.float32)]
            + [jnp.zeros((total - scores.shape[0], dense.n), jnp.float32)],
            axis=0,
        )
    # a singleton-household vector is the kernel's households=None semantics,
    # so the mapped function keeps one signature either way
    hh = (
        jnp.asarray(households, jnp.int32)
        if households is not None
        else jnp.arange(dense.n, dtype=jnp.int32)
    )
    draw = _draw_callable(mesh, B_local, sharded_scores)
    panels, ok = draw(
        dense,
        keys,
        scores if scores is not None else jnp.zeros((1, dense.n), jnp.float32),
        hh,
    )
    return panels[:batch], ok[:batch]


def _round_callable(mesh: Mesh, per_device_batch: int, n: int):
    """Memoized MC round: one draw + psum-reduced count/pair statistics."""
    key = (mesh, per_device_batch, n)
    fn = _ROUND_CACHE.get(key)
    if fn is None:
        # varying-axis audit off (shard_map_compat): the sampler's scan
        # carries state replicated that becomes device-varying through the
        # per-device keys
        @partial(
            shard_map_compat,
            mesh=mesh,
            in_specs=(P(), P(("chains", "agents"))),
            out_specs=(P(("chains", "agents")), P(("chains", "agents")), P(), P()),
        )
        def fn(dense, local_keys):
            panels, ok = _sample_panels_kernel(dense, local_keys[0], per_device_batch)
            S = jnp.zeros((per_device_batch, n), dtype=jnp.float32)
            S = S.at[jnp.arange(per_device_batch)[:, None], panels].set(1.0)
            S = S * ok[:, None].astype(jnp.float32)
            counts = jax.lax.psum(jnp.sum(S, axis=0), ("chains", "agents"))
            pair = jax.lax.psum(S.T @ S, ("chains", "agents"))
            pair = pair * (1.0 - jnp.eye(n, dtype=pair.dtype))
            return panels, ok, counts, pair

        _ROUND_CACHE[key] = fn
    return fn


def distributed_mc_round(
    dense: DenseInstance, key, mesh: Mesh, per_device_batch: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One chain-parallel Monte-Carlo round over the mesh.

    Each device draws ``per_device_batch`` panels; returns
    ``(panels [ndev*B, k], ok [ndev*B], counts [n], pair [n, n])`` where
    ``counts``/``pair`` are the psum-reduced selection counts and pair
    co-selection counts of all accepted panels.
    """
    ndev = mesh.devices.size
    keys = jax.random.split(key, ndev)
    round_fn = _round_callable(mesh, per_device_batch, dense.n)
    return round_fn(dense, keys)


def _matvec_callable(mesh: Mesh):
    key = mesh
    fn = _MATVEC_CACHE.get(key)
    if fn is None:

        @partial(
            shard_map_compat,
            mesh=mesh,
            in_specs=(P("chains", "agents"), P("chains")),
            out_specs=P("agents"),
        )
        def fn(P_local, p_local):
            return jax.lax.psum(P_local.T @ p_local, "chains")

        _MATVEC_CACHE[key] = fn
    return fn


def distributed_allocation(P_matrix, probs, mesh: Mesh):
    """π = Pᵀ p with the portfolio row-sharded over the ``chains`` axis and the
    agent axis sharded over ``agents`` — the layout used by the device LP
    solver at large portfolio sizes."""
    P_sharded = jax.device_put(P_matrix, NamedSharding(mesh, P("chains", "agents")))
    p_sharded = jax.device_put(probs, NamedSharding(mesh, P("chains")))
    return _matvec_callable(mesh)(P_sharded, p_sharded)
