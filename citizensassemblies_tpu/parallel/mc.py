"""Distributed Monte-Carlo and portfolio reductions via shard_map + collectives.

Replaces the reference's sequential 10,000-iteration LEGACY loop
(``analysis.py:180-187``) with chain-parallel sampling across the device mesh:
every device draws its own batch of panels with the jitted greedy kernel, and
the per-agent selection counts plus the n×n pair co-selection matrix are
reduced with ``psum`` over the ``chains`` axis (ICI collectives — the
framework's "communication backend", cf. SURVEY.md §5 "Distributed
communication backend").
"""

from __future__ import annotations

from functools import partial
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding
from jax.sharding import PartitionSpec as P

from citizensassemblies_tpu.core.instance import DenseInstance
from citizensassemblies_tpu.models.legacy import _sample_panels_kernel, chain_keys_for
from citizensassemblies_tpu.parallel.mesh import shard_map_compat


def distributed_sample_panels(
    dense: DenseInstance,
    key,
    batch: int,
    mesh: Mesh,
    scores=None,
    households=None,
) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """Chain-parallel panel draw over the mesh, bit-identical to the
    single-device kernel.

    Every chain's randomness comes from its *global* chain id
    (:func:`~citizensassemblies_tpu.models.legacy.chain_keys_for`), so device
    d simply evaluates chains ``[d·B_local, (d+1)·B_local)`` of the same
    stream the single-device kernel would produce — the production routing
    for the reference's 10k-draw estimator loop (``analysis.py:180-187``).
    Returns ``(panels int32[batch, k], ok bool[batch])``.
    """
    ndev = mesh.devices.size
    B_local = -(-batch // ndev)  # ceil
    total = B_local * ndev
    keys = chain_keys_for(key, 0, total)
    if scores is not None and getattr(scores, "ndim", 1) == 2 and scores.shape[0] > 1:
        if scores.shape[0] < total:
            scores = jnp.concatenate(
                [jnp.asarray(scores, jnp.float32)]
                + [jnp.zeros((total - scores.shape[0], dense.n), jnp.float32)],
                axis=0,
            )
        score_spec = P(("chains", "agents"))
    else:
        score_spec = P()

    @partial(
        shard_map_compat,
        mesh=mesh,
        in_specs=(P(("chains", "agents")), score_spec),
        out_specs=(P(("chains", "agents")), P(("chains", "agents"))),
    )
    def draw(local_keys, local_scores):
        return _sample_panels_kernel(
            dense,
            local_keys[0],
            B_local,
            local_scores,
            households,
            chain_keys=local_keys,
        )

    panels, ok = draw(keys, scores if scores is not None else jnp.zeros((1, dense.n), jnp.float32))
    return panels[:batch], ok[:batch]


def distributed_mc_round(
    dense: DenseInstance, key, mesh: Mesh, per_device_batch: int
) -> Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]:
    """One chain-parallel Monte-Carlo round over the mesh.

    Each device draws ``per_device_batch`` panels; returns
    ``(panels [ndev*B, k], ok [ndev*B], counts [n], pair [n, n])`` where
    ``counts``/``pair`` are the psum-reduced selection counts and pair
    co-selection counts of all accepted panels.
    """
    n = dense.n
    ndev = mesh.devices.size
    keys = jax.random.split(key, ndev)

    # varying-axis audit off (shard_map_compat): the sampler's scan carries
    # state replicated that becomes device-varying through the per-device keys
    @partial(
        shard_map_compat,
        mesh=mesh,
        in_specs=P(("chains", "agents")),
        out_specs=(P(("chains", "agents")), P(("chains", "agents")), P(), P()),
    )
    def round_fn(local_keys):
        panels, ok = _sample_panels_kernel(dense, local_keys[0], per_device_batch)
        S = jnp.zeros((per_device_batch, n), dtype=jnp.float32)
        S = S.at[jnp.arange(per_device_batch)[:, None], panels].set(1.0)
        S = S * ok[:, None].astype(jnp.float32)
        counts = jax.lax.psum(jnp.sum(S, axis=0), ("chains", "agents"))
        pair = jax.lax.psum(S.T @ S, ("chains", "agents"))
        pair = pair * (1.0 - jnp.eye(n, dtype=pair.dtype))
        return panels, ok, counts, pair

    return round_fn(keys)


def distributed_allocation(P_matrix, probs, mesh: Mesh):
    """π = Pᵀ p with the portfolio row-sharded over the ``chains`` axis and the
    agent axis sharded over ``agents`` — the layout used by the device LP
    solver at large portfolio sizes."""
    P_sharded = jax.device_put(P_matrix, NamedSharding(mesh, P("chains", "agents")))
    p_sharded = jax.device_put(probs, NamedSharding(mesh, P("chains")))

    @partial(
        shard_map_compat,
        mesh=mesh,
        in_specs=(P("chains", "agents"), P("chains")),
        out_specs=P("agents"),
    )
    def matvec(P_local, p_local):
        return jax.lax.psum(P_local.T @ p_local, "chains")

    return matvec(P_sharded, p_sharded)
