"""Device mesh construction for the framework's parallel axes.

The reference is single-process and single-threaded (SURVEY.md §2 "Parallelism
& communication": none of any kind); the TPU framework's parallelism is
greenfield, specified over two natural axes:

* ``chains`` — data parallelism over Monte-Carlo chains / pricing candidates
  (the 10k-draw loop at ``analysis.py:180-187`` and the batched pricing oracle),
  reduced with ``psum`` over ICI.
* ``agents`` — model parallelism over the agent axis for the n×n pair matrix,
  portfolio matvecs, and dual-LP iterations at large n.

Multi-host execution uses the same meshes via ``jax.distributed`` +
``jax.sharding.Mesh`` over all processes' devices; XLA inserts the collectives
(ICI within a slice, DCN across slices).
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


_DEFAULT_MESH: Optional[Mesh] = None


def shard_map_compat(f, *, mesh: Mesh, in_specs, out_specs):
    """``shard_map`` across the JAX API migration, varying-axis audit off.

    Newer JAX exposes ``jax.shard_map`` with the audit knob named
    ``check_vma``; 0.4-era releases only have
    ``jax.experimental.shard_map.shard_map`` with it named ``check_rep``.
    Every shard-mapped program in this package disables the audit (their
    scans carry replicated state that becomes device-varying through
    per-device keys), so one compat entry point keeps the same decorator
    working on both — without it the whole ``parallel/`` layer fails to
    even decorate on a 0.4 runtime.
    """
    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
    )


def default_mesh() -> Mesh:
    """Process-wide chains×agents mesh over every visible device (cached).

    The auto-distribution hook of ``sample_panels_batch`` uses this so the
    production estimator shards without the caller managing a mesh; tests and
    the driver's ``dryrun_multichip`` build explicit meshes instead.
    """
    global _DEFAULT_MESH
    if _DEFAULT_MESH is None or _DEFAULT_MESH.devices.size != len(jax.devices()):
        _DEFAULT_MESH = make_mesh()
    return _DEFAULT_MESH


def make_mesh(
    n_devices: Optional[int] = None,
    axis_names: Tuple[str, str] = ("chains", "agents"),
    agents_axis: int = 1,
) -> Mesh:
    """Build a (chains × agents) mesh over the first ``n_devices`` devices.

    ``agents_axis`` devices are dedicated to sharding the agent dimension; the
    rest parallelize chains. Defaults to pure chain parallelism, the right
    layout for every reference-scale instance (n ≤ 2000 fits one chip).
    """
    devices = jax.devices()
    n = n_devices or len(devices)
    devices = np.asarray(devices[:n])
    if n % agents_axis != 0:
        raise ValueError(f"n_devices={n} not divisible by agents_axis={agents_axis}")
    return Mesh(devices.reshape(n // agents_axis, agents_axis), axis_names)
