"""Device mesh construction for the framework's parallel axes.

The reference is single-process and single-threaded (SURVEY.md §2 "Parallelism
& communication": none of any kind); the TPU framework's parallelism is
greenfield, specified over two natural axes:

* ``chains`` — data parallelism over Monte-Carlo chains / pricing candidates
  (the 10k-draw loop at ``analysis.py:180-187`` and the batched pricing oracle),
  reduced with ``psum`` over ICI.
* ``agents`` — model parallelism over the agent axis for the n×n pair matrix,
  portfolio matvecs, and dual-LP iterations at large n.

Topology construction itself lives in the graftpod runtime
(``dist/runtime.py``), which owns the canonical axis names, the multi-process
bootstrap and the hosts×devices layout; this module is the compatibility
surface existing call sites import (``make_mesh``/``default_mesh`` delegate)
plus the ``shard_map`` API-migration shim.
"""

from __future__ import annotations

from typing import Optional, Tuple

from jax.sharding import Mesh

from citizensassemblies_tpu.dist import runtime as _runtime
from citizensassemblies_tpu.dist.runtime import CHAIN_AXES


def shard_map_compat(f, *, mesh: Mesh, in_specs, out_specs):
    """``shard_map`` across the JAX API migration, varying-axis audit off.

    Newer JAX exposes ``jax.shard_map`` with the audit knob named
    ``check_vma``; 0.4-era releases only have
    ``jax.experimental.shard_map.shard_map`` with it named ``check_rep``.
    Every shard-mapped program in this package disables the audit (their
    scans carry replicated state that becomes device-varying through
    per-device keys), so one compat entry point keeps the same decorator
    working on both — without it the whole ``parallel/`` layer fails to
    even decorate on a 0.4 runtime.
    """
    import jax

    if hasattr(jax, "shard_map"):
        return jax.shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=False
        )
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(
        f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=False
    )


def default_mesh() -> Mesh:
    """Process-wide chains×agents mesh over every visible device (cached in
    the graftpod topology).

    The auto-distribution hook of ``sample_panels_batch`` uses this so the
    production estimator shards without the caller managing a mesh; tests and
    the driver's ``dryrun_multichip`` build explicit meshes instead.
    """
    return _runtime.default_topology().mesh


def make_mesh(
    n_devices: Optional[int] = None,
    axis_names: Tuple[str, str] = CHAIN_AXES,
    agents_axis: int = 1,
) -> Mesh:
    """Build a (chains × agents) mesh over the first ``n_devices`` devices.

    ``agents_axis`` devices are dedicated to sharding the agent dimension; the
    rest parallelize chains. Defaults to pure chain parallelism, the right
    layout for every reference-scale instance (n ≤ 2000 fits one chip).
    Delegates to :func:`citizensassemblies_tpu.dist.runtime.build_topology`,
    which also lays multi-process device sets out host-major.
    """
    return _runtime.topology_mesh(
        n_devices, axis_names=axis_names, agents_axis=agents_axis
    )
