"""Batched-instance sweep: vmap the panel sampler over many instances at once.

SURVEY §7.7's batch-parallel axis: parameter studies run thousands of
Monte-Carlo estimates over *different* pools (synthetic sweeps, bootstrap
resamples, quota sensitivity scans). The reference would loop its sequential
10,000-draw estimator per instance; here instances are padded to a common
(n_max, F_max) shape and the whole sweep is one ``jax.vmap`` of the batched
sampler — a single device program whose leading axis can further be sharded
across a mesh with ``shard_map`` (``parallel/mc.py``).

Padding is semantically inert by construction: padding agents have all-zero
incidence rows, so they belong to no quota cell and can never be picked;
padding features have ``qmax = 0``, so they are never eligible urgent cells
and never constrain a draw (verified in ``tests/test_parallel.py``).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from citizensassemblies_tpu.core.instance import DenseInstance


def pad_and_stack(denses: Sequence[DenseInstance]) -> Tuple[DenseInstance, np.ndarray]:
    """Stack instances into one batched :class:`DenseInstance` pytree.

    All instances must share ``k`` (vmap requires a common scan length).
    Returns ``(batched, n_real int64[B])`` where ``batched.A`` is
    ``bool[B, n_max, F_max]``.
    """
    ks = {d.k for d in denses}
    if len(ks) != 1:
        raise ValueError(f"sweep requires a common panel size k, got {sorted(ks)}")
    n_max = max(d.n for d in denses)
    f_max = max(d.n_features for d in denses)
    A = np.zeros((len(denses), n_max, f_max), dtype=bool)
    qmin = np.zeros((len(denses), f_max), dtype=np.int32)
    qmax = np.zeros((len(denses), f_max), dtype=np.int32)
    cat = np.zeros((len(denses), f_max), dtype=np.int32)
    for i, d in enumerate(denses):
        A[i, : d.n, : d.n_features] = np.asarray(d.A)
        qmin[i, : d.n_features] = np.asarray(d.qmin)
        qmax[i, : d.n_features] = np.asarray(d.qmax)
        cat[i, : d.n_features] = np.asarray(d.cat_of_feature)
    batched = DenseInstance(
        A=jnp.asarray(A),
        qmin=jnp.asarray(qmin),
        qmax=jnp.asarray(qmax),
        cat_of_feature=jnp.asarray(cat),
        k=denses[0].k,
        n_categories=max(d.n_categories for d in denses),
    )
    return batched, np.asarray([d.n for d in denses], dtype=np.int64)


def sweep_legacy_allocations(
    denses: Sequence[DenseInstance],
    chains_per_instance: int = 1024,
    seed: int = 0,
    key=None,
) -> Tuple[np.ndarray, np.ndarray]:
    """LEGACY Monte-Carlo allocations for every instance in one device call.

    Returns ``(allocations float64[B, n_max], accept_rate float64[B])`` —
    per-agent selection frequencies over the accepted chains of each
    instance (padding agents report 0).
    """
    from citizensassemblies_tpu.models.legacy import _sample_panels_kernel

    batched, n_real = pad_and_stack(denses)
    if key is None:
        key = jax.random.PRNGKey(seed)
    keys = jax.random.split(key, len(denses))

    def one(dense_i: DenseInstance, key_i):
        panels, ok = _sample_panels_kernel(dense_i, key_i, chains_per_instance)
        n_max = dense_i.A.shape[0]
        onehot = jax.nn.one_hot(panels, n_max, dtype=jnp.float32)  # [B, k, n]
        counts = jnp.einsum("bkn,b->n", onehot, ok.astype(jnp.float32))
        denom = jnp.maximum(ok.sum(), 1)
        return counts / denom, ok.mean()

    # batch every array leaf; static fields (k, n_categories) ride along as aux
    axes = jax.tree_util.tree_map(lambda _: 0, batched)
    alloc, rate = jax.vmap(one, in_axes=(axes, 0))(batched, keys)
    return np.asarray(alloc, dtype=np.float64), np.asarray(rate, dtype=np.float64)
