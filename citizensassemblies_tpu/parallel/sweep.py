"""Batched-instance sweep: vmap the panel sampler over many instances at once.

SURVEY §7.7's batch-parallel axis: parameter studies run thousands of
Monte-Carlo estimates over *different* pools (synthetic sweeps, bootstrap
resamples, quota sensitivity scans). The reference would loop its sequential
10,000-draw estimator per instance; here instances are padded to a common
(n_max, F_max) shape and the whole sweep is one ``jax.vmap`` of the batched
sampler — a single device program whose leading axis can further be sharded
across a mesh with ``shard_map`` (``parallel/mc.py``).

Padding is semantically inert by construction: padding agents have all-zero
incidence rows, so they belong to no quota cell and can never be picked;
padding features have ``qmax = 0``, so they are never eligible urgent cells
and never constrain a draw (verified in ``tests/test_parallel.py``).
"""

from __future__ import annotations

from typing import List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from citizensassemblies_tpu.core.instance import DenseInstance
from citizensassemblies_tpu.lint.registry import IRCase, register_ir_core
from citizensassemblies_tpu.obs.hooks import dispatch_span

#: memoized jitted sweep core — one traced program per (k, padded shape)
#: via the jit cache, instead of re-tracing the vmap on every sweep call
_SWEEP_ALLOC_CORE = None


def _get_sweep_alloc_core():
    """Build (once) the jitted vmap-over-instances MC allocation program.

    Per instance: draw ``B`` chains with the scan sampler, reduce accepted
    panels to per-agent selection frequencies and the acceptance rate. The
    vmap adds the instance axis; ``B`` stays static so the inner scan
    kernel's chain count is a compile-time constant.
    """
    global _SWEEP_ALLOC_CORE
    if _SWEEP_ALLOC_CORE is None:
        from functools import partial

        from citizensassemblies_tpu.models.legacy import _sample_panels_kernel

        def one(dense_i: DenseInstance, key_i, B: int):
            panels, ok = _sample_panels_kernel(dense_i, key_i, B)
            n_max = dense_i.A.shape[0]
            onehot = jax.nn.one_hot(panels, n_max, dtype=jnp.float32)  # [B, k, n]
            counts = jnp.einsum("bkn,b->n", onehot, ok.astype(jnp.float32))
            denom = jnp.maximum(ok.sum(), 1)
            return counts / denom, ok.mean()

        vmapped = jax.vmap(one, in_axes=(0, 0, None))

        def alloc(batched: DenseInstance, keys, *, B: int):
            return vmapped(batched, keys, B)

        _SWEEP_ALLOC_CORE = partial(jax.jit, static_argnames=("B",))(alloc)
    return _SWEEP_ALLOC_CORE


@register_ir_core("sweep.alloc_core", span="sweep.alloc_core")
def _ir_sweep_alloc_core() -> IRCase:
    """A two-instance padded sweep at the scan sampler's small shape — the
    whole estimator fleet as one device program (lint/ir.py)."""
    S = jax.ShapeDtypeStruct
    i32 = jnp.int32
    I, n, F, k, B = 2, 40, 12, 6, 32
    batched = DenseInstance(
        A=S((I, n, F), jnp.bool_), qmin=S((I, F), i32), qmax=S((I, F), i32),
        cat_of_feature=S((I, F), i32), k=k, n_categories=3,
    )
    return IRCase(
        fn=_get_sweep_alloc_core(),
        args=(batched, S((I, 2), jnp.uint32)),
        static=dict(B=B),
    )


def pad_and_stack(denses: Sequence[DenseInstance]) -> Tuple[DenseInstance, np.ndarray]:
    """Stack instances into one batched :class:`DenseInstance` pytree.

    All instances must share ``k`` (vmap requires a common scan length).
    Returns ``(batched, n_real int64[B])`` where ``batched.A`` is
    ``bool[B, n_max, F_max]``.
    """
    ks = {d.k for d in denses}
    if len(ks) != 1:
        raise ValueError(f"sweep requires a common panel size k, got {sorted(ks)}")
    n_max = max(d.n for d in denses)
    f_max = max(d.n_features for d in denses)
    A = np.zeros((len(denses), n_max, f_max), dtype=bool)
    qmin = np.zeros((len(denses), f_max), dtype=np.int32)
    qmax = np.zeros((len(denses), f_max), dtype=np.int32)
    cat = np.zeros((len(denses), f_max), dtype=np.int32)
    for i, d in enumerate(denses):
        A[i, : d.n, : d.n_features] = np.asarray(d.A)
        qmin[i, : d.n_features] = np.asarray(d.qmin)
        qmax[i, : d.n_features] = np.asarray(d.qmax)
        cat[i, : d.n_features] = np.asarray(d.cat_of_feature)
    batched = DenseInstance(
        A=jnp.asarray(A),
        qmin=jnp.asarray(qmin),
        qmax=jnp.asarray(qmax),
        cat_of_feature=jnp.asarray(cat),
        k=denses[0].k,
        n_categories=max(d.n_categories for d in denses),
    )
    return batched, np.asarray([d.n for d in denses], dtype=np.int64)


def sweep_legacy_allocations(
    denses: Sequence[DenseInstance],
    chains_per_instance: int = 1024,
    seed: int = 0,
    key=None,
) -> Tuple[np.ndarray, np.ndarray]:
    """LEGACY Monte-Carlo allocations for every instance in one device call.

    Returns ``(allocations float64[B, n_max], accept_rate float64[B])`` —
    per-agent selection frequencies over the accepted chains of each
    instance (padding agents report 0).
    """
    batched, n_real = pad_and_stack(denses)
    if key is None:
        key = jax.random.PRNGKey(seed)
    keys = jax.random.split(key, len(denses))

    # one jitted program per (k, padded shape): the memoized core batches
    # every array leaf; static fields (k, n_categories) ride along as aux
    core = _get_sweep_alloc_core()
    with dispatch_span("sweep.alloc_core", instances=len(denses)) as _ds:
        alloc, rate = core(batched, keys, B=int(chains_per_instance))
        _ds.out = (alloc, rate)
    return np.asarray(alloc, dtype=np.float64), np.asarray(rate, dtype=np.float64)


def sweep_lp_batch(
    problems,
    cfg=None,
    log=None,
    mesh=None,
    warm_key: Optional[str] = None,
    tol: Optional[float] = None,
    max_iters: Optional[int] = None,
):
    """Shard whole LP buckets of a sweep across the mesh.

    The batch-parallel sibling of :func:`sweep_legacy_allocations` for the
    *convex-solve* fleets a sweep produces (one final ε-LP / recovery LP per
    instance): ``problems`` is a sequence of
    :class:`~citizensassemblies_tpu.solvers.batch_lp.BatchLP` instances, and
    the shape-bucketed engine solves each padded bucket as ONE vmapped
    device call with the batch axis laid out over the mesh — the same
    engine, executable cache, and warm-start slots the single-chip call
    sites use, so multi-instance sweeps inherit the bucketing policy
    instead of growing a second dispatch path. With one visible device the
    mesh layout degenerates to the plain single-chip call.
    """
    from citizensassemblies_tpu.solvers.batch_lp import solve_lp_batch

    if mesh is None and jax.device_count() > 1:
        from citizensassemblies_tpu.parallel.mesh import default_mesh

        mesh = default_mesh()
    return solve_lp_batch(
        problems, cfg=cfg, log=log, warm_key=warm_key, tol=tol,
        max_iters=max_iters, mesh=mesh,
    )


def sweep_final_primal_eps(
    portfolios: Sequence[np.ndarray],
    targets: Sequence[np.ndarray],
    cfg=None,
    log=None,
    mesh=None,
    tol: Optional[float] = None,
) -> List[Tuple[np.ndarray, float]]:
    """Final ε-LPs of a whole sweep in bucketed, mesh-sharded device calls.

    For every (portfolio ``P_i`` bool[C_i, n_i], target ``t_i`` float[n_i])
    pair, solves ``min ε s.t. P_iᵀp ≥ t_i − ε, Σp = 1, p ≥ 0``
    (``leximin.py:453-464``) and returns ``[(p_i, ε_i), …]`` with ``ε_i``
    the float64 *arithmetic* downward deviation ``max(t_i − P_iᵀp, 0)`` of
    the returned normalized mixture (the quantity this LP minimizes) — the
    same solver-independent certificate style the single-instance paths
    use, so a non-converged lane is visible in its ε, never silently wrong.
    """
    from citizensassemblies_tpu.solvers.batch_lp import final_primal_batch_lp

    problems = [
        final_primal_batch_lp(P, t, tol=tol)
        for P, t in zip(portfolios, targets)
    ]
    sols = sweep_lp_batch(problems, cfg=cfg, log=log, mesh=mesh, tol=tol)
    out: List[Tuple[np.ndarray, float]] = []
    for P, t, sol in zip(portfolios, targets, sols):
        C = P.shape[0]
        p = np.maximum(np.asarray(sol.x[:C], dtype=np.float64), 0.0)
        total = p.sum()
        if not np.isfinite(total) or total <= 0.0:
            p = np.full(C, 1.0 / max(C, 1))
        else:
            p = p / total
        deficit = np.asarray(t, dtype=np.float64) - P.T.astype(np.float64) @ p
        out.append((p, float(np.maximum(deficit, 0.0).max())))
    return out
