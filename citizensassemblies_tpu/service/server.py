"""graftserve: the async selection-as-a-service front end.

One :class:`SelectionService` owns a request queue, a pool of worker threads,
a :class:`~citizensassemblies_tpu.service.batcher.CrossRequestBatcher` and a
:class:`~citizensassemblies_tpu.service.session.TenantRegistry`. Clients
:meth:`~SelectionService.submit` whole selection instances (pool + quotas +
k + algorithm ∈ {legacy, leximin, xmin}) and get back a
:class:`ResultChannel` that streams progress events while the job runs and
delivers the final allocation plus a per-request exactness-audit stamp.

Request lifecycle::

    submit(SelectionRequest) ──admission──▶ queued ──worker──▶ running
        │                                                        │
        ▶ AdmissionError when                    RequestContext installed:
          serve_queue_depth in-flight           per-request Config + RunLog,
          requests already exist                tenant session, warm store,
                                                cross-request batcher
                                                         │
    ResultChannel ◀── progress events ── RunLog lines ───┤
    ResultChannel ◀── ("result", RequestResult + audit stamp) on success
    ResultChannel ◀── ("error", message) on failure

Concurrency model: ``serve_admission_cap`` worker threads execute requests;
every solver-visible piece of per-request state rides the ambient
``RequestContext`` (config, log, warm slots), so concurrent requests are
fully isolated — the re-entrancy contract ``tests/test_service.py`` pins by
diffing interleaved runs against their serial twins bit-for-bit. Batchable
LP fleets from different in-flight requests fuse through the batcher into
shared padded device dispatches (the cross-request occupancy the serve bench
measures).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from citizensassemblies_tpu.obs.metrics import MetricsRegistry
from citizensassemblies_tpu.service.batcher import CrossRequestBatcher
from citizensassemblies_tpu.service.context import (
    RequestContext,
    _next_request_id,
    use_context,
)
from citizensassemblies_tpu.service.session import TenantRegistry
from citizensassemblies_tpu.utils.config import Config, default_config
from citizensassemblies_tpu.utils.logging import RunLog


class AdmissionError(RuntimeError):
    """The service's queue is at ``serve_queue_depth``; retry later."""


@dataclasses.dataclass
class SelectionRequest:
    """One whole selection job: an instance plus how to solve it.

    Pass either ``instance`` (a ``core.generator`` Instance — the service
    featurizes it) or a pre-featurized ``(dense, space)`` pair. ``cfg``
    overrides the service's default config FOR THIS REQUEST only (the
    re-entrancy refactor exists so that this is safe). ``iterations``/
    ``seed`` parameterize the LEGACY Monte-Carlo estimator and are ignored
    by the exact algorithms. ``dropout`` (per-agent no-show probabilities)
    parameterizes the "dropout" scenario algorithm; ``rounds`` the "multi"
    scenario (``None`` → ``Config.scenario_rounds``).
    """

    algorithm: str = "leximin"  # "legacy" | "leximin" | "xmin" | "dropout" | "multi"
    instance: Any = None
    dense: Any = None
    space: Any = None
    households: Optional[np.ndarray] = None
    cfg: Optional[Config] = None
    tenant: str = "default"
    request_id: Optional[str] = None
    iterations: int = 1_000
    seed: int = 0
    dropout: Optional[np.ndarray] = None
    rounds: Optional[int] = None
    #: graftdelta: a ``solvers.delta.ReviseSpec`` (one registry edit against
    #: an identified base solve). Only meaningful with algorithm="leximin";
    #: the service re-certifies incrementally when the tenant session holds
    #: the base certificate, and falls back BIT-IDENTICALLY to from-scratch
    #: when it cannot (cold session, oversized edit, Config.delta_solve=False)
    revise: Any = None


@dataclasses.dataclass
class RequestResult:
    """Terminal payload of a request's channel."""

    request_id: str
    tenant: str
    algorithm: str
    allocation: np.ndarray
    result: Any  # Distribution (leximin/xmin) or LegacyResult (legacy)
    audit: Dict[str, Any]
    seconds: float
    from_memo: bool = False


class ResultChannel:
    """Streamed events of one request: ``("progress", line)`` while the job
    runs, then exactly one terminal ``("result", RequestResult)`` or
    ``("error", message)``. Events are retained, so :meth:`events` and
    :meth:`result` may be called in any order (or repeatedly).

    Retention is CAPPED (``Config.serve_channel_cap``): a long request's
    progress + metrics stream cannot grow without bound — past the cap,
    incoming non-terminal events are dropped and counted
    (:attr:`dropped`); the terminal result + audit stamp is always
    retained."""

    _TERMINAL = ("result", "error")

    def __init__(self, request_id: str, cap: int = 1024):
        self.request_id = request_id
        self._cond = threading.Condition()
        self._events: List[Tuple[str, Any]] = []
        self._done = False
        self._cap = max(int(cap), 8)
        #: non-terminal events dropped by the retention cap
        self.dropped = 0

    def push(self, kind: str, payload: Any) -> None:
        with self._cond:
            if kind not in self._TERMINAL and len(self._events) >= self._cap:
                self.dropped += 1
                return
            self._events.append((kind, payload))
            if kind in self._TERMINAL:
                self._done = True
            self._cond.notify_all()

    def events(self, timeout: Optional[float] = None) -> Iterator[Tuple[str, Any]]:
        """Yield events in order, blocking for new ones until terminal."""
        i = 0
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._cond:
                while i >= len(self._events):
                    if self._done:
                        return
                    remaining = (
                        None if deadline is None else deadline - time.monotonic()
                    )
                    if remaining is not None and remaining <= 0:
                        raise TimeoutError(
                            f"request {self.request_id}: no event within timeout"
                        )
                    self._cond.wait(timeout=remaining)
                event = self._events[i]
            i += 1
            yield event
            if event[0] in self._TERMINAL:
                return

    def result(self, timeout: Optional[float] = None) -> RequestResult:
        """Block until the terminal event; raise on request failure."""
        for kind, payload in self.events(timeout=timeout):
            if kind == "result":
                return payload
            if kind == "error":
                raise RuntimeError(
                    f"request {self.request_id} failed: {payload}"
                )
        raise RuntimeError(f"request {self.request_id}: channel closed early")


class _ChannelLog(RunLog):
    """A RunLog that additionally streams every line as a progress event."""

    def __init__(self, channel: ResultChannel):
        super().__init__(echo=False)
        self._channel = channel

    def emit(self, message: str) -> str:
        super().emit(message)
        self._channel.push("progress", message)
        return message


class SelectionService:
    """Persistent async serving layer over the solver stack."""

    def __init__(self, cfg: Optional[Config] = None):
        self.cfg = cfg or default_config()
        #: hard cap on in-flight (queued + running) requests; submit()
        #: raises AdmissionError beyond it (Config.serve_queue_depth)
        self.queue_depth = max(int(self.cfg.serve_queue_depth), 1)
        #: worker threads — the number of requests RUNNING concurrently
        #: (Config.serve_admission_cap)
        self.workers = max(int(self.cfg.serve_admission_cap), 1)
        self.batcher = CrossRequestBatcher(self.cfg)
        self.tenants = TenantRegistry(
            cap_per_tenant=int(self.cfg.serve_tenant_memo_cap)
        )
        self._pool = ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix="graftserve"
        )
        self._lock = threading.Lock()
        self._in_flight = 0
        self._completed = 0
        self._failed = 0
        self._memo_served = 0
        # --- grafttrace observability (citizensassemblies_tpu/obs) --------
        #: the fleet-level typed metrics registry: per-tenant request
        #: counters, queue/batcher gauges, request-latency histogram —
        #: rendered by metrics_text() (Prometheus) and streamed as periodic
        #: ("metrics", …) channel events by the snapshot loop below
        self.metrics = MetricsRegistry(
            max_label_sets=int(getattr(self.cfg, "obs_max_label_sets", 64))
        )
        #: open channels the snapshot loop broadcasts into (rid → channel)
        self._channels: Dict[str, ResultChannel] = {}
        #: finished per-request tracers, newest last (bounded retention) —
        #: export_traces() merges them into one Chrome trace document
        self._traces: List[Any] = []
        self._snap_stop = threading.Event()
        self._snap_thread: Optional[threading.Thread] = None
        #: drain bookkeeping: rid → (future, channel). Shutdown cancels the
        #: queued-but-unstarted futures and pushes each a typed terminal
        #: rejection; running requests complete (or are deadline-bounded)
        self._futures: Dict[str, Tuple[Any, ResultChannel]] = {}
        self._closed = False
        # --- graftscope SLO engine (obs/slo.py) ----------------------------
        #: built from Config.obs_slo_spec when non-empty: every terminal
        #: request outcome is recorded, breach TRANSITIONS are streamed as
        #: ("slo", …) events into every open channel and counted
        #: (graftserve_slo_breach_total); a malformed spec fails here, at
        #: construction, not silently at evaluation time
        self.slo = None
        slo_spec = str(getattr(self.cfg, "obs_slo_spec", "") or "")
        if slo_spec:
            from citizensassemblies_tpu.obs.slo import SloEngine

            self.slo = SloEngine(slo_spec)
        # --- graftfleet load management (obs/slo.py SloLoadPolicy) ---------
        #: Config.serve_shed=True closes the SLO loop into an actuator:
        #: sustained fast-window burn turns on admission shedding (typed
        #: ShedRejection terminal events, counted graftserve_shed_total) and
        #: walks the service-level degradation ladder; recovery re-arms.
        #: Off (default) keeps the engine observe-only — pre-fleet behavior.
        self.load_policy = None
        if self.slo is not None and bool(getattr(self.cfg, "serve_shed", False)):
            from citizensassemblies_tpu.obs.slo import SloLoadPolicy

            self.load_policy = SloLoadPolicy(self.slo, self.cfg)
        # --- graftboot AOT executable cache (aot/) -------------------------
        #: the boot-loaded executable store. Tri-state Config.aot_cache:
        #: None loads a cache when one exists (missing → None, serve JIT),
        #: True fails HERE, at construction, when the artifact is absent or
        #: mismatched (fleets that must not boot cold), False never loads.
        #: submit() speculatively pre-warms it on each tenant's first
        #: admission; _finish() stamps its counters on every audit.
        self.aot_store = None
        if getattr(self.cfg, "aot_cache", None) is not False:
            from citizensassemblies_tpu.aot import boot

            self.aot_store = boot(self.cfg)
        self._prewarmed_tenants: set = set()

    # --- public API ---------------------------------------------------------

    def submit(self, request: SelectionRequest) -> ResultChannel:
        """Admit one request; returns its streaming channel immediately."""
        # load management first (shutdown still dominates below): the policy
        # re-evaluates the fast window on EVERY submit, so a fully-shedding
        # service recovers by event aging alone — no terminal outcomes needed
        if self.load_policy is not None and not self._closed:
            self.load_policy.update()
            if self.load_policy.shedding:
                return self._shed(request)
        with self._lock:
            if self._closed:
                self.metrics.counter(
                    "graftserve_admission_rejected_total",
                    help="submissions refused by back-pressure",
                ).inc()
                raise AdmissionError("service is shut down")
            if self._in_flight >= self.queue_depth:
                self.metrics.counter(
                    "graftserve_admission_rejected_total",
                    help="submissions refused by back-pressure",
                ).inc()
                raise AdmissionError(
                    f"queue full: {self._in_flight} requests in flight "
                    f"(serve_queue_depth={self.queue_depth})"
                )
            self._in_flight += 1
        rid = request.request_id or _next_request_id()
        cfg = request.cfg or self.cfg
        channel = ResultChannel(
            rid, cap=int(getattr(cfg, "serve_channel_cap", 1024) or 1024)
        )
        with self._lock:
            self._channels[rid] = channel
        self._ensure_snapshot_loop()
        self._maybe_prewarm(request.tenant, cfg)
        # the submission timestamp rides into the worker so the sojourn
        # decomposition can attribute queue wait (worker pickup − submit)
        fut = self._pool.submit(
            self._run_request, request, rid, channel, time.monotonic()
        )
        with self._lock:
            self._futures[rid] = (fut, channel)
        return channel

    def run(self, request: SelectionRequest, timeout: Optional[float] = None):
        """Convenience: submit and block for the result."""
        return self.submit(request).result(timeout=timeout)

    def _shed(self, request: SelectionRequest) -> ResultChannel:
        """Typed load-shed rejection: the channel terminates immediately
        with ``("error", {"kind": "ShedRejection", "audit": …})`` — the
        audit stub records WHY (burn, threshold, rung, window) so a shed is
        evidence, not a bare refusal. Sheds never consume queue depth."""
        rid = request.request_id or _next_request_id()
        cfg = request.cfg or self.cfg
        channel = ResultChannel(
            rid, cap=int(getattr(cfg, "serve_channel_cap", 1024) or 1024)
        )
        stub = self.load_policy.shed(request.tenant, rid)
        self.metrics.counter(
            "graftserve_shed_total",
            help="submissions shed by the SLO load-management policy",
            labelnames=("tenant",),
        ).labels(tenant=request.tenant).inc()
        channel.push(
            "error",
            {
                "kind": "ShedRejection",
                "message": (
                    f"request {rid} shed: fast-window SLO burn "
                    f"{stub['worst_burn']:.2f} ≥ {stub['burn_threshold']:.2f}; "
                    "retry after recovery"
                ),
                "audit": stub,
            },
        )
        return channel

    def _maybe_prewarm(self, tenant: str, cfg: Config) -> None:
        """Speculative bucket pre-warm on a tenant's FIRST admission: touch
        the cached batch-LP bucket executables off-thread so the buffers the
        tenant's solves will fault in are resident before its request leaves
        the queue. Tri-state ``Config.aot_prewarm``: None warms whenever a
        store is loaded, False never, True is reserved for boot-time eager
        warming (the coldboot bench child). Speculative by definition —
        failures are swallowed by ``ExecStore.prewarm`` itself."""
        store = self.aot_store
        if store is None or getattr(cfg, "aot_prewarm", None) is False:
            return
        with self._lock:
            if tenant in self._prewarmed_tenants:
                return
            self._prewarmed_tenants.add(tenant)
        threading.Thread(
            target=store.prewarm,
            kwargs={"families": ("batch_lp.",)},
            name=f"graftboot-prewarm-{tenant}",
            daemon=True,
        ).start()

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "in_flight": self._in_flight,
                "completed": self._completed,
                "failed": self._failed,
                "memo_served": self._memo_served,
            }
        out["batcher"] = self.batcher.stats()
        out["tenants"] = self.tenants.all_stats()
        return out

    # --- observability (grafttrace) -----------------------------------------

    def _ensure_snapshot_loop(self) -> None:
        """Start the periodic metrics-snapshot broadcaster lazily (first
        submission), when ``Config.obs_metrics_interval_s`` > 0. One daemon
        thread per service; every open ResultChannel receives a
        ``("metrics", snapshot)`` progress event per tick, so a streaming
        client sees queue depth / fusion ratio / eviction pressure evolve
        while its own request runs."""
        interval = float(getattr(self.cfg, "obs_metrics_interval_s", 0.0) or 0.0)
        if interval <= 0:
            return
        with self._lock:
            if self._snap_thread is not None:
                return
            self._snap_thread = threading.Thread(
                target=self._snapshot_loop,
                args=(interval,),
                daemon=True,
                name="graftserve-metrics",
            )
            self._snap_thread.start()

    def _snapshot_loop(self, interval: float) -> None:
        while not self._snap_stop.wait(interval):
            snap = self.metrics_snapshot()
            with self._lock:
                channels = list(self._channels.values())
            for ch in channels:
                ch.push("metrics", snap)

    def _refresh_gauges(self) -> None:
        """Fold the service's derived state into the registry's gauges —
        called before every snapshot/render so scrapes are current."""
        st = self.stats()
        m = self.metrics
        m.gauge("graftserve_in_flight", help="admitted, unfinished requests").set(
            st["in_flight"]
        )
        m.gauge("graftserve_queue_depth", help="admission cap (config)").set(
            self.queue_depth
        )
        b = st["batcher"]
        m.gauge(
            "graftserve_batcher_fusion_ratio",
            help="fused dispatches / dispatches (cross-request batching)",
        ).set(
            round(b.get("fused_dispatches", 0) / max(b.get("dispatches", 0), 1), 4)
        )
        m.gauge(
            "graftserve_batcher_solves_per_dispatch",
            help="cross-request occupancy",
        ).set(round(b.get("solves", 0) / max(b.get("dispatches", 0), 1), 2))
        from citizensassemblies_tpu.utils.memo import memo_evictions_by_owner

        for owner, n in memo_evictions_by_owner().items():
            m.gauge(
                "graftserve_tenant_evictions",
                help="LRU evictions attributed per owner",
                labelnames=("owner",),
            ).labels(owner=owner).set(n)
        # graftfleet load-policy state (cumulative process gauges, same
        # exposition shape as the graftboot counters below)
        if self.load_policy is not None:
            ps = self.load_policy.stamp()
            m.gauge(
                "graftserve_shed_active",
                help="1 while the load policy is shedding admissions",
            ).set(int(ps["shedding"]))
            m.gauge(
                "graftserve_degrade_rung",
                help="current service-level degradation-ladder rung",
            ).set(ps["rung"])
            m.gauge(
                "graftserve_shed_rearm_total",
                help="load-policy recovery re-arms (cumulative)",
            ).set(ps["rearm_total"])
            m.gauge(
                "graftserve_shed_burn_worst",
                help="worst fast-window SLO burn at last policy update",
            ).set(ps["worst_burn"])
        # graftboot store counters (cumulative process gauges): how much of
        # the fleet's dispatch is riding pre-compiled executables
        if self.aot_store is not None:
            aot = self.aot_store.stamp()
            m.gauge(
                "aot_cache_hit",
                help="dispatches served by boot-loaded AOT executables",
            ).set(aot["hits"])
            m.gauge(
                "aot_cache_miss",
                help="dispatches at signatures the cache does not hold",
            ).set(aot["misses"])
            m.gauge(
                "aot_cache_stale",
                help="cache entries invalidated at load or first use",
            ).set(aot["stale"])
            m.gauge(
                "aot_prewarmed",
                help="executables touched by speculative pre-warming",
            ).set(aot["prewarmed"])

    def metrics_snapshot(self) -> Dict[str, Any]:
        """Structured fleet snapshot: the typed registry plus the raw
        service/batcher/tenant stats (the periodic channel event payload)."""
        self._refresh_gauges()
        snap = self.metrics.snapshot()
        snap["service"] = self.stats()
        if self.slo is not None:
            snap["slo"] = self.slo.evaluate()
        if self.load_policy is not None:
            snap["load_policy"] = self.load_policy.stamp()
        snap["ts"] = time.time()
        return snap

    def metrics_text(self) -> str:
        """Prometheus text exposition of the fleet registry — the scrape
        dump ``bench.py --serve`` writes next to its row."""
        self._refresh_gauges()
        return self.metrics.render_prometheus()

    def export_traces(self, path: Optional[str] = None) -> Dict[str, Any]:
        """Merge the retained per-request tracers (obs_trace=True requests)
        into one Chrome trace document — each request a process lane."""
        from citizensassemblies_tpu.obs.trace import export_chrome_trace

        with self._lock:
            tracers = list(self._traces)
        return export_chrome_trace(tracers, path=path)

    def shutdown(self, wait: bool = True) -> None:
        """Drain semantics: in-flight requests COMPLETE (their channels get
        a normal terminal event), queued-but-unstarted requests get a typed
        ``ServiceShutdown`` rejection, new submissions raise
        ``AdmissionError``, and the snapshot thread is joined — no service
        thread outlives the call (``tests/test_robust.py`` asserts via
        thread enumeration)."""
        with self._lock:
            self._closed = True
        self._snap_stop.set()
        # cancel_futures rejects the queued tail; wait=True drains the
        # running requests to their terminal events first
        self._pool.shutdown(wait=wait, cancel_futures=True)
        with self._lock:
            cancelled = [
                (rid, ch)
                for rid, (fut, ch) in self._futures.items()
                if fut is not None and fut.cancelled()
            ]
            self._futures.clear()
        for rid, ch in cancelled:
            with self._lock:
                self._failed += 1
                self._in_flight -= 1
                self._channels.pop(rid, None)
            self.metrics.counter(
                "graftserve_shutdown_rejected_total",
                help="queued requests rejected by shutdown drain",
            ).inc()
            ch.push(
                "error",
                {
                    "kind": "ServiceShutdown",
                    "message": f"request {rid} cancelled before start: "
                    "service shut down",
                },
            )
        if self._snap_thread is not None:
            self._snap_thread.join(timeout=5.0)

    def __enter__(self) -> "SelectionService":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(wait=True)

    # --- the worker ---------------------------------------------------------

    def _featurize(self, request: SelectionRequest):
        if request.dense is not None:
            return request.dense, request.space
        from citizensassemblies_tpu.core.instance import featurize

        return featurize(request.instance)

    def _slo_record(self, tenant: str, latency_s: float, ok: bool) -> None:
        """Feed one terminal outcome into the SLO engine and stream any
        breach TRANSITIONS into every open channel (steady-state breaching
        does not re-emit per request; recovery re-arms the transition)."""
        if self.slo is None:
            return
        self.slo.record(tenant, latency_s, ok)
        if self.load_policy is not None:
            self.load_policy.update()
        breaches = self.slo.new_breaches()
        if not breaches:
            return
        with self._lock:
            channels = list(self._channels.values())
        for breach in breaches:
            self.metrics.counter(
                "graftserve_slo_breach_total",
                help="SLO breach transitions per tenant and objective",
                labelnames=("tenant", "objective"),
            ).labels(
                tenant=breach["tenant"], objective=breach["objective"]
            ).inc()
            for ch in channels:
                ch.push("slo", breach)

    def _run_request(
        self,
        request: SelectionRequest,
        rid: str,
        channel: ResultChannel,
        t_submit: Optional[float] = None,
    ) -> None:
        import contextlib

        from citizensassemblies_tpu.obs.memory import use_ledger
        from citizensassemblies_tpu.robust.inject import (
            FaultInjected,
            FaultInjector,
        )
        from citizensassemblies_tpu.robust.policy import (
            Deadline,
            DeadlineExceeded,
            DegradationLadder,
            RetryBudget,
        )
        from citizensassemblies_tpu.utils.guards import CompilationGuard

        t0 = time.monotonic()  # worker pickup; queue wait = t0 - t_submit
        if t_submit is None:
            t_submit = t0
        base_cfg = request.cfg or self.cfg
        log = _ChannelLog(channel)
        # graftfleet: an armed load policy runs admitted requests under its
        # CURRENT ladder rungs (rung 0 ≡ unchanged — bit-identical when the
        # policy is idle); the per-request retry ladder below then degrades
        # further from that base on transient faults
        if self.load_policy is not None:
            base_cfg = self.load_policy.degraded(base_cfg, log)
        # --- graftfault per-request machinery (robust/) --------------------
        injector = None
        if getattr(base_cfg, "fault_sites", ""):
            import zlib

            # per-request schedule: derive from the request id so the fleet
            # doesn't fire identical faults in lockstep — still fully
            # deterministic given fault_seed + submission order
            injector = FaultInjector(
                base_cfg.fault_sites,
                seed=int(getattr(base_cfg, "fault_seed", 0))
                + zlib.crc32(rid.encode()),
            )
        dl_s = float(getattr(base_cfg, "serve_deadline_s", 0.0) or 0.0)
        deadline = Deadline(dl_s) if dl_s > 0 else None
        retry = RetryBudget(
            int(getattr(base_cfg, "serve_retry_max", 2)),
            float(getattr(base_cfg, "serve_retry_backoff_s", 0.05)),
        )
        ladder = DegradationLadder()
        cfg = base_cfg
        ctx: Optional[RequestContext] = None
        success = False
        try:
            if injector is not None and injector.fire("queue_stall"):
                # chaos: artificial stall before execution — the deadline
                # accounting (and graceful rejection) must absorb it
                log.count("fault_queue_stall")
                time.sleep(0.25 if dl_s <= 0 else min(0.25, dl_s))
            # per-request tracing: obs_trace=True is the opt-in sampling
            # mode — every request gets its OWN Tracer (disjoint traces by
            # construction), installed ambiently by use_context below and
            # carried on the log so worker threads (anchor pricer, batcher
            # leader) attribute to the owning request
            tracer = None
            if getattr(base_cfg, "obs_trace", None) is True:
                from citizensassemblies_tpu.obs.trace import Tracer

                tracer = Tracer(name=rid, sample_device=True)
                log.tracer = tracer
            # graftscope: obs_memory=True gives the request its own memory
            # ledger — dispatch hooks snapshot at span boundaries while it
            # is ambient, and the audit stamp carries the summary block
            ledger = None
            if getattr(base_cfg, "obs_memory", None) is True:
                from citizensassemblies_tpu.obs.memory import MemoryLedger

                ledger = MemoryLedger(name=rid)
                ledger.snapshot("request_start")
            session = self.tenants.session(request.tenant)
            dense, space = self._featurize(request)
            fp = self._fingerprint(request, dense, base_cfg)
            memo_hit = session.memo_get((request.algorithm, fp))
            if memo_hit is not None:
                ctx = self._build_context(
                    request, rid, cfg, log, session, tracer, deadline, retry,
                    injector,
                )
                success = True
                with self._lock:
                    self._memo_served += 1
                    self._completed += 1
                    self._in_flight -= 1
                channel.push("progress", f"request {rid}: served from tenant memo")
                t_memo = time.monotonic()
                payload = self._finish(
                    request, rid, memo_hit, t0, ctx, compiles=0,
                    from_memo=True, sojourn=(t_submit, t_memo, t_memo),
                    ledger=ledger,
                )
                self._slo_record(
                    request.tenant, time.monotonic() - t_submit, ok=True
                )
                channel.push("result", payload)
                return
            # --- transient-fault retry loop (robust/policy) ----------------
            # each retry backs off exponentially and walks ONE rung down the
            # certified degradation ladder; the deadline bounds the whole
            # loop (a retry that cannot fit its backoff rejects gracefully)
            t_exec0 = time.monotonic()  # sojourn: the solve window opens
            while True:
                ctx = self._build_context(
                    request, rid, cfg, log, session, tracer, deadline, retry,
                    injector,
                )
                try:
                    if deadline is not None:
                        deadline.check("request start", log=log)
                    # single-use context managers — rebuilt every retry
                    mem_scope = (
                        use_ledger(ledger)
                        if ledger is not None
                        else contextlib.nullcontext()
                    )
                    with use_context(ctx), mem_scope:
                        with CompilationGuard(name=f"serve_{rid}", log=log) as guard:
                            if tracer is not None:
                                with tracer.span(
                                    "request", algorithm=request.algorithm,
                                    tenant=request.tenant,
                                ):
                                    result = self._execute(
                                        request, dense, space, ctx, fp
                                    )
                            else:
                                result = self._execute(request, dense, space, ctx, fp)
                    break
                except FaultInjected as exc:
                    delay = retry.take()
                    if delay is None:
                        raise  # budget exhausted: the fault is the outcome
                    # roll back the failed attempt's request-scoped writes
                    # before retrying (half-written warm state must not
                    # seed the retry), then degrade one rung
                    ctx.teardown(success=False)
                    log.count("robust_retry")
                    cfg = ladder.degrade(cfg, log)
                    log.emit(
                        f"request {rid}: transient fault "
                        f"({exc.site}); retry {retry.used}/{retry.attempts} "
                        f"after {delay * 1000:.0f}ms"
                        + (
                            f", degraded to {ladder.steps[-1]}"
                            if ladder.steps else ""
                        )
                    )
                    if deadline is not None and deadline.remaining() <= delay:
                        deadline.check("retry backoff", log=log)
                    time.sleep(delay)
            t_exec1 = time.monotonic()  # sojourn: the solve window closes
            session.memo_put((request.algorithm, fp), result)
            session.finish_request(rid)
            success = True
            payload = self._finish(
                request, rid, result, t0, ctx, compiles=guard.count,
                sojourn=(t_submit, t_exec0, t_exec1), ledger=ledger,
            )
            if tracer is not None:
                with self._lock:
                    self._traces.append(tracer)
                    del self._traces[:-64]  # bounded retention, newest kept
            self.metrics.counter(
                "graftserve_requests_total",
                help="finished requests per tenant and algorithm",
                labelnames=("tenant", "algorithm"),
            ).labels(tenant=request.tenant, algorithm=request.algorithm).inc()
            self.metrics.histogram(
                "graftserve_request_seconds",
                help="request sojourn time (submit to result)",
            ).observe(time.monotonic() - t0)
            with self._lock:
                self._completed += 1
                self._in_flight -= 1
            # SLO before the terminal event so a breach this request caused
            # is visible on its own channel too (events stop at terminal)
            self._slo_record(
                request.tenant, time.monotonic() - t_submit, ok=True
            )
            channel.push("result", payload)
        except DeadlineExceeded as exc:
            # graceful rejection: a typed terminal event carrying a PARTIAL
            # audit stamp (elapsed, counters, best-so-far evidence from the
            # raising layer) instead of a hang or a bare timeout
            self.metrics.counter(
                "graftserve_deadline_total",
                help="requests rejected by their deadline, per tenant",
                labelnames=("tenant",),
            ).labels(tenant=request.tenant).inc()
            with self._lock:
                self._failed += 1
                self._in_flight -= 1
            self._slo_record(
                request.tenant, time.monotonic() - t_submit, ok=False
            )
            channel.push(
                "error",
                {
                    "kind": "DeadlineExceeded",
                    "message": str(exc),
                    "audit": {
                        "request_id": rid,
                        "tenant": request.tenant,
                        "algorithm": request.algorithm,
                        "deadline_s": dl_s,
                        "elapsed_s": round(time.monotonic() - t0, 3),
                        "degrade_steps": list(ladder.steps),
                        "retries_used": retry.used,
                        "counters": log.counters,
                        **exc.partial,
                    },
                },
            )
        except BaseException as exc:
            self.metrics.counter(
                "graftserve_failed_total", help="failed requests per tenant",
                labelnames=("tenant",),
            ).labels(tenant=request.tenant).inc()
            with self._lock:
                self._failed += 1
                self._in_flight -= 1
            self._slo_record(
                request.tenant, time.monotonic() - t_submit, ok=False
            )
            channel.push("error", f"{type(exc).__name__}: {exc}")
        finally:
            if ctx is not None:
                # non-success exits roll back the request's warm slots and
                # session pack writes (satellite: no half-written tenant
                # state on any failure path)
                ctx.teardown(success=success)
            with self._lock:
                self._channels.pop(rid, None)
                self._futures.pop(rid, None)

    def _build_context(
        self, request, rid, cfg, log, session, tracer, deadline, retry,
        injector,
    ) -> RequestContext:
        return RequestContext(
            cfg=cfg,
            log=log,
            request_id=rid,
            tenant=request.tenant,
            warm_store=session.warm_store_for(rid),
            session=session,
            batcher=self.batcher,
            tracer=tracer,
            deadline=deadline,
            retry=retry,
            injector=injector,
        )

    def _fingerprint(self, request: SelectionRequest, dense, cfg: Config) -> str:
        from citizensassemblies_tpu.utils.checkpoint import problem_fingerprint

        fp = problem_fingerprint(dense, cfg, request.households)
        if request.algorithm == "legacy":
            fp = f"{fp}:{request.iterations}:{request.seed}"
        elif request.algorithm == "dropout":
            # the no-show vector is part of the problem identity: two
            # requests on the same instance with different dropout profiles
            # must not share a memo slot
            import zlib

            d = np.ascontiguousarray(
                np.asarray(request.dropout, dtype=np.float64)
                if request.dropout is not None
                else np.zeros(0)
            )
            fp = f"{fp}:drop{zlib.crc32(d.tobytes()) & 0xFFFFFFFF:08x}"
        elif request.algorithm == "multi":
            fp = f"{fp}:R{request.rounds if request.rounds is not None else cfg.scenario_rounds}"
        return fp

    def _execute(self, request: SelectionRequest, dense, space, ctx, fp: str):
        """Run the request's algorithm with the context installed."""
        from citizensassemblies_tpu.robust import inject

        # chaos: a worker crash at execution start is the canonical
        # transient fault — the retry loop above absorbs it
        inject.raise_if("worker_crash", ctx.log)
        algo = request.algorithm
        if algo == "legacy":
            from citizensassemblies_tpu.models.legacy import legacy_probabilities

            return legacy_probabilities(
                dense, iterations=request.iterations, seed=request.seed,
                cfg=ctx.cfg, households=request.households,
            )
        if algo == "leximin":
            from citizensassemblies_tpu.models.leximin import (
                find_distribution_leximin,
            )

            if request.revise is not None:
                return self._serve_revise(request, dense, space, ctx, fp)
            return find_distribution_leximin(
                dense, space, cfg=ctx.cfg, households=request.households,
                log=ctx.log,
            )
        if algo == "xmin":
            from citizensassemblies_tpu.models.xmin import find_distribution_xmin

            # session win: an XMIN request whose LEXIMIN seed was already
            # solved for the SAME problem (fingerprint match) reuses it —
            # the expansion + L2 stage is all that runs
            seed_dist = None
            if ctx.session is not None:
                seed_dist = ctx.session.memo_get(("leximin", fp))
                if seed_dist is not None:
                    ctx.log.emit(
                        "XMIN: reusing the tenant session's LEXIMIN seed "
                        "(fingerprint match)."
                    )
            return find_distribution_xmin(
                dense, space, cfg=ctx.cfg, households=request.households,
                log=ctx.log, leximin=seed_dist,
            )
        if algo == "dropout":
            from citizensassemblies_tpu.scenarios import find_distribution_dropout

            if request.dropout is None:
                raise ValueError(
                    "algorithm 'dropout' requires request.dropout "
                    "(per-agent no-show probabilities)"
                )
            return find_distribution_dropout(
                dense, space, dropout=request.dropout, cfg=ctx.cfg,
                households=request.households, log=ctx.log,
            )
        if algo == "multi":
            from citizensassemblies_tpu.scenarios import find_distribution_multi

            return find_distribution_multi(
                dense, space, rounds=request.rounds, cfg=ctx.cfg,
                households=request.households, log=ctx.log,
            )
        raise ValueError(
            f"unknown algorithm {algo!r} (legacy|leximin|xmin|dropout|multi)"
        )

    def _serve_revise(self, request: SelectionRequest, dense, space, ctx, fp: str):
        """graftdelta front door: serve a ``revise`` request incrementally.

        Decision ladder:

        * ``Config.delta_solve=False`` — hard off: run the plain leximin
          path, BIT-IDENTICAL to a request without ``revise`` (pinned by
          test), never touching the delta store;
        * spec inconsistent with the request instance (the edited registry's
          content fingerprint must equal the request's) — from-scratch,
          WITHOUT priming: a wrong spec must never seed future deltas;
        * cold session / edit above ``delta_max_edit_frac`` / household
          quotient — from-scratch answer (``delta_fallback``), then prime
          the delta store with a base certificate so the NEXT edit on this
          instance re-certifies warm;
        * warm — ``recertify`` (cache hit / resume / screened full ladder),
          project the certificate onto the request's reduction, realize the
          panel portfolio, stamp ``delta_cert`` on the audit, store the
          successor state under the post-edit fingerprint.

        Every fallback is the exact from-scratch solver — a delta answer is
        only ever served under a verified certificate.
        """
        from citizensassemblies_tpu.data.registry import apply_edit
        from citizensassemblies_tpu.models.leximin import (
            find_distribution_leximin,
            realize_typespace,
        )
        from citizensassemblies_tpu.solvers import delta as graftdelta
        from citizensassemblies_tpu.solvers.native_oracle import TypeReduction
        from citizensassemblies_tpu.utils.checkpoint import problem_fingerprint

        cfg, log, spec = ctx.cfg, ctx.log, request.revise
        gate = getattr(cfg, "delta_solve", None)

        def from_scratch():
            return find_distribution_leximin(
                dense, space, cfg=cfg, households=request.households,
                log=log,
            )

        if gate is False:
            return from_scratch()

        # fingerprints are computed with the REQUEST's config (the one the
        # memo/delta stores key by), not a retry-degraded ctx.cfg
        cfg0 = request.cfg or self.cfg

        # consistency: the edited registry must BE the request instance —
        # an inconsistent spec can never be served delta results (and never
        # primes the store either)
        try:
            reg_after = apply_edit(spec.reg_before, spec.edit)
            dense_after, _ = reg_after.to_dense()
            fp_after = problem_fingerprint(
                dense_after, cfg0, request.households
            )
        except Exception as exc:
            log.count("delta_fallback")
            log.emit(f"graftdelta: invalid revise spec ({exc}); from-scratch.")
            return from_scratch()
        if fp_after != fp:
            log.count("delta_fallback")
            log.emit(
                "graftdelta: revise spec inconsistent with the request "
                "instance (fingerprint mismatch); from-scratch."
            )
            return from_scratch()

        def fallback(reason: str):
            log.count("delta_fallback")
            if gate is True:
                # delta_solve=True is the LOUD mode: every fallback explains
                # itself in the request log (None falls back silently)
                log.emit(f"graftdelta: {reason}; serving from-scratch.")
            result = from_scratch()
            # prime the store so the NEXT edit re-certifies warm (consistent
            # spec only — certify_base returns None outside the enumerable
            # delta envelope)
            if ctx.session is not None:
                state = graftdelta.certify_base(
                    reg_after, cfg=cfg, log=log, fingerprint=fp
                )
                if state is not None:
                    ctx.session.delta_put(
                        fp, state, request_id=ctx.request_id
                    )
            return result

        if request.households is not None:
            # the delta certificate lives in plain type space; the household
            # quotient augments the instance, so it takes the exact path
            return fallback("household quotient not on the delta path")
        base_fp = spec.base_fingerprint
        if not base_fp:
            dense_before, _ = spec.reg_before.to_dense()
            base_fp = problem_fingerprint(
                dense_before, cfg0, request.households
            )
        frac = float(getattr(cfg, "delta_max_edit_frac", 0.05))
        if int(spec.edit.magnitude) > max(1.0, frac * dense.n):
            return fallback(
                f"edit magnitude {spec.edit.magnitude} above "
                f"delta_max_edit_frac ({frac:g} of n={dense.n})"
            )
        state = None
        if ctx.session is not None:
            state = ctx.session.delta_get(base_fp)
        if state is None:
            return fallback("no base certificate in the tenant session")

        outcome = graftdelta.recertify(
            state, spec.edit, spec.reg_before, cfg=cfg, log=log,
            fingerprint=fp,
        )
        if outcome is None:
            return fallback("edit left the delta envelope")
        reduction = TypeReduction(dense)
        ts = graftdelta.project_to_reduction(outcome.state, reduction)
        if ts is None:
            return fallback("certificate does not project onto the instance")
        result = realize_typespace(
            dense, reduction, ts, cfg, log, households=None, enumerated=True,
        )
        result.delta_cert = outcome.cert
        if ctx.session is not None:
            ctx.session.delta_put(
                fp, outcome.state, request_id=ctx.request_id
            )
        return result

    def _finish(
        self,
        request: SelectionRequest,
        rid: str,
        result,
        t0: float,
        ctx: RequestContext,
        compiles: int,
        from_memo: bool = False,
        sojourn: Optional[Tuple[float, float, float]] = None,
        ledger=None,
    ) -> RequestResult:
        """Assemble the terminal payload + per-request audit stamp."""
        from citizensassemblies_tpu.utils.memo import memo_evictions_by_owner

        seconds = time.monotonic() - t0
        allocation = np.asarray(result.allocation)
        counters = ctx.log.counters
        audit: Dict[str, Any] = {
            "request_id": rid,
            "tenant": request.tenant,
            "algorithm": request.algorithm,
            "seconds": round(seconds, 4),
            "from_memo": from_memo,
            "xla_compiles": int(compiles),
            # host↔device round-trip gauge of the decomposition rounds
            # (ROADMAP item 2's measurement prerequisite) — 0 when the
            # request never entered the face loop
            "decomp_host_syncs": int(counters.get("decomp_host_syncs", 0)),
            "counters": counters,
            "timers": {k: round(v, 4) for k, v in ctx.log.timers.items()},
        }
        # exactness stamp: the solver-carried realization deviation and its
        # 1e-3 L∞ contract verdict (legacy is a Monte-Carlo estimate — it
        # carries a draw count instead of a certificate)
        if hasattr(result, "realization_dev"):
            audit["realization_dev"] = float(result.realization_dev)
            audit["contract_ok"] = bool(result.contract_ok)
        if hasattr(result, "draws_attempted"):
            audit["draws_attempted"] = int(result.draws_attempted)
        # scenario models (scenarios/) carry their own audit block — bucket
        # counts, fallback reasons, MC realization stamps, pair gauges
        if hasattr(result, "scenario_audit"):
            audit["scenario"] = dict(result.scenario_audit)
        # graftdelta: how an incremental re-certification obtained this
        # answer (cache_hit | resume | full_ladder) with its screen stats,
        # drift and ε bound — the served certificate, auditable per request
        if hasattr(result, "delta_cert"):
            audit["delta_cert"] = dict(result.delta_cert)
        if ctx.session is not None:
            audit["session"] = ctx.session.stats()
            audit["tenant_memo_evictions"] = memo_evictions_by_owner().get(
                ctx.session.owner, 0
            )
        # graftfault evidence: retries taken, deadline headroom, and (chaos
        # runs) the injector's deterministic fire schedule — every recovery
        # counter (sentinel_*, robust_*, fault_*) is already in "counters"
        if ctx.retry is not None and ctx.retry.used:
            audit["retries_used"] = int(ctx.retry.used)
        if ctx.deadline is not None:
            audit["deadline_remaining_s"] = round(ctx.deadline.remaining(), 3)
        if ctx.injector is not None:
            audit["faults"] = ctx.injector.stats()
        if ctx.tracer is not None:
            from citizensassemblies_tpu.obs.trace import TRACE_SCHEMA_VERSION

            audit["obs"] = {
                "span_count": ctx.tracer.span_count,
                "dropped_spans": ctx.tracer.dropped,
                "schema_version": TRACE_SCHEMA_VERSION,
            }
        # graftscope sojourn decomposition, from MEASURED boundaries:
        # submit → worker pickup (queue wait) → solve window opens
        # (prepare: featurize, fingerprint, memo probe) → solve window
        # closes → audit assembly. The four components partition the
        # sojourn exactly; batch_window (the cross-request fusion wait,
        # from the batcher's timer) is a sub-component of the solve window.
        if sojourn is not None:
            t_submit, t_x0, t_x1 = sojourn
            now = time.monotonic()
            batch_window = float(ctx.log.timers.get("batch_window", 0.0))
            solve = max(t_x1 - t_x0, 0.0)
            audit["sojourn"] = {
                "total_s": round(max(now - t_submit, 0.0), 4),
                "queue_wait_s": round(max(t0 - t_submit, 0.0), 4),
                "prepare_s": round(max(t_x0 - t0, 0.0), 4),
                "solve_s": round(solve, 4),
                "batch_window_s": round(min(batch_window, solve), 4),
                "audit_s": round(max(now - t_x1, 0.0), 4),
            }
        # graftboot: the executable store's serving counters — how much of
        # this process's dispatch is riding pre-compiled executables
        if self.aot_store is not None:
            audit["aot"] = self.aot_store.stamp()
        # graftscope memory ledger: the request's device-memory summary
        if ledger is not None:
            ledger.snapshot("request_end")
            audit["memory"] = ledger.stamp()
        return RequestResult(
            request_id=rid,
            tenant=request.tenant,
            algorithm=request.algorithm,
            allocation=allocation,
            result=result,
            audit=audit,
            seconds=seconds,
            from_memo=from_memo,
        )
