"""graftserve: the async selection-as-a-service front end.

One :class:`SelectionService` owns a request queue, a pool of worker threads,
a :class:`~citizensassemblies_tpu.service.batcher.CrossRequestBatcher` and a
:class:`~citizensassemblies_tpu.service.session.TenantRegistry`. Clients
:meth:`~SelectionService.submit` whole selection instances (pool + quotas +
k + algorithm ∈ {legacy, leximin, xmin}) and get back a
:class:`ResultChannel` that streams progress events while the job runs and
delivers the final allocation plus a per-request exactness-audit stamp.

Request lifecycle::

    submit(SelectionRequest) ──admission──▶ queued ──worker──▶ running
        │                                                        │
        ▶ AdmissionError when                    RequestContext installed:
          serve_queue_depth in-flight           per-request Config + RunLog,
          requests already exist                tenant session, warm store,
                                                cross-request batcher
                                                         │
    ResultChannel ◀── progress events ── RunLog lines ───┤
    ResultChannel ◀── ("result", RequestResult + audit stamp) on success
    ResultChannel ◀── ("error", message) on failure

Concurrency model: ``serve_admission_cap`` worker threads execute requests;
every solver-visible piece of per-request state rides the ambient
``RequestContext`` (config, log, warm slots), so concurrent requests are
fully isolated — the re-entrancy contract ``tests/test_service.py`` pins by
diffing interleaved runs against their serial twins bit-for-bit. Batchable
LP fleets from different in-flight requests fuse through the batcher into
shared padded device dispatches (the cross-request occupancy the serve bench
measures).
"""

from __future__ import annotations

import dataclasses
import threading
import time
from concurrent.futures import ThreadPoolExecutor
from typing import Any, Dict, Iterator, List, Optional, Tuple

import numpy as np

from citizensassemblies_tpu.service.batcher import CrossRequestBatcher
from citizensassemblies_tpu.service.context import (
    RequestContext,
    _next_request_id,
    use_context,
)
from citizensassemblies_tpu.service.session import TenantRegistry
from citizensassemblies_tpu.utils.config import Config, default_config
from citizensassemblies_tpu.utils.logging import RunLog


class AdmissionError(RuntimeError):
    """The service's queue is at ``serve_queue_depth``; retry later."""


@dataclasses.dataclass
class SelectionRequest:
    """One whole selection job: an instance plus how to solve it.

    Pass either ``instance`` (a ``core.generator`` Instance — the service
    featurizes it) or a pre-featurized ``(dense, space)`` pair. ``cfg``
    overrides the service's default config FOR THIS REQUEST only (the
    re-entrancy refactor exists so that this is safe). ``iterations``/
    ``seed`` parameterize the LEGACY Monte-Carlo estimator and are ignored
    by the exact algorithms.
    """

    algorithm: str = "leximin"  # "legacy" | "leximin" | "xmin"
    instance: Any = None
    dense: Any = None
    space: Any = None
    households: Optional[np.ndarray] = None
    cfg: Optional[Config] = None
    tenant: str = "default"
    request_id: Optional[str] = None
    iterations: int = 1_000
    seed: int = 0


@dataclasses.dataclass
class RequestResult:
    """Terminal payload of a request's channel."""

    request_id: str
    tenant: str
    algorithm: str
    allocation: np.ndarray
    result: Any  # Distribution (leximin/xmin) or LegacyResult (legacy)
    audit: Dict[str, Any]
    seconds: float
    from_memo: bool = False


class ResultChannel:
    """Streamed events of one request: ``("progress", line)`` while the job
    runs, then exactly one terminal ``("result", RequestResult)`` or
    ``("error", message)``. Events are retained, so :meth:`events` and
    :meth:`result` may be called in any order (or repeatedly)."""

    _TERMINAL = ("result", "error")

    def __init__(self, request_id: str):
        self.request_id = request_id
        self._cond = threading.Condition()
        self._events: List[Tuple[str, Any]] = []
        self._done = False

    def push(self, kind: str, payload: Any) -> None:
        with self._cond:
            self._events.append((kind, payload))
            if kind in self._TERMINAL:
                self._done = True
            self._cond.notify_all()

    def events(self, timeout: Optional[float] = None) -> Iterator[Tuple[str, Any]]:
        """Yield events in order, blocking for new ones until terminal."""
        i = 0
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            with self._cond:
                while i >= len(self._events):
                    if self._done:
                        return
                    remaining = (
                        None if deadline is None else deadline - time.monotonic()
                    )
                    if remaining is not None and remaining <= 0:
                        raise TimeoutError(
                            f"request {self.request_id}: no event within timeout"
                        )
                    self._cond.wait(timeout=remaining)
                event = self._events[i]
            i += 1
            yield event
            if event[0] in self._TERMINAL:
                return

    def result(self, timeout: Optional[float] = None) -> RequestResult:
        """Block until the terminal event; raise on request failure."""
        for kind, payload in self.events(timeout=timeout):
            if kind == "result":
                return payload
            if kind == "error":
                raise RuntimeError(
                    f"request {self.request_id} failed: {payload}"
                )
        raise RuntimeError(f"request {self.request_id}: channel closed early")


class _ChannelLog(RunLog):
    """A RunLog that additionally streams every line as a progress event."""

    def __init__(self, channel: ResultChannel):
        super().__init__(echo=False)
        self._channel = channel

    def emit(self, message: str) -> str:
        super().emit(message)
        self._channel.push("progress", message)
        return message


class SelectionService:
    """Persistent async serving layer over the solver stack."""

    def __init__(self, cfg: Optional[Config] = None):
        self.cfg = cfg or default_config()
        #: hard cap on in-flight (queued + running) requests; submit()
        #: raises AdmissionError beyond it (Config.serve_queue_depth)
        self.queue_depth = max(int(self.cfg.serve_queue_depth), 1)
        #: worker threads — the number of requests RUNNING concurrently
        #: (Config.serve_admission_cap)
        self.workers = max(int(self.cfg.serve_admission_cap), 1)
        self.batcher = CrossRequestBatcher(self.cfg)
        self.tenants = TenantRegistry(
            cap_per_tenant=int(self.cfg.serve_tenant_memo_cap)
        )
        self._pool = ThreadPoolExecutor(
            max_workers=self.workers, thread_name_prefix="graftserve"
        )
        self._lock = threading.Lock()
        self._in_flight = 0
        self._completed = 0
        self._failed = 0
        self._memo_served = 0

    # --- public API ---------------------------------------------------------

    def submit(self, request: SelectionRequest) -> ResultChannel:
        """Admit one request; returns its streaming channel immediately."""
        with self._lock:
            if self._in_flight >= self.queue_depth:
                raise AdmissionError(
                    f"queue full: {self._in_flight} requests in flight "
                    f"(serve_queue_depth={self.queue_depth})"
                )
            self._in_flight += 1
        rid = request.request_id or _next_request_id()
        channel = ResultChannel(rid)
        self._pool.submit(self._run_request, request, rid, channel)
        return channel

    def run(self, request: SelectionRequest, timeout: Optional[float] = None):
        """Convenience: submit and block for the result."""
        return self.submit(request).result(timeout=timeout)

    def stats(self) -> Dict[str, Any]:
        with self._lock:
            out = {
                "in_flight": self._in_flight,
                "completed": self._completed,
                "failed": self._failed,
                "memo_served": self._memo_served,
            }
        out["batcher"] = self.batcher.stats()
        out["tenants"] = self.tenants.all_stats()
        return out

    def shutdown(self, wait: bool = True) -> None:
        self._pool.shutdown(wait=wait)

    def __enter__(self) -> "SelectionService":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown(wait=True)

    # --- the worker ---------------------------------------------------------

    def _featurize(self, request: SelectionRequest):
        if request.dense is not None:
            return request.dense, request.space
        from citizensassemblies_tpu.core.instance import featurize

        return featurize(request.instance)

    def _run_request(
        self, request: SelectionRequest, rid: str, channel: ResultChannel
    ) -> None:
        from citizensassemblies_tpu.utils.guards import CompilationGuard

        t0 = time.monotonic()
        try:
            cfg = request.cfg or self.cfg
            log = _ChannelLog(channel)
            session = self.tenants.session(request.tenant)
            ctx = RequestContext(
                cfg=cfg,
                log=log,
                request_id=rid,
                tenant=request.tenant,
                warm_store=session.warm_store_for(rid),
                session=session,
                batcher=self.batcher,
            )
            dense, space = self._featurize(request)
            fp = self._fingerprint(request, dense, cfg)
            memo_hit = session.memo_get((request.algorithm, fp))
            if memo_hit is not None:
                with self._lock:
                    self._memo_served += 1
                    self._completed += 1
                    self._in_flight -= 1
                channel.push("progress", f"request {rid}: served from tenant memo")
                channel.push(
                    "result",
                    self._finish(
                        request, rid, memo_hit, t0, ctx, compiles=0,
                        from_memo=True,
                    ),
                )
                return
            with use_context(ctx):
                with CompilationGuard(name=f"serve_{rid}", log=log) as guard:
                    result = self._execute(request, dense, space, ctx, fp)
            session.memo_put((request.algorithm, fp), result)
            payload = self._finish(
                request, rid, result, t0, ctx, compiles=guard.count
            )
            with self._lock:
                self._completed += 1
                self._in_flight -= 1
            channel.push("result", payload)
        except BaseException as exc:
            with self._lock:
                self._failed += 1
                self._in_flight -= 1
            channel.push("error", f"{type(exc).__name__}: {exc}")

    def _fingerprint(self, request: SelectionRequest, dense, cfg: Config) -> str:
        from citizensassemblies_tpu.utils.checkpoint import problem_fingerprint

        fp = problem_fingerprint(dense, cfg, request.households)
        if request.algorithm == "legacy":
            fp = f"{fp}:{request.iterations}:{request.seed}"
        return fp

    def _execute(self, request: SelectionRequest, dense, space, ctx, fp: str):
        """Run the request's algorithm with the context installed."""
        algo = request.algorithm
        if algo == "legacy":
            from citizensassemblies_tpu.models.legacy import legacy_probabilities

            return legacy_probabilities(
                dense, iterations=request.iterations, seed=request.seed,
                cfg=ctx.cfg, households=request.households,
            )
        if algo == "leximin":
            from citizensassemblies_tpu.models.leximin import (
                find_distribution_leximin,
            )

            return find_distribution_leximin(
                dense, space, cfg=ctx.cfg, households=request.households,
                log=ctx.log,
            )
        if algo == "xmin":
            from citizensassemblies_tpu.models.xmin import find_distribution_xmin

            # session win: an XMIN request whose LEXIMIN seed was already
            # solved for the SAME problem (fingerprint match) reuses it —
            # the expansion + L2 stage is all that runs
            seed_dist = None
            if ctx.session is not None:
                seed_dist = ctx.session.memo_get(("leximin", fp))
                if seed_dist is not None:
                    ctx.log.emit(
                        "XMIN: reusing the tenant session's LEXIMIN seed "
                        "(fingerprint match)."
                    )
            return find_distribution_xmin(
                dense, space, cfg=ctx.cfg, households=request.households,
                log=ctx.log, leximin=seed_dist,
            )
        raise ValueError(f"unknown algorithm {algo!r} (legacy|leximin|xmin)")

    def _finish(
        self,
        request: SelectionRequest,
        rid: str,
        result,
        t0: float,
        ctx: RequestContext,
        compiles: int,
        from_memo: bool = False,
    ) -> RequestResult:
        """Assemble the terminal payload + per-request audit stamp."""
        from citizensassemblies_tpu.utils.memo import memo_evictions_by_owner

        seconds = time.monotonic() - t0
        allocation = np.asarray(result.allocation)
        counters = ctx.log.counters
        audit: Dict[str, Any] = {
            "request_id": rid,
            "tenant": request.tenant,
            "algorithm": request.algorithm,
            "seconds": round(seconds, 4),
            "from_memo": from_memo,
            "xla_compiles": int(compiles),
            # host↔device round-trip gauge of the decomposition rounds
            # (ROADMAP item 2's measurement prerequisite) — 0 when the
            # request never entered the face loop
            "decomp_host_syncs": int(counters.get("decomp_host_syncs", 0)),
            "counters": counters,
            "timers": {k: round(v, 4) for k, v in ctx.log.timers.items()},
        }
        # exactness stamp: the solver-carried realization deviation and its
        # 1e-3 L∞ contract verdict (legacy is a Monte-Carlo estimate — it
        # carries a draw count instead of a certificate)
        if hasattr(result, "realization_dev"):
            audit["realization_dev"] = float(result.realization_dev)
            audit["contract_ok"] = bool(result.contract_ok)
        if hasattr(result, "draws_attempted"):
            audit["draws_attempted"] = int(result.draws_attempted)
        if ctx.session is not None:
            audit["session"] = ctx.session.stats()
            audit["tenant_memo_evictions"] = memo_evictions_by_owner().get(
                ctx.session.owner, 0
            )
        return RequestResult(
            request_id=rid,
            tenant=request.tenant,
            algorithm=request.algorithm,
            allocation=allocation,
            result=result,
            audit=audit,
            seconds=seconds,
            from_memo=from_memo,
        )
