"""graftserve: persistent async selection-as-a-service layer.

Public surface::

    from citizensassemblies_tpu.service import (
        SelectionService, SelectionRequest, RequestContext,
    )

    with SelectionService(cfg) as svc:
        ch = svc.submit(SelectionRequest(instance=inst, algorithm="leximin",
                                         tenant="city-a"))
        for kind, payload in ch.events():
            ...                      # ("progress", line) stream
        res = ch.result()            # RequestResult: allocation + audit stamp

See ``service/server.py`` for the request lifecycle, ``service/batcher.py``
for the cross-request shape-bucketed batching, ``service/session.py`` for
per-tenant state, and ``service/context.py`` for the per-request re-entrancy
contract the solver stack now honors.
"""

from citizensassemblies_tpu.service.batcher import CrossRequestBatcher
from citizensassemblies_tpu.service.context import (
    RequestContext,
    current_context,
    use_context,
)
from citizensassemblies_tpu.service.fleet import (
    FleetProcess,
    FleetRouter,
    covering_tenants,
    fleet_aggregate,
    open_loop_schedule,
    plan_from_config,
    plan_open_loop,
    rendezvous_route,
)
from citizensassemblies_tpu.service.server import (
    AdmissionError,
    RequestResult,
    ResultChannel,
    SelectionRequest,
    SelectionService,
)
from citizensassemblies_tpu.service.session import TenantRegistry, TenantSession

__all__ = [
    "AdmissionError",
    "CrossRequestBatcher",
    "FleetProcess",
    "FleetRouter",
    "RequestContext",
    "RequestResult",
    "ResultChannel",
    "SelectionRequest",
    "SelectionService",
    "TenantRegistry",
    "TenantSession",
    "covering_tenants",
    "current_context",
    "fleet_aggregate",
    "open_loop_schedule",
    "plan_from_config",
    "plan_open_loop",
    "rendezvous_route",
    "use_context",
]
