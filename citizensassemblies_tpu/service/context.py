"""Per-request execution context: the re-entrancy spine of the service layer.

Before the serving layer existed, one process ran one selection job: a
``Config`` and a ``RunLog`` were passed down the call stack, and the few
pieces of cross-call state — the batched LP engine's warm-start slots, the
memo caches — lived at module level keyed by *semantic* names
(``"decomp_polish_screen"``). That is exactly the shape that breaks under
concurrent requests: two jobs in flight share counters, warm iterates and
knobs through those process-global names.

:class:`RequestContext` lifts all of it to per-request scope. It bundles the
request's ``Config`` and ``RunLog`` with its identity (tenant + request id),
its warm-slot store, its tenant session (packed-operand and result memos) and
the cross-request batcher, and is made AMBIENT for the duration of the
request via a ``contextvars.ContextVar`` — per-thread/per-task by
construction, so two requests on two worker threads each see only their own
context. Deep call sites that cannot reasonably grow a new parameter (the
batched LP engine's warm-slot keying, the fused L2 stage's pack memo) consult
:func:`current_context`; the model entry points additionally accept ``ctx``
explicitly and install it with :func:`use_context`.

Nothing here imports jax — the context layer must stay importable from the
lint tooling and from host-only code paths.
"""

from __future__ import annotations

import dataclasses
import threading
from contextlib import contextmanager
from contextvars import ContextVar
from typing import TYPE_CHECKING, Optional

from citizensassemblies_tpu.utils.config import Config, default_config
from citizensassemblies_tpu.utils.logging import RunLog

if TYPE_CHECKING:  # pragma: no cover - typing only
    from citizensassemblies_tpu.obs.trace import Tracer
    from citizensassemblies_tpu.robust.inject import FaultInjector
    from citizensassemblies_tpu.robust.policy import Deadline, RetryBudget
    from citizensassemblies_tpu.service.batcher import CrossRequestBatcher
    from citizensassemblies_tpu.service.session import TenantSession
    from citizensassemblies_tpu.solvers.batch_lp import WarmSlotStore

#: the ambient per-request context — ContextVar semantics give each thread
#: (and each asyncio task) its own slot, which IS the isolation contract
_ACTIVE: ContextVar[Optional["RequestContext"]] = ContextVar(
    "citizens_tpu_request_context", default=None
)

_REQUEST_SEQ_LOCK = threading.Lock()
_REQUEST_SEQ = 0


def _next_request_id() -> str:
    """Process-unique fallback id for contexts created without one."""
    global _REQUEST_SEQ
    with _REQUEST_SEQ_LOCK:
        _REQUEST_SEQ += 1
        return f"req-{_REQUEST_SEQ:06d}"


@dataclasses.dataclass
class RequestContext:
    """Everything one selection request owns, threaded through the solvers.

    ``cfg``/``log`` are the knobs and the in-band log channel that used to be
    the loose (cfg, log) parameter pair. ``tenant``/``request_id`` identify
    the request for warm-slot namespacing and eviction attribution.
    ``warm_store`` is the request's PRIVATE warm-start slot store for the
    batched LP engine (``solvers/batch_lp.WarmSlotStore``) — module-level
    slots are never touched while a context is active. ``session`` is the
    tenant's cross-request state (result memos, packed ELL operands), LRU-
    capped with per-tenant eviction accounting. ``batcher`` is the service's
    cross-request shape-bucketed batcher; when present, batchable LP fleets
    are routed through it so fleets from DIFFERENT concurrent requests fuse
    into one padded vmapped dispatch.
    """

    cfg: Config
    log: RunLog
    request_id: str
    tenant: str = "default"
    warm_store: Optional["WarmSlotStore"] = None
    session: Optional["TenantSession"] = None
    batcher: Optional["CrossRequestBatcher"] = None
    #: per-request grafttrace tracer (``obs.trace``): installed as the
    #: AMBIENT tracer for the request's scope by :func:`use_context`, so
    #: concurrent requests produce disjoint, well-nested span trees — the
    #: trace-isolation contract ``tests/test_obs.py`` pins
    tracer: Optional["Tracer"] = None
    # --- graftfault (citizensassemblies_tpu/robust) -------------------------
    #: per-request wall-clock deadline (``Config.serve_deadline_s``): the CG
    #: round loop checks it once per round at the existing sync point and
    #: raises a graceful ``DeadlineExceeded`` past it
    deadline: Optional["Deadline"] = None
    #: per-request transient-fault retry budget (exponential backoff); the
    #: service walks the degradation ladder one rung per retry
    retry: Optional["RetryBudget"] = None
    #: per-request fault injector (``Config.fault_sites``) — chaos runs
    #: only; None in production (the hot-boundary consults short-circuit)
    injector: Optional["FaultInjector"] = None

    def teardown(self, success: bool) -> None:
        """Request-scoped state cleanup, called on EVERY exit path.

        On a non-success exit the request's warm slots and any session
        packs it wrote are rolled back — an aborted request must not leave
        half-written warm state for its tenant's next request to trip over
        (a failed solve's iterates are exactly the ones not to reuse).
        Success leaves the session state in place (that reuse is the
        session's point)."""
        if success:
            return
        if self.warm_store is not None:
            self.warm_store.clear()
        if self.session is not None:
            self.session.rollback_request(self.request_id)

    @classmethod
    def create(
        cls,
        cfg: Optional[Config] = None,
        log: Optional[RunLog] = None,
        request_id: Optional[str] = None,
        tenant: str = "default",
        **kw,
    ) -> "RequestContext":
        return cls(
            cfg=cfg or default_config(),
            log=log or RunLog(echo=False),
            request_id=request_id or _next_request_id(),
            tenant=tenant,
            **kw,
        )

    def scoped_warm_key(self, base: str) -> str:
        """Namespace a semantic warm-slot key (``"decomp_polish_screen"``)
        by this request's identity, so two concurrent requests using the
        same call site cannot share (or clobber) warm iterates."""
        return f"{self.tenant}/{self.request_id}/{base}"


def current_context() -> Optional[RequestContext]:
    """The ambient RequestContext of the calling thread/task, or None when
    running outside the service (the offline single-job path)."""
    return _ACTIVE.get()


@contextmanager
def use_context(ctx: Optional[RequestContext]):
    """Install ``ctx`` as the ambient context for the scope. ``None`` is a
    no-op passthrough so entry points can wrap unconditionally."""
    if ctx is None:
        yield None
        return
    token = _ACTIVE.set(ctx)
    trace_token = None
    if ctx.tracer is not None:
        # install the request's tracer on the same ContextVar mechanics as
        # the context itself — per-thread/per-task, so concurrent requests'
        # spans cannot interleave into each other's traces
        from citizensassemblies_tpu.obs.trace import activate_tracer

        trace_token = activate_tracer(ctx.tracer)
    try:
        yield ctx
    finally:
        if trace_token is not None:
            from citizensassemblies_tpu.obs.trace import deactivate_tracer

            deactivate_tracer(trace_token)
        _ACTIVE.reset(token)


def resolve(
    ctx: Optional[RequestContext],
    cfg: Optional[Config],
    log: Optional[RunLog],
) -> tuple:
    """Back-compat resolution for entry points that accept all three of
    ``ctx``/``cfg``/``log``: explicit ``cfg``/``log`` win (they always did),
    then the context's, then the defaults. Returns ``(ctx, cfg, log)`` where
    ``ctx`` may be None (pure offline call)."""
    if ctx is None:
        ctx = current_context()
    if ctx is not None:
        cfg = cfg or ctx.cfg
        log = log or ctx.log
    return ctx, cfg or default_config(), log or RunLog(echo=False)
