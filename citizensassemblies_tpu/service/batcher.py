"""Cross-request shape-bucketed batching: fuse LP fleets ACROSS requests.

The batched LP engine (``solvers/batch_lp``) fuses the many small solves of
ONE selection job into padded vmapped dispatches — but a serving workload is
a fleet of whole jobs, each of whose LP fleets is small (a mass_like_24-sized
tenant instance prescreens a handful of probe LPs per stage). Each job alone
still pays the dispatch floor per call. This module is the serving stack's
continuous-batching layer on top of the engine: when concurrent requests'
worker threads reach ``solve_lp_batch``, their fleets are briefly held open
(``Config.serve_batch_window_ms``) and merged — same iteration schedule, any
mix of shapes (the engine's shape buckets then group the union) — into ONE
engine call, so a probe fleet from tenant A and one from tenant B land in the
same padded dispatch.

Correctness invariants:

* **per-instance math unchanged** — merging only concatenates instance
  lists; each instance keeps its own tolerance (materialized into
  ``BatchLP.tol`` before the merge) and gets its own convergence mask lane,
  exactly as within-request batching already guaranteed;
* **schedule compatibility** — fleets merge only within a group key of
  (max_iters, check_every, bucket cap, transfer-guard mode), the knobs that
  select/parameterize the compiled core, so no request executes under
  another's schedule;
* **warm-slot isolation** — each submission's warm slots are loaded from and
  written back to its OWN request's store under its tenant/request-scoped
  key before/after the merge; positions inside the merged list never touch
  the slot keys;
* **no deadlock** — the first submitter of a group becomes its leader,
  sleeps out the window (GIL released), then dispatches whatever joined;
  followers wait on an event with a timeout fallback that re-claims their
  fleet and solves it directly if the leader ever died.

The batcher owns no threads — it runs entirely on the submitting requests'
worker threads — and holds no jax state; it is pure host-side coordination.
"""

from __future__ import annotations

import dataclasses
import threading
import time
from typing import Dict, List, Optional, Sequence, Set, Tuple

from citizensassemblies_tpu.dist import runtime as dist_runtime
from citizensassemblies_tpu.robust import inject
from citizensassemblies_tpu.utils.config import Config, default_config

#: follower safety net of last resort: past this, a follower re-claims its
#: own fleet and solves solo even if leadership state looks healthy
_FOLLOWER_TIMEOUT_S = 120.0

#: floor on the follower watchdog's poll interval — each wake checks the
#: leader's liveness (thread dead / claim released), so a dead leader is
#: detected within ~2 window widths instead of the 120 s safety net
_WATCHDOG_POLL_S = 0.05

#: graftfleet: one multi-device program in flight per process. Concurrent
#: mesh-spanning dispatches from distinct batcher leaders can interleave
#: their per-device launch order (dev0 runs program A's shard while dev1
#: runs program B's), and the in-process collective rendezvous then waits
#: on a partner that is queued behind the other program — a cross-program
#: deadlock, observed under the fleet's open-loop drive on the forced
#: multi-device host platform. Single-device dispatches never take this
#: lock: they cannot participate in a launch-order cycle.
_MESH_DISPATCH_LOCK = threading.Lock()


class _Pending:
    """One request's deferred fleet, parked until the group dispatches."""

    def __init__(self, problems, ctx, warm_key: Optional[str], log):
        self.problems = list(problems)
        self.ctx = ctx
        self.warm_key = warm_key
        self.log = log
        self.event = threading.Event()
        self.results: Optional[list] = None
        self.error: Optional[BaseException] = None


class CrossRequestBatcher:
    """Merge compatible ``solve_lp_batch`` fleets from concurrent requests."""

    def __init__(self, cfg: Optional[Config] = None):
        cfg = cfg or default_config()
        #: how long the group leader holds the window open for other
        #: requests' fleets to join (Config.serve_batch_window_ms)
        self.window_s = max(float(cfg.serve_batch_window_ms), 0.0) / 1000.0
        self._lock = threading.Lock()
        self._groups: Dict[tuple, List[_Pending]] = {}
        self._leaders: Set[tuple] = set()
        #: the leader's THREAD per claimed group — the followers' heartbeat:
        #: a claim whose thread is no longer alive is a dead leader, and the
        #: first follower to notice re-elects itself and dispatches
        self._leader_threads: Dict[tuple, threading.Thread] = {}
        # --- occupancy accounting (read by the bench's BENCH row) ----------
        self._stats = {
            "submissions": 0,          # solve_lp_batch calls deferred here
            "dispatches": 0,           # merged engine calls made
            "fused_dispatches": 0,     # … that merged ≥2 distinct requests
            "solves": 0,               # real LP instances solved
            "max_requests_fused": 0,   # largest request count in one merge
            "leader_deaths": 0,        # leaders that died before dispatch
            "leader_reclaims": 0,      # follower re-elections after a death
            # --- graftfleet mesh-spanning dispatch accounting --------------
            "mesh_dispatches": 0,      # merged calls laid out over a mesh
            "mesh_devices_max": 0,     # widest mesh a dispatch spanned
            "dist_placements": 0,      # operands placed into their sharding
            "dist_reshards": 0,        # PR 11 gauge: steady state must be 0
        }

    # --- public API ---------------------------------------------------------

    def submit(
        self,
        problems: Sequence,
        ctx,
        cfg: Optional[Config] = None,
        log=None,
        warm_key: Optional[str] = None,
        tol: Optional[float] = None,
        max_iters: Optional[int] = None,
    ) -> list:
        """Solve ``problems`` through the cross-request window; returns the
        per-instance solutions in input order (the ``solve_lp_batch``
        contract — call sites cannot tell they were fused)."""
        cfg = cfg or default_config()
        # materialize each instance's effective tolerance NOW: after the
        # merge there is no per-submission tol argument anymore
        base_tol = float(tol if tol is not None else cfg.pdhg_tol)
        problems = [
            p if p.tol is not None else dataclasses.replace(p, tol=base_tol)
            for p in problems
        ]
        key = (
            int(max_iters if max_iters is not None else cfg.pdhg_max_iters),
            int(cfg.pdhg_check_every),
            int(cfg.lp_batch_bucket_max),
            str(cfg.transfer_guard),
        )
        pend = _Pending(problems, ctx, warm_key, log)
        with self._lock:
            self._stats["submissions"] += 1
            self._groups.setdefault(key, []).append(pend)
            lead = key not in self._leaders
            if lead:
                self._leaders.add(key)
                self._leader_threads[key] = threading.current_thread()
        if lead:
            dispatched = False
            try:
                if self.window_s > 0:
                    # the leader's share of the fusion window — timed as
                    # "batch_window" so the sojourn decomposition and the
                    # trace CLI's fusion timeline see it (followers time
                    # their whole coupled wait under the same name)
                    if log is not None:
                        with log.timer("batch_window"):
                            time.sleep(self.window_s)  # GIL released
                    else:
                        time.sleep(self.window_s)  # GIL released; followers join
                # chaos: the leader "dies" after claiming the group, before
                # dispatch — the exact hang the follower watchdog exists for
                inject.raise_if("batcher_leader_death", log)
                with self._lock:
                    batch = self._groups.pop(key, [])
                    self._leaders.discard(key)
                    self._leader_threads.pop(key, None)
                dispatched = True
                self._dispatch(key, batch, cfg)
            finally:
                if not dispatched:
                    # the leader is dying between claim and dispatch (an
                    # exception here; a hard thread kill skips this and is
                    # caught by the is_alive() heartbeat instead): release
                    # the claim so the watchdog re-elects promptly
                    with self._lock:
                        self._leaders.discard(key)
                        self._leader_threads.pop(key, None)
                        self._stats["leader_deaths"] += 1
        else:
            if pend.log is not None:
                with pend.log.timer("batch_window"):
                    self._follower_wait(key, pend, cfg)
            else:
                self._follower_wait(key, pend, cfg)
        if pend.error is not None:
            raise pend.error
        return pend.results

    def _follower_wait(self, key: tuple, pend: _Pending, cfg: Config) -> None:
        """Wait for the leader's dispatch under the liveness watchdog.

        Every poll interval the follower checks the group's leadership: a
        claim that was released without a dispatch, or whose leader THREAD
        is no longer alive, is a dead leader — the first follower to see it
        re-elects itself and dispatches the whole remaining group (so its
        group-mates are rescued too, not just its own fleet). The old
        120 s full-window wait is kept only as the safety net of last
        resort."""
        waited = 0.0
        poll = max(self.window_s * 2.0, _WATCHDOG_POLL_S)
        while not pend.event.wait(timeout=poll):
            waited += poll
            with self._lock:
                in_group = any(p is pend for p in self._groups.get(key, []))
                lt = self._leader_threads.get(key)
                leader_dead = in_group and (
                    key not in self._leaders
                    or (lt is not None and not lt.is_alive())
                )
                if leader_dead:
                    # re-elect: claim the group before releasing the lock so
                    # exactly one follower becomes the new leader
                    self._leaders.add(key)
                    self._leader_threads[key] = threading.current_thread()
                    self._stats["leader_reclaims"] += 1
            if leader_dead:
                if pend.log is not None:
                    pend.log.count("batcher_leader_reclaim")
                with self._lock:
                    batch = self._groups.pop(key, [])
                    self._leaders.discard(key)
                    self._leader_threads.pop(key, None)
                self._dispatch(key, batch, cfg)
                return
            if waited >= _FOLLOWER_TIMEOUT_S:
                # last-resort: re-claim only our own fleet and solve solo
                with self._lock:
                    group = self._groups.get(key, [])
                    mine = pend in group
                    if mine:
                        group.remove(pend)
                if mine:
                    self._dispatch(key, [pend], cfg)
                else:
                    pend.event.wait()  # dispatch in flight — finish it
                return

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return dict(self._stats)

    # --- dispatch -----------------------------------------------------------

    def _dispatch(self, key: tuple, batch: List[_Pending], cfg: Config) -> None:
        """Run the merged fleet through the engine and fan results back."""
        from citizensassemblies_tpu.solvers.batch_lp import (
            _DEFAULT_WARM_STORE,
            solve_lp_batch,
        )

        if not batch:
            return
        max_iters, _check, _cap, _tg = key
        try:
            merged = []
            spans: List[Tuple[int, int]] = []
            for pend in batch:
                start = len(merged)
                store = scoped = None
                if pend.warm_key is not None and pend.ctx is not None:
                    store = pend.ctx.warm_store or _DEFAULT_WARM_STORE
                    scoped = pend.ctx.scoped_warm_key(pend.warm_key)
                probs = []
                for i, inst in enumerate(pend.problems):
                    if inst.warm is None and store is not None:
                        slot = store.get((scoped, i))
                        if slot is not None:
                            inst = dataclasses.replace(inst, warm=slot[:3])
                    probs.append(inst)
                merged.extend(probs)
                spans.append((start, len(merged)))
            # pod runs: hand the merged fleet to the engine pre-laid-out over
            # the process's mesh slice (None on single-device topologies — the
            # engine's host path is unchanged). Sub-device fleets stay
            # unsharded: sharding pays only with >= one lane per device, and
            # the unsharded dispatch is the layout the solo-solve bit-identity
            # contract pins
            mesh = dist_runtime.effective_mesh(cfg)
            if mesh is not None and len(merged) < int(mesh.devices.size):
                mesh = None
            # graftfleet: the engine counts its sharded-merge layout work
            # (dist_placements / dist_reshards) into this dispatch-scoped
            # log — harvested into the batcher stats below so the fleet
            # rollup can hold the PR 11 zero-steady-state-reshard gauge
            # at zero across every cross-request mesh dispatch
            from citizensassemblies_tpu.utils.logging import RunLog

            dispatch_log = RunLog(echo=False)
            if mesh is not None:
                with _MESH_DISPATCH_LOCK:
                    sols = solve_lp_batch(
                        merged, cfg=cfg, log=dispatch_log, warm_key=None,
                        max_iters=max_iters, defer=False, mesh=mesh,
                    )
            else:
                sols = solve_lp_batch(
                    merged, cfg=cfg, log=dispatch_log, warm_key=None,
                    max_iters=max_iters, defer=False, mesh=mesh,
                )
            n_requests = len({
                (p.ctx.tenant, p.ctx.request_id)
                for p in batch if p.ctx is not None
            })
            with self._lock:
                self._stats["dispatches"] += 1
                self._stats["solves"] += len(merged)
                if n_requests > 1:
                    self._stats["fused_dispatches"] += 1
                self._stats["max_requests_fused"] = max(
                    self._stats["max_requests_fused"], n_requests
                )
                if mesh is not None:
                    self._stats["mesh_dispatches"] += 1
                    self._stats["mesh_devices_max"] = max(
                        self._stats["mesh_devices_max"],
                        int(mesh.devices.size),
                    )
                self._stats["dist_placements"] += int(
                    dispatch_log.counters.get("dist_placements", 0)
                )
                self._stats["dist_reshards"] += int(
                    dispatch_log.counters.get("dist_reshards", 0)
                )
            for pend, (start, end) in zip(batch, spans):
                out = sols[start:end]
                if pend.warm_key is not None and pend.ctx is not None:
                    store = pend.ctx.warm_store or _DEFAULT_WARM_STORE
                    scoped = pend.ctx.scoped_warm_key(pend.warm_key)
                    for i, (inst, sol) in enumerate(zip(pend.problems, out)):
                        store.put(
                            (scoped, i),
                            (sol.x, sol.lam, sol.mu, int(inst.tail_vars)),
                        )
                if pend.log is not None:
                    pend.log.count("lp_batch_solves", len(out))
                    pend.log.count("lp_batch_xreq_dispatches")
                    if n_requests > 1:
                        pend.log.count("lp_batch_xreq_fused")
                pend.results = out
                pend.event.set()
        except BaseException as exc:
            for pend in batch:
                if pend.results is None:
                    pend.error = exc
                    pend.event.set()
            raise
