"""graftfleet: tenant-affine routing and open-loop load over N processes.

graftserve is one process; graftpod is one SPMD program. A civic-lottery
*platform* is neither — it is a FLEET: N independent serving processes
(each a :class:`~citizensassemblies_tpu.service.server.SelectionService`
over its own device mesh), a front router placing tenants, and a load
policy that keeps the whole thing inside its SLOs when the offered rate
exceeds capacity. This module owns the fleet's host-side coordination:

* **tenant-affine placement** — :func:`rendezvous_route` maps every tenant
  to exactly one serving process by highest-random-weight (rendezvous)
  hashing over a keyed blake2b digest. The hash is stable across processes
  and interpreter runs (no ``PYTHONHASHSEED`` dependence), every process
  computes the same placement with no coordination traffic, and growing
  the fleet from N to N+1 moves only ~1/(N+1) of the tenants — so a
  tenant's warm slots, session ``EllPack``s, memo/delta stores and AOT
  prewarm stay process-local for the life of the fleet.
* **open-loop load** — :func:`open_loop_schedule` draws seeded Poisson
  arrivals at a configured offered rate. Open-loop means arrivals do NOT
  wait for completions (the closed-loop drive of ``bench.py --serve``
  measures a different thing): the offered rate is an external fact, and
  the fleet's sustained rate at fixed p50/p99 sojourn is the measurement.
* **per-process drive + fleet rollup** — :class:`FleetProcess` drives one
  process's share of a global plan and reports a rollup;
  :func:`fleet_aggregate` merges N rollups into the fleet-level row
  (sustained req/s, pooled sojourn percentiles, summed batcher/mesh/shed
  accounting, the PR 11 zero-steady-state-reshard gauge).

Everything here is deterministic given (seed, rate, tenants, fleet size):
the fleet bench's children each rebuild the identical global plan and
filter their own share, so no IPC beyond process launch is needed.
"""

from __future__ import annotations

import dataclasses
import hashlib
import time
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from citizensassemblies_tpu.service.server import (
    AdmissionError,
    SelectionRequest,
    SelectionService,
)
from citizensassemblies_tpu.utils.config import Config

FLEET_SCHEMA_VERSION = 1


# --- tenant-affine placement (rendezvous hashing) ---------------------------


def rendezvous_weight(tenant: str, slot: int) -> int:
    """The (tenant, slot) rendezvous weight: a keyed blake2b digest read as
    an integer. Deterministic across processes and runs by construction —
    ``hash()`` would silently reshuffle the fleet per interpreter."""
    digest = hashlib.blake2b(
        f"{tenant}|{slot}".encode("utf-8"), digest_size=8
    ).digest()
    return int.from_bytes(digest, "big")


def rendezvous_route(tenant: str, n_processes: int) -> int:
    """Highest-random-weight owner of ``tenant`` among ``n_processes``
    slots. Ties are impossible in practice (64-bit digests); the max over
    slots makes membership churn minimal — removing one slot only moves
    the tenants that slot owned."""
    n = max(int(n_processes), 1)
    return max(range(n), key=lambda slot: rendezvous_weight(tenant, slot))


class FleetRouter:
    """The front router: tenant → owning process, with routing accounting.

    Stateless beyond counters — every process can instantiate its own
    router and agree on placement, which is what makes the fleet bench's
    no-IPC plan-sharing work."""

    def __init__(self, n_processes: int):
        self.n_processes = max(int(n_processes), 1)
        self._routed: Dict[int, int] = {i: 0 for i in range(self.n_processes)}

    def route(self, tenant: str) -> int:
        owner = rendezvous_route(tenant, self.n_processes)
        self._routed[owner] += 1
        return owner

    def placement(self, tenants: Sequence[str]) -> Dict[str, int]:
        """The full tenant → process map (counts NOT advanced — this is the
        planning view, :meth:`route` is the serving path)."""
        return {
            t: rendezvous_route(t, self.n_processes) for t in sorted(set(tenants))
        }

    def stats(self) -> Dict[str, Any]:
        total = sum(self._routed.values())
        return {
            "processes": self.n_processes,
            "routed_total": total,
            "routed_per_process": dict(self._routed),
            # the affinity skew gauge: max process share over the fair share
            "skew": round(
                max(self._routed.values()) * self.n_processes / max(total, 1), 3
            ),
        }


def covering_tenants(
    n_tenants: int, n_processes: int, prefix: str = "tenant"
) -> List[str]:
    """At least ``n_tenants`` tenant names, deterministically extended until
    every process owns ≥1 tenant under rendezvous placement — the fleet
    bench's workload must exercise ALL N processes, and with few tenants
    the hash can legitimately leave a slot empty. Pure function of its
    arguments, so every fleet process derives the identical list."""
    names = [f"{prefix}{i}" for i in range(max(int(n_tenants), 1))]
    n = max(int(n_processes), 1)
    i = len(names)
    while len(set(rendezvous_route(t, n) for t in names)) < n and i < 64 * n:
        names.append(f"{prefix}{i}")
        i += 1
    return names


# --- open-loop arrivals -----------------------------------------------------


def open_loop_schedule(rate_hz: float, n: int, seed: int = 0) -> np.ndarray:
    """``n`` seeded Poisson arrival offsets (seconds from drive start) at
    ``rate_hz`` offered requests/second: the cumulative sum of exponential
    inter-arrival gaps from ``np.random.default_rng(seed)``. Deterministic
    across runs and platforms — the property the fleet's no-IPC plan
    sharing and the determinism test both pin."""
    rate = max(float(rate_hz), 1e-9)
    rng = np.random.default_rng(int(seed))
    gaps = rng.exponential(scale=1.0 / rate, size=max(int(n), 0))
    return np.cumsum(gaps)


@dataclasses.dataclass(frozen=True)
class PlannedArrival:
    """One slot of the global open-loop plan."""

    index: int  # global arrival index (schedule order)
    t_offset_s: float  # arrival offset from drive start
    tenant: str
    owner: int  # owning fleet process (rendezvous placement)


def plan_open_loop(
    tenants: Sequence[str],
    n_requests: int,
    rate_hz: float,
    n_processes: int,
    seed: int = 0,
) -> List[PlannedArrival]:
    """The global fleet plan: ``n_requests`` Poisson arrivals at the fleet
    offered rate, each assigned a tenant (seeded draw over ``tenants``) and
    its rendezvous owner. Every fleet process rebuilds this identical plan
    from the same (seed, rate, tenants, fleet size) and serves the slice
    ``owner == fleet_process_index()`` — placement without coordination."""
    offsets = open_loop_schedule(rate_hz, n_requests, seed=seed)
    rng = np.random.default_rng(int(seed) + 0x5EED)
    names = list(tenants)
    picks = rng.integers(0, max(len(names), 1), size=max(int(n_requests), 0))
    return [
        PlannedArrival(
            index=i,
            t_offset_s=float(offsets[i]),
            tenant=names[int(picks[i])] if names else "default",
            owner=rendezvous_route(
                names[int(picks[i])] if names else "default", n_processes
            ),
        )
        for i in range(int(n_requests))
    ]


def plan_from_config(
    cfg,
    n_requests: int,
    seed: int = 0,
    n_processes: Optional[int] = None,
    rate_hz: Optional[float] = None,
) -> Tuple[List[str], List[PlannedArrival]]:
    """The global fleet plan derived from the Config knobs: a
    ``fleet_tenants``-sized covering tenant set over the fleet (every
    process owns ≥1 tenant) and ``n_requests`` Poisson arrivals at
    ``fleet_offered_rate_hz``. ``n_processes``/``rate_hz`` override the
    knob resolution (the bench's smoke mode and env contract)."""
    from citizensassemblies_tpu.dist import runtime as dist_runtime

    n = (
        int(n_processes)
        if n_processes is not None
        else dist_runtime.fleet_process_count(cfg)
    )
    rate = float(rate_hz if rate_hz is not None else cfg.fleet_offered_rate_hz)
    tenants = covering_tenants(int(cfg.fleet_tenants), n)
    return tenants, plan_open_loop(tenants, n_requests, rate, n, seed=seed)


# --- per-process drive ------------------------------------------------------


def _terminal(channel, timeout: float) -> Tuple[str, Any]:
    """The channel's terminal event (``("result", …)`` / ``("error", …)``)
    without raising — the open-loop drive classifies outcomes instead of
    aborting on the first typed rejection."""
    last = ("error", "channel closed early")
    try:
        for kind, payload in channel.events(timeout=timeout):
            last = (kind, payload)
    except TimeoutError:
        return ("error", "drain timeout")
    return last


class FleetProcess:
    """One serving process of the fleet: a :class:`SelectionService` plus
    the open-loop driver for this process's share of a global plan."""

    def __init__(
        self, index: int, n_processes: int, cfg: Optional[Config] = None
    ):
        self.index = int(index)
        self.router = FleetRouter(n_processes)
        self.service = SelectionService(cfg)

    def drive(
        self,
        arrivals: Sequence[Tuple[PlannedArrival, SelectionRequest]],
        timeout_s: float = 600.0,
        on_result=None,
    ) -> Dict[str, Any]:
        """Submit each request at its scheduled offset — open loop, never
        waiting for completions — then drain every channel and roll up this
        process's serving metrics. ``on_result(plan, result)`` is invoked
        for every completed request during the drain (the bench's hook for
        checking served allocations against serial references without the
        rollup having to carry whole result objects)."""
        ordered = sorted(arrivals, key=lambda ar: ar[0].t_offset_s)
        t0 = time.monotonic()
        live: List[Tuple[PlannedArrival, Any]] = []
        admission_rejected = 0
        for plan, request in ordered:
            self.router.route(plan.tenant)
            delay = (t0 + plan.t_offset_s) - time.monotonic()
            if delay > 0:
                time.sleep(delay)
            try:
                live.append((plan, self.service.submit(request)))
            except AdmissionError:
                admission_rejected += 1
        offered_s = max(time.monotonic() - t0, 1e-9)
        completed = 0
        memo_served = 0
        shed = 0
        failed = 0
        sojourns: List[float] = []
        for plan, channel in live:
            kind, payload = _terminal(channel, timeout_s)
            if kind == "result":
                completed += 1
                memo_served += 1 if payload.from_memo else 0
                soj = payload.audit.get("sojourn")
                sojourns.append(
                    float(soj["total_s"]) if soj else float(payload.seconds)
                )
                if on_result is not None:
                    on_result(plan, payload)
            elif isinstance(payload, dict) and payload.get("kind") == "ShedRejection":
                shed += 1
            else:
                failed += 1
        drained_s = max(time.monotonic() - t0, 1e-9)
        ordered_soj = sorted(sojourns)

        def pct(q: float) -> float:
            if not ordered_soj:
                return 0.0
            rank = min(len(ordered_soj) - 1, int(round(q * (len(ordered_soj) - 1))))
            return ordered_soj[rank]

        stats = self.service.stats()
        rollup: Dict[str, Any] = {
            "schema_version": FLEET_SCHEMA_VERSION,
            "process": self.index,
            "offered": len(ordered),
            "submitted": len(live),
            "completed": completed,
            "memo_served": memo_served,
            "shed": shed,
            "admission_rejected": admission_rejected,
            "failed": failed,
            "offered_window_s": round(offered_s, 3),
            "drained_s": round(drained_s, 3),
            "sustained_req_per_s": round(completed / drained_s, 2),
            "p50_sojourn_s": round(pct(0.50), 4),
            "p99_sojourn_s": round(pct(0.99), 4),
            "sojourns_s": [round(s, 4) for s in sojourns],
            "batcher": stats["batcher"],
            "router": self.router.stats(),
        }
        if self.service.load_policy is not None:
            rollup["load_policy"] = self.service.load_policy.stamp()
        if self.service.slo is not None:
            report = self.service.slo.evaluate()
            rollup["slo_ok"] = report["slo_ok"]
            rollup["slo_events"] = report["events"]
        return rollup

    def shutdown(self) -> None:
        self.service.shutdown()

    def __enter__(self) -> "FleetProcess":
        return self

    def __exit__(self, *exc) -> None:
        self.shutdown()


# --- fleet-level rollup -----------------------------------------------------

#: batcher counters summed process-wise into the fleet aggregate
_SUM_BATCHER = (
    "submissions", "dispatches", "fused_dispatches", "solves",
    "mesh_dispatches", "dist_placements", "dist_reshards",
)


def fleet_aggregate(rollups: Sequence[Dict[str, Any]]) -> Dict[str, Any]:
    """Merge per-process rollups into the fleet row: pooled sojourn
    percentiles (over every completed request, not averaged per-process
    percentiles), fleet sustained rate over the slowest process's window,
    and summed batcher/mesh/shed accounting. ``dist_reshards`` summed here
    IS the fleet's steady-state reshard gauge — the bench asserts 0."""
    pooled: List[float] = []
    for r in rollups:
        pooled.extend(r.get("sojourns_s", []))
    pooled.sort()

    def pct(q: float) -> float:
        if not pooled:
            return 0.0
        rank = min(len(pooled) - 1, int(round(q * (len(pooled) - 1))))
        return pooled[rank]

    wall = max((r.get("drained_s", 0.0) for r in rollups), default=1e-9)
    completed = sum(r.get("completed", 0) for r in rollups)
    batcher = {
        k: sum(int(r.get("batcher", {}).get(k, 0)) for r in rollups)
        for k in _SUM_BATCHER
    }
    batcher["mesh_devices_max"] = max(
        (int(r.get("batcher", {}).get("mesh_devices_max", 0)) for r in rollups),
        default=0,
    )
    return {
        "schema_version": FLEET_SCHEMA_VERSION,
        "processes": len(rollups),
        "offered": sum(r.get("offered", 0) for r in rollups),
        "completed": completed,
        "memo_served": sum(r.get("memo_served", 0) for r in rollups),
        "shed": sum(r.get("shed", 0) for r in rollups),
        "failed": sum(r.get("failed", 0) for r in rollups),
        "sustained_req_per_s": round(completed / max(wall, 1e-9), 2),
        "p50_sojourn_s": round(pct(0.50), 4),
        "p99_sojourn_s": round(pct(0.99), 4),
        "batcher": batcher,
        "steady_state_reshards": batcher["dist_reshards"],
        "slo_ok": all(r.get("slo_ok", True) for r in rollups),
    }
