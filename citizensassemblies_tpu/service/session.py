"""Per-tenant session state for the selection service.

A tenant that submits selection jobs repeatedly should not pay cold-start
costs per request, and MUST not leak memory as its request history grows.
:class:`TenantSession` holds the three kinds of cross-request state the
solver stack can reuse, every one LRU-capped (``utils/memo.LRU``) with
evictions attributed to the owning tenant (``memo_evictions_by_owner``):

* **warm-start slot stores** — one ``WarmSlotStore`` per in-flight request
  (``solvers/batch_lp``), keyed by request id. Keeping them in the session
  (instead of module level) is what makes two concurrent requests unable to
  share or clobber warm iterates, and the LRU cap is what stops a tenant's
  request history from pinning host buffers forever.
* **result memos** — completed ``Distribution``s keyed by the full problem
  fingerprint (``utils/checkpoint.problem_fingerprint``: incidence, quotas,
  k, config, households). An identical re-submission is answered from the
  memo (stamped ``memo_hit`` in the audit), and an XMIN request whose
  LEXIMIN seed was already solved for the same problem reuses it via
  ``find_distribution_xmin(..., leximin=...)`` — the service's cheapest win.
* **packed operands** — ``EllPack``s of committee matrices keyed by content
  hash, consulted by the fused L2 stage (``solvers/qp``) so a repeat solve
  over the same portfolio skips the pack step.

All mutation goes through the session's lock: requests of the same tenant
run concurrently on different worker threads.
"""

from __future__ import annotations

import threading
from typing import Dict, Optional

from citizensassemblies_tpu.utils.memo import LRU


class TenantSession:
    """One tenant's cross-request solver state, LRU-capped per store."""

    def __init__(self, tenant: str, cap: int = 8):
        self.tenant = tenant
        self.owner = f"tenant:{tenant}"
        cap = max(int(cap), 1)
        self._lock = threading.Lock()
        #: request_id → WarmSlotStore (solvers/batch_lp)
        self.warm_stores: LRU = LRU(cap=cap, name=f"{self.owner}:warm")
        #: problem fingerprint → Distribution
        self.memo: LRU = LRU(cap=cap, name=f"{self.owner}:memo")
        #: content hash → EllPack
        self.packs: LRU = LRU(cap=cap, name=f"{self.owner}:packs")
        #: instance content fingerprint → DeltaState (solvers/delta): the
        #: graftdelta base certificate a ``revise`` request re-certifies
        #: against. Fingerprint-keying is the staleness contract — a revised
        #: instance has a different fingerprint, so it can never pick up the
        #: pre-edit portfolio by accident
        self.delta: LRU = LRU(cap=cap, name=f"{self.owner}:delta")
        #: pack keys written per in-flight request (request_id → [keys]) —
        #: the rollback ledger: a request that fails mid-solve may have
        #: half-useful packs in the session, and its teardown removes
        #: exactly what it wrote (``rollback_request``)
        self._pack_writes: Dict[str, list] = {}
        #: delta-state keys written per in-flight request — same rollback
        #: discipline as ``_pack_writes``
        self._delta_writes: Dict[str, list] = {}
        self.memo_hits = 0
        self.pack_hits = 0
        self.delta_hits = 0

    # --- warm-slot stores ---------------------------------------------------

    def warm_store_for(self, request_id: str):
        """The request's private warm-slot store (created on first use)."""
        from citizensassemblies_tpu.solvers.batch_lp import WarmSlotStore

        with self._lock:
            store = self.warm_stores.get(request_id)
            if store is None:
                store = WarmSlotStore()
                self.warm_stores.put(request_id, store, owner=self.owner)
            return store

    # --- result memo --------------------------------------------------------

    def memo_get(self, fingerprint: str):
        with self._lock:
            hit = self.memo.get(fingerprint)
            if hit is not None:
                self.memo_hits += 1
            return hit

    def memo_put(self, fingerprint: str, dist) -> None:
        with self._lock:
            self.memo.put(fingerprint, dist, owner=self.owner)

    # --- packed-operand memo ------------------------------------------------

    def pack_get(self, key: str):
        with self._lock:
            hit = self.packs.get(key)
            if hit is not None:
                self.pack_hits += 1
            return hit

    def pack_put(self, key: str, pack, request_id: Optional[str] = None) -> None:
        with self._lock:
            self.packs.put(key, pack, owner=self.owner)
            if request_id is not None:
                self._pack_writes.setdefault(request_id, []).append(key)

    # --- graftdelta base certificates ---------------------------------------

    def delta_get(self, fingerprint: str):
        """The stored :class:`~citizensassemblies_tpu.solvers.delta.DeltaState`
        certified for exactly this instance fingerprint, or None."""
        with self._lock:
            hit = self.delta.get(fingerprint)
            if hit is not None:
                self.delta_hits += 1
            return hit

    def delta_put(
        self, fingerprint: str, state, request_id: Optional[str] = None
    ) -> None:
        with self._lock:
            self.delta.put(fingerprint, state, owner=self.owner)
            if request_id is not None:
                self._delta_writes.setdefault(request_id, []).append(fingerprint)

    # --- request-scoped rollback (robust) -----------------------------------

    def finish_request(self, request_id: str) -> None:
        """Success path: the request's writes become durable session state —
        drop its rollback ledger and keep everything it cached."""
        with self._lock:
            self._pack_writes.pop(request_id, None)
            self._delta_writes.pop(request_id, None)

    def rollback_request(self, request_id: str) -> None:
        """Failure path: remove the request's warm-slot store and every
        session pack it wrote — an aborted request must leave no
        half-written warm state behind (``RequestContext.teardown``)."""
        with self._lock:
            self.warm_stores.pop(request_id, None)
            for key in self._pack_writes.pop(request_id, []):
                self.packs.pop(key, None)
            for key in self._delta_writes.pop(request_id, []):
                self.delta.pop(key, None)

    def stats(self) -> Dict[str, int]:
        """Session-level accounting for the audit stamp."""
        with self._lock:
            return {
                "memo_entries": len(self.memo),
                "pack_entries": len(self.packs),
                "delta_entries": len(self.delta),
                "warm_stores": len(self.warm_stores),
                "memo_hits": self.memo_hits,
                "pack_hits": self.pack_hits,
                "delta_hits": self.delta_hits,
                "evictions": (
                    self.warm_stores.evictions
                    + self.memo.evictions
                    + self.packs.evictions
                    + self.delta.evictions
                ),
            }


class TenantRegistry:
    """Thread-safe tenant → session map owned by one service instance (no
    process-global registry: two services in one process stay independent)."""

    def __init__(self, cap_per_tenant: int = 8):
        self._lock = threading.Lock()
        self._sessions: Dict[str, TenantSession] = {}
        self.cap_per_tenant = max(int(cap_per_tenant), 1)

    def session(self, tenant: str) -> TenantSession:
        with self._lock:
            sess = self._sessions.get(tenant)
            if sess is None:
                sess = TenantSession(tenant, cap=self.cap_per_tenant)
                self._sessions[tenant] = sess
            return sess

    def all_stats(self) -> Dict[str, Dict[str, int]]:
        with self._lock:
            sessions = dict(self._sessions)
        return {t: s.stats() for t, s in sessions.items()}
