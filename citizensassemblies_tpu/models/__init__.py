from citizensassemblies_tpu.models.legacy import (  # noqa: F401
    LegacyResult,
    legacy_probabilities,
    sample_feasible_panels,
    sample_panels_batch,
)
