from citizensassemblies_tpu.models.legacy import (  # noqa: F401
    LegacyResult,
    legacy_probabilities,
    sample_feasible_panels,
    sample_panels_batch,
)
from citizensassemblies_tpu.scenarios import (  # noqa: F401
    DropoutDistribution,
    MultiAssemblyResult,
    find_distribution_dropout,
    find_distribution_multi,
)
