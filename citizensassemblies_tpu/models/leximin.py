"""LEXIMIN: exact lexicographic-maximin panel distributions, TPU-first.

The algorithm (mathematically the same as the reference's
``find_distribution_leximin``, ``leximin.py:338-470``) lexicographically
maximizes the minimum, then second-minimum, … per-agent selection probability
over distributions on feasible panels, via column generation:

* an **outer loop** fixes the probabilities of one tranche of agents per round
  by strict complementarity (agents with positive dual weight must be tight in
  every optimal primal solution — ``leximin.py:431-443``);
* an **inner loop** solves the dual LP over the current portfolio and prices
  new committees until none violates the dual cap (``leximin.py:388-449``);
* a **final LP** recovers panel probabilities that realize the fixed per-agent
  probabilities up to a minimized downward deviation ε (``leximin.py:453-468``).

The TPU re-design changes *how each step is executed*, not the math:

* **Portfolio seeding** — instead of 3n sequential multiplicative-weight ILP
  solves (``leximin.py:236-297``, hot loop #2), one batched device kernel draws
  thousands of diverse feasible committees at once; a per-uncovered-agent exact
  solve then guarantees the same coverage property.
* **Pricing** — instead of one exact ILP per inner iteration
  (``leximin.py:420-424``, hot loop #3), a jitted sampler prices thousands of
  candidate committees per batch and adds *several* violated columns per LP
  solve; the exact oracle only certifies termination, preserving exactness.
* **LP solves** — dense HiGHS on host ("highs"/"hybrid" backends) or PDHG on
  device ("jax" backend; see ``solvers/lp_pdhg.py``).

Failure semantics carried over: non-optimal dual LP status triggers the
shave-fixed-probabilities-and-retry fallback (``leximin.py:405-417``);
infeasible quotas raise ``InfeasibleQuotasError`` with a suggested relaxation
(``leximin.py:225-228``).
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from citizensassemblies_tpu.core.instance import DenseInstance, FeatureSpace
from citizensassemblies_tpu.models.legacy import sample_panels_batch
from citizensassemblies_tpu.solvers.highs_backend import (
    HighsCommitteeOracle,
    check_feasible_or_suggest,
    solve_dual_lp,
    solve_final_primal_lp,
)
from citizensassemblies_tpu.solvers.pricing import best_violating_panels, stochastic_price
from citizensassemblies_tpu.utils.checkpoint import (
    CGState,
    clear_cg_state,
    load_cg_state,
    problem_fingerprint,
    save_cg_state,
)
from citizensassemblies_tpu.service.context import (
    resolve as resolve_context,
    use_context,
)
from citizensassemblies_tpu.utils.config import Config
from citizensassemblies_tpu.utils.logging import RunLog
from citizensassemblies_tpu.obs.metrics import format_counters, format_timers


@dataclasses.dataclass
class Distribution:
    """A distribution over feasible committees plus derived quantities — the
    (committees, probabilities, output_lines) triple of the reference's
    uniform algorithm signature (``leximin.py:341,348-354``), densified."""

    committees: np.ndarray  # bool[|C|, n] portfolio matrix
    probabilities: np.ndarray  # float64[|C|]
    allocation: np.ndarray  # float64[n] per-agent selection probabilities
    output_lines: List[str]
    fixed_probabilities: np.ndarray  # float64[n] leximin values per agent
    covered: np.ndarray  # bool[n] agent appears in some feasible committee
    #: max |allocation − fixed_probabilities| of the panel realization; the
    #: framework contract is ≤ 1e-3 (``contract_ok``). A budget-expired
    #: agent-space rescue (see ``Config.agent_space_budget_s``) may ship a
    #: certified profile realized only to ``realization_dev`` — explicitly
    #: flagged here and in ``output_lines``, never silently.
    realization_dev: float = 0.0
    contract_ok: bool = True

    @property
    def panels(self) -> List[Tuple[int, ...]]:
        return [tuple(np.nonzero(row)[0].tolist()) for row in self.committees]

    def support(self, eps: float = 1e-11) -> List[Tuple[int, ...]]:
        """Panels with probability above ``eps`` (``analysis.py:209``)."""
        return [
            tuple(np.nonzero(row)[0].tolist())
            for row, p in zip(self.committees, self.probabilities)
            if p > eps
        ]


class _Portfolio:
    """Growing committee portfolio with O(1) dedup."""

    def __init__(self, n: int):
        self.n = n
        self.rows: List[np.ndarray] = []
        self.seen: Set[Tuple[int, ...]] = set()

    def add(self, panel: Tuple[int, ...]) -> bool:
        if panel in self.seen:
            return False
        self.seen.add(panel)
        row = np.zeros(self.n, dtype=bool)
        row[list(panel)] = True
        self.rows.append(row)
        return True

    def matrix(self) -> np.ndarray:
        return np.stack(self.rows, axis=0)

    def __len__(self) -> int:
        return len(self.rows)


def _seed_portfolio(
    dense: DenseInstance,
    oracle: HighsCommitteeOracle,
    portfolio: _Portfolio,
    cfg: Config,
    key,
    log: RunLog,
    households: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Seed a diverse portfolio covering every coverable agent.

    Replaces the reference's multiplicative-weights phase + per-uncovered-agent
    ILPs (``leximin.py:236-297``) with one batched device draw followed by
    exact coverage solves for the (typically few) agents the batch missed.
    Returns the bool[n] coverage mask.
    """
    n = dense.n
    budget = max(256, min(cfg.mw_rounds_factor * n, cfg.seed_batch))
    panels, ok = sample_panels_batch(dense, key, budget, households=households)
    panels = np.sort(np.asarray(panels), axis=1)
    ok = np.asarray(ok)
    for b in np.nonzero(ok)[0]:
        portfolio.add(tuple(panels[b].tolist()))
    covered = np.zeros(n, dtype=bool)
    for row in portfolio.rows:
        covered |= row
    log.emit(
        f"Portfolio seeding: batched sampler found {len(portfolio)} distinct feasible "
        f"committees covering {int(covered.sum())}/{n} agents."
    )

    # Exact coverage pass for agents the sampler missed: force-include agent i
    # and maximize coverage of other uncovered agents (the reference solves
    # one ILP per uncovered agent with objective e_i, leximin.py:279-289).
    for i in range(n):
        if covered[i]:
            continue
        weights = (~covered).astype(np.float64)
        try:
            panel, _ = oracle.maximize(weights, forced=(i,))
        except Exception:
            log.emit(f"Agent {i} not contained in any feasible committee.")
            continue
        portfolio.add(panel)
        covered[list(panel)] = True
    if covered.all():
        log.emit("All agents are contained in some feasible committee.")
    return covered


def _typespace_leximin(
    dense: DenseInstance,
    cfg: Config,
    log: RunLog,
    final_stage: str,
    checkpoint_path: Optional[str] = None,
    households: Optional[np.ndarray] = None,
) -> Optional[Distribution]:
    """Exact leximin in type space (see ``solvers/compositions.py``).

    Agents with identical feature rows are interchangeable, so the problem
    collapses onto distinct types: full enumeration of feasible compositions
    when the type count is small (the headline reference instances qualify —
    ``example_large_200`` has 3 types, reference runtime 1161.8 s;
    ``example_small_20`` has 4, 2.7 s; both solve here in under a second,
    exactly), otherwise column generation over compositions
    (``solvers/cg_typespace.py``).

    With ``households`` the caller passes the *augmented* household-quotient
    instance (``solvers/quotient.py``) whose distinct rows are the symmetry
    orbits; the solver stack runs unchanged on it, and the panel realization
    below keeps each panel household-disjoint.
    """
    from citizensassemblies_tpu.solvers.compositions import (
        enumerate_compositions,
        leximin_over_compositions,
    )
    from citizensassemblies_tpu.solvers.native_oracle import TypeReduction

    reduction = TypeReduction(dense)
    comps = None
    if reduction.T <= cfg.enum_max_types:
        comps = enumerate_compositions(
            reduction, cap=cfg.enum_cap, node_budget=cfg.enum_node_budget
        )
        if comps is not None and len(comps) == 0:
            comps = None
    if comps is not None:
        log.emit(
            f"Type-space enumeration: {reduction.T} agent types, "
            f"{len(comps)} feasible compositions."
        )
        with log.timer("typespace_lp"):
            # cfg rides along for the batched probe prescreen
            # (solvers/batch_lp.py) — including on SMALL enumerated
            # instances (mass_24-class), where the fixed per-run dispatch
            # floor is amortized across the whole probe fleet instead of
            # being paid per host LP
            ts = leximin_over_compositions(
                comps, reduction.msize, probe_tol=cfg.probe_tol, log=log,
                cfg=cfg,
            )
    else:
        # too many types to enumerate: column generation over compositions,
        # with TPU-batched stochastic pricing and exact MILP certification
        from citizensassemblies_tpu.solvers.cg_typespace import leximin_cg_typespace

        log.emit(
            f"Type-space column generation: {reduction.T} agent types "
            f"(enumeration over budget)."
        )
        with log.timer("typespace_cg"):
            ts = leximin_cg_typespace(
                dense, reduction, cfg=cfg, log=log, checkpoint_path=checkpoint_path
            )
        if checkpoint_path is not None:
            clear_cg_state(checkpoint_path)
    fixed_agent = ts.type_values[reduction.type_id]
    # decompose into concrete panels matching the exact type targets: CG on
    # the final LP with closed-form pricing (top-c_t dual weights per type);
    # a basic optimal solution is sparse (≤ n+1 panels, comparable to the
    # reference's portfolios) and ε converges to ~0
    if final_stage != "l2":
        return realize_typespace(
            dense, reduction, ts, cfg, log, households=households,
            enumerated=comps is not None,
        )
    with log.timer("final_stage"):
        if final_stage == "l2":
            from citizensassemblies_tpu.solvers.qp import solve_final_primal_l2

            if households is None:
                from citizensassemblies_tpu.solvers.compositions import (
                    expand_compositions,
                )

                P, p_seed = expand_compositions(
                    ts.compositions,
                    ts.probabilities,
                    reduction,
                    budget=cfg.expand_budget,
                    support_eps=cfg.support_eps,
                )
            else:
                # the rotation expansion is not household-aware; realize a
                # disjoint portfolio with the decomposing slicer instead
                from citizensassemblies_tpu.solvers.compositions import (
                    decompose_with_pricing,
                )

                realized = ts.probabilities @ (
                    ts.compositions.astype(np.float64)
                    / reduction.msize.astype(np.float64)[None, :]
                )
                P, p_seed, _ = decompose_with_pricing(
                    ts.compositions,
                    ts.probabilities,
                    reduction,
                    realized[reduction.type_id],
                    budget=cfg.decompose_budget,
                    support_eps=cfg.support_eps,
                    log=log,
                    tol=2e-5,
                    households=households,
                )
            # the expansion/decomposition probabilities are the feasible
            # ε-floor donor, so the (possibly pathological) host ε-LP never
            # runs here — see solve_final_primal_l2
            probs, eps_dev = solve_final_primal_l2(
                P, fixed_agent, iters=cfg.xmin_qp_iters, log=log,
                floor_donor=p_seed, cfg=cfg,
            )
    probs = np.clip(probs, 0.0, 1.0)
    probs = probs / probs.sum()
    allocation = P.T.astype(np.float64) @ probs
    coverable = (
        ts.coverable if hasattr(ts, "coverable") else ts.compositions.max(axis=0) > 0
    )
    covered = coverable[reduction.type_id]
    total_dev = float(np.max(np.abs(allocation - fixed_agent)))
    log.emit(
        f"Leximin done (type space): {ts.stages} stages, {ts.lp_solves} LP solves, "
        f"{P.shape[0]} panels in portfolio, final ε = {eps_dev:.2e}, "
        f"max |alloc − target| = {total_dev:.2e}."
    )
    log.emit(format_timers(log.timers))
    if log.counters:
        # the pipelined decomposition's warm-hit / overlap attribution
        # (decomp_master_warm, decomp_oracle_overlap_hit, ...) — the discrete
        # complement of the phase timers above
        log.emit(format_counters(log.counters))
    # contract_ok reports the realized deviation HONESTLY on every path,
    # including "l2": the l2 stage never falls back to agent space (its
    # callers — XMIN, warm-start re-solves — gate the deviation with their
    # own L∞ band machinery), but with the ε floor now coming from the
    # decomposition donor instead of a minimal-ε LP, a stalled donor must
    # surface as contract_ok=False rather than ship silently certified
    return Distribution(
        committees=P,
        probabilities=probs,
        allocation=allocation,
        output_lines=list(log.lines),
        fixed_probabilities=fixed_agent,
        covered=covered,
        realization_dev=total_dev,
        contract_ok=bool(total_dev <= 1e-3),
    )


def realize_typespace(
    dense: DenseInstance,
    reduction,
    ts,
    cfg: Config,
    log: RunLog,
    households: Optional[np.ndarray] = None,
    enumerated: bool = True,
) -> Distribution:
    """Realize a type-space leximin certificate as a concrete panel portfolio.

    Factored out of ``_typespace_leximin`` so the graftdelta revise path
    (``solvers/delta.py``) can turn a re-certified ``TypeLeximin`` into a
    full :class:`Distribution` without re-running the ladder: the input is
    any (compositions, probabilities, type_values) certificate over
    ``reduction``, whether it came from a fresh ladder, a warm resume, or a
    cache-hit sensitivity certificate.
    """
    from citizensassemblies_tpu.solvers.compositions import decompose_with_pricing

    fixed_agent = ts.type_values[reduction.type_id]
    with log.timer("final_stage"):
        # decompose toward the marginals the composition mixture actually
        # realizes (within ts.eps_dev of the type values): the greedy
        # water-filling is near-exact against those, whereas targeting
        # the type values directly would leave the mixture's own ε as an
        # unservable shortfall and push everything into the polish LPs
        realized = ts.probabilities @ (
            ts.compositions.astype(np.float64)
            / reduction.msize.astype(np.float64)[None, :]
        )
        P, probs, eps_dev = decompose_with_pricing(
            ts.compositions,
            ts.probabilities,
            reduction,
            realized[reduction.type_id],
            budget=cfg.decompose_budget,
            support_eps=cfg.support_eps,
            log=log,
            households=households,
            # enumerated path polishes to 1e-6 (500× below the
            # reference's own EPS=5e-4 final-LP tolerance — chasing
            # 1e-9 cost ~30 extra host LPs for precision nothing
            # downstream can see); the CG path floors the panel
            # tolerance at 2e-5 (its greedy noise scale). On LARGE
            # instances (n ≥ 200) — on EITHER path — the tolerance
            # never drops below 2.5e-4 just because the mixture's own ε
            # is tiny: precision the 1e-3 contract cannot see. A
            # nexus-class CG polish paid ~18 LPs at ~1 s for it, and an
            # enumerated n=469/k=90 single-category instance was worse
            # still — the greedy seed's panel budget scales with
            # 1/delta_cap = 1/(1.5·tol), so tol = 1e-6 built a ~6000-
            # panel portfolio whose ~940×6000 polish LPs took ~20 s
            # each while shaving ε ~5 %/round: a many-minute stall on
            # a sub-second instance. Small instances keep the tight
            # bound (the polish is ~0.1 s there). Otherwise budget
            # against the mixture ε: total contract error |alloc − v| ≤
            # tol_panel + eps_dev ≤ accept_band + 1e-4 (= 9e-4 < 1e-3
            # at the default config; derived from cfg so the knobs
            # cannot silently drift past the contract).
            tol=max(
                cfg.decomp_tol if enumerated else max(cfg.decomp_tol, 2e-5),
                min(
                    max(
                        0.5 * getattr(ts, "eps_dev", 0.0),
                        2.5e-4 if dense.n >= 200 else 0.0,
                    ),
                    max(cfg.decomp_accept, cfg.decomp_accept_stalled)
                    + 1e-4
                    - getattr(ts, "eps_dev", 0.0),
                ),
            ),
        )
    probs = np.clip(probs, 0.0, 1.0)
    keep = probs > cfg.support_eps
    P, probs = P[keep], probs[keep]
    probs = probs / probs.sum()
    allocation = P.T.astype(np.float64) @ probs
    coverable = (
        ts.coverable if hasattr(ts, "coverable") else ts.compositions.max(axis=0) > 0
    )
    covered = coverable[reduction.type_id]
    total_dev = float(np.max(np.abs(allocation - fixed_agent)))
    log.emit(
        f"Leximin done (type space): {ts.stages} stages, {ts.lp_solves} LP solves, "
        f"{P.shape[0]} panels in portfolio, final ε = {eps_dev:.2e}, "
        f"max |alloc − target| = {total_dev:.2e}."
    )
    if total_dev > 1e-3:
        # the panel realization missed the framework's 1e-3 L∞ contract
        # (e.g. a stalled household-disjoint pricing loop): never ship it
        # silently — the caller falls back to the agent-space CG, which is
        # exact regardless of the type-space machinery. The out-of-contract
        # result is still returned (flagged contract_ok=False): its PROFILE
        # is probe-certified even though the realization lags, so it serves
        # as the budget-expiry rescue of a stalled agent-space fallback
        # (VERDICT r4 #3) instead of being discarded.
        log.emit(
            f"Type-space realization missed the 1e-3 contract "
            f"(dev {total_dev:.2e}); falling back to agent-space CG."
        )
    log.emit(format_timers(log.timers))
    if log.counters:
        # the pipelined decomposition's warm-hit / overlap attribution
        # (decomp_master_warm, decomp_oracle_overlap_hit, ...) — the discrete
        # complement of the phase timers above
        log.emit(format_counters(log.counters))
    return Distribution(
        committees=P,
        probabilities=probs,
        allocation=allocation,
        output_lines=list(log.lines),
        fixed_probabilities=fixed_agent,
        covered=covered,
        realization_dev=total_dev,
        contract_ok=bool(total_dev <= 1e-3),
    )


def find_distribution_leximin(
    dense: DenseInstance,
    space: Optional[FeatureSpace] = None,
    cfg: Optional[Config] = None,
    households: Optional[np.ndarray] = None,
    log: Optional[RunLog] = None,
    initial_panels: Optional[List[Tuple[int, ...]]] = None,
    final_stage: str = "lp",
    checkpoint_path: Optional[str] = None,
    ctx=None,
) -> Distribution:
    """Compute the exact LEXIMIN distribution over feasible committees.

    ``initial_panels`` warm-starts the portfolio (the capability the reference
    exposes as ``_expand_distribution_leximin`` for XMIN, ``xmin.py:324-461``).
    ``final_stage`` selects the probability-recovery objective: "lp" minimizes
    ε only (``leximin.py:453-464``); "l2" additionally minimizes ``Σ p²`` to
    spread mass over a maximal support (``xmin.py:454``).
    ``checkpoint_path`` enables outer-round checkpointing: state is saved
    there after every fixed tranche and restored on restart, so a preempted
    long run resumes instead of recomputing from zero (SURVEY §5 — capability
    the reference lacks). The file is removed on successful completion.
    ``ctx`` (a ``service.RequestContext``) supplies per-request cfg/log and
    is installed as the ambient context for the solve — the serving layer's
    re-entrancy contract: everything this call mutates (counters, warm
    slots, knobs) is reached through it, never through process globals.
    """
    ctx, cfg, log = resolve_context(ctx, cfg, log)
    with use_context(ctx):
        return _leximin_impl(
            dense, space, cfg, households, log, initial_panels, final_stage,
            checkpoint_path,
        )


def _leximin_impl(
    dense: DenseInstance,
    space: Optional[FeatureSpace],
    cfg: Config,
    households: Optional[np.ndarray],
    log: RunLog,
    initial_panels: Optional[List[Tuple[int, ...]]],
    final_stage: str,
    checkpoint_path: Optional[str],
) -> Distribution:
    log.emit("Using leximin algorithm.")
    n = dense.n

    if space is None:
        space = FeatureSpace(categories=(), cells=())
    oracle = HighsCommitteeOracle(dense, households=households, log=log)
    check_feasible_or_suggest(dense, space, oracle, households)

    # Fast exact path: type-space (orbit-space) solve. Households do NOT
    # force agent space: they preserve a quotient symmetry — orbits are
    # (household class, base type) pairs, and per-class caps are plain quota
    # rows on an augmented instance (see ``solvers/quotient.py``) — so the
    # same pipeline runs, with household-disjoint panel realization. A valid
    # mid-run agent-space checkpoint means CG work exists to resume, honor it.
    ts_fallback: Optional[Distribution] = None
    if not initial_panels and not cfg.force_agent_space:
        has_ckpt = checkpoint_path is not None and (
            load_cg_state(checkpoint_path, n, problem_fingerprint(dense, cfg, households))
            is not None
        )
        if not has_ckpt:
            if households is None:
                dist = _typespace_leximin(dense, cfg, log, final_stage, checkpoint_path)
            else:
                from citizensassemblies_tpu.solvers.quotient import (
                    build_household_quotient,
                )

                quotient = build_household_quotient(dense, households)
                log.emit(
                    f"Household quotient: {quotient.n_classes} household "
                    f"classes over {len(quotient.class_of_household)} "
                    f"households — solving in orbit space."
                )
                try:
                    dist = _typespace_leximin(
                        quotient.dense_aug, cfg, log, final_stage,
                        checkpoint_path=None, households=quotient.households,
                    )
                except Exception as exc:  # pragma: no cover - safety net
                    # orbit space is exact when it completes; any failure
                    # falls back to the (slower, equally exact) agent-space
                    # CG below rather than aborting the run
                    log.emit(
                        f"Household quotient solve failed ({type(exc).__name__}: "
                        f"{exc}); falling back to agent-space CG."
                    )
                    dist = None
            if dist is not None:
                if dist.contract_ok or final_stage == "l2":
                    # the l2 stage never falls back (its callers — XMIN,
                    # warm re-solves — gate the deviation with their own
                    # band machinery); contract_ok still reports honestly
                    return dist
                # contract miss: run the exact agent-space CG, but keep the
                # certified-profile realization as the budget-expiry rescue —
                # at flagship scale the agent-space CG can take hours, and a
                # silent multi-hour stall is worse than an explicit ε-wide
                # result (VERDICT r4 #3)
                ts_fallback = dist

    key = jax.random.PRNGKey(cfg.solver_seed)
    portfolio = _Portfolio(n)
    resumed = None
    ckpt_fp = ""
    if checkpoint_path is not None:
        ckpt_fp = problem_fingerprint(dense, cfg, households)
        resumed = load_cg_state(checkpoint_path, n, ckpt_fp)
    if resumed is not None:
        for row in resumed.portfolio:
            portfolio.add(tuple(np.nonzero(row)[0].tolist()))
        covered = resumed.covered
        fixed = resumed.fixed
        key = jnp.asarray(resumed.key, dtype=jnp.uint32)  # raw PRNGKey data
        reduction_counter = resumed.reduction_counter
        dual_solves = resumed.dual_solves
        exact_prices = resumed.exact_prices
        log.emit(
            f"Resumed checkpoint: {len(portfolio)} committees, "
            f"{int((fixed >= 0).sum())}/{n} probabilities already fixed."
        )
    else:
        if initial_panels:
            for panel in initial_panels:
                portfolio.add(tuple(sorted(panel)))
            covered = np.zeros(n, dtype=bool)
            for row in portfolio.rows:
                covered |= row
        else:
            key, sub = jax.random.split(key)
            covered = _seed_portfolio(dense, oracle, portfolio, cfg, sub, log, households)
        fixed = np.full(n, -1.0)  # < 0 ⇒ not yet fixed
        if not initial_panels:
            # agents the exact coverage solves proved to be in no feasible
            # committee get probability 0 up front, as the reference does by
            # excluding them from the optimization (leximin.py:286-296,364)
            # — otherwise the first stages grind through z = 0 re-deriving it
            fixed[~covered] = 0.0
        reduction_counter = 0
        dual_solves = 0
        exact_prices = 0

    # Outer loop: maximize the min of unfixed probabilities, fix the tranche of
    # agents whose dual weight certifies tightness, repeat (leximin.py:381-449).
    # When a certified-profile type-space fallback exists, the loop runs under
    # a wall-clock budget: past it, the ε-wide fallback ships with an explicit
    # statement instead of letting the CG grind for hours (the independent
    # n=800 cross-check did not finish in 3.5 h — tests/test_certification.py).
    import time as _time

    deadline = (
        _time.monotonic() + cfg.agent_space_budget_s
        if ts_fallback is not None and cfg.agent_space_budget_s > 0
        else None
    )
    def _budget_expired() -> Optional[Distribution]:
        if deadline is None or _time.monotonic() <= deadline:
            return None
        # ship the certified-profile fallback with an explicit ε statement;
        # append only log lines the fallback snapshot does not already hold.
        # Today both type-space paths share this RunLog, so the snapshot is a
        # strict prefix of log.lines — but that is an invariant of the
        # CURRENT construction, not of the Distribution contract, so the
        # splice is guarded (ADVICE r5 #4): a fallback built from a different
        # RunLog gets its lines REBUILT from the live log outright instead of
        # silently splicing duplicated or misaligned lines into the record.
        prefix = ts_fallback.output_lines
        if log.lines[: len(prefix)] == prefix:
            prefix.extend(log.lines[len(prefix):])
        else:
            ts_fallback.output_lines = list(log.lines)
        msg = (
            f"Agent-space CG exceeded its {cfg.agent_space_budget_s:.0f} s "
            f"budget with {int((fixed >= 0).sum())}/{n} probabilities "
            f"fixed; shipping the certified type-space profile realized "
            f"to L-inf {ts_fallback.realization_dev:.2e} (above the 1e-3 "
            f"contract — treat per-agent probabilities as exact to that "
            f"tolerance only)."
        )
        log.emit(msg)
        ts_fallback.output_lines.append(msg)
        # the agent-space CG's partial progress is resumable state, not
        # garbage: the checkpoint is PRESERVED (ADVICE r5 #1) so an explicit
        # rerun against the same checkpoint path resumes the exact CG where
        # it stopped — a resumed run skips the type-space solve, has no
        # fallback and hence no budget, which is then the caller's stated
        # choice rather than an accidental multi-hour grind
        if checkpoint_path is not None:
            resume_msg = (
                f"Agent-space CG checkpoint preserved at {checkpoint_path}; "
                f"rerunning with the same checkpoint path resumes the exact "
                f"CG (unbudgeted) instead of re-deriving this fallback."
            )
            log.emit(resume_msg)
            ts_fallback.output_lines.append(resume_msg)
        return ts_fallback

    while (fixed < 0).any():
        expired = _budget_expired()
        if expired is not None:
            return expired
        log.emit(f"Fixed {int((fixed >= 0).sum())}/{n} probabilities.")
        if checkpoint_path is not None:
            save_cg_state(
                checkpoint_path,
                CGState(
                    portfolio=portfolio.matrix() if len(portfolio) else np.zeros((0, n), bool),
                    fixed=fixed,
                    covered=covered,
                    key=np.asarray(key),
                    reduction_counter=reduction_counter,
                    dual_solves=dual_solves,
                    exact_prices=exact_prices,
                    fingerprint=ckpt_fp,
                ),
            )
        dual_warm = None
        # stochastic pricing self-disables for the rest of a stage after two
        # consecutive zero-yield batches: near stage convergence the sampler's
        # violating-panel yield collapses while each batch still costs a full
        # device (or, on CPU, host) sweep — at n=400 the dead batches were 98 %
        # of the agent-space CG's wall-clock; the 12 ms exact oracle then
        # carries the tail exactly as the reference's loop does
        stochastic_fails = 0
        while True:
            # the budget must also bound a single stage's inner CG loop — a
            # stalled pricing loop inside one stage is exactly the
            # multi-hour scenario the budget exists for
            expired = _budget_expired()
            if expired is not None:
                return expired
            P = portfolio.matrix()
            authoritative = True  # sol comes from exact host HiGHS
            with log.timer("dual_lp"):
                if (
                    cfg.backend != "highs"
                    and jax.device_count() > 1
                    and len(portfolio) >= cfg.dual_shard_min_rows
                ):
                    # portfolio outgrew one chip's sweet spot: mesh-sharded
                    # device PDHG (rows over the mesh, psum-reduced
                    # transposes); HiGHS only on non-convergence
                    from citizensassemblies_tpu.parallel.mesh import default_mesh
                    from citizensassemblies_tpu.parallel.solver import (
                        solve_dual_lp_pdhg_sharded,
                    )

                    sol = solve_dual_lp_pdhg_sharded(P, fixed, default_mesh(), cfg=cfg)
                    dual_warm = None
                    authoritative = not sol.ok
                    if not sol.ok:
                        sol = solve_dual_lp(P, fixed)
                elif cfg.backend == "jax":
                    # device PDHG, warm-started from the previous inner round
                    # (the portfolio only gains rows, so the old optimum is
                    # nearly feasible); HiGHS only on non-convergence
                    from citizensassemblies_tpu.solvers.lp_pdhg import solve_dual_lp_pdhg

                    sol, dual_warm = solve_dual_lp_pdhg(P, fixed, cfg=cfg, warm=dual_warm)
                    authoritative = not sol.ok
                    if not sol.ok:
                        sol = solve_dual_lp(P, fixed)
                        dual_warm = None
                else:
                    sol = solve_dual_lp(P, fixed)
            dual_solves += 1
            if not sol.ok:
                # numerically infeasible: shave all fixed probabilities a bit
                # and retry (leximin.py:405-417)
                fixed = np.where(fixed >= 0, np.maximum(fixed - cfg.fixed_prob_relax_step, 0.0), fixed)
                reduction_counter += 1
                log.emit(f"Dual LP not optimal — reduced fixed probabilities "
                         f"(reduction {reduction_counter}).")
                continue

            # fast path: batched stochastic pricing; add several violated
            # columns per LP solve. Past cfg.max_portfolio the batch adds
            # stop and the exact oracle carries the tail one certified
            # column per round (the reference's loop shape), so the padded
            # dual-LP buffer stays bounded.
            if stochastic_fails < 2 and len(portfolio) < cfg.max_portfolio:
                key, sub = jax.random.split(key)
                with log.timer("stochastic_pricing"):
                    panels, values, ok = stochastic_price(
                        dense, sol.y, sub, cfg=cfg, households=households
                    )
                new = best_violating_panels(
                    panels, values, ok, sol.yhat + cfg.eps, portfolio.seen,
                    max_new=cfg.cg_columns_per_round,
                )
                for panel, _val in new:
                    row = np.zeros(n, dtype=bool)
                    row[list(panel)] = True
                    portfolio.rows.append(row)
                if new:
                    stochastic_fails = 0
                    continue
                stochastic_fails += 1

            # certification: exact pricing oracle seeded at the dual cap —
            # "does any committee beat ŷ + EPS?" (leximin.py:420-431)
            with log.timer("exact_oracle"):
                panel, value = oracle.certify(sol.y, sol.yhat + cfg.eps)
            exact_prices += 1
            log.emit(
                f"Maximin is at most {sol.objective - sol.yhat + value:.2%}, can do "
                f"{sol.objective:.2%} with {len(portfolio)} committees. "
                f"Gap {value - sol.yhat:.2%}."
            )
            if value <= sol.yhat + cfg.eps:
                if not authoritative:
                    # the convergence certificate priced against float32
                    # PDHG duals; the irreversible fix below must come from
                    # the exact host solve (same contract as the type-space
                    # path) — and if the authoritative duals still price an
                    # improving committee, keep generating instead
                    sol_h = solve_dual_lp(P, fixed)
                    if not sol_h.ok:
                        # never fix from unverified f32 duals: take the
                        # reference's numerical-failure recovery instead
                        # (shave fixed probabilities and retry,
                        # leximin.py:405-417)
                        fixed = np.where(
                            fixed >= 0,
                            np.maximum(fixed - cfg.fixed_prob_relax_step, 0.0),
                            fixed,
                        )
                        reduction_counter += 1
                        log.emit(
                            "Authoritative dual re-solve not optimal — reduced "
                            f"fixed probabilities (reduction {reduction_counter})."
                        )
                        continue
                    sol = sol_h
                    with log.timer("exact_oracle"):
                        panel, value = oracle.certify(sol.y, sol.yhat + cfg.eps)
                    exact_prices += 1
                    if value > sol.yhat + cfg.eps and portfolio.add(panel):
                        continue
                # portfolio supports an optimal solution: fix every unfixed
                # agent with certifying dual weight (strict complementarity,
                # leximin.py:431-443)
                newly = (sol.y > cfg.eps) & (fixed < 0)
                if not newly.any():
                    # numerical guard: the dual weights were too flat to clear
                    # EPS (can happen for n ≳ 1/EPS); fix the largest-weight
                    # unfixed agent so the outer loop always progresses
                    unfixed_idx = np.nonzero(fixed < 0)[0]
                    newly = np.zeros(n, dtype=bool)
                    newly[unfixed_idx[np.argmax(sol.y[unfixed_idx])]] = True
                fixed = np.where(newly, max(0.0, sol.objective), fixed)
                break
            else:
                if not portfolio.add(panel):
                    # the exact oracle returned a known committee despite a
                    # positive gap — numerical disagreement between LP and
                    # ILP; accept the current portfolio as converged
                    log.emit("Exact oracle repeated a known committee; accepting gap.")
                    newly = (sol.y > cfg.eps) & (fixed < 0)
                    if newly.any():
                        fixed = np.where(newly, max(0.0, sol.objective), fixed)
                        break
                    fixed_idx = np.nonzero(fixed < 0)[0]
                    fixed[fixed_idx[np.argmax(sol.y[fixed_idx])]] = max(0.0, sol.objective)
                    break

    # Final stage: randomization over the portfolio realizing the fixed
    # probabilities (leximin.py:451-468; "l2" variant: xmin.py:454).
    P = portfolio.matrix()
    with log.timer("final_stage"):
        if final_stage == "l2":
            from citizensassemblies_tpu.solvers.qp import solve_final_primal_l2

            probs, eps_dev = solve_final_primal_l2(P, fixed)
        elif cfg.backend == "jax":
            from citizensassemblies_tpu.solvers.lp_pdhg import solve_final_primal_lp_pdhg

            probs, eps_dev = solve_final_primal_lp_pdhg(P, fixed, cfg=cfg)
        else:
            probs, eps_dev = solve_final_primal_lp(P, fixed)
    probs = np.clip(probs, 0.0, 1.0)
    probs = probs / probs.sum()
    allocation = P.T.astype(np.float64) @ probs
    log.emit(
        f"Leximin done: {len(portfolio)} committees, {dual_solves} dual LP solves, "
        f"{exact_prices} exact pricing calls, final ε = {eps_dev:.2e}."
    )
    log.emit(format_timers(log.timers))
    if log.counters:
        log.emit(format_counters(log.counters))
    if checkpoint_path is not None:
        clear_cg_state(checkpoint_path)
    total_dev = float(np.max(np.abs(allocation - fixed)))
    return Distribution(
        committees=P,
        probabilities=probs,
        allocation=allocation,
        output_lines=list(log.lines),
        fixed_probabilities=fixed,
        covered=covered,
        realization_dev=total_dev,
        contract_ok=bool(total_dev <= 1e-3),
    )
