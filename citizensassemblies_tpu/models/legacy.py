"""LEGACY: the Sortition Foundation's greedy stratified sampler, TPU-native.

The reference implements one panel draw as a Python loop over dict-of-dict
bookkeeping (``legacy.py:178-200``): k times, pick the (category, feature) cell
with the highest urgency ratio ``(min - selected) / remaining``
(``legacy.py:124-157``, first maximum in dict order wins), select a uniformly
random remaining member of that cell, update per-cell ``selected``/``remaining``
counters, purge every member of any cell that just hit its upper quota
(``legacy.py:103-120,47-62``), and raise ``SelectionError`` whenever a cell can
no longer reach its lower quota; draws failing the final ``check_min_cats``
(``legacy.py:160-168``) are rejected and redrawn (``analysis.py:141-159``).
The Monte-Carlo estimator repeats this 10,000 times sequentially
(``analysis.py:162-191``) — hot loop #1 of the reference.

Here the whole draw is a jittable ``lax.scan`` over k steps on dense count
tensors, *batched across thousands of chains at once*: per step, one
``[B, n] @ [n, F]`` matmul recomputes every chain's remaining-counts, a masked
row-wise argmax picks each chain's urgent cell (the first-max tie-break
reproduces the reference's dict-order semantics because the flat feature axis
is in file order), an inverse-CDF gather picks the random member, and the purge
cascade is a second ``[B, F] @ [F, n]`` matmul. Rejected chains are resampled
in fresh batches (rejection sampling preserved exactly; per-seed streams differ
from the reference's ``random``-module draws, but the sampled distribution is
identical — SURVEY.md §7 "LEGACY fidelity").
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import List, Optional, Set, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from citizensassemblies_tpu.core.instance import DenseInstance, SelectionError
from citizensassemblies_tpu.lint.registry import IRCase, register_ir_core
from citizensassemblies_tpu.obs.hooks import dispatch_span
from citizensassemblies_tpu.ops.pairs import pair_matrix_from_panels
from citizensassemblies_tpu.utils.config import Config, default_config

NEG_INF = -1e30


@dataclasses.dataclass
class LegacyResult:
    """Monte-Carlo estimate bundle (the triple returned by the reference's
    ``legacy_probabilities``, ``analysis.py:189-191``)."""

    allocation: np.ndarray  # float64[n] selection frequencies
    unique_panels: Set[Tuple[int, ...]]
    pair_matrix: np.ndarray  # float32[n, n] pair co-selection probabilities
    panels: np.ndarray  # int32[iterations, k] all sampled panels (sorted rows)
    draws_attempted: int = 0


def _sample_step(A_f32, A_T_f32, qmin, qmax, n, state, noise, scores, households):
    """One greedy selection step for a whole batch of chains.

    ``scores`` biases the within-cell member choice: the member picked is
    ``argmax(scores + Gumbel noise)`` over the urgent cell's alive members.
    With ``scores ≡ 0`` this is exactly a uniform choice (Gumbel-max trick),
    reproducing LEGACY's uniform member pick (``legacy.py:149,187-197``); with
    ``scores = β·y`` it is a softmax(β·y)-weighted pick, which is how the
    LEXIMIN pricing oracle steers draws toward high-dual-weight agents.

    ``households`` is int32[n] group ids; selecting an agent evicts everyone
    in their household (the same-address deletion of ``legacy.py:78-99,
    109-113``). With distinct ids per agent it evicts only the agent.
    """
    alive, selected, failed = state  # bool[B,n], int32[B,F], bool[B]
    B = alive.shape[0]

    # remaining per cell: one MXU matmul for the whole batch (the per-cell
    # "remaining" counters of legacy.py:47-75, recomputed instead of mutated)
    remaining = (alive.astype(jnp.float32) @ A_f32).astype(jnp.int32)  # [B,F]

    deficit = qmin[None, :] - selected  # min - selected
    # A cell that cannot reach its lower quota any more means the draw is dead:
    # covers the "not enough left" checks of legacy.py:55-57,73-74,132-137 and
    # the ratio > 1 guard of legacy.py:143-144.
    starved = jnp.any(deficit > remaining, axis=1)

    # urgency ratio over eligible cells (remaining > 0 and max quota > 0,
    # legacy.py:140-141); first maximum wins as in dict iteration order.
    eligible = (remaining > 0) & (qmax[None, :] > 0)
    ratio = jnp.where(eligible, deficit.astype(jnp.float32) / remaining.astype(jnp.float32), NEG_INF)
    cell = jnp.argmax(ratio, axis=1)  # [B]

    members = alive & (A_T_f32 > 0.5)[cell]  # [B,n]: alive agents in each chain's cell
    person = jnp.argmax(jnp.where(members, scores + noise, NEG_INF), axis=1)  # [B]

    person_feats = A_f32[person].astype(jnp.int32)  # [B,F] one-hot per category
    selected = selected + person_feats

    # purge cascade: every cell of the selected person that just hit its upper
    # quota evicts all its members (legacy.py:114-119,47-62) — one matmul.
    purged = (selected == qmax[None, :]) & (person_feats > 0)  # [B,F]
    kill = (purged.astype(jnp.float32) @ A_T_f32) > 0.5  # [B,n]
    alive = alive & ~kill
    # evict the selected person and all same-household members
    alive = alive & (households[None, :] != households[person][:, None])

    failed = failed | starved
    return (alive, selected, failed), person


def chain_keys_for(key, start: int, count: int) -> jnp.ndarray:
    """Per-chain PRNG keys derived from *global* chain ids by ``fold_in``.

    Chain ``start + i`` always gets the same key regardless of how chains are
    batched or sharded, so a draw of N chains is bit-identical whether it runs
    on one device or split across a mesh — the property the 1-vs-8-device
    estimator test pins down.
    """
    ids = jnp.arange(start, start + count, dtype=jnp.uint32)
    return jax.vmap(lambda i: jax.random.fold_in(key, i))(ids)


@partial(jax.jit, static_argnames=("B",))
def _sample_panels_kernel(
    dense: DenseInstance, key, B: int, scores=None, households=None, chain_keys=None
):
    """Draw B panels in parallel; returns (panels int32[B,k], ok bool[B]).

    ``scores`` is an optional [B, n] (or broadcastable) member-pick bias; see
    :func:`_sample_step`. ``None`` means uniform picks (plain LEGACY).
    ``households`` is an optional int32[n] group-id vector enabling the
    reference's ``check_same_address`` behavior (``legacy.py:78-99``).
    ``chain_keys`` overrides the per-chain key derivation (shape [B] of key
    data) — the distributed path passes each device its slice of the global
    :func:`chain_keys_for` stream so results are device-count-invariant.
    """
    n, F, k = dense.n, dense.n_features, dense.k
    A_f32 = dense.A.astype(jnp.float32)
    A_T_f32 = A_f32.T
    qmin, qmax = dense.qmin, dense.qmax
    if scores is None:
        scores = jnp.zeros((1, n), dtype=jnp.float32)
    if households is None:
        households = jnp.arange(n, dtype=jnp.int32)
    else:
        households = jnp.asarray(households, dtype=jnp.int32)
    if chain_keys is None:
        chain_keys = chain_keys_for(key, 0, B)

    alive0 = jnp.ones((B, n), dtype=bool)
    selected0 = jnp.zeros((B, F), dtype=jnp.int32)
    failed0 = jnp.zeros((B,), dtype=bool)

    def body(state, step):
        alive, selected, failed = state
        # "run out of people" before the final pick fails the draw
        # (legacy.py:198-199); checked as part of starvation since an empty
        # pool starves every unfilled lower quota — but quota-free instances
        # (all qmin = 0) still need the explicit check.
        out_of_people = ~jnp.any(alive, axis=1)
        # per-chain, per-step noise from the chain's own key stream: chain
        # identity (not batch position) determines the draw
        noise = jax.vmap(
            lambda ck: jax.random.gumbel(
                jax.random.fold_in(ck, step), (n,), dtype=jnp.float32
            )
        )(chain_keys)
        new_state, person = _sample_step(
            A_f32, A_T_f32, qmin, qmax, n, state, noise, scores, households
        )
        alive2, selected2, failed2 = new_state
        return (alive2, selected2, failed2 | (failed | out_of_people)), person

    (alive, selected, failed), persons = jax.lax.scan(
        body, (alive0, selected0, failed0), jnp.arange(k, dtype=jnp.uint32)
    )
    panels = persons.T  # [B, k]

    # final lower-quota audit (check_min_cats, legacy.py:160-168)
    failed = failed | jnp.any(selected < qmin[None, :], axis=1)
    return panels, ~failed


@register_ir_core("legacy.scan_sampler", span="legacy.scan_sampler")
def _ir_scan_sampler() -> IRCase:
    """The scan-path batch draw at a small (n=40, F=12, k=6, B=32) shape —
    the per-step matmuls and the per-chain fold_in key stream are the
    verified structure (lint/ir.py)."""
    S = jax.ShapeDtypeStruct
    i32 = jnp.int32
    n, F, k, B = 40, 12, 6, 32
    dense = DenseInstance(
        A=S((n, F), jnp.bool_), qmin=S((F,), i32), qmax=S((F,), i32),
        cat_of_feature=S((F,), i32), k=k, n_categories=3,
    )
    return IRCase(
        fn=_sample_panels_kernel,
        args=(dense, S((2,), jnp.uint32)),
        static=dict(B=B),
    )


def sample_panels_batch(
    dense: DenseInstance, key, batch: int, scores=None, households=None,
    sampler: str = "auto", distribute: Optional[bool] = None,
):
    """Public batch draw; returns (panels[B,k], ok[B]) as device arrays.

    ``sampler``: "scan" uses the lax.scan kernel; "auto" resolves to "scan".
    The former "pallas" opt-in (``kernels/sampler.py``) is REMOVED: measured
    on a v5e across B ∈ {1024, 4096, 16384} and n ∈ {200, 1727, 2000}, its
    throughput never decisively beat the scan path (11.9k vs 11.2k panels/s
    at the reference shape, within the round-to-round variance band) —
    end-to-end sampler latency at these shapes is dominated by
    dispatch/transfer, not the HBM mask traffic the fusion removed, so VMEM
    residency had nothing to win. The package's Pallas investment moved to
    the PDHG megakernel (``kernels/pdhg_megakernel.py``), where the iterate
    loop genuinely is HBM-bound.

    ``distribute``: shard the chains across the device mesh (the production
    multi-chip path for the reference's sequential 10k-draw estimator loop,
    ``analysis.py:180-187``). ``None`` auto-enables it when more than one
    device is visible; results are bit-identical to the single-device scan
    kernel because chain randomness is keyed on global chain ids.
    """
    if distribute is None:
        distribute = len(jax.devices()) > 1 and batch >= len(jax.devices())
    if distribute and sampler in ("auto", "scan"):
        from citizensassemblies_tpu.parallel.mc import distributed_sample_panels
        from citizensassemblies_tpu.parallel.mesh import default_mesh

        return distributed_sample_panels(
            dense, key, batch, default_mesh(), scores=scores, households=households
        )
    if sampler == "auto":
        sampler = "scan"
    if sampler == "pallas":
        raise ValueError(
            "unknown sampler 'pallas': the fused sampler kernel was removed "
            "(it never beat the scan path; see README 'Pallas verdicts')"
        )
    if sampler != "scan":
        raise ValueError(f"unknown sampler {sampler!r}: expected 'auto' or 'scan'")
    with dispatch_span("legacy.scan_sampler", chains=int(batch)) as _ds:
        out = _sample_panels_kernel(dense, key, batch, scores, households)
        _ds.out = out
    return out


def sample_feasible_panels(
    dense: DenseInstance,
    num: int,
    seed: int = 0,
    cfg: Optional[Config] = None,
    key=None,
    households: Optional[np.ndarray] = None,
    distribute: Optional[bool] = None,
) -> Tuple[np.ndarray, int]:
    """Collect ``num`` accepted panels via batched rejection sampling.

    Mirrors the retry-until-valid wrapper ``legacy_find``
    (``analysis.py:141-159``) but amortized: failed chains simply don't count
    and fresh batches are drawn until enough successes accumulate. Returns
    (panels int32[num, k] with *sorted* rows, total draws attempted).
    """
    cfg = cfg or default_config()
    if num <= 0:
        return np.zeros((0, dense.k), dtype=np.int32), 0
    if distribute is None and not getattr(cfg, "dist_mesh", True):
        # mesh_to_single_device rung: the auto-distribution hook stays on
        # the single-device kernel (bit-identical — the rung's certificate)
        distribute = False
    if key is None:
        key = jax.random.PRNGKey(seed)
    B = min(cfg.mc_batch, max(256, num))
    collected: List[np.ndarray] = []
    total = 0
    attempts = 0
    draws = 0
    while total < num:
        key, sub = jax.random.split(key)
        panels, ok = sample_panels_batch(
            dense, sub, B, households=households, distribute=distribute
        )
        ok_np = np.asarray(ok)
        draws += B
        good = np.asarray(panels)[ok_np]
        if good.size:
            collected.append(good)
            total += good.shape[0]
        attempts += 1
        if attempts > cfg.mc_max_resample_rounds and total == 0:
            raise SelectionError(
                f"no feasible panel found in {attempts * B} LEGACY draws — "
                f"quotas are likely infeasible for greedy selection"
            )
    panels = np.concatenate(collected, axis=0)[:num]
    panels.sort(axis=1)
    return panels.astype(np.int32), draws


def legacy_probabilities(
    dense: DenseInstance,
    iterations: int = 10_000,
    seed: int = 0,
    cfg: Optional[Config] = None,
    households: Optional[np.ndarray] = None,
    distribute: Optional[bool] = None,
    ctx=None,
) -> LegacyResult:
    """Estimate the LEGACY probability allocation from ``iterations`` draws
    (the Monte-Carlo estimator of ``analysis.py:162-191``).

    Returns per-agent selection frequencies, the set of unique panels observed,
    and the pair co-selection probability matrix (normalized by the draw count,
    ``analysis.py:86-88``).

    ``distribute=None`` auto-shards the draws over every visible device
    (bit-identical to the single-device path — chain randomness is keyed on
    global chain ids); pass False/True to force either path. ``ctx`` (a
    ``service.RequestContext``) supplies the per-request cfg and scopes the
    estimator for the serving layer (re-entrancy contract).
    """
    from citizensassemblies_tpu.service.context import (
        resolve as resolve_context,
        use_context,
    )

    ctx, cfg, _log = resolve_context(ctx, cfg, None)
    with use_context(ctx):
        panels, draws = sample_feasible_panels(
            dense, iterations, seed=seed, cfg=cfg, households=households,
            distribute=distribute,
        )
    n = dense.n
    denom = max(iterations, 1)
    counts = np.bincount(panels.ravel(), minlength=n)
    allocation = counts.astype(np.float64) / denom
    pair_matrix = np.asarray(pair_matrix_from_panels(panels, n=n, chunk=cfg.mc_batch)) / denom
    unique_panels = set(map(tuple, panels.tolist()))
    return LegacyResult(
        allocation=allocation,
        unique_panels=unique_panels,
        pair_matrix=pair_matrix,
        panels=panels,
        draws_attempted=draws,
    )
