"""XMIN: LEXIMIN's per-agent probabilities spread over a maximal panel support.

The fork's third algorithm (``xmin.py:484-544``) keeps LEXIMIN's (optimal)
per-agent selection probabilities but re-distributes the panel probabilities
over *many more* panels, so repeated assemblies don't keep drawing from the
same small portfolio. Reference procedure: seed with a full LEXIMIN run
(``xmin.py:506-508``); then up to 5n times, sample one LEGACY panel not yet in
the portfolio (≤3n attempts each, ``xmin.py:464-474``), append it, and re-run
the entire column-generation solve over the grown portfolio with a final QP
that adds ``Σ p²`` to the objective (``xmin.py:324-461,454``) — hot loop #4,
by far the reference's most expensive path (O(n) full LP re-solves).

TPU re-design: the portfolio is expanded *in one batched draw* (the device
sampler produces thousands of distinct feasible panels at once — no reason to
add them one at a time), the leximin probabilities are computed **once**, and
the min-L2 final stage runs once over the enlarged portfolio. The quadratic
final stage is what spreads the mass: its unique optimum puts positive weight
on every panel that can help realize the targets, which is exactly the support
-maximization the reference iterates toward. The outer re-solve loop collapses
because the fixed per-agent probabilities are already leximin-optimal and
adding columns cannot change them (they are the unique leximin values over the
*full* feasible-panel polytope, which the portfolio under-approximates tightly
after certification).
"""

from __future__ import annotations

from typing import List, Optional

import jax
import numpy as np

from citizensassemblies_tpu.core.instance import DenseInstance, FeatureSpace
from citizensassemblies_tpu.models.legacy import sample_panels_batch
from citizensassemblies_tpu.models.leximin import Distribution, find_distribution_leximin
from citizensassemblies_tpu.service.context import (
    resolve as resolve_context,
    use_context,
)
from citizensassemblies_tpu.solvers.qp import solve_final_primal_l2
from citizensassemblies_tpu.utils.config import Config
from citizensassemblies_tpu.utils.logging import RunLog


def find_distribution_xmin(
    dense: DenseInstance,
    space: Optional[FeatureSpace] = None,
    cfg: Optional[Config] = None,
    households: Optional[np.ndarray] = None,
    log: Optional[RunLog] = None,
    leximin: Optional[Distribution] = None,
    ctx=None,
) -> Distribution:
    """Compute the XMIN distribution: leximin-optimal per-agent probabilities
    over an expanded, support-maximized portfolio.

    ``leximin`` optionally supplies a precomputed LEXIMIN distribution for
    the same (dense, cfg, households) problem, skipping step 1 — callers
    that already hold one (the analysis cache, benchmarks, the service's
    tenant-session memo) avoid a duplicate full solve. ``ctx`` (a
    ``service.RequestContext``) supplies per-request cfg/log and is
    installed as the ambient context for the solve (re-entrancy contract —
    see ``find_distribution_leximin``)."""
    ctx, cfg, log = resolve_context(ctx, cfg, log)
    with use_context(ctx):
        return _xmin_impl(dense, space, cfg, households, log, leximin)


def _xmin_impl(
    dense: DenseInstance,
    space: Optional[FeatureSpace],
    cfg: Config,
    households: Optional[np.ndarray],
    log: RunLog,
    leximin: Optional[Distribution],
) -> Distribution:
    # 1) exact leximin (fixes every agent's probability; xmin.py:506-508)
    if leximin is None:
        leximin = find_distribution_leximin(
            dense, space, cfg=cfg, households=households, log=log
        )
    n = dense.n

    # 2) portfolio expansion: the reference draws up to 5n fresh LEGACY panels
    #    one-by-one (xmin.py:511-522); we draw the same budget in batches.
    #    The reference budget counts *distinct additions* (each of its 5n
    #    iterations appends one panel not yet in the portfolio, retrying up
    #    to 3n samples for it, ``xmin.py:464-474``) — so collect until 5n
    #    new panels or the matching total-draw effort bound is spent.
    target_new = max(1, int(round(cfg.xmin_iterations_factor * n)))
    # total-draw effort bound: dedup_attempts_factor·n tries per distinct
    # addition (the reference's 3n, ``xmin.py:466``) × target_new additions
    # (cfg.xmin_iterations_factor·n distinct panels — see config.py for why
    # that exceeds the reference's literal 5n iteration count)
    max_draws = int(cfg.xmin_dedup_attempts_factor * n * target_new)
    # dedup keys are the raw bytes of the sorted member rows: at sf_e scale
    # the expansion handles ~14k panels of k=110 members, where building a
    # 110-int Python tuple per panel dominated the host side of this loop
    seen = {
        np.sort(np.nonzero(row)[0]).astype(np.int32).tobytes()
        for row in leximin.committees
    }
    new_rows: List[np.ndarray] = []
    key = jax.random.PRNGKey(cfg.solver_seed + 1)
    drawn = 0
    while len(new_rows) < target_new and drawn < max_draws:
        B = min(cfg.pricing_batch, max_draws - drawn)
        key, sub = jax.random.split(key)
        with log.timer("xmin_draws"):
            panels, ok = sample_panels_batch(dense, sub, B, households=households)
            panels = np.sort(np.asarray(panels), axis=1).astype(np.int32)
            ok = np.asarray(ok)
        drawn += B
        with log.timer("xmin_dedup"):
            # in-batch dedup vectorized; cross-batch via the bytes set.
            # Iterate in FIRST-DRAWN order (np.unique returns rows sorted
            # lexicographically — truncating that order at target_new would
            # bias the final batch toward low-index agents)
            ok_panels = panels[ok]
            _, first = np.unique(ok_panels, axis=0, return_index=True)
            for prow in ok_panels[np.sort(first)]:
                kb = prow.tobytes()
                if kb not in seen:
                    seen.add(kb)
                    row = np.zeros(n, dtype=bool)
                    row[prow] = True
                    new_rows.append(row)
                    if len(new_rows) >= target_new:
                        break
    if new_rows:
        P = np.concatenate([leximin.committees, np.stack(new_rows)], axis=0)
    else:
        P = leximin.committees
    log.emit(
        f"XMIN expansion: portfolio grew from {leximin.committees.shape[0]} to "
        f"{P.shape[0]} committees ({drawn} draws)."
    )

    # 3) min-L2 redistribution over the grown portfolio (xmin.py:447-455).
    # The LEXIMIN probabilities are the feasible ε-floor donor: they realize
    # the targets within the leximin stage's own ε over the portfolio PREFIX,
    # so the (possibly pathological — see solve_final_primal_l2) host ε-LP
    # never runs on the expansion path. With the batched LP engine enabled
    # the min-ε anchor + ε-floor pick + dual ascent run FUSED as one jitted
    # device call with an on-device convergence check (qp._get_l2_fused_core
    # — the timer below then contains `l2_fused` instead of the serial
    # `l2_eps_pdhg`/`l2_dual_ascent` pair, and `lp_batch_l2_fused` appears
    # in the run's phase counters)
    with log.timer("xmin_l2"):
        probs, eps_dev = solve_final_primal_l2(
            P, leximin.fixed_probabilities, iters=cfg.xmin_qp_iters, log=log,
            floor_donor=leximin.probabilities, cfg=cfg,
            # the anchor gate must track THIS run's spread band: a donor
            # whose deviation sits between the gate and the band would skip
            # the anchor and silently disable the spread (step 4 below)
            anchor_if_above=0.5 * cfg.xmin_linf_band,
        )
    probs = np.clip(probs, 0.0, 1.0)
    probs = probs / probs.sum()
    allocation = P.T.astype(np.float64) @ probs

    # 4) maximal uniform blend over the expansion panels, inside the L∞
    #    budget. The dual-ascent spread degrades on strongly heterogeneous
    #    instances (its step size collapses with the portfolio's column
    #    sums), and the reference's own QP only trades spread against a
    #    bounded ε (``xmin.py:447-455``). The mix
    #    ``(1−γ)·p + γ·uniform(new panels)`` is the closed-form
    #    support-maximal move: by convexity its allocation deviation is at
    #    most ``(1−γ)·dev(p) + γ·dev(uniform)``, so γ is chosen — exact
    #    arithmetic, no solver — as the largest weight keeping the deviation
    #    within ``cfg.xmin_linf_band``; every expansion panel then carries
    #    mass γ/|new| ≫ the support threshold.
    if new_rows:
        PT = P.T.astype(np.float64)
        t = leximin.fixed_probabilities
        band = cfg.xmin_linf_band
        dev_l2 = float(np.abs(allocation - t).max())
        if dev_l2 > 0.9 * band:
            # the ascent's spread overshot the band (its step size collapses
            # on heterogeneous portfolios): keep its iterate only as a
            # *donor* and restart the shipped mixture from the leximin
            # probabilities, whose deviation is the decomposition ε
            p_l2 = probs
            probs = np.zeros(P.shape[0])
            probs[: leximin.committees.shape[0]] = leximin.probabilities
            allocation = PT @ probs
        else:
            p_l2 = None
        dev_now = float(np.abs(allocation - t).max())
        # candidate donors: the L2 iterate (near-band deviation, broad
        # support) and the uniform over expansion panels (guaranteed full
        # expansion support, large deviation); for each, the largest blend
        # weight γ with (1−γ)·dev_now + γ·dev_donor ≤ band — convexity makes
        # the bound exact arithmetic — and keep the blend with the larger
        # realized support
        donors = [
            np.concatenate(
                [np.zeros(leximin.committees.shape[0]), np.full(len(new_rows), 1.0 / len(new_rows))]
            )
        ]
        if p_l2 is not None:
            donors.append(p_l2)
        best = None
        for q in donors:
            dev_q = float(np.abs(PT @ q - t).max())
            if dev_q <= band:
                gamma = 1.0
            elif dev_now < band:
                gamma = (band - dev_now) / (dev_q - dev_now)
            else:
                continue
            cand = (1.0 - gamma) * probs + gamma * q
            support = int((cand > cfg.support_eps).sum())
            if best is None or support > best[1]:
                best = (cand, support, gamma)
        if best is not None and best[1] > int((probs > cfg.support_eps).sum()):
            probs, support, gamma = best
            allocation = PT @ probs
            log.emit(
                f"XMIN spread: γ = {gamma:.4f} over {len(new_rows)} expansion "
                f"panels → support {support} "
                f"(L∞ dev {float(np.abs(allocation - t).max()):.2e} ≤ band {band:g})."
            )
    if log.counters.get("lp_batch_l2_fused"):
        log.emit(
            "XMIN L2 stage ran fused on the batched LP engine "
            "(anchor + floor pick + spread in one device call)."
        )
    log.emit(f"XMIN done: support {(probs > 1e-11).sum()} committees, ε = {eps_dev:.2e}.")
    final_dev = float(np.abs(allocation - leximin.fixed_probabilities).max())
    return Distribution(
        committees=P,
        probabilities=probs,
        allocation=allocation,
        output_lines=list(log.lines),
        fixed_probabilities=leximin.fixed_probabilities,
        covered=leximin.covered,
        realization_dev=final_dev,
        contract_ok=bool(final_dev <= 1e-3),
    )
