"""Ratio products: per-agent over-representation scores.

Reference ``analysis.py:411-431``: for each (category, feature) cell, the
representation ratio is ``pool_share / (quota_midpoint / k)``; an agent's ratio
product is the product of her cells' ratios. On the dense representation this
is one log-space matvec: ``exp(A @ log r)`` where ``r ∈ R^F`` is the per-cell
ratio vector.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from citizensassemblies_tpu.core.instance import DenseInstance


@jax.jit
def compute_ratio_products(dense: DenseInstance) -> jnp.ndarray:
    """float32[n] ratio products in agent order (``analysis.py:427-431``)."""
    A = dense.A.astype(jnp.float32)
    n = A.shape[0]
    pool_share = jnp.sum(A, axis=0) / n
    quota_midpoint = (dense.qmin + dense.qmax).astype(jnp.float32) / 2.0
    cell_ratio = pool_share / (quota_midpoint / dense.k)
    return jnp.exp(A @ jnp.log(cell_ratio))
