"""Ratio products: per-agent over-representation scores.

Reference ``analysis.py:411-431``: for each (category, feature) cell, the
representation ratio is ``pool_share / (quota_midpoint / k)``; an agent's ratio
product is the product of her cells' ratios. On the dense representation this
is one log-space matvec: ``exp(A @ log r)`` where ``r ∈ R^F`` is the per-cell
ratio vector.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from citizensassemblies_tpu.core.instance import DenseInstance


@jax.jit
def compute_ratio_products(dense: DenseInstance) -> jnp.ndarray:
    """float32[n] ratio products in agent order (``analysis.py:427-431``)."""
    A = dense.A.astype(jnp.float32)
    n = A.shape[0]
    pool_share = jnp.sum(A, axis=0) / n
    quota_midpoint = (dense.qmin + dense.qmax).astype(jnp.float32) / 2.0
    cell_ratio = pool_share * dense.k / jnp.maximum(quota_midpoint, 1e-12)
    # cells with no pool members never touch any agent's product (A[i,f] = 0);
    # mask them so 0 * log(0) cannot poison the matvec with NaNs (the
    # reference only materializes ratios for observed cells, analysis.py:415-425)
    log_ratio = jnp.where(pool_share > 0, jnp.log(jnp.maximum(cell_ratio, 1e-30)), 0.0)
    return jnp.exp(A @ log_ratio)
