"""Fairness statistics over probability allocations, as jittable reductions.

Replaces the reference's dict-based statistics (``analysis.py:231-268``):
Gini coefficient (Damgaard & Weiner formulation, ``analysis.py:243-245``),
geometric mean with the LEGACY-only 1e-4 floor (``analysis.py:247-251``),
minimum probability, the share-below-threshold metric (``analysis.py:600``),
and the Jeffreys 99% upper confidence bound (``analysis.py:258-268``, host-side
via scipy — a reporting-path scalar, not worth a device round-trip).

An allocation here is a dense vector ``π ∈ [0,1]^n`` in agent-id order; given a
portfolio matrix ``P ∈ {0,1}^{|C|×n}`` and panel probabilities ``p``,
``π = P.T @ p`` (:func:`allocation_from_portfolio`).
"""

from __future__ import annotations

import dataclasses
from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@dataclasses.dataclass
class ProbAllocationStats:
    """Mirror of the reference's stats container (``analysis.py:61-65``)."""

    gini: float
    geometric_mean: float
    min: float


@jax.jit
def allocation_from_portfolio(P: jnp.ndarray, probs: jnp.ndarray) -> jnp.ndarray:
    """π_i = Σ_{panels P ∋ i} p_P (the adapter loop at ``analysis.py:205-207``
    as a single matvec)."""
    return P.T.astype(probs.dtype) @ probs


@jax.jit
def gini(alloc: jnp.ndarray) -> jnp.ndarray:
    """Gini coefficient of a probability allocation.

    Reference formula (``analysis.py:241-245``): with probabilities sorted
    ascending, ``Σ_i (2i - n + 1) π_i / (n k)`` where ``k = round(Σ π)``.
    """
    n = alloc.shape[0]
    sorted_probs = jnp.sort(alloc)
    k = jnp.round(jnp.sum(alloc))
    i = jnp.arange(n, dtype=alloc.dtype)
    return jnp.sum((2.0 * i - n + 1.0) * sorted_probs) / (n * k)


@partial(jax.jit, static_argnames=("cap",))
def geometric_mean(alloc: jnp.ndarray, cap: bool = False) -> jnp.ndarray:
    """Geometric mean of selection probabilities.

    With ``cap=True``, probabilities below 1/10,000 are floored first — the
    advantage the reference grants only to the LEGACY benchmark so its zeros
    don't collapse the mean (``analysis.py:234-236,247-249``).
    """
    x = jnp.maximum(alloc, 1.0 / 10_000) if cap else alloc
    return jnp.exp(jnp.mean(jnp.log(x)))


@jax.jit
def share_below(alloc: jnp.ndarray, threshold: jnp.ndarray) -> jnp.ndarray:
    """Fraction of agents with probability strictly below ``threshold``
    (``analysis.py:600``: share of LEGACY probabilities below the LEXIMIN min)."""
    return jnp.mean((alloc < threshold).astype(jnp.float32))


def prob_allocation_stats(alloc, cap_for_geometric_mean: bool) -> ProbAllocationStats:
    """Host-facing bundle matching ``compute_prob_allocation_stats``
    (``analysis.py:231-255``)."""
    # graftlint: disable=R4 -- f64 only when jax_enable_x64 is on; else explicit f32
    alloc = jnp.asarray(alloc, dtype=jnp.float64 if jax.config.jax_enable_x64 else jnp.float32)
    return ProbAllocationStats(
        gini=float(gini(alloc)),
        geometric_mean=float(geometric_mean(alloc, cap=cap_for_geometric_mean)),
        min=float(jnp.min(alloc)),
    )


def upper_confidence_bound(num_trials: int, sample_proportion: float) -> float:
    """99th percentile of the Jeffreys posterior Beta(.5 + s, .5 + f) for a
    binomial proportion (``analysis.py:258-268``); returns 1.0 when every trial
    succeeded. Host-side scalar (scipy), used only in the report path."""
    from scipy.stats import beta

    num_successes = round(sample_proportion * num_trials)
    if num_successes == num_trials:
        return 1.0
    return float(beta.ppf(0.99, 0.5 + num_successes, 0.5 + num_trials - num_successes))


def allocation_dict_to_vector(alloc_dict, n: int) -> np.ndarray:
    """Convert a reference-style ``{agent_id: prob}`` mapping (agent ids are
    row indices, ``analysis.py:132``) to the dense vector representation."""
    v = np.zeros(n, dtype=np.float64)
    for agent_id, prob in alloc_dict.items():
        v[int(agent_id)] = prob
    return v
