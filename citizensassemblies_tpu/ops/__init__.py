from citizensassemblies_tpu.ops.stats import (  # noqa: F401
    ProbAllocationStats,
    allocation_from_portfolio,
    gini,
    geometric_mean,
    prob_allocation_stats,
    share_below,
    upper_confidence_bound,
)
from citizensassemblies_tpu.ops.pairs import (  # noqa: F401
    pair_matrix_from_panels,
    pair_matrix_from_portfolio,
    sorted_pair_values,
    uniform_pair_value,
)
from citizensassemblies_tpu.ops.ratio import compute_ratio_products  # noqa: F401
