"""Pairwise co-selection probabilities as dense symmetric matrices.

The reference's ``PairHistogram`` (``analysis.py:68-98``) is a Python dict over
all C(n,2) unordered pairs, updated in O(Σ k²) Python loops — the fork's key
addition and a prime vectorization target (SURVEY.md §2 C4). Here the same
object is the symmetric matrix ``M = Pᵀ diag(w) P`` with zeroed diagonal, built
on the MXU in batched chunks: for one-hot panel rows ``S ∈ {0,1}^{B×n}`` and
panel weights ``w``, ``M[i,j] = Σ_b w_b S[b,i] S[b,j]`` is exactly the pair
co-selection mass of the portfolio (``analysis.py:90-95``).
"""

from __future__ import annotations

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np


@partial(jax.jit, static_argnames=("n",))
def _one_hot_panels(panels: jnp.ndarray, n: int) -> jnp.ndarray:
    """panels: int32[B, k] agent indices -> bool[B, n] membership rows."""
    B = panels.shape[0]
    S = jnp.zeros((B, n), dtype=jnp.float32)
    return S.at[jnp.arange(B)[:, None], panels].set(1.0)


@partial(jax.jit, static_argnames=("n",))
def _pair_chunk(panels: jnp.ndarray, weights: jnp.ndarray, n: int) -> jnp.ndarray:
    S = _one_hot_panels(panels, n)
    M = (S * weights[:, None]).T @ S
    return M * (1.0 - jnp.eye(n, dtype=M.dtype))


def pair_matrix_from_panels(
    panels, weights=None, *, n: int, chunk: int = 2048
) -> jnp.ndarray:
    """Accumulate the pair matrix over a (possibly huge) batch of panels.

    ``panels`` is int[B, k]; ``weights`` defaults to 1 per panel (Monte-Carlo
    counting; divide by the draw count afterwards as the reference does at
    ``analysis.py:86-88``). Chunked so the one-hot buffer stays ≤ chunk×n.
    """
    panels = jnp.asarray(panels)
    B = panels.shape[0]
    if weights is None:
        weights = jnp.ones((B,), dtype=jnp.float32)
    else:
        weights = jnp.asarray(weights, dtype=jnp.float32)
    M = jnp.zeros((n, n), dtype=jnp.float32)
    for start in range(0, B, chunk):
        M = M + _pair_chunk(panels[start : start + chunk], weights[start : start + chunk], n)
    return M


def pair_matrix_from_portfolio(P, probs) -> jnp.ndarray:
    """Pair matrix of a weighted portfolio: ``Pᵀ diag(p) P`` with zero diagonal
    (the exact-distribution path, ``analysis.py:208,226``)."""
    P = jnp.asarray(P, dtype=jnp.float32)
    probs = jnp.asarray(probs, dtype=jnp.float32)
    M = (P * probs[:, None]).T @ P
    n = M.shape[0]
    return M * (1.0 - jnp.eye(n, dtype=M.dtype))


def sorted_pair_values(M) -> np.ndarray:
    """All C(n,2) upper-triangle values sorted ascending — the series plotted
    by the pair-probability curve (``analysis.py:339-347``)."""
    M = np.asarray(M)
    iu = np.triu_indices(M.shape[0], k=1)
    vals = M[iu]
    vals.sort()
    return vals


def uniform_pair_value(n: int) -> float:
    """The uniform baseline 1/C(n,2) (``analysis.py:70-74``)."""
    return 1.0 / (n * (n - 1) // 2)
