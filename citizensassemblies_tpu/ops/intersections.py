"""Intersectional representation: shares and MSEs for 2-feature groups.

Reference ``analysis.py:459-530``: an optional ``intersections.csv`` (schema
``category 1,feature 1,category 2,feature 2,population share``) lists 2-feature
intersections with their population shares; for each, the panel share under an
allocation is ``Σ_i π_i [agent i has both features] / k``, the pool share is the
fraction of the pool in the group, and the quota share is the product of quota
midpoint shares (``analysis.py:466-471``). Seven MSEs over share pairs are the
headline numbers (``analysis.py:509-517``; golden values in
``reference_output/sf_e_110_statistics.txt:15-21``).

Dense form: stack the per-row pair masks as ``G ∈ {0,1}^{R×n}`` with
``G[r] = A[:, f1_r] * A[:, f2_r]``; then all panel/pool shares are matvecs.
"""

from __future__ import annotations

import csv
import dataclasses
from pathlib import Path
from typing import Dict, List, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np

from citizensassemblies_tpu.core.instance import DenseInstance, FeatureSpace

#: the seven share pairs the reference reports MSEs for (``analysis.py:509-512``)
DIFF_PAIRS: Tuple[Tuple[str, str], ...] = (
    ("panel share LEXIMIN", "population share"),
    ("panel share LEGACY", "population share"),
    ("panel share LEXIMIN", "pool share"),
    ("panel share LEGACY", "pool share"),
    ("panel share LEXIMIN", "quota share"),
    ("panel share LEGACY", "quota share"),
    ("panel share LEXIMIN", "panel share LEGACY"),
)


@dataclasses.dataclass
class IntersectionTable:
    """Parsed intersections.csv plus the dense group-membership matrix."""

    rows: List[Tuple[str, str, str, str]]  # (cat1, feat1, cat2, feat2)
    population_share: np.ndarray  # float[R]
    group_mask: np.ndarray  # bool[R, n]
    quota_share: np.ndarray  # float[R]


def read_intersections(
    path: Union[str, Path], dense: DenseInstance, space: FeatureSpace
) -> IntersectionTable:
    rows: List[Tuple[str, str, str, str]] = []
    pop: List[float] = []
    with open(path, "r", encoding="utf-8") as fh:
        for entry in csv.DictReader(fh):
            rows.append(
                (entry["category 1"], entry["feature 1"], entry["category 2"], entry["feature 2"])
            )
            pop.append(float(entry["population share"]))

    A = dense.A_np
    qmin = dense.qmin_np
    qmax = dense.qmax_np
    masks = np.zeros((len(rows), A.shape[0]), dtype=bool)
    quota_share = np.zeros(len(rows))
    for r, (c1, f1, c2, f2) in enumerate(rows):
        i1 = space.feature_index(c1, f1)
        i2 = space.feature_index(c2, f2)
        masks[r] = A[:, i1] & A[:, i2]
        mid1 = (qmin[i1] + qmax[i1]) / 2.0
        mid2 = (qmin[i2] + qmax[i2]) / 2.0
        # product of quota-midpoint panel shares (``analysis.py:466-471``)
        quota_share[r] = (mid1 / dense.k) * (mid2 / dense.k)

    return IntersectionTable(
        rows=rows,
        population_share=np.asarray(pop),
        group_mask=masks,
        quota_share=quota_share,
    )


def intersection_shares(
    table: IntersectionTable,
    k: int,
    allocations: Dict[str, Sequence[float]],
) -> Dict[str, np.ndarray]:
    """Compute all share series. ``allocations`` maps a label (e.g. "LEGACY")
    to a dense allocation vector; returns ``panel share <label>`` per entry,
    plus ``pool share``, ``quota share``, ``population share``."""
    G = jnp.asarray(table.group_mask, dtype=jnp.float32)
    out: Dict[str, np.ndarray] = {
        "population share": table.population_share,
        "pool share": np.asarray(jnp.mean(G, axis=1)),
        "quota share": table.quota_share,
    }
    for label, alloc in allocations.items():
        pi = jnp.asarray(alloc, dtype=jnp.float32)
        out[f"panel share {label}"] = np.asarray(G @ pi / k)
    return out


def intersection_mses(
    shares: Dict[str, np.ndarray],
    diff_pairs: Sequence[Tuple[str, str]] = DIFF_PAIRS,
) -> Dict[Tuple[str, str], float]:
    """MSEs between share series (``analysis.py:513-517``)."""
    return {
        (s1, s2): float(np.mean((shares[s1] - shares[s2]) ** 2)) for s1, s2 in diff_pairs
    }
