"""``python -m citizensassemblies_tpu`` — the analysis CLI (reference
``analysis.py:646-705``)."""

from citizensassemblies_tpu.analysis.cli import main

if __name__ == "__main__":
    raise SystemExit(main())
