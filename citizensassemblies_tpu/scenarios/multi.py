"""Multi-assembly scheduling: leximin over R successive disjoint panels.

R panels are drawn in sequence from one pool with the cross-panel constraint
that NO agent is seated twice. The construction keeps every certificate of
the single-panel engine:

* **Capped enumeration** — compositions are enumerated with per-type caps
  ``⌊m_t/R⌋`` (a shallow msize override on the type reduction). Any R panels
  whose compositions respect the cap need at most ``R·⌊m_t/R⌋ ≤ m_t`` agents
  of each type in total, so EVERY drawn R-round schedule can be realized with
  zero repeats by within-type relabeling — disjointness is a property of the
  composition support, not a constraint the LP has to carry.
* **Aggregate leximin** — ``leximin_over_compositions(comps, msize / R)``
  certifies the per-type AGGREGATE value ``a_t = R·c̄_t/m_t ∈ [0, 1]``: with
  zero repeats an agent's seated-count over R rounds is 0/1, so the aggregate
  marginal IS the probability of serving on at least one of the R panels —
  the quantity leximin should equalize across rounds.
* **R-fold LP fleet** — each round's panel probabilities are recovered by one
  final ε-LP over that round's portfolio (the base portfolio under a
  within-type rotation, which spreads pair co-occurrence across rounds à la
  XMIN). The R same-shape LPs compile into ONE batched dispatch through
  ``solvers/batch_lp.py`` (cross-fleet bucketing: R lanes, one bucket), with
  the serial host LP as the engine-off / non-convergence fallback.

Pair-probability equity is gauged against the uniform pair value
(``ops/pairs.py``): the expected co-seating mass summed over rounds is
``R·C(k,2)``, and the gauge reports the max pair probability relative to that
mass spread uniformly over all ``C(n,2)`` pairs.
"""

from __future__ import annotations

import copy
import dataclasses
from typing import Any, Dict, List, Optional

import numpy as np

from citizensassemblies_tpu.core.instance import DenseInstance, FeatureSpace
from citizensassemblies_tpu.service.context import (
    resolve as resolve_context,
    use_context,
)
from citizensassemblies_tpu.utils.config import Config
from citizensassemblies_tpu.utils.logging import RunLog


@dataclasses.dataclass
class MultiAssemblyResult:
    """R round portfolios plus aggregate certificates and the pair gauge.

    ``allocation``/``fixed_probabilities`` are AGGREGATE (probability of
    serving on ≥ 1 of the R panels), so the service audit's 1e-3 L∞ contract
    stamp reads the same as the single-panel models. ``realize`` draws one
    concrete zero-repeat schedule.
    """

    rounds: int
    committees: np.ndarray  # bool[C, n] base (round-0) portfolio
    round_portfolios: List[np.ndarray]  # R × bool[C, n]
    round_probabilities: List[np.ndarray]  # R × float64[C]
    allocation: np.ndarray  # float64[n] aggregate Σ_r P_rᵀ p_r
    output_lines: List[str]
    fixed_probabilities: np.ndarray  # float64[n] certified aggregate values
    covered: np.ndarray  # bool[n]
    type_id: np.ndarray  # int32[n]
    pair_max: float  # max cross-agent pair probability over the R rounds
    pair_uniform: float  # uniform-spread pair value R·C(k,2)/C(n,2)
    pair_ratio: float  # pair_max / pair_uniform (1.0 = perfectly spread)
    realization_dev: float = 0.0
    contract_ok: bool = True
    scenario_audit: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def probabilities(self) -> np.ndarray:
        """Round-0 probabilities (Distribution-shaped convenience view)."""
        return self.round_probabilities[0]

    def realize(self, seed: int = 0) -> np.ndarray:
        """Draw one concrete R-round schedule with zero agent repeats.

        Each round draws a panel from its portfolio; members already seated
        in an earlier round are swapped for an unseated agent of the same
        type (always possible — the composition caps guarantee the pool
        never runs dry, see the module docstring). Returns int32[R, k]
        sorted agent ids per round.
        """
        rng = np.random.default_rng(seed)
        n = self.allocation.shape[0]
        seated = np.zeros(n, dtype=bool)
        rows: List[np.ndarray] = []
        for r in range(self.rounds):
            p = self.round_probabilities[r]
            c = rng.choice(len(p), p=p)
            panel = set(np.nonzero(self.round_portfolios[r][c])[0].tolist())
            taken: set = set()
            for i in sorted(panel):
                if not seated[i]:
                    taken.add(i)
                    continue
                mates = np.nonzero(
                    (self.type_id == self.type_id[i]) & ~seated
                )[0]
                mates = [j for j in mates if j not in panel and j not in taken]
                if not mates:  # pragma: no cover - excluded by the caps
                    raise RuntimeError(
                        f"round {r}: no unseated type-{self.type_id[i]} "
                        f"replacement for agent {i}"
                    )
                taken.add(int(rng.choice(mates)))
            row = np.sort(np.asarray(sorted(taken), dtype=np.int32))
            seated[row] = True
            rows.append(row)
        return np.stack(rows, axis=0)


def _rotation(members: List[np.ndarray], n: int, shift: int) -> np.ndarray:
    """Within-type rotation ``src`` such that ``P[:, src]`` gives agent
    ``mem[(j+shift) % m]`` the column of ``mem[j]`` — round r's portfolio is
    the base portfolio advanced r steps around each type's member ring."""
    src = np.arange(n, dtype=np.int64)
    for mem in members:
        m = len(mem)
        if m > 1:
            src[mem[(np.arange(m) + shift) % m]] = mem
    return src


def find_distribution_multi(
    dense: DenseInstance,
    space: Optional[FeatureSpace] = None,
    rounds: Optional[int] = None,
    cfg: Optional[Config] = None,
    households: Optional[np.ndarray] = None,
    log: Optional[RunLog] = None,
    ctx=None,
) -> MultiAssemblyResult:
    """Leximin over ``rounds`` successive panels with zero agent repeats.

    ``rounds`` defaults to ``Config.scenario_rounds``. Raises
    :class:`~citizensassemblies_tpu.scenarios.SchedulingInfeasible` when the
    per-round caps leave the quotas unsatisfiable, and
    :class:`~citizensassemblies_tpu.scenarios.ScenarioError` when the type
    space is not enumerable (the multi model has no CG path — its
    disjointness argument is a property of the enumeration caps).
    """
    from citizensassemblies_tpu.scenarios import ScenarioError

    ctx, cfg, log = resolve_context(ctx, cfg, log)
    if households is not None:
        raise ScenarioError(
            "the multi-assembly model does not support household constraints "
            "yet (the rotation realization is not household-aware)"
        )
    R = int(rounds) if rounds is not None else int(cfg.scenario_rounds)
    if R < 1:
        raise ScenarioError(f"rounds must be >= 1, got {R}")
    with use_context(ctx):
        return _multi_impl(dense, R, cfg, log, ctx)


def _multi_impl(
    dense: DenseInstance, R: int, cfg: Config, log: RunLog, ctx
) -> MultiAssemblyResult:
    from citizensassemblies_tpu.scenarios import ScenarioError, SchedulingInfeasible
    from citizensassemblies_tpu.solvers.batch_lp import (
        final_primal_batch_lp,
        lp_batch_enabled,
        solve_lp_batch,
    )
    from citizensassemblies_tpu.solvers.compositions import (
        decompose_with_pricing,
        enumerate_compositions,
        leximin_over_compositions,
    )
    from citizensassemblies_tpu.solvers.highs_backend import (
        solve_final_primal_lp_duals,
    )
    from citizensassemblies_tpu.solvers.native_oracle import TypeReduction
    from citizensassemblies_tpu.ops.pairs import (
        pair_matrix_from_portfolio,
        uniform_pair_value,
    )

    log.emit(f"Using multi-assembly scheduling over {R} rounds (scenarios/multi).")
    reduction = TypeReduction(dense)
    if reduction.T > cfg.enum_max_types:
        raise ScenarioError(
            f"multi-assembly needs an enumerable type space: {reduction.T} "
            f"types > enum_max_types={cfg.enum_max_types}"
        )
    if ctx is not None and ctx.deadline is not None:
        ctx.deadline.check("scenario_multi_enum", log)
    # capped enumeration: a shallow msize override is all the enumerator
    # reads, and the caps are what make every schedule disjoint-realizable
    capped = copy.copy(reduction)
    capped.msize = (reduction.msize // R).astype(np.int32)
    comps = enumerate_compositions(
        capped, cap=cfg.enum_cap, node_budget=cfg.enum_node_budget
    )
    if comps is None:
        raise ScenarioError(
            f"capped composition enumeration exceeded its budget "
            f"(cap={cfg.enum_cap}, node_budget={cfg.enum_node_budget})"
        )
    if len(comps) == 0:
        raise SchedulingInfeasible(
            f"no feasible composition with per-type caps ⌊m_t/{R}⌋ — "
            f"{R} disjoint rounds cannot satisfy the quotas "
            f"(pool of {dense.n} supports at most "
            f"{int(np.sum(reduction.msize // R))} capped seats for k={dense.k})"
        )
    log.emit(
        f"Multi-assembly: {reduction.T} types, caps ⌊m/{R}⌋, "
        f"{len(comps)} feasible compositions."
    )
    if ctx is not None and ctx.deadline is not None:
        ctx.deadline.check("scenario_multi_leximin", log)
    with log.timer("scenario_leximin"):
        # m/R divisor ⇒ certified values are R·c/m — the aggregate
        # (≥ 1-of-R) seating probability under a zero-repeat schedule
        ts = leximin_over_compositions(
            comps,
            reduction.msize.astype(np.float64) / float(R),
            probe_tol=cfg.probe_tol,
            log=log,
            cfg=cfg,
        )
    agg_type = ts.probabilities @ (
        ts.compositions.astype(np.float64)
        * float(R)
        / reduction.msize.astype(np.float64)[None, :]
    )
    a_agent = agg_type[reduction.type_id]
    per_round_target = a_agent / float(R)
    if ctx is not None and ctx.deadline is not None:
        ctx.deadline.check("scenario_multi_decompose", log)
    with log.timer("scenario_decompose"):
        P, p_seed, eps_seed = decompose_with_pricing(
            ts.compositions,
            ts.probabilities,
            reduction,
            per_round_target,
            budget=cfg.decompose_budget,
            support_eps=cfg.support_eps,
            log=log,
            tol=max(cfg.decomp_tol, 2e-5),
        )
    p_seed = np.clip(p_seed, 0.0, 1.0)
    keep = p_seed > cfg.support_eps
    P, p_seed = P[keep], p_seed[keep]
    p_seed = p_seed / p_seed.sum()

    # R round portfolios: the base portfolio under within-type rotations —
    # marginals are (near-)invariant because the decomposition target is
    # constant within type, while pair co-occurrence decorrelates
    portfolios = [P[:, _rotation(reduction.members, dense.n, r)] for r in range(R)]

    if ctx is not None and ctx.deadline is not None:
        ctx.deadline.check("scenario_multi_fleet", log)
    with log.timer("scenario_fleet"):
        probs_r: List[np.ndarray] = []
        eps_r: List[float] = []
        if lp_batch_enabled(cfg):
            # the R-fold fleet: R same-shape ε-LPs, one bucketed dispatch
            fleet = [
                final_primal_batch_lp(Pr, per_round_target) for Pr in portfolios
            ]
            sols = solve_lp_batch(
                fleet, cfg, log, warm_key="scenario_multi", common_bucket=True
            )
            for sol in sols:
                if sol.ok:
                    p = np.clip(np.asarray(sol.x[: P.shape[0]], dtype=np.float64), 0.0, 1.0)
                    probs_r.append(p / p.sum())
                    eps_r.append(float(sol.x[P.shape[0]]))
                else:
                    probs_r.append(p_seed)
                    eps_r.append(float(eps_seed))
        else:
            for Pr in portfolios:
                p, eps, _y, _mu = solve_final_primal_lp_duals(Pr, per_round_target)
                p = np.clip(np.asarray(p, dtype=np.float64), 0.0, 1.0)
                probs_r.append(p / p.sum())
                eps_r.append(float(eps))

    allocation = np.zeros(dense.n, dtype=np.float64)
    pair = np.zeros((dense.n, dense.n), dtype=np.float64)
    for Pr, pr in zip(portfolios, probs_r):
        allocation += Pr.T.astype(np.float64) @ pr
        pair += np.asarray(pair_matrix_from_portfolio(Pr, pr), dtype=np.float64)
    coverable = (
        ts.coverable if hasattr(ts, "coverable") else ts.compositions.max(axis=0) > 0
    )
    covered = coverable[reduction.type_id]
    total_dev = float(np.max(np.abs(allocation - a_agent)))
    k = int(dense.k)
    pair_uniform = float(R) * (k * (k - 1) / 2.0) * float(uniform_pair_value(dense.n))
    offdiag = pair[~np.eye(dense.n, dtype=bool)]
    pair_max = float(offdiag.max()) if offdiag.size else 0.0
    pair_ratio = pair_max / pair_uniform if pair_uniform > 0 else 0.0
    log.emit(
        f"Multi-assembly done: {ts.stages} stages, {ts.lp_solves} LP solves, "
        f"{P.shape[0]} panels/round, round ε ≤ {max(eps_r):.2e}, aggregate "
        f"max |alloc − target| = {total_dev:.2e}, pair gauge "
        f"{pair_ratio:.2f}× uniform."
    )
    audit: Dict[str, Any] = {
        "model": "multi",
        "rounds": R,
        "types": int(reduction.T),
        "compositions": int(len(comps)),
        "panels_per_round": int(P.shape[0]),
        "fleet_backend": "batch_lp" if lp_batch_enabled(cfg) else "host",
        "round_eps_max": round(max(eps_r), 8),
        "pair_max": round(pair_max, 8),
        "pair_uniform": round(pair_uniform, 8),
        "pair_ratio": round(pair_ratio, 4),
        "certified_min_aggregate": round(
            float(agg_type[coverable].min()) if coverable.any() else 0.0, 6
        ),
    }
    return MultiAssemblyResult(
        rounds=R,
        committees=P,
        round_portfolios=portfolios,
        round_probabilities=probs_r,
        allocation=allocation,
        output_lines=list(log.lines),
        fixed_probabilities=a_agent,
        covered=covered,
        type_id=reduction.type_id.astype(np.int32),
        pair_max=pair_max,
        pair_uniform=pair_uniform,
        pair_ratio=pair_ratio,
        realization_dev=total_dev,
        contract_ok=bool(total_dev <= 1e-3),
        scenario_audit=audit,
    )
