"""Dropout-robust leximin: maximize REALIZED minimum selection probability.

Each agent i carries a no-show probability ``q_i`` (attendance ``w_i = 1 −
q_i``). A seat given to agent i is only *realized* with probability ``w_i``
(the replacement policy refills the seat type-matched, so the no-show's seat
does not change anyone else's realization — see the policy semantics in
``parallel/mc.py``). The quantity to leximin-maximize is therefore ``w_i ·
π_i`` (realized seating probability), not the paper probability ``π_i``.

The fold into the existing machinery is one line: the composition engine's
allocation matrix is ``M = c_t / msize_t`` (``solvers/compositions.py``), and
every downstream certificate only consumes ``M`` — so running
``leximin_over_compositions(comps, msize / w)`` makes the engine optimize
``w_t · c_t / m_t``, the attendance-weighted realized value, with the whole
probe-certification stack unchanged. Attendance enters the TYPE STRUCTURE by
augmenting the instance with a one-hot attendance-bucket category under
vacuous quotas ``[0, k]``: agents of one base type but different attendance
become distinct product types (same feasible panels, finer symmetry classes),
at the price of a ``×B`` type-count blowup — gated by ``Config.enum_max_types``
with an explicit attendance-unaware fallback stamped on the scenario audit.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Optional, Tuple

import jax
import numpy as np

from citizensassemblies_tpu.core.instance import DenseInstance, FeatureSpace, HostView
from citizensassemblies_tpu.service.context import (
    resolve as resolve_context,
    use_context,
)
from citizensassemblies_tpu.utils.config import Config
from citizensassemblies_tpu.utils.logging import RunLog

#: no-show probabilities are clipped here: a ``q → 1`` agent would blow the
#: effective divisor ``m/w`` up without bound (the model would hand the whole
#: panel to near-certain no-shows to push their realized value off the floor)
_MAX_NOSHOW = 0.95


@dataclasses.dataclass
class DropoutDistribution:
    """A panel distribution optimized for realized (post-dropout) equity.

    Field names mirror :class:`~citizensassemblies_tpu.models.leximin.
    Distribution` so the service audit stamps (``realization_dev``,
    ``contract_ok``, ``allocation``) read identically; ``allocation`` and
    ``fixed_probabilities`` stay in SELECTION space (probability of being
    *seated on paper*), while ``realized_values`` carries the certified
    attendance-weighted objective the model actually leximin-maximized.
    """

    committees: np.ndarray  # bool[C, n] portfolio matrix
    probabilities: np.ndarray  # float64[C]
    allocation: np.ndarray  # float64[n] selection probability realized
    output_lines: List[str]
    fixed_probabilities: np.ndarray  # float64[n] selection-space targets
    covered: np.ndarray  # bool[n]
    attendance: np.ndarray  # float64[n] show-up probability w
    realized_values: np.ndarray  # float64[n] certified w·π leximin values
    #: BASE-type labels (identical feature rows of the ORIGINAL instance) —
    #: the "type" replacement policy matches on these: a same-base-type
    #: replacement has the same feature row, so refills preserve the quotas
    #: exactly; matching on the attendance bucket too would only shrink the
    #: candidate pool without buying any quota guarantee
    type_id: np.ndarray
    realization_dev: float = 0.0
    contract_ok: bool = True
    scenario_audit: Dict[str, Any] = dataclasses.field(default_factory=dict)

    @property
    def panels(self) -> List[Tuple[int, ...]]:
        return [tuple(np.nonzero(row)[0].tolist()) for row in self.committees]

    def support(self, eps: float = 1e-11) -> List[Tuple[int, ...]]:
        return [
            tuple(np.nonzero(row)[0].tolist())
            for row, p in zip(self.committees, self.probabilities)
            if p > eps
        ]


def _attendance_buckets(
    noshow: np.ndarray, n_buckets: int
) -> Tuple[np.ndarray, np.ndarray, float]:
    """Quantize no-show probabilities into equal-width buckets over [0, 1].

    Returns ``(bucket int32[n] dense ids, w_rep float64[n_occupied] mean
    attendance per occupied bucket, linf quantization error)``. Only occupied
    buckets get ids, so the type-space blowup is bounded by the attendance
    diversity actually present, not the knob.
    """
    q = np.clip(np.asarray(noshow, dtype=np.float64), 0.0, _MAX_NOSHOW)
    raw = np.minimum((q * n_buckets).astype(np.int64), n_buckets - 1)
    occupied, bucket = np.unique(raw, return_inverse=True)
    w = 1.0 - q
    w_rep = np.array(
        [w[bucket == b].mean() for b in range(len(occupied))], dtype=np.float64
    )
    linf = float(np.max(np.abs(w - w_rep[bucket]))) if len(w) else 0.0
    return bucket.astype(np.int32), w_rep, linf


def _augment_with_buckets(
    dense: DenseInstance, bucket: np.ndarray, n_occupied: int
) -> DenseInstance:
    """Append a one-hot attendance-bucket category with vacuous quotas
    ``[0, k]`` — feasible panels are unchanged, but the type reduction now
    distinguishes attendance classes within each base type."""
    A = dense.A_np
    n = A.shape[0]
    onehot = np.zeros((n, n_occupied), dtype=bool)
    onehot[np.arange(n), bucket] = True
    A_aug = np.hstack([A, onehot])
    qmin = np.concatenate(
        [dense.qmin_np, np.zeros(n_occupied, dtype=np.int32)]
    ).astype(np.int32)
    qmax = np.concatenate(
        [dense.qmax_np, np.full(n_occupied, dense.k, dtype=np.int32)]
    ).astype(np.int32)
    cat = np.concatenate(
        [
            np.asarray(dense.cat_of_feature, dtype=np.int32),
            np.full(n_occupied, dense.n_categories, dtype=np.int32),
        ]
    ).astype(np.int32)
    import jax.numpy as jnp

    return DenseInstance(
        A=jnp.asarray(A_aug),
        qmin=jnp.asarray(qmin),
        qmax=jnp.asarray(qmax),
        cat_of_feature=jnp.asarray(cat),
        k=dense.k,
        n_categories=dense.n_categories + 1,
        host=HostView(A_aug, qmin, qmax),
    )


def _attendance_unaware_fallback(
    dense: DenseInstance,
    space: Optional[FeatureSpace],
    w: np.ndarray,
    cfg: Config,
    log: RunLog,
    reason: str,
    audit: Dict[str, Any],
) -> DropoutDistribution:
    """Degrade to the plain (attendance-blind) leximin, explicitly flagged:
    the selection-space certificate still holds, only the objective is not
    attendance-weighted."""
    from citizensassemblies_tpu.models.leximin import find_distribution_leximin
    from citizensassemblies_tpu.solvers.native_oracle import TypeReduction

    log.emit(f"Dropout model falling back to attendance-unaware leximin: {reason}")
    dist = find_distribution_leximin(dense, space, cfg=cfg, log=log)
    audit["fallback"] = reason
    realized = w * dist.allocation
    audit["certified_min_realized"] = round(
        float(realized[dist.covered].min()) if dist.covered.any() else 0.0, 6
    )
    result = DropoutDistribution(
        committees=dist.committees,
        probabilities=dist.probabilities,
        allocation=dist.allocation,
        output_lines=dist.output_lines,
        fixed_probabilities=dist.fixed_probabilities,
        covered=dist.covered,
        attendance=w,
        realized_values=realized,
        type_id=TypeReduction(dense).type_id.astype(np.int32),
        realization_dev=dist.realization_dev,
        contract_ok=dist.contract_ok,
        scenario_audit=audit,
    )
    # the degraded portfolio ships with the same realized-evaluation stamp
    # as the aware path — the audit must show what the shipped distribution
    # actually realizes, not just that the objective was blind
    if cfg.scenario_mc_draws > 0:
        audit["mc"] = evaluate_realization(
            result, dense, cfg=cfg, draws=cfg.scenario_mc_draws,
            policy=cfg.scenario_replacement,
        )
    return result


def find_distribution_dropout(
    dense: DenseInstance,
    space: Optional[FeatureSpace] = None,
    dropout: Optional[np.ndarray] = None,
    cfg: Optional[Config] = None,
    households: Optional[np.ndarray] = None,
    log: Optional[RunLog] = None,
    ctx=None,
) -> DropoutDistribution:
    """Compute the dropout-robust leximin distribution.

    ``dropout`` is float[n] per-agent NO-SHOW probability (clipped to
    ``[0, 0.95]``). The certified objective is the realized seating
    probability ``w_i · π_i`` under a type-matched replacement policy; the
    returned ``allocation`` is the selection-space marginal the portfolio
    realizes, ``realized_values`` the attendance-weighted certified values.
    With ``Config.scenario_mc_draws > 0`` a Monte-Carlo realization audit
    (``parallel/mc.py``) under ``Config.scenario_replacement`` is stamped on
    ``scenario_audit["mc"]``.
    """
    from citizensassemblies_tpu.scenarios import ScenarioError

    ctx, cfg, log = resolve_context(ctx, cfg, log)
    if households is not None:
        raise ScenarioError(
            "the dropout model does not support household constraints yet "
            "(the bucket augmentation and the household quotient both rewrite "
            "the instance; composing them is future work)"
        )
    if dropout is None:
        raise ScenarioError("the dropout model requires per-agent no-show probabilities")
    dropout = np.asarray(dropout, dtype=np.float64).reshape(-1)
    if dropout.shape[0] != dense.n:
        raise ScenarioError(
            f"dropout has {dropout.shape[0]} entries for {dense.n} agents"
        )
    with use_context(ctx):
        return _dropout_impl(dense, space, dropout, cfg, log, ctx)


def _dropout_impl(
    dense: DenseInstance,
    space: Optional[FeatureSpace],
    dropout: np.ndarray,
    cfg: Config,
    log: RunLog,
    ctx,
) -> DropoutDistribution:
    from citizensassemblies_tpu.solvers.compositions import (
        decompose_with_pricing,
        enumerate_compositions,
        leximin_over_compositions,
    )
    from citizensassemblies_tpu.solvers.native_oracle import TypeReduction

    log.emit("Using dropout-robust leximin (scenarios/dropout).")
    w = 1.0 - np.clip(dropout, 0.0, _MAX_NOSHOW)
    bucket, w_rep, quant_err = _attendance_buckets(
        dropout, max(1, int(cfg.scenario_dropout_buckets))
    )
    audit: Dict[str, Any] = {
        "model": "dropout",
        "buckets": int(len(w_rep)),
        "quantization_linf": round(quant_err, 6),
        "replacement": cfg.scenario_replacement,
    }
    if ctx is not None and ctx.deadline is not None:
        ctx.deadline.check("scenario_dropout_reduce", log)

    dense_aug = _augment_with_buckets(dense, bucket, len(w_rep))
    reduction = TypeReduction(dense_aug)
    audit["types"] = int(reduction.T)
    if reduction.T > cfg.enum_max_types:
        return _attendance_unaware_fallback(
            dense, space, w, cfg, log,
            f"product type-space has {reduction.T} types "
            f"(> enum_max_types={cfg.enum_max_types})",
            audit,
        )
    comps = enumerate_compositions(
        reduction, cap=cfg.enum_cap, node_budget=cfg.enum_node_budget
    )
    if comps is None or len(comps) == 0:
        return _attendance_unaware_fallback(
            dense, space, w, cfg, log,
            "product composition enumeration exceeded its budget"
            if comps is None
            else "no feasible composition in the product type-space",
            audit,
        )
    # per-type representative attendance: all members of a product type share
    # one bucket by construction
    w_type = w_rep[bucket[np.array([m[0] for m in reduction.members])]]
    log.emit(
        f"Dropout product type-space: {reduction.T} types over "
        f"{len(w_rep)} attendance buckets, {len(comps)} feasible compositions."
    )
    if ctx is not None and ctx.deadline is not None:
        ctx.deadline.check("scenario_dropout_leximin", log)
    with log.timer("scenario_leximin"):
        # the one-line fold: dividing msize by the attendance weight turns the
        # engine's allocation matrix c/m into w·c/m — certified REALIZED values
        ts = leximin_over_compositions(
            comps,
            reduction.msize.astype(np.float64) / w_type,
            probe_tol=cfg.probe_tol,
            log=log,
            cfg=cfg,
        )
    # selection-space marginal the composition mixture realizes (plain integer
    # msize divisor) — the decomposition target, constant within type
    sel_type = ts.probabilities @ (
        ts.compositions.astype(np.float64)
        / reduction.msize.astype(np.float64)[None, :]
    )
    target_agent = sel_type[reduction.type_id]
    if ctx is not None and ctx.deadline is not None:
        ctx.deadline.check("scenario_dropout_decompose", log)
    with log.timer("scenario_decompose"):
        P, probs, eps_dev = decompose_with_pricing(
            ts.compositions,
            ts.probabilities,
            reduction,
            target_agent,
            budget=cfg.decompose_budget,
            support_eps=cfg.support_eps,
            log=log,
            tol=max(cfg.decomp_tol, 2e-5),
        )
    probs = np.clip(probs, 0.0, 1.0)
    keep = probs > cfg.support_eps
    P, probs = P[keep], probs[keep]
    probs = probs / probs.sum()
    allocation = P.T.astype(np.float64) @ probs
    coverable = (
        ts.coverable if hasattr(ts, "coverable") else ts.compositions.max(axis=0) > 0
    )
    covered = coverable[reduction.type_id]
    realized_values = ts.type_values[reduction.type_id]
    total_dev = float(np.max(np.abs(allocation - target_agent)))
    w_agent = w_type[reduction.type_id]
    min_realized = float((w_agent * allocation)[covered].min()) if covered.any() else 0.0
    audit["certified_min_realized"] = round(
        float(realized_values[covered].min()) if covered.any() else 0.0, 6
    )
    log.emit(
        f"Dropout leximin done: {ts.stages} stages, {ts.lp_solves} LP solves, "
        f"{P.shape[0]} panels, ε = {eps_dev:.2e}, realized-min "
        f"{min_realized:.4f}, max |alloc − target| = {total_dev:.2e}."
    )
    result = DropoutDistribution(
        committees=P,
        probabilities=probs,
        allocation=allocation,
        output_lines=list(log.lines),
        fixed_probabilities=target_agent,
        covered=covered,
        attendance=w,
        realized_values=realized_values,
        type_id=TypeReduction(dense).type_id.astype(np.int32),
        realization_dev=total_dev,
        contract_ok=bool(total_dev <= 1e-3),
        scenario_audit=audit,
    )
    if cfg.scenario_mc_draws > 0:
        if ctx is not None and ctx.deadline is not None:
            ctx.deadline.check("scenario_dropout_mc", log)
        audit["mc"] = evaluate_realization(
            result, dense, cfg=cfg, draws=cfg.scenario_mc_draws,
            policy=cfg.scenario_replacement,
        )
        log.emit(
            f"MC realization audit ({cfg.scenario_replacement}, "
            f"{audit['mc']['draws']} draws): realized-min "
            f"{audit['mc']['realized_min']:.4f}, quota-ok rate "
            f"{audit['mc']['quota_ok_rate']:.3f}."
        )
    return result


def evaluate_realization(
    dist,
    dense: DenseInstance,
    cfg: Optional[Config] = None,
    draws: int = 4_096,
    policy: str = "type",
    seed: int = 0,
    mesh=None,
) -> Dict[str, Any]:
    """Monte-Carlo realized-outcome audit of any panel distribution under
    dropout. ``dist`` needs ``committees``/``probabilities`` plus the
    ``attendance``/``type_id``/``covered`` arrays (a
    :class:`DropoutDistribution`, or a plain Distribution wrapped by the
    bench baseline). Returns a plain-dict stamp. ``realized_min`` is the
    minimum covered-agent probability of being seated on a VALID realized
    panel (one satisfying every quota) — a quota-broken assembly is a failed
    realization, so a policy that refills seats by breaking quotas gets no
    credit for those seats; ``realized_min_any`` is the unconditional
    seating frequency for comparison.
    """
    from citizensassemblies_tpu.parallel.mc import dropout_realization_round

    real = dropout_realization_round(
        np.asarray(dist.committees, dtype=bool),
        np.asarray(dist.probabilities, dtype=np.float64),
        np.asarray(dist.attendance, dtype=np.float64),
        np.asarray(dist.type_id, dtype=np.int32),
        dense,
        jax.random.PRNGKey(seed),
        int(draws),
        policy=policy,
        mesh=mesh,
    )
    freq = real.frequencies_valid
    freq_any = real.frequencies
    covered = np.asarray(dist.covered, dtype=bool)
    return {
        "policy": policy,
        "draws": int(real.draws),
        "realized_min": round(float(freq[covered].min()) if covered.any() else 0.0, 6),
        "realized_min_any": round(
            float(freq_any[covered].min()) if covered.any() else 0.0, 6
        ),
        "realized_mean": round(float(freq.mean()), 6),
        "quota_ok_rate": round(real.quota_ok_rate, 6),
        "fill_rate": round(real.fill_rate, 6),
    }
