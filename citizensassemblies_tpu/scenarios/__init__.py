"""graftscenario: deployment-shaped selection models over the CG machinery.

The core pipeline selects ONE panel from a static pool that always shows up.
Real deployments face two departures from that model — no-shows and repeated
assemblies — and both collapse onto the same certified type-space engine
(``solvers/compositions.py``) through a *product type-space* construction:

* **Dropout-robust leximin** (:mod:`~citizensassemblies_tpu.scenarios.dropout`)
  quantizes per-agent attendance probabilities into buckets, augments the
  instance with a vacuous-quota bucket category (so agents of one base type
  but different attendance become distinct product types), and runs the
  ordinary composition leximin with an attendance-weighted divisor — the
  certified ``type_values`` are then *realized* (post-dropout) selection
  probabilities, not paper probabilities. A vmapped/chain-sharded realization
  kernel (``parallel/mc.py::dropout_realization_round``) evaluates the
  distribution under a replacement policy against the naive re-draw baseline.

* **Multi-assembly scheduling** (:mod:`~citizensassemblies_tpu.scenarios.multi`)
  runs leximin over R successive panels with a no-agent-seated-twice
  constraint: enumeration is capped at ``⌊m_t/R⌋`` seats per type per round —
  which makes ANY drawn R-round schedule disjoint-realizable — and the
  aggregate (≥1-round) selection probabilities are certified by the same
  composition leximin with an ``m/R`` divisor. The R per-round probability
  recoveries compile into one R-fold LP fleet through ``solvers/batch_lp.py``
  (cross-fleet bucketing: R same-shape lanes, one dispatch), and pair-level
  equity is gauged against the uniform pair value à la XMIN (``ops/pairs.py``).

Both models register as first-class ``algorithm`` values in the service layer
and carry a ``scenario_audit`` stamp into the per-request audit record.
"""

from __future__ import annotations


class ScenarioError(RuntimeError):
    """A scenario model cannot run on this instance as configured."""


class SchedulingInfeasible(ScenarioError):
    """No feasible R-round disjoint schedule exists: the per-round type caps
    ``⌊m_t/R⌋`` leave the quotas unsatisfiable. Lower ``rounds`` or relax
    the quotas."""


from citizensassemblies_tpu.scenarios.dropout import (  # noqa: E402,F401
    DropoutDistribution,
    find_distribution_dropout,
)
from citizensassemblies_tpu.scenarios.multi import (  # noqa: E402,F401
    MultiAssemblyResult,
    find_distribution_multi,
)
