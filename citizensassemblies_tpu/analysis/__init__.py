"""Analysis & reporting layer (reference ``analysis.py`` L3-L5).

* :mod:`.cache` — pickle memoization of algorithm runs (``analysis.py:271-327``).
* :mod:`.plots` — the five-figure plotting suite (``analysis.py:330-456,519-528``).
* :mod:`.report` — the ``analyze_instance`` orchestrator, statistics.txt writer
  and timing harness (``analysis.py:533-636``).
* :mod:`.cli` — the data-scanning argparse driver (``analysis.py:646-705``).
"""

from citizensassemblies_tpu.analysis.cache import (  # noqa: F401
    AlgorithmRun,
    run_legacy_or_retrieve,
    run_leximin_or_retrieve,
    run_xmin_or_retrieve,
)
from citizensassemblies_tpu.analysis.report import analyze_instance  # noqa: F401
from citizensassemblies_tpu.analysis.cli import main  # noqa: F401
