"""Plotting suite (reference ``analysis.py:330-456`` + jointplot ``:519-528``).

Five figures + two raw-data CSVs, written to ``<out_dir>/`` with the upstream
filename conventions:

* ``<stem>_prob_allocs.pdf`` + ``<stem>_prob_allocs_data.csv`` — sorted
  per-agent selection probabilities per algorithm (``analysis.py:381-408``).
  The CSV uses the upstream ``algorithm,percentile of pool members,selection
  probability`` schema that the fork accidentally dropped (its ``:406`` saves
  the figure as ``_prob_allocs_data.pdf`` and writes no CSV — SURVEY §2 C20).
* ``<stem>_pair_probability_graph.pdf`` — sorted pair co-selection
  probabilities per algorithm plus the uniform C(n,2) baseline
  (``analysis.py:330-353``).
* ``<stem>_number_of_unique_panels.pdf`` — bar chart of unique-panel counts
  (``analysis.py:356-378``).
* ``<stem>_ratio_product.pdf`` + ``<stem>_ratio_product_data.csv`` — feature
  over-representation ratio products vs LEGACY probability
  (``analysis.py:434-456``).
* ``<stem>_intersections.pdf`` — seaborn jointplot of intersectional panel
  shares vs population shares (``analysis.py:519-528``).

Matplotlib runs on the Agg backend (no display needed); all figure writers are
host-side — the arrays they render are the jit-computed outputs of
:mod:`citizensassemblies_tpu.ops`.
"""

from __future__ import annotations

import csv
from pathlib import Path
from typing import Dict, Optional, Sequence, Union

import numpy as np

import matplotlib

matplotlib.use("Agg")
import matplotlib.pyplot as plt  # noqa: E402

from citizensassemblies_tpu.ops.pairs import sorted_pair_values, uniform_pair_value  # noqa: E402

#: display names per algorithm tag (reference legend labels, ``analysis.py:399``)
_LABELS = {"legacy": "Legacy", "leximin": "LEXIMIN", "xmin": "XMIN"}


def _label(tag: str) -> str:
    return _LABELS.get(tag, tag)


def plot_probability_allocations(
    allocations: Dict[str, np.ndarray],
    out_dir: Union[str, Path],
    stem: str,
) -> Path:
    """Sorted selection-probability curves + raw-data CSV
    (``analysis.py:381-408``; CSV schema from
    ``reference_output/example_small_20_prob_allocs_data.csv:1``)."""
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    pdf_path = out_dir / f"{stem}_prob_allocs.pdf"
    csv_path = out_dir / f"{stem}_prob_allocs_data.csv"

    fig, ax = plt.subplots(figsize=(8, 5))
    with open(csv_path, "w", encoding="utf-8", newline="") as fh:
        writer = csv.writer(fh)
        writer.writerow(["algorithm", "percentile of pool members", "selection probability"])
        for tag, alloc in allocations.items():
            alloc = np.sort(np.asarray(alloc, dtype=np.float64))
            n = alloc.shape[0]
            pct = 100.0 * np.arange(n) / n
            ax.plot(pct, alloc, label=_label(tag))
            for p, a in zip(pct, alloc):
                writer.writerow([_label(tag), p, round(float(a), 4)])
    ax.set_xlabel("percentile of pool members")
    ax.set_ylabel("selection probability")
    ax.set_ylim(bottom=0.0)
    ax.legend()
    fig.tight_layout()
    fig.savefig(pdf_path)
    plt.close(fig)
    return pdf_path


def plot_pair_probability(
    pair_matrices: Dict[str, np.ndarray],
    n: int,
    k: int,
    out_dir: Union[str, Path],
    stem: str,
) -> Path:
    """Sorted pair co-selection probability curves + the uniform baseline
    ``k(k-1)/(n(n-1))`` over all C(n,2) pairs (``analysis.py:330-353``)."""
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    pdf_path = out_dir / f"{stem}_pair_probability_graph.pdf"

    fig, ax = plt.subplots(figsize=(8, 5))
    for tag, M in pair_matrices.items():
        vals = sorted_pair_values(np.asarray(M))
        pct = 100.0 * np.arange(vals.shape[0]) / max(vals.shape[0], 1)
        ax.plot(pct, vals, label=_label(tag))
    # uniform co-selection baseline C(k,2)/C(n,2) = k(k-1)/(n(n-1))
    uniform = uniform_pair_value(n) * (k * (k - 1) // 2)
    ax.axhline(uniform, linestyle="--", color="gray", label="uniform")
    ax.set_xlabel("percentile of pairs")
    ax.set_ylabel("pair selection probability")
    ax.legend()
    fig.tight_layout()
    fig.savefig(pdf_path)
    plt.close(fig)
    return pdf_path


def plot_number_of_panels(
    counts: Dict[str, int],
    out_dir: Union[str, Path],
    stem: str,
) -> Path:
    """Unique-panel count bar chart (``analysis.py:356-378``)."""
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    pdf_path = out_dir / f"{stem}_number_of_unique_panels.pdf"

    fig, ax = plt.subplots(figsize=(6, 5))
    labels = [_label(t) for t in counts]
    values = list(counts.values())
    bars = ax.bar(labels, values)
    ax.bar_label(bars)
    ax.set_ylabel("number of unique panels")
    fig.tight_layout()
    fig.savefig(pdf_path)
    plt.close(fig)
    return pdf_path


def plot_ratio_products(
    ratio_products: np.ndarray,
    legacy_allocation: np.ndarray,
    out_dir: Union[str, Path],
    stem: str,
) -> Path:
    """Ratio-product scatter + CSV (``analysis.py:434-456``; CSV schema from
    ``reference_output/example_small_20_ratio_product_data.csv:1``)."""
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    pdf_path = out_dir / f"{stem}_ratio_product.pdf"
    csv_path = out_dir / f"{stem}_ratio_product_data.csv"

    rp = np.asarray(ratio_products, dtype=np.float64)
    alloc = np.asarray(legacy_allocation, dtype=np.float64)
    with open(csv_path, "w", encoding="utf-8", newline="") as fh:
        writer = csv.writer(fh)
        writer.writerow(["ratio product", "selection probability"])
        for r, a in zip(rp, alloc):
            writer.writerow([float(r), round(float(a), 4)])

    fig, ax = plt.subplots(figsize=(8, 5))
    ax.scatter(rp, alloc, s=12, alpha=0.6)
    ax.set_xlabel("ratio product")
    ax.set_ylabel("LEGACY selection probability")
    fig.tight_layout()
    fig.savefig(pdf_path)
    plt.close(fig)
    return pdf_path


def plot_intersectional_representation(
    shares: Dict[str, np.ndarray],
    out_dir: Union[str, Path],
    stem: str,
    pairs: Sequence[str] = ("panel share LEXIMIN", "panel share LEGACY"),
    against: str = "population share",
) -> Optional[Path]:
    """Jointplot of intersectional panel shares vs population share
    (``analysis.py:519-528``); falls back to a scatter grid if seaborn is
    unavailable."""
    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    pdf_path = out_dir / f"{stem}_intersections.pdf"

    try:
        import pandas as pd
        import seaborn as sns

        frames = []
        for col in pairs:
            if col not in shares:
                continue
            frames.append(
                pd.DataFrame(
                    {
                        against: shares[against],
                        "panel share": shares[col],
                        "algorithm": _label(col.replace("panel share ", "").lower()),
                    }
                )
            )
        if not frames:
            return None
        df = pd.concat(frames, ignore_index=True)
        grid = sns.jointplot(data=df, x=against, y="panel share", hue="algorithm", height=6)
        lim = float(max(df[against].max(), df["panel share"].max())) * 1.05
        grid.ax_joint.plot([0, lim], [0, lim], linestyle="--", color="gray", linewidth=1)
        grid.savefig(pdf_path)
        plt.close("all")
    except Exception:  # pragma: no cover — seaborn/pandas missing or headless quirk
        fig, ax = plt.subplots(figsize=(6, 6))
        for col in pairs:
            if col in shares:
                ax.scatter(shares[against], shares[col], s=10, alpha=0.6, label=_label(col))
        ax.set_xlabel(against)
        ax.set_ylabel("panel share")
        ax.legend()
        fig.tight_layout()
        fig.savefig(pdf_path)
        plt.close(fig)
    return pdf_path
