"""Pickle memoization of algorithm runs (reference ``analysis.py:271-327``).

Each (instance, k, algorithm) result is cached as
``<cache_dir>/<name>_<k>_<tag>.pickle`` with tags ``legacy_first`` /
``legacy_second`` / ``leximin`` / ``xmin`` — the same file layout the reference
uses under ``./distributions/``. LEGACY runs twice with seeds 0 and 1
(``analysis.py:277-282``): the first sample locates the minimizer agent, the
second gives an unbiased estimate of that agent's probability
(``analysis.py:564-571``).

The cached payload is a plain dict of numpy arrays + metadata (not the live
result objects) so caches stay readable across framework versions.
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import pickle
from pathlib import Path
from typing import List, Optional, Set, Tuple, Union

import numpy as np

from citizensassemblies_tpu.core.instance import DenseInstance, FeatureSpace
from citizensassemblies_tpu.models.legacy import legacy_probabilities
from citizensassemblies_tpu.models.leximin import Distribution, find_distribution_leximin
from citizensassemblies_tpu.models.xmin import find_distribution_xmin
from citizensassemblies_tpu.ops.pairs import pair_matrix_from_portfolio
from citizensassemblies_tpu.utils.config import Config, default_config
from citizensassemblies_tpu.utils.logging import RunLog


@dataclasses.dataclass
class AlgorithmRun:
    """The (allocation, unique panels, pair matrix) triple every
    ``*_probabilities`` adapter returns (``analysis.py:162,194,213``)."""

    algorithm: str  # "legacy" | "leximin" | "xmin"
    allocation: np.ndarray  # float64[n] per-agent selection probability
    unique_panels: Set[Tuple[int, ...]]
    pair_matrix: np.ndarray  # float64[n, n] pair co-selection probabilities
    output_lines: List[str]
    #: number of Monte-Carlo draws (LEGACY) or committees in support (others)
    num_draws: int = 0
    #: the exact algorithms' realization-contract report (None for LEGACY):
    #: max |allocation − certified profile| and whether it met the 1e-3 L∞
    #: contract — a budget-expired rescue ships contract_ok=False
    #: (``Distribution.contract_ok``), and the statistics report states it.
    realization_dev: Optional[float] = None
    contract_ok: Optional[bool] = None

    def to_payload(self) -> dict:
        return {
            "algorithm": self.algorithm,
            "allocation": np.asarray(self.allocation, dtype=np.float64),
            "unique_panels": sorted(self.unique_panels),
            "pair_matrix": np.asarray(self.pair_matrix, dtype=np.float64),
            "output_lines": list(self.output_lines),
            "num_draws": int(self.num_draws),
            "realization_dev": self.realization_dev,
            "contract_ok": self.contract_ok,
        }

    @classmethod
    def from_payload(cls, payload: dict) -> "AlgorithmRun":
        return cls(
            algorithm=payload["algorithm"],
            allocation=np.asarray(payload["allocation"]),
            unique_panels=set(map(tuple, payload["unique_panels"])),
            pair_matrix=np.asarray(payload["pair_matrix"]),
            output_lines=list(payload["output_lines"]),
            num_draws=int(payload.get("num_draws", 0)),
            realization_dev=payload.get("realization_dev"),
            contract_ok=payload.get("contract_ok"),
        )


#: config fields that determine each algorithm's output — the cache key
#: includes them so a result computed under different settings is recomputed,
#: not silently reused (the reference's fixed-filename cache has this hazard)
_KEY_FIELDS = {
    "legacy": ("mc_iterations", "mc_batch", "mc_max_resample_rounds"),
    "leximin": (
        "eps", "fixed_prob_relax_step", "support_eps", "mw_rounds_factor",
        "pricing_batch", "seed_batch",
        "cg_columns_per_round", "max_portfolio", "pdhg_max_iters", "pdhg_tol",
        "backend", "solver_seed", "force_agent_space",
    ),
}
_KEY_FIELDS["xmin"] = _KEY_FIELDS["leximin"] + (
    "xmin_iterations_factor", "xmin_dedup_attempts_factor", "xmin_qp_iters",
)


def _config_key(cfg: Config, algorithm: str, households=None) -> dict:
    key = {f: getattr(cfg, f) for f in _KEY_FIELDS[algorithm]}
    # household constraints change every algorithm's output; key their digest
    # so constrained and unconstrained runs are never interchanged
    key["households"] = (
        None
        if households is None
        else hashlib.sha256(np.asarray(households, dtype=np.int64).tobytes()).hexdigest()
    )
    return key


def _cache_path(cache_dir: Union[str, Path], name: str, k: int, tag: str) -> Path:
    return Path(cache_dir) / f"{name}_{k}_{tag}.pickle"


def _load_or_compute(path: Optional[Path], compute, config_key: dict) -> AlgorithmRun:
    if path is not None and path.exists():
        try:
            with open(path, "rb") as fh:
                payload = pickle.load(fh)
        except Exception:
            payload = None  # corrupt/truncated cache ⇒ recompute, don't crash
        if payload is not None and payload.get("config_key") == config_key:
            return AlgorithmRun.from_payload(payload)
    run = compute()
    if path is not None:
        path.parent.mkdir(parents=True, exist_ok=True)
        payload = run.to_payload()
        payload["config_key"] = config_key
        tmp = path.with_suffix(path.suffix + ".tmp")
        with open(tmp, "wb") as fh:
            pickle.dump(payload, fh)
        os.replace(tmp, path)  # atomic: a crash mid-dump leaves no partial cache
    return run


def _run_from_distribution(algorithm: str, dist: Distribution, support_eps: float) -> AlgorithmRun:
    probs = np.asarray(dist.probabilities, dtype=np.float64)
    keep = probs > support_eps  # reference filters the support (analysis.py:209)
    P = dist.committees[keep]
    pair = np.asarray(pair_matrix_from_portfolio(P, probs[keep]), dtype=np.float64)
    return AlgorithmRun(
        algorithm=algorithm,
        allocation=np.asarray(dist.allocation, dtype=np.float64),
        unique_panels={tuple(np.nonzero(row)[0].tolist()) for row in P},
        pair_matrix=pair,
        output_lines=list(dist.output_lines),
        num_draws=int(keep.sum()),
        realization_dev=float(dist.realization_dev),
        contract_ok=bool(dist.contract_ok),
    )


def run_legacy_or_retrieve(
    dense: DenseInstance,
    name: str,
    k: int,
    resample: bool = False,
    cache_dir: Optional[Union[str, Path]] = None,
    cfg: Optional[Config] = None,
    households: Optional[np.ndarray] = None,
) -> AlgorithmRun:
    """Monte-Carlo LEGACY estimate, memoized (``analysis.py:271-293``).

    ``resample=False`` uses seed 0 (tag ``legacy_first``); ``resample=True``
    uses seed 1 (tag ``legacy_second``) for the unbiased minimizer estimate.
    """
    cfg = cfg or default_config()
    seed = 1 if resample else 0
    tag = "legacy_second" if resample else "legacy_first"
    path = _cache_path(cache_dir, name, k, tag) if cache_dir is not None else None

    def compute() -> AlgorithmRun:
        res = legacy_probabilities(dense, iterations=cfg.mc_iterations, seed=seed, cfg=cfg,
                                   households=households)
        run = AlgorithmRun(
            algorithm="legacy",
            allocation=res.allocation,
            unique_panels=res.unique_panels,
            pair_matrix=res.pair_matrix,
            output_lines=[],
            num_draws=cfg.mc_iterations,
        )
        assert abs(run.allocation.sum() - k) < 1e-6 * k + 1e-6  # analysis.py:292
        return run

    return _load_or_compute(path, compute, _config_key(cfg, "legacy", households))


def run_leximin_or_retrieve(
    dense: DenseInstance,
    space: FeatureSpace,
    name: str,
    k: int,
    cache_dir: Optional[Union[str, Path]] = None,
    cfg: Optional[Config] = None,
    households: Optional[np.ndarray] = None,
) -> AlgorithmRun:
    """Exact LEXIMIN distribution, memoized (``analysis.py:313-327``)."""
    cfg = cfg or default_config()
    path = _cache_path(cache_dir, name, k, "leximin") if cache_dir is not None else None

    def compute() -> AlgorithmRun:
        dist = find_distribution_leximin(
            dense, space, cfg=cfg, households=households, log=RunLog(echo=False)
        )
        run = _run_from_distribution("leximin", dist, cfg.support_eps)
        assert abs(run.allocation.sum() - k) < 1e-4 * k + 1e-4  # analysis.py:326
        return run

    return _load_or_compute(path, compute, _config_key(cfg, "leximin", households))


def run_xmin_or_retrieve(
    dense: DenseInstance,
    space: FeatureSpace,
    name: str,
    k: int,
    cache_dir: Optional[Union[str, Path]] = None,
    cfg: Optional[Config] = None,
    households: Optional[np.ndarray] = None,
) -> AlgorithmRun:
    """XMIN distribution, memoized (``analysis.py:296-310``)."""
    cfg = cfg or default_config()
    path = _cache_path(cache_dir, name, k, "xmin") if cache_dir is not None else None

    def compute() -> AlgorithmRun:
        dist = find_distribution_xmin(
            dense, space, cfg=cfg, households=households, log=RunLog(echo=False)
        )
        run = _run_from_distribution("xmin", dist, cfg.support_eps)
        assert abs(run.allocation.sum() - k) < 1e-4 * k + 1e-4  # analysis.py:309
        return run

    return _load_or_compute(path, compute, _config_key(cfg, "xmin", households))
