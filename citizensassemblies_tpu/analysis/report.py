"""The ``analyze_instance`` orchestrator (reference ``analysis.py:533-636``).

Runs the four cached algorithm passes (LEGACY twice with seeds 0/1, LEXIMIN,
XMIN), computes every statistic the reference reports, tees them to console and
``<out_dir>/<name>_<k>_statistics.txt`` in the fork's layout (asterisk-ruled
sections, ``analysis/example_small_20_statistics.txt``), renders all five
plots, and finally times three fresh LEXIMIN runs and reports the median
(``analysis.py:625-634``) unless ``skip_timing``.

Two fork bugs noted in SURVEY §2 are fixed here: the XMIN geometric-mean line
prints the XMIN value (the fork printed LEXIMIN's, ``analysis.py:598``), and
the probability-allocation figure is saved as ``_prob_allocs.pdf`` with its
raw-data CSV restored (the fork saved ``_prob_allocs_data.pdf`` and no CSV,
``analysis.py:406``).
"""

from __future__ import annotations

import dataclasses
import statistics as pystats
import time
from pathlib import Path
from typing import Dict, Optional, Union

import numpy as np

from citizensassemblies_tpu.analysis.cache import (
    AlgorithmRun,
    run_legacy_or_retrieve,
    run_leximin_or_retrieve,
    run_xmin_or_retrieve,
)
from citizensassemblies_tpu.analysis import plots
from citizensassemblies_tpu.core.instance import Instance, featurize, validate_quotas
from citizensassemblies_tpu.models.leximin import find_distribution_leximin
from citizensassemblies_tpu.ops.intersections import (
    intersection_mses,
    intersection_shares,
    read_intersections,
)
from citizensassemblies_tpu.ops.ratio import compute_ratio_products
from citizensassemblies_tpu.ops.stats import (
    prob_allocation_stats,
    share_below,
    upper_confidence_bound,
)
from citizensassemblies_tpu.utils.config import Config, default_config
from citizensassemblies_tpu.utils.logging import RunLog, tee_file

_RULE = "*" * 80


def _percent(v: float) -> str:
    """Reference percent formatting (``analysis.py:547``-style ``{:.1%}``)."""
    return f"{v:.1%}"


@dataclasses.dataclass
class AnalysisResult:
    """Everything ``analyze_instance`` computed, for programmatic consumers."""

    runs: Dict[str, AlgorithmRun]
    stats: Dict[str, dict]
    minimizer_ucb: float
    share_below_leximin_min: float
    intersection_mses: Optional[Dict] = None
    timing_median_s: Optional[float] = None
    statistics_path: Optional[Path] = None


def analyze_instance(
    instance: Instance,
    out_dir: Union[str, Path] = "analysis",
    cache_dir: Optional[Union[str, Path]] = "distributions",
    intersections_path: Optional[Union[str, Path]] = None,
    skip_timing: bool = False,
    cfg: Optional[Config] = None,
    echo: bool = True,
    households=None,
) -> AnalysisResult:
    """Full analysis pass over one instance (``analysis.py:533-636``).

    ``households`` (int32[n] group ids, from
    :func:`~citizensassemblies_tpu.core.instance.compute_households`) enables
    the reference's ``check_same_address`` capability end-to-end: at most one
    member per household in every panel, in all four algorithm passes (the
    reference carries the flag through its uniform signature,
    ``leximin.py:338-341``, though its own analysis always passes False).
    """
    cfg = cfg or default_config()
    dense, space = featurize(instance)
    validate_quotas(instance)  # quota sanity asserts (analysis.py:174-176)
    n, k = dense.n, dense.k
    # the directory stem is <name>_<k>; the report's "instance:" line strips
    # the trailing _<k> (reference statistics.txt line 1)
    name = instance.name or "instance"
    stem = name if name.endswith(f"_{k}") else f"{name}_{k}"
    base = stem[: -len(f"_{k}")] if stem.endswith(f"_{k}") else stem

    out_dir = Path(out_dir)
    out_dir.mkdir(parents=True, exist_ok=True)
    stats_path = out_dir / f"{stem}_statistics.txt"

    with tee_file(stats_path, echo=echo) as log:
        # --- four cached algorithm passes (analysis.py:536-543) -------------
        legacy_first = run_legacy_or_retrieve(dense, name=base, k=k, resample=False,
                                              cache_dir=cache_dir, cfg=cfg,
                                              households=households)
        legacy_second = run_legacy_or_retrieve(dense, name=base, k=k, resample=True,
                                               cache_dir=cache_dir, cfg=cfg,
                                               households=households)
        leximin = run_leximin_or_retrieve(dense, space, name=base, k=k,
                                          cache_dir=cache_dir, cfg=cfg,
                                          households=households)
        xmin = run_xmin_or_retrieve(dense, space, name=base, k=k,
                                    cache_dir=cache_dir, cfg=cfg,
                                    households=households)
        # the reference plots the *second* (seed-1) LEGACY sample and reports
        # its unique-panel count (analysis.py:575-589,604-607), while stats,
        # share-below, ratio and intersections use the first (:548,600,612,615)
        runs = {"legacy": legacy_second, "leximin": leximin, "xmin": xmin}

        # --- headline stats (analysis.py:548-602) ----------------------------
        st = {
            "legacy": dataclasses.asdict(
                prob_allocation_stats(legacy_first.allocation, cap_for_geometric_mean=True)
            ),
            "leximin": dataclasses.asdict(
                prob_allocation_stats(leximin.allocation, cap_for_geometric_mean=False)
            ),
            "xmin": dataclasses.asdict(
                prob_allocation_stats(xmin.allocation, cap_for_geometric_mean=False)
            ),
        }

        # minimizer cross-validation: argmin over sample 1, unbiased estimate
        # from sample 2, Jeffreys 99% UCB (analysis.py:564-571)
        minimizer = int(np.argmin(legacy_first.allocation))
        resampled_prob = float(legacy_second.allocation[minimizer])
        # trial count comes from the cached run itself, not the live config —
        # a cache produced under a different --mc-iterations must not tighten
        # the confidence bound
        num_trials = legacy_second.num_draws or cfg.mc_iterations
        ucb = upper_confidence_bound(num_trials, resampled_prob)

        frac_below = float(
            share_below(np.asarray(legacy_first.allocation), st["leximin"]["min"])
        )

        log.log("instance:", base)
        log.log("pool size n:", n)
        log.log("panel size k:", k)
        log.log("# quota categories:", dense.n_categories)
        log.log("mean selection probability k/n:", _percent(k / n))
        log.log(_RULE)
        log.log(
            "LEGACY minimum probability:",
            f"≤ {resampled_prob if ucb == 1.0 else ucb:.2%} (99% upper confidence bound "
            f"based on Jeffreys interval for a binomial parameter, calculated from sample "
            f"proportion {resampled_prob:.4f} and sample size {num_trials:,})",
        )
        log.log("LEXIMIN minimum probability (exact):", _percent(st["leximin"]["min"]))
        log.log("XMIN minimum probability (exact):", _percent(st["xmin"]["min"]))
        log.log(_RULE)
        log.log("LEGACY number of unique panels seen:", len(legacy_second.unique_panels))
        log.log("LEXIMIN number of unique panels possible:", len(leximin.unique_panels))
        log.log("XMIN number of unique panels possible:", len(xmin.unique_panels))
        log.log(_RULE)
        log.log("gini coefficient of LEGACY:", _percent(st["legacy"]["gini"]))
        log.log("gini coefficient of LEXIMIN:", _percent(st["leximin"]["gini"]))
        log.log("gini coefficient of XMIN:", _percent(st["xmin"]["gini"]))
        log.log(_RULE)
        log.log("geometric mean of LEGACY:", _percent(st["legacy"]["geometric_mean"]))
        log.log("geometric mean of LEXIMIN:", _percent(st["leximin"]["geometric_mean"]))
        log.log("geometric mean of XMIN:", _percent(st["xmin"]["geometric_mean"]))
        log.log(_RULE)
        log.log(
            "share selected by LEGACY with probability below LEXIMIN minimum "
            "selection probability:",
            _percent(frac_below),
        )
        # realization-contract status of the exact algorithms (ADVICE r5 #1:
        # a budget-expired rescue ships contract_ok=False and ε-wide
        # probabilities — the report must say so, not just output_lines)
        log.log(_RULE)
        for tag in ("leximin", "xmin"):
            run = runs[tag]
            if run.contract_ok is None:
                continue
            status = (
                "satisfied"
                if run.contract_ok
                else "MISSED — per-agent probabilities exact only to the stated deviation"
            )
            log.log(
                f"{tag.upper()} realization contract (L-inf <= 1e-3):",
                f"{status} (max |alloc - certified profile| = "
                f"{run.realization_dev:.2e})",
            )

        # --- plots (analysis.py:578-619) -------------------------------------
        plots.plot_number_of_panels(
            {
                "legacy": len(legacy_second.unique_panels),
                "leximin": len(leximin.unique_panels),
                "xmin": len(xmin.unique_panels),
            },
            out_dir, stem,
        )
        plots.plot_pair_probability(
            {tag: run.pair_matrix for tag, run in runs.items()}, n, k, out_dir, stem
        )
        pdf = plots.plot_probability_allocations(
            {tag: run.allocation for tag, run in runs.items()}, out_dir, stem
        )
        log.log(f"Plot of probability allocation created at {pdf}.")
        ratio = np.asarray(compute_ratio_products(dense))
        pdf = plots.plot_ratio_products(ratio, legacy_first.allocation, out_dir, stem)
        log.log(f"Plot of ratio products created at {pdf}.")

        # --- intersectional representation (analysis.py:459-530) -------------
        mses = None
        if intersections_path is not None and Path(intersections_path).exists():
            table = read_intersections(intersections_path, dense, space)
            shares = intersection_shares(
                table, k,
                {"LEGACY": legacy_first.allocation, "LEXIMIN": leximin.allocation},
            )
            mses = intersection_mses(shares)
            log.log(_RULE)
            # golden layout has no colon on these lines
            # (reference_output/sf_e_110_statistics.txt:15-21)
            for (s1, s2), mse in mses.items():
                log.log(f"MSE({s1}, {s2})", f"{mse:.2e}")
            plots.plot_intersectional_representation(shares, out_dir, stem)

        # --- timing harness (analysis.py:625-634) -----------------------------
        timing_median = None
        if skip_timing:
            log.log("Skip timing.")
        else:
            durations = []
            for _ in range(3):
                t0 = time.perf_counter()
                find_distribution_leximin(dense, space, cfg=cfg, log=RunLog(echo=False),
                                          households=households)
                durations.append(time.perf_counter() - t0)
            timing_median = pystats.median(durations)
            log.log(
                # two decimals, not the reference's one: our sub-second
                # LEXIMIN medians rounded to a meaningless "0.0 seconds"
                # (VERDICT r5 weak #5) — the value differs from the golden
                # run by definition, so the extra digit costs no parity
                f"Out of 3 runs, LEXIMIN took a median running time of "
                f"{timing_median:.2f} seconds."
            )

    return AnalysisResult(
        runs=runs,
        stats=st,
        minimizer_ucb=ucb,
        share_below_leximin_min=frac_below,
        intersection_mses=mses,
        timing_median_s=timing_median,
        statistics_path=stats_path,
    )
