"""CLI driver (reference ``analysis.py:646-705``).

``python -m citizensassemblies_tpu <name> <k> [--skiptiming]`` scans
``<data_dir>`` for ``<name>_<k>`` instance directories containing
``categories.csv`` + ``respondents.csv`` (``analysis.py:649-668``), lists the
valid ones in the argparse epilog (``:669-686``), and dispatches to
``read_instance`` + ``analyze_instance`` (``:703-705``). An
``intersections.csv`` in the instance directory is picked up automatically
(``analysis.py:483-506``).

Extras over the reference: ``--data-dir``/``--out-dir``/``--cache-dir``
overrides, ``--no-cache``, ``--mc-iterations``, and a ``--generate`` mode that
writes the synthetic example datasets (reference
``data/generate_examples/main.py``) so the repo ships no CSV data.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path
from typing import List, Optional, Tuple

from citizensassemblies_tpu.utils.config import default_config


def _valid_instances(data_dir: Path) -> List[Tuple[str, int]]:
    """Scan for ``<name>_<k>`` dirs holding both CSVs (``analysis.py:649-668``)."""
    found = []
    if not data_dir.is_dir():
        return found
    for entry in sorted(data_dir.iterdir()):
        if not entry.is_dir():
            continue
        stem, _, k_str = entry.name.rpartition("_")
        if not stem or not k_str.isdigit():
            continue
        if (entry / "categories.csv").exists() and (entry / "respondents.csv").exists():
            found.append((stem, int(k_str)))
    return found


def _generate_examples(data_dir: Path) -> None:
    """Write the synthetic example datasets (reference
    ``data/generate_examples/main.py:37-44`` — with the reference's
    ``categories.cvs`` typo fixed so the driver accepts them)."""
    from citizensassemblies_tpu.core.generator import (
        cross_product_instance,
        example_small_like_instance,
        write_instance_csvs,
    )

    small = example_small_like_instance()
    write_instance_csvs(small, data_dir / "example_small_20")
    large = cross_product_instance(
        categories=["gender", "political leaning"],
        features=[["female", "male"], ["liberal", "conservative"]],
        quotas=[[(99, 200), (99, 200)], [(99, 200), (99, 200)]],
        counts=[999, 1, 0, 1000],
        k=200,
        name="example_large_200",
    )
    write_instance_csvs(large, data_dir / "example_large_200")
    print(f"Wrote example datasets under {data_dir}/.")


def build_parser(data_dir: Path) -> argparse.ArgumentParser:
    instances = _valid_instances(data_dir)
    epilog_lines = ["valid instances (<name> <k>):"] + [
        f"  {name} {k}" for name, k in instances
    ]
    if not instances:
        epilog_lines.append(
            "  (none found — run with --generate to create the example datasets)"
        )
    parser = argparse.ArgumentParser(
        prog="citizensassemblies_tpu",
        description="TPU-native fair citizens'-assembly selection analysis",
        epilog="\n".join(epilog_lines),
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("name", nargs="?", help="instance name (directory stem)")
    parser.add_argument("k", nargs="?", type=int, help="panel size")
    parser.add_argument("--skiptiming", action="store_true",
                        help="skip the 3-run LEXIMIN timing harness")
    parser.add_argument("--data-dir", default=str(data_dir), help="instance data root")
    parser.add_argument("--out-dir", default="analysis", help="reports/plots output dir")
    parser.add_argument("--cache-dir", default="distributions",
                        help="pickle memoization dir")
    parser.add_argument("--no-cache", action="store_true",
                        help="disable pickle memoization")
    parser.add_argument("--mc-iterations", type=int, default=None,
                        help="override the 10,000 LEGACY Monte-Carlo draws")
    parser.add_argument("--generate", action="store_true",
                        help="generate the synthetic example datasets and exit")
    parser.add_argument("--address-columns", nargs="+", default=None,
                        metavar="COL",
                        help="respondents.csv columns identifying a household; "
                             "when given, every algorithm selects at most one "
                             "member per household (the reference's "
                             "check_same_address capability)")
    return parser


def main(argv: Optional[List[str]] = None) -> int:
    argv = list(sys.argv[1:] if argv is None else argv)
    # resolve --data-dir before building the epilog scan
    data_dir = Path("data")
    for i, a in enumerate(argv):
        if a == "--data-dir" and i + 1 < len(argv):
            data_dir = Path(argv[i + 1])
        elif a.startswith("--data-dir="):
            data_dir = Path(a.split("=", 1)[1])

    parser = build_parser(data_dir)
    args = parser.parse_args(argv)
    data_dir = Path(args.data_dir)

    if args.generate:
        _generate_examples(data_dir)
        return 0

    if args.name is None or args.k is None:
        parser.print_help()
        return 2

    inst_dir = data_dir / f"{args.name}_{args.k}"
    if not (inst_dir / "categories.csv").exists() or not (
        inst_dir / "respondents.csv"
    ).exists():
        parser.error(
            f"instance directory {inst_dir} must contain categories.csv and "
            f"respondents.csv (see --help for valid instances)"
        )

    from citizensassemblies_tpu.analysis.report import analyze_instance
    from citizensassemblies_tpu.core.instance import read_instance_dir

    cfg = default_config()
    if args.mc_iterations is not None:
        cfg = cfg.replace(mc_iterations=args.mc_iterations)

    households = None
    if args.address_columns:
        from citizensassemblies_tpu.core.instance import (
            compute_households,
            read_instance,
        )

        instance = read_instance(
            inst_dir / "categories.csv",
            inst_dir / "respondents.csv",
            k=args.k,
            name=inst_dir.name,
            extra_columns=args.address_columns,
        )
        households = compute_households(instance, args.address_columns)
    else:
        instance = read_instance_dir(inst_dir, k=args.k)
    intersections = inst_dir / "intersections.csv"
    analyze_instance(
        instance,
        out_dir=args.out_dir,
        cache_dir=None if args.no_cache else args.cache_dir,
        intersections_path=intersections if intersections.exists() else None,
        skip_timing=args.skiptiming,
        cfg=cfg,
        households=households,
    )
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
