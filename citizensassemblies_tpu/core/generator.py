"""Synthetic instance generation.

Covers the reference's cross-product generator (``data/generate_examples/main.py``:
hard-coded category/feature/quota lists, respondents as the cross product of all
feature combinations with per-combination counts) and adds parameterized random
instance families used for benchmarking at reference scale (e.g. an
``sf_e_110``-like pool: n=1727, k=110, 7 categories — the real pool is withheld
for privacy, reference ``README.md:125-132``, so benchmarks run on synthetic
pools with matching shape statistics).
"""

from __future__ import annotations

import csv
import itertools
import math
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np

from citizensassemblies_tpu.core.instance import Instance, Quota


def cross_product_instance(
    categories: Sequence[str],
    features: Sequence[Sequence[str]],
    quotas: Sequence[Sequence[Tuple[int, int]]],
    counts: Sequence[int],
    k: int,
    name: str = "synthetic",
) -> Instance:
    """Build an instance whose pool enumerates the cross product of all feature
    combinations, repeating combination ``i`` ``counts[i]`` times — the
    reference generator's respondent layout (``data/generate_examples/main.py``).
    """
    combos = list(itertools.product(*features))
    if len(counts) != len(combos):
        raise ValueError(f"need {len(combos)} counts, got {len(counts)}")
    cat_quotas: Dict[str, Dict[str, Quota]] = {}
    for ci, cat in enumerate(categories):
        cat_quotas[cat] = {feat: tuple(quotas[ci][fi]) for fi, feat in enumerate(features[ci])}
    agents: List[Dict[str, str]] = []
    for combo, count in zip(combos, counts):
        for _ in range(count):
            agents.append({cat: feat for cat, feat in zip(categories, combo)})
    return Instance(k=k, categories=cat_quotas, agents=agents, name=name)


def random_instance(
    n: int,
    k: int,
    n_categories: int,
    features_per_category: Union[int, Sequence[int]] = 3,
    seed: int = 0,
    quota_slack: float = 0.35,
    concentration: float = 2.0,
    name: str = "",
) -> Instance:
    """Generate a random feasible instance with realistic quota structure.

    Feature shares per category are drawn from a Dirichlet(``concentration``);
    each agent samples one feature per category independently. Quotas bracket
    the proportional panel composition: for pool share ``s`` the quota is
    ``[floor((1-slack)*s*k), ceil((1+slack)*s*k)]``, then adjusted so each
    category's lower quotas sum to ≤ k and upper quotas to ≥ k (the sanity
    conditions the reference asserts at ``analysis.py:174-176``). Proportional
    quotas around observed pool shares guarantee the pool itself scales down to
    a feasible panel, so the instance is feasible by construction.
    """
    rng = np.random.default_rng(seed)
    if isinstance(features_per_category, int):
        features_per_category = [features_per_category] * n_categories

    categories: Dict[str, Dict[str, Quota]] = {}
    assignments: List[np.ndarray] = []
    for ci in range(n_categories):
        m = features_per_category[ci]
        if n < m:
            raise ValueError(
                f"need n >= {m} agents so every feature of category {ci} can appear in the pool"
            )
        shares = rng.dirichlet([concentration] * m)
        # ensure every feature actually appears in the pool; repairs only
        # overwrite indices of features that occur more than once, so one
        # repair cannot erase another feature's sole occurrence
        labels = rng.choice(m, size=n, p=shares)
        for f in range(m):
            if not np.any(labels == f):
                counts = np.bincount(labels, minlength=m)
                candidates = np.nonzero(counts[labels] > 1)[0]
                labels[rng.choice(candidates)] = f
        assignments.append(labels)
        counts = np.bincount(labels, minlength=m)
        pool_shares = counts / n
        quotas: Dict[str, Quota] = {}
        for f in range(m):
            lo = int(math.floor((1 - quota_slack) * pool_shares[f] * k))
            hi = int(math.ceil((1 + quota_slack) * pool_shares[f] * k))
            hi = max(hi, lo + 1, 1)
            quotas[f"c{ci}f{f}"] = (lo, hi)
        # repair category-level sanity: sum(lo) <= k <= sum(hi)
        los = [quotas[f"c{ci}f{f}"][0] for f in range(m)]
        his = [quotas[f"c{ci}f{f}"][1] for f in range(m)]
        f = 0
        while sum(los) > k:
            if los[f % m] > 0:
                los[f % m] -= 1
            f += 1
        f = 0
        while sum(his) < k:
            his[f % m] += 1
            f += 1
        for ff in range(m):
            quotas[f"c{ci}f{ff}"] = (los[ff], his[ff])
        categories[f"cat{ci}"] = quotas

    agents = [
        {f"cat{ci}": f"c{ci}f{assignments[ci][i]}" for ci in range(n_categories)}
        for i in range(n)
    ]
    return Instance(
        k=k, categories=categories, agents=agents, name=name or f"random_{n}_{k}_{seed}"
    )


def skewed_instance(
    n: int,
    k: int,
    n_categories: int,
    features_per_category: Union[int, Sequence[int]] = 3,
    seed: int = 0,
    quota_slack: float = 0.12,
    skew: float = 1.0,
    name: str = "",
) -> Instance:
    """A heterogeneous-allocation instance: quotas target a Dirichlet
    distribution *decoupled* from the pool composition.

    ``random_instance`` brackets quotas around observed pool shares, which
    makes the leximin allocation near-uniform (everyone ≈ k/n). Real pools are
    self-selected while quotas mirror the population, so over-represented
    groups get low selection probabilities — the reference's production
    instances have LEXIMIN Gini 37–68 % (BASELINE.md). Here target shares are
    drawn independently of the pool (blended with pool shares by ``skew``;
    many fully skewed categories can be *jointly* infeasible) and repaired for
    per-category feasibility, reproducing that heterogeneity.
    """
    rng = np.random.default_rng(seed)
    base = random_instance(
        n, k, n_categories, features_per_category, seed=seed, name=name or f"skewed_{n}_{k}"
    )
    cats: Dict[str, Dict[str, Quota]] = {}
    for cat, feats in base.categories.items():
        names = list(feats)
        m = len(names)
        pool = np.array(
            [sum(1 for a in base.agents if a[cat] == f) for f in names], dtype=float
        )
        pool /= pool.sum()
        target = (1.0 - skew) * pool + skew * rng.dirichlet([1.2] * m)
        avail = {f: sum(1 for a in base.agents if a[cat] == f) for f in names}
        lo = {}
        hi = {}
        for f, s in zip(names, target):
            lo[f] = min(int(np.floor((1 - quota_slack) * s * k)), avail[f])
            hi[f] = max(min(int(np.ceil((1 + quota_slack) * s * k)), avail[f]), lo[f])
        while sum(lo.values()) > k:
            f = max(lo, key=lambda x: lo[x])
            lo[f] -= 1
        while sum(hi.values()) < k:
            f = max(names, key=lambda x: avail[x] - hi[x])
            if avail[f] == hi[f]:
                break
            hi[f] += 1
        cats[cat] = {f: (lo[f], hi[f]) for f in names}
    import dataclasses

    inst = dataclasses.replace(base, categories=cats)

    # Per-category repair does not imply joint feasibility: with many fully
    # skewed categories no single panel may satisfy every quota at once (all
    # tested n=1727/7-category draws were jointly infeasible). Real instances
    # are feasible because organizers relax quotas until a panel exists — do
    # the same with the framework's own minimal-relaxation MILP (the
    # reference's 1+2/q cost model, ``leximin.py:90-187``), which preserves
    # the heterogeneous structure while guaranteeing feasibility.
    from citizensassemblies_tpu.core.instance import featurize
    from citizensassemblies_tpu.solvers.cg_typespace import CompositionOracle
    from citizensassemblies_tpu.solvers.highs_backend import relax_infeasible_quotas
    from citizensassemblies_tpu.solvers.native_oracle import TypeReduction

    dense, space = featurize(inst)
    red = TypeReduction(dense)
    if CompositionOracle(red).maximize(np.zeros(red.T)) is None:
        suggested, _ = relax_infeasible_quotas(dense, space)
        repaired = {
            cat: {f: suggested[(cat, f)] for f in feats}
            for cat, feats in inst.categories.items()
        }
        inst = dataclasses.replace(inst, categories=repaired)
    return inst


def cca_skewed_instance(seed: int = 1) -> Instance:
    """Heterogeneous synthetic stand-in shaped like ``cca_75`` — the
    second-hardest reference instance (n=825, k=75, 4 categories, LEXIMIN
    Gini 67.8 % / runtime 433.5 s,
    ``reference_output/cca_75_statistics.txt:2-5,9,15``). The real pool is
    withheld; skew 1.0 with the default seed lands the exact leximin profile
    in the real band — measured Gini 0.687 / min 2.1 % vs the real 0.678 /
    2.4 %."""
    return skewed_instance(
        n=825,
        k=75,
        n_categories=4,
        features_per_category=[2, 4, 5, 3],
        seed=seed,
        skew=1.0,
        name="cca_skewed_75",
    )


def obf_skewed_instance(seed: int = 1) -> Instance:
    """Heterogeneous synthetic stand-in shaped like ``obf_30`` — the
    most category-rich reference instance (n=321, k=30, 8 categories,
    LEXIMIN Gini 42.7 % / runtime 183.9 s,
    ``reference_output/obf_30_statistics.txt:2-5,9,15``). Skew 0.65 with the
    default seed lands in the real band — measured Gini 0.446 / min 4.9 % vs
    the real 0.427 / 4.7 %."""
    return skewed_instance(
        n=321,
        k=30,
        n_categories=8,
        features_per_category=[2, 3, 4, 2, 3, 2, 4, 5],
        seed=seed,
        skew=0.65,
        name="obf_skewed_30",
    )


def hd_skewed_instance(seed: int = 2) -> Instance:
    """Heterogeneous synthetic stand-in shaped like ``hd_30`` (n=239, k=30,
    7 categories, LEXIMIN Gini 52.9 % / min 5.1 % / runtime 37.2 s,
    ``reference_output/hd_30_statistics.txt:2-5,9,15``). Skew 0.8 with the
    default seed matches the real Gini closely (measured 0.535 vs 0.529)
    though its minimum probability sits lower (2.5 % vs 5.1 %)."""
    return skewed_instance(
        n=239,
        k=30,
        n_categories=7,
        features_per_category=[2, 3, 2, 4, 3, 2, 3],
        seed=seed,
        skew=0.8,
        name="hd_skewed_30",
    )


def sf_d_skewed_instance(seed: int = 1) -> Instance:
    """Heterogeneous synthetic stand-in shaped like ``sf_d_40`` (n=404, k=40,
    6 categories, LEXIMIN Gini 48.7 % / min 4.7 % / runtime 46.2 s,
    ``reference_output/sf_d_40_statistics.txt:2-5,9,15``). Skew 0.8 with the
    default seed lands near the real band — measured Gini 0.419 / min 3.8 %."""
    return skewed_instance(
        n=404,
        k=40,
        n_categories=6,
        features_per_category=[2, 3, 4, 2, 3, 3],
        seed=seed,
        skew=0.8,
        name="sf_d_skewed_40",
    )


def nexus_skewed_instance(seed: int = 1) -> Instance:
    """Heterogeneous synthetic stand-in shaped like ``nexus_170`` — the
    high-selection-ratio reference instance (n=342, k=170: half the pool is
    selected; 5 categories, LEXIMIN Gini 25.4 % / min 32.5 % / runtime
    83.4 s, ``reference_output/nexus_170_statistics.txt:2-5,9,15``). Skew 0.5
    with the default seed lands in the real band — measured Gini 0.292 /
    min 26.4 %."""
    return skewed_instance(
        n=342,
        k=170,
        n_categories=5,
        features_per_category=[2, 3, 4, 2, 3],
        seed=seed,
        skew=0.5,
        name="nexus_skewed_170",
    )


def sf_e_skewed_instance(
    seed: int = 1,
    quota_slack: float = 0.12,
    skew: float = 0.4,
    features_per_category: Optional[Sequence[int]] = None,
) -> Instance:
    """Heterogeneous synthetic stand-in for the withheld ``sf_e_110`` pool in
    its *realistic* allocation regime.

    Shape from ``reference_output/sf_e_110_statistics.txt:2-5`` (n=1727,
    k=110, 7 categories); ``skew=0.4`` with the default seed tuned so the
    exact leximin profile lands in the band of the real instance — Gini
    ≈ 0.5 with the minimum probability around 0.4·k/n (the reference reports
    Gini 51.2 %, min 2.6 % vs k/n 6.4 %, lines 6-11) — unlike
    :func:`sf_e_like_instance`, whose pool-proportional quotas make leximin
    collapse to the uniform k/n. Other seeds vary the profile (seed 0 lands
    at Gini ≈ 0.27, a milder but still heterogeneous regime). The keyword
    knobs span the bench's flagship SEED FAMILY (VERDICT r4 #1): tighter
    ``quota_slack`` narrows every quota band, a different ``skew`` shifts
    the heterogeneity, and ``features_per_category`` varies the distinct
    type count the solvers face.
    """
    return skewed_instance(
        n=1727,
        k=110,
        n_categories=7,
        features_per_category=list(features_per_category or [2, 4, 5, 3, 2, 4, 6]),
        seed=seed,
        quota_slack=quota_slack,
        skew=skew,
        name="sf_e_skewed_110",
    )


def mass_like_instance(seed: int = 3) -> Instance:
    """A mass_24-shaped instance: n=70, k=24, 5 categories, with two
    categories fully pinned (min = max on every cell) — the degenerate/tight
    regime SURVEY §7 flags as a top risk (the real mass pool is withheld;
    shape from ``reference_output/mass_24_statistics.txt:2-4``, baseline
    runtime 0.5 s at line 15)."""
    import dataclasses

    base = random_instance(
        n=70, k=24, n_categories=5, features_per_category=[2, 3, 2, 3, 2],
        seed=seed, name="mass_like_24",
    )
    cats: Dict[str, Dict[str, Quota]] = {}
    for ci, (cat, feats) in enumerate(base.categories.items()):
        names = list(feats)
        counts = np.array(
            [sum(1 for a in base.agents if a[cat] == f) for f in names], float
        )
        if ci < 2:
            # pin to the proportional integer composition: min = max
            exact = np.floor(counts / 70.0 * 24.0).astype(int)
            order = np.argsort(-(counts / 70.0 * 24.0 - exact))
            for j in order[: 24 - exact.sum()]:
                exact[j] += 1
            cats[cat] = {f: (int(c), int(c)) for f, c in zip(names, exact)}
        else:
            cats[cat] = feats
    return dataclasses.replace(base, categories=cats)


def sf_a_skewed_instance(seed: int = 1) -> Instance:
    """Heterogeneous synthetic stand-in shaped like ``sf_a_35`` (n=312, k=35,
    6 categories, LEXIMIN Gini 37.3 % / min 6.7 % / runtime 19.6 s,
    ``reference_output/sf_a_35_statistics.txt:2-5,9,15``)."""
    return skewed_instance(
        n=312,
        k=35,
        n_categories=6,
        features_per_category=[2, 3, 4, 2, 3, 3],
        seed=seed,
        skew=0.6,
        name="sf_a_skewed_35",
    )


def sf_b_skewed_instance(seed: int = 1) -> Instance:
    """Heterogeneous synthetic stand-in shaped like ``sf_b_20`` (n=250, k=20,
    6 categories, LEXIMIN Gini 47.4 % / min 4.0 % / runtime 8.8 s,
    ``reference_output/sf_b_20_statistics.txt:2-5,9,15``)."""
    return skewed_instance(
        n=250,
        k=20,
        n_categories=6,
        features_per_category=[2, 3, 3, 2, 4, 3],
        seed=seed,
        skew=0.7,
        name="sf_b_skewed_20",
    )


def sf_c_skewed_instance(seed: int = 1) -> Instance:
    """Heterogeneous synthetic stand-in shaped like ``sf_c_44`` (n=161, k=44,
    7 categories, LEXIMIN Gini 52.5 % / min 8.6 % / runtime 6.0 s,
    ``reference_output/sf_c_44_statistics.txt:2-5,9,15``)."""
    return skewed_instance(
        n=161,
        k=44,
        n_categories=7,
        features_per_category=[2, 3, 2, 3, 2, 3, 2],
        seed=seed,
        skew=0.7,
        name="sf_c_skewed_44",
    )


def sf_e_like_instance(seed: int = 0) -> Instance:
    """Synthetic stand-in for the withheld ``sf_e_110`` pool: n=1727, k=110,
    7 quota categories (shape from ``reference_output/sf_e_110_statistics.txt:2-5``)."""
    return random_instance(
        n=1727,
        k=110,
        n_categories=7,
        features_per_category=[2, 4, 5, 3, 2, 4, 6],
        seed=seed,
        quota_slack=0.3,
        name="sf_e_like_110",
    )


def sf_e_schema_instance(seed: int = 1, n: int = 1727, k: int = 110) -> Instance:
    """sf_e_110-shaped synthetic pool carrying the REAL anonymized schema of
    the one sf_e artifact the reference ships,
    ``data/sf_e_110/intersections.csv`` (346 rows; the pool itself is withheld
    for privacy, ``README.md:125-132``): 7 categories named ``a``–``g`` with
    feature counts (3, 4, 12, 3, 5, 2, 2) and features named ``a1``…``g2``, so
    the shipped intersections file parses against this pool's feature space
    verbatim and the C21 pipeline (``ops/intersections.py``) can be exercised
    on real data end-to-end. ``n``/``k`` default to the real shape; smaller
    values keep the schema (every feature still appears in the pool) for
    CPU-sized tests.
    """
    import dataclasses

    base = skewed_instance(
        n=n,
        k=k,
        n_categories=7,
        features_per_category=[3, 4, 12, 3, 5, 2, 2],
        seed=seed,
        skew=0.4,
        name="sf_e_110",
    )
    cat_names = ["a", "b", "c", "d", "e", "f", "g"]
    renames: Dict[str, Tuple[str, Dict[str, str]]] = {}
    categories: Dict[str, Dict[str, Quota]] = {}
    for (old_cat, feats), new_cat in zip(base.categories.items(), cat_names):
        fmap = {old: f"{new_cat}{i + 1}" for i, old in enumerate(feats)}
        renames[old_cat] = (new_cat, fmap)
        categories[new_cat] = {fmap[old]: q for old, q in feats.items()}
    agents = [
        {renames[c][0]: renames[c][1][f] for c, f in agent.items()}
        for agent in base.agents
    ]
    return dataclasses.replace(base, categories=categories, agents=agents)


def example_small_like_instance(seed: int = 0) -> Instance:
    """Synthetic stand-in shaped like ``example_small_20``: n=200, k=20, two
    binary categories with quotas [9, 20] (see
    ``data/example_small_20/categories.csv``)."""
    rng = np.random.default_rng(seed)
    categories = {
        "gender": {"female": (9, 20), "male": (9, 20)},
        "leaning": {"liberal": (9, 20), "conservative": (9, 20)},
    }
    agents = [
        {
            "gender": "female" if rng.random() < 0.5 else "male",
            "leaning": "liberal" if rng.random() < 0.65 else "conservative",
        }
        for _ in range(200)
    ]
    return Instance(k=20, categories=categories, agents=agents, name="example_small_like_20")


def write_instance_csvs(instance: Instance, directory: Union[str, Path]) -> None:
    """Write ``categories.csv`` + ``respondents.csv`` in the reference input
    schema (``README.md`` data format; note the reference generator writes
    typo'd ``categories.cvs``/``respondentes.cvs`` — we emit the names the CLI
    actually consumes, ``analysis.py:660-666``)."""
    directory = Path(directory)
    directory.mkdir(parents=True, exist_ok=True)
    with open(directory / "categories.csv", "w", newline="", encoding="utf-8") as fh:
        writer = csv.writer(fh)
        writer.writerow(["category", "feature", "min", "max"])
        for cat, feats in instance.categories.items():
            for feat, (lo, hi) in feats.items():
                writer.writerow([cat, feat, lo, hi])
    cat_names = list(instance.categories)
    with open(directory / "respondents.csv", "w", newline="", encoding="utf-8") as fh:
        writer = csv.writer(fh)
        writer.writerow(cat_names)
        for agent in instance.agents:
            writer.writerow([agent[c] for c in cat_names])
