"""Problem instances: CSV input schema and the dense TPU representation.

Host-side, an :class:`Instance` mirrors the reference's problem container
(``analysis.py:54-58``: panel size ``k``, per-category per-feature quotas, and
one categorical feature per category per agent), read from the two-CSV schema
documented in the reference README (``categories.csv`` with columns
``category,feature,min,max``; ``respondents.csv`` with one column per category —
reference ``analysis.py:108-138``, agent ids are row indices).

Device-side, :func:`featurize` lowers an instance to a :class:`DenseInstance`:

* ``A`` — the ``{0,1}^{n×F}`` agent×feature-value incidence matrix, where the
  flat feature axis enumerates ``(category, feature)`` cells in file order
  (category order of ``categories.csv``, feature order of first appearance) —
  the same iteration order as the reference's nested dicts, which matters for
  LEGACY's first-max tie-breaking (``legacy.py:124-157``).
* ``qmin``/``qmax`` — per-cell quota vectors.
* ``cat_of_feature`` — flat-cell → category index (each agent has exactly one
  cell per category: ``A @ cat_onehot`` rows sum to 1 per category).

Everything downstream (samplers, LPs, statistics) operates on these arrays.
"""

from __future__ import annotations

import csv
import dataclasses
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Tuple, Union

import jax.numpy as jnp
import numpy as np
from flax import struct

Quota = Tuple[int, int]  # (min, max)


@dataclasses.dataclass
class Instance:
    """Host-side problem container (reference ``analysis.py:54-58``).

    ``categories`` maps category name -> feature name -> (min, max) quota, in
    file order. ``agents`` is a list indexed by agent id (row index in
    ``respondents.csv``, reference ``analysis.py:131-132``), each a mapping
    category -> feature. ``columns_data`` optionally carries extra per-agent
    columns (e.g. address fields for household constraints,
    ``legacy.py:78-99``).
    """

    k: int
    categories: Dict[str, Dict[str, Quota]]
    agents: List[Dict[str, str]]
    name: str = ""
    columns_data: Optional[List[Dict[str, str]]] = None

    @property
    def n(self) -> int:
        return len(self.agents)


@dataclasses.dataclass(frozen=True)
class FeatureSpace:
    """Static metadata naming the flat feature axis of a :class:`DenseInstance`."""

    categories: Tuple[str, ...]  # category names, file order
    cells: Tuple[Tuple[str, str], ...]  # flat index -> (category, feature)

    @property
    def n_features(self) -> int:
        return len(self.cells)

    @property
    def n_categories(self) -> int:
        return len(self.categories)

    def feature_index(self, category: str, feature: str) -> int:
        return self.cells.index((category, feature))

    def cells_of_category(self, category: str) -> List[int]:
        return [i for i, (c, _) in enumerate(self.cells) if c == category]


class InfeasibleQuotasError(Exception):
    """Raised when no panel can satisfy the quotas; carries a suggested minimal
    relaxation (reference ``leximin.py:81-87``)."""

    def __init__(self, quotas: Dict[Tuple[str, str], Quota], output: List[str]):
        self.quotas = quotas
        self.output = ["The quotas are infeasible:"] + output
        super().__init__("\n".join(self.output))

    def __str__(self) -> str:
        return "\n".join(self.output)


class SelectionError(Exception):
    """Raised when panel selection fails (reference ``legacy.py:34-36``)."""

    def __init__(self, message: str):
        self.msg = message
        super().__init__(message)


class HostView:
    """Host-side numpy mirror of a :class:`DenseInstance`'s arrays.

    The host LP/MILP solvers (HiGHS, the type reduction, the native B&B
    oracle) need plain numpy; pulling the device arrays back with
    ``np.asarray(dense.A)`` costs a device→host transfer that can take
    *minutes* through a TPU tunnel. ``featurize`` stores the originals here
    instead. Carried as a static (non-pytree) field, so hash/eq are by
    content — jit caching keys stay stable across re-featurizations of the
    same instance.
    """

    __slots__ = ("A", "qmin", "qmax", "_h")

    def __init__(self, A: np.ndarray, qmin: np.ndarray, qmax: np.ndarray):
        self.A = A
        self.qmin = qmin
        self.qmax = qmax
        self._h = hash((A.shape, A.tobytes(), qmin.tobytes(), qmax.tobytes()))

    def __hash__(self) -> int:
        return self._h

    def __eq__(self, other) -> bool:
        return (
            isinstance(other, HostView)
            and self._h == other._h
            and np.array_equal(self.A, other.A)
            and np.array_equal(self.qmin, other.qmin)
            and np.array_equal(self.qmax, other.qmax)
        )


@struct.dataclass
class DenseInstance:
    """Device-side dense instance pytree.

    Attributes:
      A: bool[n, F] incidence matrix (agent has feature-cell f).
      qmin: int32[F] lower quotas.
      qmax: int32[F] upper quotas.
      cat_of_feature: int32[F] category index per flat cell.
      k: static panel size.
      n_categories: static number of categories.
      host: optional host-side numpy mirror (see :class:`HostView`).
    """

    A: jnp.ndarray
    qmin: jnp.ndarray
    qmax: jnp.ndarray
    cat_of_feature: jnp.ndarray
    k: int = struct.field(pytree_node=False)
    n_categories: int = struct.field(pytree_node=False)
    host: Optional[HostView] = struct.field(pytree_node=False, default=None)

    @property
    def n(self) -> int:
        return self.A.shape[0]

    @property
    def n_features(self) -> int:
        return self.A.shape[1]

    @property
    def A_np(self) -> np.ndarray:
        """bool[n, F] incidence on host (no device pull when mirrored)."""
        return self.host.A if self.host is not None else np.asarray(self.A)

    @property
    def qmin_np(self) -> np.ndarray:
        return self.host.qmin if self.host is not None else np.asarray(self.qmin)

    @property
    def qmax_np(self) -> np.ndarray:
        return self.host.qmax if self.host is not None else np.asarray(self.qmax)


def read_instance(
    feature_file: Union[str, Path],
    pool_file: Union[str, Path],
    k: int,
    name: str = "",
    extra_columns: Sequence[str] = (),
) -> Instance:
    """Read an instance from the two-CSV schema (reference ``analysis.py:108-138``).

    Unlike the reference, unknown feature values in the pool raise a clean
    error instead of a ``KeyError``, and extra per-agent columns (for household
    checks) can be retained via ``extra_columns``.
    """
    categories: Dict[str, Dict[str, Quota]] = {}
    with open(feature_file, "r", encoding="utf-8") as fh:
        for line in csv.DictReader(fh):
            cat, feat = line["category"], line["feature"]
            categories.setdefault(cat, {})
            categories[cat][feat] = (int(line["min"]), int(line["max"]))

    cat_names = list(categories)
    agents: List[Dict[str, str]] = []
    columns_data: List[Dict[str, str]] = []
    with open(pool_file, "r", encoding="utf-8") as fh:
        for i, line in enumerate(csv.DictReader(fh)):
            agent = {}
            for cat in cat_names:
                feat = line.get(cat)
                if feat is None:
                    raise ValueError(f"respondent row {i} is missing category column {cat!r}")
                if feat not in categories[cat]:
                    raise ValueError(
                        f"respondent row {i} has feature {feat!r} for category {cat!r} "
                        f"which does not appear in the categories file"
                    )
                agent[cat] = feat
            agents.append(agent)
            if extra_columns:
                columns_data.append({col: line.get(col, "") for col in extra_columns})

    return Instance(
        k=k,
        categories=categories,
        agents=agents,
        name=name or Path(pool_file).parent.name,
        columns_data=columns_data or None,
    )


def read_instance_dir(directory: Union[str, Path], k: Optional[int] = None) -> Instance:
    """Read ``<name>_<k>/categories.csv`` + ``respondents.csv`` the way the
    reference CLI resolves instances (``analysis.py:649-668,703-705``)."""
    directory = Path(directory)
    if k is None:
        stem, _, k_str = directory.name.rpartition("_")
        if not stem or not k_str.isdigit():
            raise ValueError(
                f"directory name {directory.name!r} does not end in underscore + panel size"
            )
        k = int(k_str)
    return read_instance(
        directory / "categories.csv", directory / "respondents.csv", k, name=directory.name
    )


def featurize(instance: Instance) -> Tuple[DenseInstance, FeatureSpace]:
    """Lower a host instance to its dense device representation."""
    cells: List[Tuple[str, str]] = []
    qmin: List[int] = []
    qmax: List[int] = []
    cat_of_feature: List[int] = []
    cell_index: Dict[Tuple[str, str], int] = {}
    cat_names = list(instance.categories)
    for ci, cat in enumerate(cat_names):
        for feat, (lo, hi) in instance.categories[cat].items():
            cell_index[(cat, feat)] = len(cells)
            cells.append((cat, feat))
            qmin.append(lo)
            qmax.append(hi)
            cat_of_feature.append(ci)

    n, F = len(instance.agents), len(cells)
    A = np.zeros((n, F), dtype=bool)
    for i, agent in enumerate(instance.agents):
        for cat in cat_names:
            A[i, cell_index[(cat, agent[cat])]] = True

    qmin_np = np.asarray(qmin, dtype=np.int32)
    qmax_np = np.asarray(qmax, dtype=np.int32)
    dense = DenseInstance(
        A=jnp.asarray(A),
        qmin=jnp.asarray(qmin, dtype=jnp.int32),
        qmax=jnp.asarray(qmax, dtype=jnp.int32),
        cat_of_feature=jnp.asarray(cat_of_feature, dtype=jnp.int32),
        k=instance.k,
        n_categories=len(cat_names),
        host=HostView(A, qmin_np, qmax_np),
    )
    space = FeatureSpace(categories=tuple(cat_names), cells=tuple(cells))
    return dense, space


def validate_quotas(instance: Instance) -> None:
    """Per-category sanity asserted by the reference before Monte-Carlo
    estimation (``analysis.py:174-176``): the lower quotas of a category must
    not exceed k in total, and the upper quotas must reach k."""
    for cat, feats in instance.categories.items():
        lo = sum(q[0] for q in feats.values())
        hi = sum(q[1] for q in feats.values())
        if lo > instance.k:
            raise SelectionError(f"lower quotas of category {cat!r} sum to {lo} > k={instance.k}")
        if hi < instance.k:
            raise SelectionError(f"upper quotas of category {cat!r} sum to {hi} < k={instance.k}")


def compute_households(
    instance: Instance, address_columns: Sequence[str]
) -> np.ndarray:
    """Group agents into households by equality on the address columns
    (the reference's ``_compute_households``, ``leximin.py:359-362``, and the
    same-address matching of ``legacy.py:78-99``, which compares the two
    ``check_same_address_columns`` values of every pair).

    Returns int32[n] household ids suitable for the samplers' and oracles'
    ``households`` argument. Requires the instance to have been read with
    ``extra_columns=address_columns``.
    """
    if not instance.columns_data:
        raise ValueError(
            "instance has no columns_data — re-read it with "
            f"extra_columns={list(address_columns)!r} to enable household checks"
        )
    ids: Dict[Tuple[str, ...], int] = {}
    out = np.zeros(len(instance.agents), dtype=np.int32)
    for i, cols in enumerate(instance.columns_data):
        key = tuple(cols.get(c, "") for c in address_columns)
        out[i] = ids.setdefault(key, len(ids))
    return out


def panels_to_matrix(panels: Sequence[Sequence[int]], n: int) -> np.ndarray:
    """Stack agent-index panels into a binary portfolio matrix P ∈ {0,1}^{|C|×n}."""
    P = np.zeros((len(panels), n), dtype=bool)
    for row, panel in enumerate(panels):
        P[row, list(panel)] = True
    return P


def matrix_to_panels(P: np.ndarray) -> List[Tuple[int, ...]]:
    """Inverse of :func:`panels_to_matrix` (sorted agent ids per row)."""
    return [tuple(np.nonzero(row)[0].tolist()) for row in np.asarray(P)]
