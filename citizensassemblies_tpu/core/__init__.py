from citizensassemblies_tpu.core.instance import (  # noqa: F401
    DenseInstance,
    FeatureSpace,
    Instance,
    featurize,
    read_instance,
    validate_quotas,
)
