from citizensassemblies_tpu.core.instance import (  # noqa: F401
    DenseInstance,
    FeatureSpace,
    Instance,
    compute_households,
    featurize,
    read_instance,
    validate_quotas,
)
