"""Instance families: the nationwide civic-lottery registry generator."""

from citizensassemblies_tpu.data.registry import (  # noqa: F401
    Registry,
    nationwide_registry,
)
