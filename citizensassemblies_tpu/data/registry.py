"""Seeded synthetic nationwide-registry instances (the graftpod workload).

The source paper's deployments select from pools of hundreds to a few
thousand volunteers; the self-selection line of work points at the real
target — a standing nationwide civic-lottery registry with n = 10⁵-10⁶
volunteers and thousands of household classes. This module generates that
instance family at scale:

* **Vectorized all the way.** ``core/generator.py`` builds agents as a list
  of per-agent dicts, which is fine at n ≤ 10⁴ and hopeless at 10⁶ (tens of
  seconds and ~1 GB of dict overhead). Here the pool is a single
  ``int32[n, C]`` assignment matrix drawn per category from a seeded
  Dirichlet-weighted categorical, and :meth:`Registry.to_dense` lowers it
  straight to the ``DenseInstance`` incidence arrays with numpy scatter —
  no per-agent Python objects anywhere. ``to_instance()`` exists for
  interop with the CSV-shaped pipeline and is priced for modest n only.

* **Feasible quotas by construction.** Quotas are synthesized around a
  *witness panel*: draw k agents uniformly without replacement, count their
  per-cell composition, and bracket each cell's quota around that count
  with a ±slack band. The witness satisfies every quota by definition, so
  the instance is feasible with a checkable certificate
  (:meth:`Registry.check_witness`), and per-category quota sums
  automatically bracket k (they sum to k at the witness point).

* **Household classes.** Every agent carries a household id over a
  configurable class count (≥ 5k at the nationwide tier — the scale that
  justifies a sharded mesh), consumable by the samplers' ``households``
  argument.
"""

from __future__ import annotations

import dataclasses
from typing import List, Optional, Sequence, Tuple

import numpy as np

from citizensassemblies_tpu.core.instance import (
    DenseInstance,
    FeatureSpace,
    HostView,
    Instance,
)

#: default civic-lottery demography: (category, features) in file order.
DEFAULT_CATEGORIES: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("gender", ("female", "male")),
    ("age", ("16-24", "25-34", "35-44", "45-54", "55-64", "65-74", "75+")),
    (
        "region",
        tuple(f"region_{i:02d}" for i in range(12)),
    ),
    ("education", ("none", "secondary", "vocational", "tertiary")),
    ("urbanicity", ("urban", "suburban", "rural")),
)


@dataclasses.dataclass
class Registry:
    """A generated nationwide-registry instance (host-side, all numpy).

    ``assignments[i, c]`` is agent i's feature index within category c;
    ``qmin``/``qmax`` are flat per-cell quotas in ``FeatureSpace`` order;
    ``witness`` is the k-panel the quotas were synthesized around (the
    feasibility certificate); ``household_id`` labels household classes.
    """

    name: str
    k: int
    categories: Tuple[str, ...]
    features: Tuple[Tuple[str, ...], ...]
    assignments: np.ndarray  # int32[n, C]
    qmin: np.ndarray  # int32[F]
    qmax: np.ndarray  # int32[F]
    household_id: np.ndarray  # int32[n]
    witness: np.ndarray  # int64[k], sorted agent ids
    seed: int

    @property
    def n(self) -> int:
        return int(self.assignments.shape[0])

    @property
    def n_categories(self) -> int:
        return int(self.assignments.shape[1])

    @property
    def n_households(self) -> int:
        return int(self.household_id.max()) + 1 if self.household_id.size else 0

    @property
    def cell_offsets(self) -> np.ndarray:
        """Flat-cell index of each category's first feature."""
        sizes = np.asarray([len(f) for f in self.features], dtype=np.int64)
        return np.concatenate([[0], np.cumsum(sizes)[:-1]])

    def incidence(self) -> np.ndarray:
        """bool[n, F] agent×cell incidence, built by vectorized scatter."""
        n, C = self.assignments.shape
        F = int(sum(len(f) for f in self.features))
        A = np.zeros((n, F), dtype=bool)
        offsets = self.cell_offsets
        rows = np.arange(n)
        for c in range(C):
            A[rows, offsets[c] + self.assignments[:, c]] = True
        return A

    def check_witness(self) -> bool:
        """Re-verify the feasibility certificate: the witness panel has k
        distinct members and satisfies every cell quota."""
        if len(np.unique(self.witness)) != self.k:
            return False
        counts = self.incidence()[self.witness].sum(axis=0)
        return bool(np.all((counts >= self.qmin) & (counts <= self.qmax)))

    def to_dense(self) -> Tuple[DenseInstance, FeatureSpace]:
        """Lower straight to the device representation (no per-agent dicts
        — this is the only path priced for n = 10⁶)."""
        import jax.numpy as jnp

        A = self.incidence()
        qmin = self.qmin.astype(np.int32)
        qmax = self.qmax.astype(np.int32)
        cat_of_feature = np.concatenate(
            [
                np.full(len(feats), ci, dtype=np.int32)
                for ci, feats in enumerate(self.features)
            ]
        )
        dense = DenseInstance(
            A=jnp.asarray(A),
            qmin=jnp.asarray(qmin),
            qmax=jnp.asarray(qmax),
            cat_of_feature=jnp.asarray(cat_of_feature),
            k=self.k,
            n_categories=len(self.categories),
            host=HostView(A, qmin, qmax),
        )
        space = FeatureSpace(
            categories=self.categories,
            cells=tuple(
                (cat, feat)
                for cat, feats in zip(self.categories, self.features)
                for feat in feats
            ),
        )
        return dense, space

    def to_instance(self) -> Instance:
        """CSV-shaped host container (per-agent dicts — modest n only)."""
        cat_quotas = {}
        flat = 0
        for cat, feats in zip(self.categories, self.features):
            cat_quotas[cat] = {
                feat: (int(self.qmin[flat + j]), int(self.qmax[flat + j]))
                for j, feat in enumerate(feats)
            }
            flat += len(feats)
        agents = [
            {
                cat: self.features[c][self.assignments[i, c]]
                for c, cat in enumerate(self.categories)
            }
            for i in range(self.n)
        ]
        return Instance(
            k=self.k, categories=cat_quotas, agents=agents, name=self.name
        )


def nationwide_registry(
    n: int = 100_000,
    seed: int = 0,
    k: Optional[int] = None,
    categories: Optional[Sequence[Tuple[str, Sequence[str]]]] = None,
    household_classes: Optional[int] = None,
    quota_slack: float = 0.08,
    name: str = "",
) -> Registry:
    """Generate a seeded nationwide-registry instance of ``n`` volunteers.

    The same ``(n, seed, …)`` always yields the identical registry (numpy
    ``default_rng`` stream, no global state). ``quota_slack`` is the ±band
    around the witness composition, as a fraction of k (floored at ±1 seat,
    so every instance has real selection freedom without losing the
    witness-feasibility guarantee). ``household_classes`` defaults to
    ``max(5000, n // 3)`` capped at n — the nationwide tier's ≥ 5k classes
    — and scales down to ``n // 3`` on small test instances.
    """
    if n <= 0:
        raise ValueError(f"registry size n={n} must be positive")
    rng = np.random.default_rng(seed)
    cats = tuple(
        (str(c), tuple(str(f) for f in feats))
        for c, feats in (categories or DEFAULT_CATEGORIES)
    )
    cat_names = tuple(c for c, _ in cats)
    cat_feats = tuple(f for _, f in cats)

    if k is None:
        k = int(max(24, min(400, round(n ** 0.5))))
    if k > n:
        raise ValueError(f"panel size k={k} exceeds pool size n={n}")

    # per-category Dirichlet-weighted categorical marginals: skewed enough
    # to look like census marginals, never degenerate (alpha > 1)
    assignments = np.empty((n, len(cats)), dtype=np.int32)
    for c, feats in enumerate(cat_feats):
        probs = rng.dirichlet(np.full(len(feats), 4.0))
        assignments[:, c] = rng.choice(len(feats), size=n, p=probs)

    # household classes: contiguous labels over the configured class count
    H = household_classes
    if H is None:
        H = min(n, max(5000, n // 3)) if n >= 5000 else max(1, n // 3)
    H = max(1, min(int(H), n))
    household_id = rng.integers(0, H, size=n, dtype=np.int32)
    # guarantee every class is inhabited (cardinality is part of the tier
    # contract): deal the first H agents one class each, then shuffle
    household_id[:H] = np.arange(H, dtype=np.int32)
    rng.shuffle(household_id)

    # witness panel → quotas bracketing its composition (feasible by
    # construction; the witness is retained as the certificate)
    witness = np.sort(rng.choice(n, size=k, replace=False)).astype(np.int64)
    slack = max(1, int(round(quota_slack * k)))
    qmin_parts, qmax_parts = [], []
    for c, feats in enumerate(cat_feats):
        counts = np.bincount(assignments[witness, c], minlength=len(feats))
        qmin_parts.append(np.maximum(0, counts - slack))
        qmax_parts.append(np.minimum(k, counts + slack))
    qmin = np.concatenate(qmin_parts).astype(np.int32)
    qmax = np.concatenate(qmax_parts).astype(np.int32)

    return Registry(
        name=name or f"registry_n{n}_s{seed}",
        k=int(k),
        categories=cat_names,
        features=cat_feats,
        assignments=assignments,
        qmin=qmin,
        qmax=qmax,
        household_id=household_id,
        witness=witness,
        seed=int(seed),
    )


# --- registry churn: the graftdelta edit model --------------------------------
#
# A real registry is never static: volunteers join and drop daily, quotas get
# amended mid-recruitment, and occasionally a whole new demographic class
# appears. ``RegistryEdit`` is the atomic unit of that churn — small enough
# that the delta solver (``solvers/delta.py``) can re-certify in ~O(edit) —
# and ``churn_trail`` generates seeded sequences of them that provably keep
# every intermediate registry witness-feasible (``check_witness``).

#: the five edit classes the delta solver distinguishes (each maps onto the
#: type space differently — see ``solvers/delta.py``).
EDIT_KINDS: Tuple[str, ...] = (
    "agents_add",  # volunteers join existing types (pool weights shift)
    "agents_drop",  # volunteers leave (never witness members)
    "quota_relax",  # a cell's band widens (new compositions become feasible)
    "quota_tighten",  # a cell's band narrows toward the witness count
    "new_type",  # a new feature value (household class) appears in a category
)


@dataclasses.dataclass(frozen=True)
class RegistryEdit:
    """One atomic registry edit (see :data:`EDIT_KINDS`).

    ``rows`` carries the appended agents' feature-index rows for
    ``agents_add``/``new_type`` (for ``new_type`` the edited category's
    index is the NEW feature slot, i.e. ``len(features[category])`` at
    application time); ``agents`` the dropped agent ids for
    ``agents_drop``; ``cell``/``dlo``/``dhi`` the flat quota cell and band
    deltas for the quota edits; ``category``/``feature`` the new feature's
    placement for ``new_type`` (its quota band is ``[0, dhi]`` — the lower
    bound MUST be 0 so the witness panel, which contains none of the new
    type, stays feasible).
    """

    kind: str
    rows: Optional[np.ndarray] = None  # int32 [e, C]
    agents: Optional[np.ndarray] = None  # int64 [e]
    cell: int = -1
    dlo: int = 0
    dhi: int = 0
    category: int = -1
    feature: str = ""

    @property
    def magnitude(self) -> int:
        """Edit size in its natural unit: agents touched, or quota seats
        moved — the quantity ``Config.delta_max_edit_frac`` gates on."""
        if self.kind in ("agents_add", "new_type"):
            return int(self.rows.shape[0]) if self.rows is not None else 0
        if self.kind == "agents_drop":
            return int(len(self.agents)) if self.agents is not None else 0
        return abs(int(self.dlo)) + abs(int(self.dhi))

    def describe(self) -> str:
        if self.kind in ("agents_add", "agents_drop"):
            return f"{self.kind}({self.magnitude} agents)"
        if self.kind == "new_type":
            return (
                f"new_type(cat {self.category} += {self.feature!r}, "
                f"{self.magnitude} agents, band [0, {self.dhi}])"
            )
        return f"{self.kind}(cell {self.cell}, dlo {self.dlo:+d}, dhi {self.dhi:+d})"


def apply_edit(reg: Registry, edit: RegistryEdit) -> Registry:
    """Apply one :class:`RegistryEdit`, returning a NEW registry (the input
    is never mutated — the delta solver diffs the two).

    Validates structural sanity (index ranges, band ordering, witness
    survival on drops) and raises ``ValueError`` on violation; quota
    FEASIBILITY preservation is the trail generator's contract, checkable
    afterwards via :meth:`Registry.check_witness`.
    """
    C = reg.n_categories
    feats = tuple(tuple(f) for f in reg.features)
    assignments = reg.assignments
    household_id = reg.household_id
    witness = reg.witness
    qmin, qmax = reg.qmin.copy(), reg.qmax.copy()

    if edit.kind in ("agents_add", "new_type"):
        rows = np.asarray(edit.rows, dtype=np.int32)
        if rows.ndim != 2 or rows.shape[1] != C or rows.shape[0] == 0:
            raise ValueError(f"{edit.kind}: rows must be int [e>0, {C}]")
        if edit.kind == "new_type":
            c = int(edit.category)
            if not (0 <= c < C):
                raise ValueError(f"new_type: category {c} out of range")
            name = edit.feature or f"{reg.categories[c]}_new"
            if name in feats[c]:
                raise ValueError(f"new_type: feature {name!r} already exists")
            if edit.dhi <= 0:
                raise ValueError("new_type: dhi must be > 0 (the new cell's band)")
            new_slot = len(feats[c])
            if not np.all(rows[:, c] == new_slot):
                raise ValueError(
                    f"new_type: rows must reference the new slot {new_slot} "
                    f"in category {c}"
                )
            feats = tuple(
                f + (name,) if ci == c else f for ci, f in enumerate(feats)
            )
            # the flat quota layout shifts: insert the new cell (band
            # [0, dhi]) at the end of category c's block
            at = int(reg.cell_offsets[c]) + new_slot
            qmin = np.insert(qmin, at, 0).astype(np.int32)
            qmax = np.insert(qmax, at, min(int(edit.dhi), reg.k)).astype(np.int32)
        sizes = np.asarray([len(f) for f in feats])
        if np.any(rows < 0) or np.any(rows >= sizes[None, :]):
            raise ValueError(f"{edit.kind}: feature index out of range")
        e = rows.shape[0]
        assignments = np.concatenate([assignments, rows], axis=0)
        # joiners arrive as fresh household classes (the conservative
        # reading: churn does not merge households)
        base = int(household_id.max()) + 1 if household_id.size else 0
        household_id = np.concatenate(
            [household_id, base + np.arange(e, dtype=np.int32)]
        )
    elif edit.kind == "agents_drop":
        drop = np.unique(np.asarray(edit.agents, dtype=np.int64))
        if drop.size == 0 or drop.min() < 0 or drop.max() >= reg.n:
            raise ValueError("agents_drop: agent ids out of range")
        if np.intersect1d(drop, witness).size:
            raise ValueError(
                "agents_drop: dropping a witness member would void the "
                "feasibility certificate"
            )
        keep = np.ones(reg.n, dtype=bool)
        keep[drop] = False
        assignments = assignments[keep]
        household_id = household_id[keep]
        # witness ids shift down past each dropped agent
        witness = witness - np.searchsorted(drop, witness)
    elif edit.kind in ("quota_relax", "quota_tighten"):
        f = int(edit.cell)
        if not (0 <= f < len(qmin)):
            raise ValueError(f"{edit.kind}: cell {f} out of range")
        lo = int(qmin[f]) + int(edit.dlo)
        hi = int(qmax[f]) + int(edit.dhi)
        lo, hi = max(0, lo), min(int(reg.k), hi)
        if lo > hi:
            raise ValueError(f"{edit.kind}: band [{lo}, {hi}] is empty")
        qmin[f], qmax[f] = lo, hi
    else:
        raise ValueError(f"unknown edit kind {edit.kind!r} (see EDIT_KINDS)")

    return Registry(
        name=reg.name,
        k=reg.k,
        categories=reg.categories,
        features=feats,
        assignments=assignments,
        qmin=qmin,
        qmax=qmax,
        household_id=household_id,
        witness=witness,
        seed=reg.seed,
    )


def churn_trail(
    reg: Registry,
    n_edits: int,
    seed: int = 0,
    max_edit_agents: int = 64,
    max_new_types: int = 3,
    weights: Optional[dict] = None,
) -> List[RegistryEdit]:
    """Seeded churn trail: ``n_edits`` edits whose SEQUENTIAL application
    keeps every intermediate registry witness-feasible.

    The generator simulates each candidate edit on a working copy before
    emitting it, so the guarantee is by construction, not by hope:

    * agent adds/joins copy feature rows of existing agents (no accidental
      new types) and never touch quotas;
    * drops avoid witness members;
    * tighten edits only move a band edge TOWARD the witness count, never
      past it; relax edits widen within ``[0, k]``;
    * ``new_type`` appends a feature with band ``[0, hi]`` (the witness has
      zero of it) and is capped at ``max_new_types`` per trail so the type
      space stays enumerable.

    Deterministic in ``(reg, n_edits, seed, …)``: the same inputs always
    yield the identical trail (``numpy.default_rng``, no global state).
    """
    rng = np.random.default_rng(seed)
    w = dict(weights or {
        "agents_add": 0.30,
        "agents_drop": 0.28,
        "quota_relax": 0.16,
        "quota_tighten": 0.16,
        "new_type": 0.10,
    })
    kinds = [kk for kk in EDIT_KINDS if w.get(kk, 0.0) > 0]
    probs = np.asarray([w[kk] for kk in kinds], dtype=np.float64)
    probs = probs / probs.sum()

    cur = reg
    new_types = 0
    trail: List[RegistryEdit] = []
    while len(trail) < n_edits:
        kind = kinds[int(rng.choice(len(kinds), p=probs))]
        edit: Optional[RegistryEdit] = None
        if kind == "new_type" and new_types >= max_new_types:
            kind = "agents_add"
        if kind == "agents_add":
            e = int(rng.integers(1, max_edit_agents + 1))
            src = rng.integers(0, cur.n, size=e)
            edit = RegistryEdit(
                kind="agents_add", rows=cur.assignments[src].copy()
            )
        elif kind == "agents_drop":
            mask = np.ones(cur.n, dtype=bool)
            mask[cur.witness] = False
            pool = np.nonzero(mask)[0]
            if pool.size == 0:
                continue
            e = int(min(rng.integers(1, max_edit_agents + 1), pool.size))
            edit = RegistryEdit(
                kind="agents_drop",
                agents=np.sort(rng.choice(pool, size=e, replace=False)).astype(
                    np.int64
                ),
            )
        elif kind in ("quota_relax", "quota_tighten"):
            f = int(rng.integers(0, len(cur.qmin)))
            wc = int(cur.incidence()[cur.witness].sum(axis=0)[f])
            lo, hi = int(cur.qmin[f]), int(cur.qmax[f])
            if kind == "quota_tighten":
                dlo = 1 if lo < wc else 0
                dhi = -1 if hi > wc else 0
                if dlo == 0 and dhi == 0:
                    kind = "quota_relax"
                else:
                    edit = RegistryEdit(
                        kind="quota_tighten", cell=f, dlo=dlo, dhi=dhi
                    )
            if kind == "quota_relax":
                # exactly ONE arm per edit: a relax that widened both bounds
                # at once is a 2-unit step — outside the single-unit edit
                # grammar every consumer (delta re-certifier sensitivity,
                # trail replays) is sized for. Both arms open → rng picks.
                arms = []
                if lo > 0:
                    arms.append((-1, 0))
                if hi < cur.k:
                    arms.append((0, 1))
                if not arms:
                    continue
                dlo, dhi = arms[int(rng.integers(0, len(arms)))]
                edit = RegistryEdit(kind="quota_relax", cell=f, dlo=dlo, dhi=dhi)
        elif kind == "new_type":
            c = int(rng.integers(0, cur.n_categories))
            e = int(rng.integers(1, 9))
            new_slot = len(cur.features[c])
            src = rng.integers(0, cur.n, size=e)
            rows = cur.assignments[src].copy()
            rows[:, c] = new_slot
            edit = RegistryEdit(
                kind="new_type",
                rows=rows,
                category=c,
                feature=f"{cur.categories[c]}_new{new_types}",
                dhi=int(rng.integers(1, 4)),
            )
        if edit is None:
            continue
        nxt = apply_edit(cur, edit)
        if not nxt.check_witness():  # pragma: no cover - defensive
            raise AssertionError(
                f"churn_trail generated an infeasible edit: {edit.describe()}"
            )
        if edit.kind == "new_type":
            new_types += 1
        trail.append(edit)
        cur = nxt
    return trail
