"""Seeded synthetic nationwide-registry instances (the graftpod workload).

The source paper's deployments select from pools of hundreds to a few
thousand volunteers; the self-selection line of work points at the real
target — a standing nationwide civic-lottery registry with n = 10⁵-10⁶
volunteers and thousands of household classes. This module generates that
instance family at scale:

* **Vectorized all the way.** ``core/generator.py`` builds agents as a list
  of per-agent dicts, which is fine at n ≤ 10⁴ and hopeless at 10⁶ (tens of
  seconds and ~1 GB of dict overhead). Here the pool is a single
  ``int32[n, C]`` assignment matrix drawn per category from a seeded
  Dirichlet-weighted categorical, and :meth:`Registry.to_dense` lowers it
  straight to the ``DenseInstance`` incidence arrays with numpy scatter —
  no per-agent Python objects anywhere. ``to_instance()`` exists for
  interop with the CSV-shaped pipeline and is priced for modest n only.

* **Feasible quotas by construction.** Quotas are synthesized around a
  *witness panel*: draw k agents uniformly without replacement, count their
  per-cell composition, and bracket each cell's quota around that count
  with a ±slack band. The witness satisfies every quota by definition, so
  the instance is feasible with a checkable certificate
  (:meth:`Registry.check_witness`), and per-category quota sums
  automatically bracket k (they sum to k at the witness point).

* **Household classes.** Every agent carries a household id over a
  configurable class count (≥ 5k at the nationwide tier — the scale that
  justifies a sharded mesh), consumable by the samplers' ``households``
  argument.
"""

from __future__ import annotations

import dataclasses
from typing import Optional, Sequence, Tuple

import numpy as np

from citizensassemblies_tpu.core.instance import (
    DenseInstance,
    FeatureSpace,
    HostView,
    Instance,
)

#: default civic-lottery demography: (category, features) in file order.
DEFAULT_CATEGORIES: Tuple[Tuple[str, Tuple[str, ...]], ...] = (
    ("gender", ("female", "male")),
    ("age", ("16-24", "25-34", "35-44", "45-54", "55-64", "65-74", "75+")),
    (
        "region",
        tuple(f"region_{i:02d}" for i in range(12)),
    ),
    ("education", ("none", "secondary", "vocational", "tertiary")),
    ("urbanicity", ("urban", "suburban", "rural")),
)


@dataclasses.dataclass
class Registry:
    """A generated nationwide-registry instance (host-side, all numpy).

    ``assignments[i, c]`` is agent i's feature index within category c;
    ``qmin``/``qmax`` are flat per-cell quotas in ``FeatureSpace`` order;
    ``witness`` is the k-panel the quotas were synthesized around (the
    feasibility certificate); ``household_id`` labels household classes.
    """

    name: str
    k: int
    categories: Tuple[str, ...]
    features: Tuple[Tuple[str, ...], ...]
    assignments: np.ndarray  # int32[n, C]
    qmin: np.ndarray  # int32[F]
    qmax: np.ndarray  # int32[F]
    household_id: np.ndarray  # int32[n]
    witness: np.ndarray  # int64[k], sorted agent ids
    seed: int

    @property
    def n(self) -> int:
        return int(self.assignments.shape[0])

    @property
    def n_categories(self) -> int:
        return int(self.assignments.shape[1])

    @property
    def n_households(self) -> int:
        return int(self.household_id.max()) + 1 if self.household_id.size else 0

    @property
    def cell_offsets(self) -> np.ndarray:
        """Flat-cell index of each category's first feature."""
        sizes = np.asarray([len(f) for f in self.features], dtype=np.int64)
        return np.concatenate([[0], np.cumsum(sizes)[:-1]])

    def incidence(self) -> np.ndarray:
        """bool[n, F] agent×cell incidence, built by vectorized scatter."""
        n, C = self.assignments.shape
        F = int(sum(len(f) for f in self.features))
        A = np.zeros((n, F), dtype=bool)
        offsets = self.cell_offsets
        rows = np.arange(n)
        for c in range(C):
            A[rows, offsets[c] + self.assignments[:, c]] = True
        return A

    def check_witness(self) -> bool:
        """Re-verify the feasibility certificate: the witness panel has k
        distinct members and satisfies every cell quota."""
        if len(np.unique(self.witness)) != self.k:
            return False
        counts = self.incidence()[self.witness].sum(axis=0)
        return bool(np.all((counts >= self.qmin) & (counts <= self.qmax)))

    def to_dense(self) -> Tuple[DenseInstance, FeatureSpace]:
        """Lower straight to the device representation (no per-agent dicts
        — this is the only path priced for n = 10⁶)."""
        import jax.numpy as jnp

        A = self.incidence()
        qmin = self.qmin.astype(np.int32)
        qmax = self.qmax.astype(np.int32)
        cat_of_feature = np.concatenate(
            [
                np.full(len(feats), ci, dtype=np.int32)
                for ci, feats in enumerate(self.features)
            ]
        )
        dense = DenseInstance(
            A=jnp.asarray(A),
            qmin=jnp.asarray(qmin),
            qmax=jnp.asarray(qmax),
            cat_of_feature=jnp.asarray(cat_of_feature),
            k=self.k,
            n_categories=len(self.categories),
            host=HostView(A, qmin, qmax),
        )
        space = FeatureSpace(
            categories=self.categories,
            cells=tuple(
                (cat, feat)
                for cat, feats in zip(self.categories, self.features)
                for feat in feats
            ),
        )
        return dense, space

    def to_instance(self) -> Instance:
        """CSV-shaped host container (per-agent dicts — modest n only)."""
        cat_quotas = {}
        flat = 0
        for cat, feats in zip(self.categories, self.features):
            cat_quotas[cat] = {
                feat: (int(self.qmin[flat + j]), int(self.qmax[flat + j]))
                for j, feat in enumerate(feats)
            }
            flat += len(feats)
        agents = [
            {
                cat: self.features[c][self.assignments[i, c]]
                for c, cat in enumerate(self.categories)
            }
            for i in range(self.n)
        ]
        return Instance(
            k=self.k, categories=cat_quotas, agents=agents, name=self.name
        )


def nationwide_registry(
    n: int = 100_000,
    seed: int = 0,
    k: Optional[int] = None,
    categories: Optional[Sequence[Tuple[str, Sequence[str]]]] = None,
    household_classes: Optional[int] = None,
    quota_slack: float = 0.08,
    name: str = "",
) -> Registry:
    """Generate a seeded nationwide-registry instance of ``n`` volunteers.

    The same ``(n, seed, …)`` always yields the identical registry (numpy
    ``default_rng`` stream, no global state). ``quota_slack`` is the ±band
    around the witness composition, as a fraction of k (floored at ±1 seat,
    so every instance has real selection freedom without losing the
    witness-feasibility guarantee). ``household_classes`` defaults to
    ``max(5000, n // 3)`` capped at n — the nationwide tier's ≥ 5k classes
    — and scales down to ``n // 3`` on small test instances.
    """
    if n <= 0:
        raise ValueError(f"registry size n={n} must be positive")
    rng = np.random.default_rng(seed)
    cats = tuple(
        (str(c), tuple(str(f) for f in feats))
        for c, feats in (categories or DEFAULT_CATEGORIES)
    )
    cat_names = tuple(c for c, _ in cats)
    cat_feats = tuple(f for _, f in cats)

    if k is None:
        k = int(max(24, min(400, round(n ** 0.5))))
    if k > n:
        raise ValueError(f"panel size k={k} exceeds pool size n={n}")

    # per-category Dirichlet-weighted categorical marginals: skewed enough
    # to look like census marginals, never degenerate (alpha > 1)
    assignments = np.empty((n, len(cats)), dtype=np.int32)
    for c, feats in enumerate(cat_feats):
        probs = rng.dirichlet(np.full(len(feats), 4.0))
        assignments[:, c] = rng.choice(len(feats), size=n, p=probs)

    # household classes: contiguous labels over the configured class count
    H = household_classes
    if H is None:
        H = min(n, max(5000, n // 3)) if n >= 5000 else max(1, n // 3)
    H = max(1, min(int(H), n))
    household_id = rng.integers(0, H, size=n, dtype=np.int32)
    # guarantee every class is inhabited (cardinality is part of the tier
    # contract): deal the first H agents one class each, then shuffle
    household_id[:H] = np.arange(H, dtype=np.int32)
    rng.shuffle(household_id)

    # witness panel → quotas bracketing its composition (feasible by
    # construction; the witness is retained as the certificate)
    witness = np.sort(rng.choice(n, size=k, replace=False)).astype(np.int64)
    slack = max(1, int(round(quota_slack * k)))
    qmin_parts, qmax_parts = [], []
    for c, feats in enumerate(cat_feats):
        counts = np.bincount(assignments[witness, c], minlength=len(feats))
        qmin_parts.append(np.maximum(0, counts - slack))
        qmax_parts.append(np.minimum(k, counts + slack))
    qmin = np.concatenate(qmin_parts).astype(np.int32)
    qmax = np.concatenate(qmax_parts).astype(np.int32)

    return Registry(
        name=name or f"registry_n{n}_s{seed}",
        k=int(k),
        categories=cat_names,
        features=cat_feats,
        assignments=assignments,
        qmin=qmin,
        qmax=qmax,
        household_id=household_id,
        witness=witness,
        seed=int(seed),
    )
