"""Column-generation LEXIMIN in composition (type) space.

For instances with too many distinct agent types to enumerate every feasible
composition (``solvers/compositions.py``), the column-generation algorithm of
the reference (``leximin.py:338-470``) still collapses onto types: columns are
*compositions* ``c ∈ Z^T`` rather than agent subsets, the stage LP has one
constraint per type instead of one per agent, and the exact pricing ILP has T
bounded-integer variables and one row per feature — dramatically smaller than
the reference's n-binary-variable committee ILP (``leximin.py:190-233``) and
solved by HiGHS in tens of milliseconds where the agent-space search took
seconds.

Per inner iteration the dual weights steer a *batched* TPU draw of feasible
panels (``models/legacy.py::sample_panels_batch`` with weight-proportional
member scores); sampled panels map onto compositions by type-counting, giving
many violated columns per LP solve. The exact MILP oracle then certifies each
stage's termination, so the fixing logic keeps the reference's exactness
guarantee (``leximin.py:429-443``) at a fraction of its solve count.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np
import scipy.optimize
import scipy.sparse

from citizensassemblies_tpu.solvers.lp_util import probe_confirm_tranche, robust_linprog
from citizensassemblies_tpu.solvers.native_oracle import TypeReduction
from citizensassemblies_tpu.utils.config import Config, default_config
from citizensassemblies_tpu.utils.logging import RunLog

_SLACK = 1e-9
#: deduction applied to every fixed leximin value: the solver-reported stage
#: optimum can overstate the true optimum by its own tolerance (~1e-8), and
#: floors encoding overstated values leave later stages genuinely infeasible
#: — a ratchet that compounds across stages. Fixing at z − margin keeps every
#: floor strictly achievable; the understatement is far below the 1e-3 bar.
_FIX_MARGIN = 1e-7


class CompositionOracle:
    """Exact ``max Σ_t w_t c_t`` over feasible compositions (HiGHS MILP).

    The type-space collapse of the reference's committee-generation ILP
    (``leximin.py:190-233``): variables are per-type member counts with bounds
    ``[0, m_t]``, constraints are ``Σc = k`` plus one row per feature quota.
    """

    def __init__(self, reduction: TypeReduction, log: Optional[RunLog] = None):
        #: optional RunLog for oracle-mix attribution (every maximize is a
        #: scipy/HiGHS MILP; the device pricer counts its own lane, so bench
        #: rows show the native / HiGHS / device split per run)
        self.log = log
        self.red = reduction
        T, F = reduction.T, reduction.F
        tf = np.zeros((T, F))
        for t in range(T):
            tf[t, reduction.type_feature[t]] = 1.0
        A = scipy.sparse.vstack(
            [scipy.sparse.csr_matrix(np.ones((1, T))), scipy.sparse.csr_matrix(tf.T)]
        )
        self._constraints = scipy.optimize.LinearConstraint(
            A,
            np.concatenate([[reduction.k], reduction.qmin]),
            np.concatenate([[reduction.k], reduction.qmax]),
        )
        self._integrality = np.ones(T)

    def maximize(
        self, weights: np.ndarray, forced_type: Optional[int] = None,
        rel_gap: float = 0.0,
    ) -> Optional[Tuple[np.ndarray, float]]:
        """Best feasible composition for per-type ``weights``; optionally force
        ``c_t ≥ 1`` for one type (the coverage solves of ``leximin.py:279-289``).
        Returns None when infeasible.

        ``rel_gap`` relaxes the MILP's optimality gap for callers that use the
        result as a *heuristic column* rather than a certificate (the face
        loop's anchor columns: acceptance there is the arithmetic residual of
        the master iterate, so anchor optimality buys nothing — but each
        exact solve at T ≈ 1000 costs ~0.2 s and the anchors were ~20 % of
        the flagship decomposition wall-clock). Certification calls keep the
        exact default."""
        if self.log is not None:
            self.log.count("oracle_backend_highs")
        lo = np.zeros(self.red.T)
        if forced_type is not None:
            lo[forced_type] = 1.0
        res = scipy.optimize.milp(
            c=-np.asarray(weights, dtype=np.float64),
            constraints=self._constraints,
            bounds=scipy.optimize.Bounds(lo, self.red.msize.astype(np.float64)),
            integrality=self._integrality,
            options={"mip_rel_gap": rel_gap} if rel_gap > 0.0 else None,
        )
        if res.status != 0 or res.x is None:
            return None
        comp = np.round(res.x).astype(np.int32)
        return comp, float(-res.fun)


def _relaxation_bound(
    reduction: TypeReduction, fixed: np.ndarray
) -> Tuple[float, np.ndarray]:
    """Stage upper bound from the LP relaxation over expected type counts.

    ``max z`` over fractional ``x ∈ [0, m]`` with ``Σx = k``, feature quota
    rows, ``x_t ≥ z·m_t`` (unfixed) and ``x_t ≥ f_t·m_t`` (fixed). Any
    distribution over feasible compositions has its expectation in this
    polytope, so no stage can exceed ``z_UB``; when the master LP reaches it,
    the stage is certified optimal without an exact pricing call. The
    optimizer ``x*`` is a vertex with at most #rows fractional coordinates —
    its randomized roundings are injected as master columns so the portfolio
    spans near-optimal mixtures immediately instead of discovering them one
    pricing round at a time.
    """
    T, F = reduction.T, reduction.F
    tf = np.zeros((T, F))
    for t in range(T):
        tf[t, reduction.type_feature[t]] = 1.0
    m = reduction.msize.astype(np.float64)
    unfixed = fixed < 0
    # variables [x (T), z]
    c = np.zeros(T + 1)
    c[T] = -1.0
    rows = []
    b = []
    # quota rows: lo ≤ tfᵀ x ≤ hi  →  two inequality blocks
    rows.append(np.concatenate([-tf.T, np.zeros((F, 1))], axis=1))
    b.append(-reduction.qmin.astype(np.float64))
    rows.append(np.concatenate([tf.T, np.zeros((F, 1))], axis=1))
    b.append(reduction.qmax.astype(np.float64))
    # floor rows: z·m_t − x_t ≤ 0 (unfixed), f_t·m_t − x_t ≤ 0 (fixed)
    floor = np.zeros((T, T + 1))
    floor[np.arange(T), np.arange(T)] = -1.0
    floor[unfixed, T] = m[unfixed]
    rows.append(floor)
    b.append(np.where(unfixed, 0.0, -(np.maximum(fixed, 0.0) * m - _SLACK)))
    A_ub = np.concatenate(rows, axis=0)
    b_ub = np.concatenate(b)
    A_eq = np.concatenate([np.ones(T), [0.0]])[None, :]
    res = robust_linprog(
        c, A_ub=A_ub, b_ub=b_ub, A_eq=A_eq, b_eq=[float(reduction.k)],
        bounds=[(0, mm) for mm in m] + [(0, None)],
    )
    if res.status != 0:
        return float("inf"), np.zeros(T)
    return float(res.x[T]), res.x[:T]


def _round_relaxation(
    x: np.ndarray,
    reduction: TypeReduction,
    rng: np.random.Generator,
    count: int = 256,
) -> List[np.ndarray]:
    """Randomized quota-feasible integer roundings of a fractional type-count
    vector (probability-proportional on the fractional coordinates, with a
    Σ=k repair step); infeasible roundings are discarded."""
    T = reduction.T
    k = reduction.k
    lo = reduction.qmin
    hi = reduction.qmax
    base = np.floor(x).astype(np.int64)
    frac = x - base
    fidx = np.nonzero(frac > 1e-12)[0]
    tf = np.zeros((T, reduction.F), dtype=np.int64)
    for t in range(T):
        tf[t, reduction.type_feature[t]] = 1
    cands = np.repeat(base[None, :], count, axis=0)
    for r in range(count):
        c = cands[r]
        c[fidx] += rng.random(len(fidx)) < frac[fidx]
        gap = k - int(c.sum())
        order = rng.permutation(fidx)
        for t in order:
            if gap == 0:
                break
            if gap > 0 and c[t] == base[t]:
                c[t] += 1
                gap -= 1
            elif gap < 0 and c[t] > base[t]:
                c[t] -= 1
                gap += 1
        if gap != 0:
            c[0] = -1  # mark infeasible
    ok = cands[:, 0] >= 0
    counts = cands @ tf  # [count, F]
    ok &= np.all(counts >= lo[None, :], axis=1) & np.all(counts <= hi[None, :], axis=1)
    return [c.astype(np.int32) for c in cands[ok]]


def _quota_system(reduction: TypeReduction) -> Tuple[np.ndarray, np.ndarray]:
    """Stacked two-sided quota rows over type counts: ``A x ≤ b`` encodes
    ``qmin ≤ tfᵀ x ≤ qmax`` (A is [2F, T])."""
    T, F = reduction.T, reduction.F
    tf = np.zeros((T, F))
    for t in range(T):
        tf[t, reduction.type_feature[t]] = 1.0
    A = np.concatenate([-tf.T, tf.T], axis=0)
    b = np.concatenate(
        [-reduction.qmin.astype(np.float64), reduction.qmax.astype(np.float64)]
    )
    return A, b


def _marginal_probe_confirm(
    reduction: TypeReduction,
    fixed: np.ndarray,
    z: float,
    cand: np.ndarray,
    probe_tol: float = 1e-7,
    floor_slack: float = _SLACK,
    log: Optional[RunLog] = None,
    exclude: Optional[np.ndarray] = None,
) -> np.ndarray:
    """Certify which candidate types are capped at ``z`` on the *marginal*
    optimal face ``{x ∈ X : x_u ≥ z·m_u ∀ unfixed u, x_f ≥ f·m_f}``.

    One group LP maximizing ``Σ_cand x_t/m_t`` confirms every candidate at
    once when its optimum is ``|cand|·z`` (each term is ≥ z on the face, so
    none can exceed z anywhere); per-candidate probes resolve disagreement.
    Because the composition hull is contained in the marginal polytope, a
    marginal certificate is also valid for the hull face at the same ``z`` —
    the cheap, bounds-only certification used by the stage-CG fixing. Returns
    a bool mask over ``cand``.
    """
    T = reduction.T
    m = reduction.msize.astype(np.float64)
    if exclude is not None and exclude.any():
        # mirror the stage LP's pinning (x_t = 0): leaving the full upper
        # bound would let the probe face route mass through excluded types —
        # a strictly larger polytope than the one being optimized, whose
        # probes can then fail on genuinely tight candidates and push the
        # stage into the uncertified dual-heuristic fallback
        m = np.where(exclude, 0.0, m)
    k = float(reduction.k)
    quota_A, quota_b = _quota_system(reduction)
    unfixed = fixed < 0
    # the stage LP's unfixed floors are EXACT (x_u ≥ z·m_u rows, no slack),
    # so its optimum provably lies on the face with floors z − probe_relax
    # for any probe_relax > 0 — only solver feasibility tolerance needs
    # covering, not the fixing margin. The floor stays at 1e-8, BELOW
    # HiGHS's ~1e-7 primal tolerance, deliberately: raising it to 1e-7
    # inflates slack_gain ≈ probe_relax·Σm past ALLOWANCE_CAP at n ≈ 1700,
    # which makes every sound group-probe budget unpassable and degrades
    # tranche certification to one LP per candidate (measured: ~1001 probe
    # LPs and +7 s on the sf_e_like stage loop). The rare numerically-empty
    # face a sub-tolerance relaxation can produce is handled by the
    # empty-face detection plus the 10×-relaxed retry face below, which
    # costs one extra LP only when it actually occurs. A loose face (the
    # old margin+slack relaxation) freed (margin+slack)·Σm ≈ 1e-4-scale
    # reroutable mass — same failure mode, same lesson.
    probe_relax = max(1e-8, floor_slack)
    A_eq = np.ones((1, T))

    def _bounds_at(relax: float):
        lo = np.where(
            unfixed,
            np.maximum(z - relax, 0.0) * m,
            (np.maximum(fixed, 0.0) - floor_slack) * m,
        )
        lo = np.clip(lo, 0.0, m)
        return [(lo[t], m[t]) for t in range(T)]

    bounds = _bounds_at(probe_relax)
    bounds_relaxed = _bounds_at(10.0 * probe_relax)

    def _face_max_over(bnds):
        def fm(w: np.ndarray):
            r = robust_linprog(
                -w, A_ub=quota_A, b_ub=quota_b, A_eq=A_eq, b_eq=[k], bounds=bnds
            )
            if r.status == 0:
                return float(-r.fun), np.asarray(r.x)
            # infeasible vs failed — no optimizer either way
            return (-np.inf, None) if r.status == 2 else (None, None)
        return fm

    face_max = _face_max_over(bounds)
    # retry probe for objective-specific infeasible reports: same face with
    # floors 10× looser — a superset, so its optimum is a valid upper bound
    face_max_relaxed = _face_max_over(bounds_relaxed)

    cand = np.asarray(cand)
    if z >= 1.0 - probe_tol:
        # normalized type values cannot exceed 1 (x_t ≤ m_t), so every
        # candidate is trivially capped at z — no LP needed, and the face at
        # z ≈ 1 is often numerically empty anyway
        return np.ones(len(cand), dtype=bool)
    # the face floors are relaxed by probe_relax·m_t (unfixed) and
    # floor_slack·m_t (fixed) raw units; at most their sum can be re-routed
    # into a candidate, so tightness must be judged up to that freed mass
    # (normalized by m_t) or genuinely tight types probe "loose" on large
    # pools, inflating later stage values by exactly the slack (the shared
    # prober clamps the allowance so an escalated slack ladder can never
    # certify at a tolerance material against the 1e-3 bar); each
    # candidate's own value may also sit up to probe_relax below z on the
    # face, which the prober charges against the group test's budget
    slack_gain = probe_relax * float(m[unfixed].sum()) + floor_slack * float(
        m[~unfixed].sum()
    )
    objectives = np.zeros((len(cand), T))
    objectives[np.arange(len(cand)), cand] = 1.0 / m[cand]
    return probe_confirm_tranche(
        face_max,
        objectives,
        z,
        probe_tol,
        slack_gain / m[cand],
        term_deficit=probe_relax,
        log=log.emit if log is not None else None,
        face_max_relaxed=face_max_relaxed,
    )


def _leximin_relaxation(
    reduction: TypeReduction,
    log: Optional[RunLog] = None,
    probe_tol: float = 1e-7,
    exclude: Optional[np.ndarray] = None,
) -> Tuple[np.ndarray, np.ndarray]:
    """Exact leximin of ``x/m`` over the marginal relaxation polytope
    ``X = {x ∈ [0, m] : Σx = k, lo ≤ tfᵀx ≤ hi}``.

    Every achievable allocation profile is the expectation of a composition
    distribution and hence lies in ``X/m``, so this leximin profile dominates
    the true one in leximin order; when the decomposition LP later realizes it
    exactly (ε ≈ 0), it *is* the true leximin — certified without any
    stage-wise column generation. Runs the same fix-tranche stage loop as
    ``leximin_over_compositions`` but each stage is a T-variable LP solved in
    milliseconds (fixed-type floors live in the variable bounds, so the row
    count shrinks as fixing progresses).

    Tranche fixing is **probe-certified**, not dual-heuristic: a vertex dual
    ``y_t > 0`` proves tightness only at *one* optimum (the reference leans on
    Gurobi's strictly-complementary barrier for the stronger claim,
    ``leximin.py:325-327,431-443``). Here candidates proposed by the duals are
    confirmed against the optimal face ``{x ∈ X : x_u ≥ z·m_u ∀ unfixed u}``:
    one group LP maximizing ``Σ_cand x_t/m_t`` certifies the whole tranche when
    its optimum is ``|cand|·z`` (then no candidate can exceed ``z`` anywhere on
    the face); otherwise per-candidate probes keep exactly the types whose face
    maximum is ``z``. Returns ``(v [T] leximin type values, x_final [T] an
    optimal marginal)``.

    ``exclude`` (bool[T]) pins types proven to appear in NO integer
    composition at value 0 with ``x_t = 0``: leaving them free lets the
    relaxation route mass through them fractionally, inflating other types'
    values past what any composition mixture can realize (the face
    decomposition then stalls on an irreducible residual).
    """
    log = log or RunLog(echo=False)
    T, F = reduction.T, reduction.F
    m = reduction.msize.astype(np.float64)
    if exclude is not None and exclude.any():
        m = np.where(exclude, 0.0, m)  # upper bound 0 ⇒ x_t = 0 throughout
    k = float(reduction.k)
    fixed = np.full(T, -1.0)
    if exclude is not None:
        fixed[exclude] = 0.0
    x_last = np.zeros(T)
    quota_A, quota_b = _quota_system(reduction)
    stage = 0
    probes = 0
    floor_slack = 0.0
    while (fixed < 0).any():
        stage += 1
        unfixed = fixed < 0
        uidx = np.nonzero(unfixed)[0]
        nu = len(uidx)
        # stage LP over [x, z]: max z s.t. x ∈ X, x_u ≥ z·m_u (unfixed),
        # x_t ≥ (f_t − slack)·m_t via lower bounds (fixed). The slack ladder
        # covers HiGHS's own primal feasibility tolerance: fixing at a
        # solver-reported optimum can overstate the true optimum by ~1e-7,
        # leaving later stages *genuinely* (numerically) infeasible at a
        # 1e-9 slack; the probe allowances scale with the slack in use, so
        # escalation costs tolerance budget only when actually needed.
        A_dense = np.zeros((2 * F + nu, T + 1))
        A_dense[: 2 * F, :T] = quota_A
        A_dense[2 * F + np.arange(nu), uidx] = -1.0
        A_dense[2 * F :, T] = m[uidx]
        # the floor block is −I plus one dense column: sparse storage roughly
        # halves HiGHS's stage-LP time at T ≈ 1000
        A_ub = scipy.sparse.csr_matrix(A_dense)
        b_ub = np.concatenate([quota_b, np.zeros(nu)])
        c = np.zeros(T + 1)
        c[T] = -1.0
        res = None
        for slack in sorted({floor_slack, 1e-8, 1e-7, 1e-6, 1e-5}):
            if slack < floor_slack:
                continue
            lo_b = np.clip((np.where(unfixed, 0.0, np.maximum(fixed, 0.0)) - slack) * m, 0.0, m)
            lo_b[unfixed] = 0.0
            res = robust_linprog(
                c, A_ub=A_ub, b_ub=b_ub,
                A_eq=np.concatenate([np.ones(T), [0.0]])[None, :], b_eq=[k],
                bounds=[(lo_b[t], m[t]) for t in range(T)] + [(0, None)],
            )
            if res.status == 0:
                if slack > floor_slack:
                    log.emit(
                        f"Relaxation stage {stage}: floor slack escalated to "
                        f"{slack:.0e} (solver-tolerance infeasibility)."
                    )
                floor_slack = slack
                break
        if res is None or res.status != 0:
            raise RuntimeError(f"relaxation stage LP failed: {res.message}")
        z = float(res.x[T])
        x_last = res.x[:T]
        y = -np.asarray(res.ineqlin.marginals)[2 * F :]  # unfixed floor duals
        # candidate gate on the dimensionless contribution y_t·m_t (the duals
        # satisfy Σ y_t·m_t = 1, so an absolute cut is scale-inconsistent)
        cand = np.nonzero(y * m[uidx] > 1e-9)[0]
        if len(cand) == 0:
            cand = np.array([int(np.argmax(y * m[uidx]))])

        conf = _marginal_probe_confirm(
            reduction, fixed, z, uidx[cand], probe_tol, floor_slack=floor_slack,
            log=log, exclude=exclude,
        )
        probes += 1 + (0 if conf.all() else len(cand))
        confirmed = np.zeros(T, dtype=bool)
        confirmed[uidx[cand[conf]]] = True
        if not confirmed.any():
            # the dual candidates all probe loose — scan the remaining unfixed
            # types (descending dual weight) for one that is genuinely capped;
            # at a stage optimum at least one must be (else z could increase)
            rest = uidx[np.argsort(-(y * m[uidx]))]
            rest = np.array([t for t in rest if t not in set(uidx[cand])], dtype=int)
            for t in rest:
                if _marginal_probe_confirm(
                    reduction, fixed, z, np.array([t]), probe_tol,
                    floor_slack=floor_slack, log=log, exclude=exclude,
                )[0]:
                    confirmed[t] = True
                    break
                probes += 1
            if not confirmed.any():
                # numerics left nothing certifiable: fall back to the largest
                # dual weight so the loop always progresses (reference
                # heuristic, leximin.py:431-443)
                confirmed[uidx[np.argmax(y * m[uidx])]] = True
                log.emit(
                    f"Relaxation stage {stage}: no probe-certified type at "
                    f"z={z:.6f}; falling back to the dual heuristic."
                )
        fixed = np.where(confirmed, max(0.0, z - _FIX_MARGIN), fixed)
    log.emit(f"Relaxation leximin: {stage} stages, ~{probes} probe LPs, values in "
             f"[{fixed.min():.6f}, {fixed.max():.6f}].")
    return fixed, x_last


def _decomp_lp(MT: np.ndarray, v: np.ndarray) -> Tuple[float, np.ndarray, float, np.ndarray]:
    """Two-sided decomposition master: ``min ε`` s.t.
    ``v − ε ≤ M p ≤ v + ε``, ``Σp = 1``, ``p ≥ 0`` (host, sparse IPM).

    One-sided feasibility (the reference's final-LP shape,
    ``leximin.py:453-464``) lets the surplus ``Σ(alloc − v) = 0`` concentrate:
    a deficit of ε per type funds an overshoot of up to T·ε on one type,
    which breaks the L∞ acceptance bar even at small ε. The two-sided form
    bounds the allocation error by ε directly. Returns ``(ε, w, μ, p)`` with
    pricing weights ``w = y_lower − y_upper`` (mixed sign): a composition
    improves the master iff ``w·(c/m) > −μ``.
    """
    T, C = MT.shape
    v = np.asarray(v, dtype=np.float64)
    G = scipy.sparse.vstack(
        [
            scipy.sparse.hstack(
                [scipy.sparse.csr_matrix(-MT), scipy.sparse.csr_matrix(-np.ones((T, 1)))]
            ),
            scipy.sparse.hstack(
                [scipy.sparse.csr_matrix(MT), scipy.sparse.csr_matrix(-np.ones((T, 1)))]
            ),
        ]
    ).tocsr()
    h = np.concatenate([-(v - _SLACK), v + _SLACK])
    A_eq = scipy.sparse.csr_matrix(np.concatenate([np.ones(C), [0.0]])[None, :])
    c_obj = np.zeros(C + 1)
    c_obj[C] = 1.0
    # dual simplex wins on the small host masters (~25 % over IPM at
    # T ≈ 150, C ≈ 2000) but degrades badly on tall systems — a T = 1199
    # polish took ~100 s via ds vs ~10 s via IPM — so the order flips on T
    methods = (
        ("highs-ds", "highs-ipm", "highs")
        if T <= 384
        else ("highs-ipm", "highs")
    )
    res = robust_linprog(
        c_obj, A_ub=G, b_ub=h, A_eq=A_eq, b_eq=[1.0],
        bounds=[(0, None)] * (C + 1), methods=methods,
    )
    if res.status != 0:
        raise RuntimeError(f"decomposition LP failed: {res.message}")
    lam = -np.asarray(res.ineqlin.marginals)  # ≥ 0
    w = lam[:T] - lam[T:]
    mu = float(res.eqlin.marginals[0])
    return float(res.x[C]), w, mu, np.maximum(res.x[:C], 0.0)


def _slice_relaxation(
    x: np.ndarray,
    reduction: TypeReduction,
    R: int = 512,
    j0: int = 0,
    chunks: int = 1,
    max_passes: Optional[int] = None,
) -> List[np.ndarray]:
    """Systematic apportionment of a fractional marginal into ``R`` integer
    compositions whose uniform mixture reproduces ``x`` to within ~1/R.

    Slice j takes ``c_t(j) = ⌊j·x_t⌋ − ⌊(j−1)·x_t⌋`` (cumulative largest-
    remainder rounding, so every type's total over slices is exact to ±1),
    then repairs ``Σc = k`` by moving units between types with the smallest
    rounding residuals, subject to the feature quotas. Slices that cannot be
    repaired feasibly are dropped. Unlike independent randomized roundings
    (≈5–20 % feasible on tight instances), these columns are *aimed*: their
    hull surrounds ``x`` by construction, which is what the decomposition
    master needs."""
    from citizensassemblies_tpu.solvers.native_oracle import slice_stream_native

    # one native call for the whole stream when the toolchain is available:
    # the per-slice path below costs ~0.3 ms/slice of ctypes marshalling and
    # numpy bookkeeping, which at R ≈ 1000 dominated mid-tier leximin solves.
    # j0 offsets the tie streams (fresh slices of the same hull on repeated
    # calls); chunks > 1 runs that many GIL-released streams in parallel.
    if max_passes is None:
        max_passes = 3 * reduction.F
    streamed = slice_stream_native(
        reduction, np.asarray(x, dtype=np.float64), R,
        max_passes=max_passes, j0=j0, chunks=chunks,
    )
    if streamed is not None:
        return list(streamed)

    if chunks > 1:
        # match the native semantics without the toolchain (ADVICE r4):
        # `chunks` independent phase-spaced streams of R // chunks slices,
        # run sequentially — same offsets (j0 + i·(1<<16)) and hull
        # diversity as the parallel native streams
        out: List[np.ndarray] = []
        sizes = [R // chunks + (1 if i < R % chunks else 0) for i in range(chunks)]
        for i, r in enumerate(sizes):
            out.extend(
                _slice_relaxation(
                    x, reduction, R=r, j0=j0 + i * (1 << 16), chunks=1,
                    max_passes=max_passes,
                )
            )
        return out

    T = reduction.T
    k = reduction.k
    lo, hi = reduction.qmin, reduction.qmax
    tf = np.zeros((T, reduction.F), dtype=np.int64)
    for t in range(T):
        tf[t, reduction.type_feature[t]] = 1
    x = np.asarray(x, dtype=np.float64)
    msize = reduction.msize.astype(np.int64)
    # cumulative feedback: each slice apportions the *residual* j·x −
    # assigned, and every unit actually emitted (including quota repairs)
    # feeds back into `assigned` — so repair deviations self-correct in later
    # slices and the uniform mixture tracks x to ~1/R per type
    assigned = np.zeros(T, dtype=np.int64)
    feat_of = np.asarray(reduction.type_feature)  # [T, ncat]
    ncat = feat_of.shape[1]
    tidx = np.arange(T)

    def swap_repair(c: np.ndarray, counts: np.ndarray, j: int, need: np.ndarray) -> bool:
        """Greedy best-swap quota repair, vectorized per iteration.

        Each pass scores every (donor, receiver) unit move by its exact
        violation change — per-type removal/addition effects from the
        feature-count deltas, with a correction for categories where donor
        and receiver share a feature (their effects cancel there) — and
        applies a best strictly-improving swap. Ties (ubiquitous on integer
        scores) are broken by the slice's *tracking residual* ``c − need``
        plus per-slice random noise: preferring donors above their stream
        target and receivers below it means a repair corrects the
        apportionment error instead of compounding it — repair drift, not
        the ±1 rounding, is what set the decomposition's starting ε. Pure
        random ties remain in the mix because fully deterministic repair
        collapses slice diversity (measured: support 87 vs 180 columns,
        ε 3.8e-2 vs 2.0e-2). Replaces a python double loop that dominated
        the slicer's runtime at T ≈ 800.
        """
        tie = np.random.default_rng(j)
        for _ in range(max_passes):
            track = np.clip(c - need, -2.0, 2.0)
            pref_sub = -0.4 * track  # donate where above target ⇒ lower score
            pref_add = 0.4 * track  # receive where below target ⇒ lower score
            viol = np.maximum(counts - hi, 0) + np.maximum(lo - counts, 0)
            total = int(viol.sum())
            if total == 0:
                return True
            # per-feature violation deltas for one removal / one addition
            dv_sub_f = (
                np.maximum(counts - 1 - hi, 0) + np.maximum(lo - counts + 1, 0) - viol
            )
            dv_add_f = (
                np.maximum(counts + 1 - hi, 0) + np.maximum(lo - counts - 1, 0) - viol
            )
            dv_sub = dv_sub_f[feat_of].sum(axis=1)  # [T] effect of c_t -= 1
            dv_add = dv_add_f[feat_of].sum(axis=1)  # [T] effect of c_t += 1
            # restrict to the worst violated features' member types — the
            # all-pairs matrix at T ≈ 800 is what made repair slow
            over = np.nonzero(counts > hi)[0]
            under = np.nonzero(counts < lo)[0]
            if len(over):
                worst = over[np.argmax(viol[over])]
                donors = np.nonzero((tf[:, worst] > 0) & (c > 0))[0]
            else:
                donors = np.nonzero(c > 0)[0]
            if len(under):
                worst = under[np.argmax(viol[under])]
                receivers = np.nonzero((tf[:, worst] > 0) & (c < msize))[0]
            else:
                receivers = np.nonzero(c < msize)[0]
            if len(donors) == 0 or len(receivers) == 0:
                return False
            # score the exact (donor, receiver) delta only on the most
            # promising 16 per side (per-type scores + random tie noise):
            # the full cross product over hundreds of types per pass was
            # the slicer's dominant cost at T ≈ 800, and the best swap
            # almost always lives among the top per-type scores
            if len(donors) > 16:
                donors = donors[
                    np.argsort(
                        dv_sub[donors] + pref_sub[donors] + tie.random(len(donors)) * 0.3
                    )[:16]
                ]
            if len(receivers) > 16:
                receivers = receivers[
                    np.argsort(
                        dv_add[receivers]
                        + pref_add[receivers]
                        + tie.random(len(receivers)) * 0.3
                    )[:16]
                ]
            delta = dv_sub[donors][:, None] + dv_add[receivers][None, :]
            # shared-feature correction: in a category where donor and
            # receiver have the same feature the move is a no-op there
            for ci in range(ncat):
                same = feat_of[donors, ci][:, None] == feat_of[receivers, ci][None, :]
                corr = (
                    dv_sub_f[feat_of[donors, ci]][:, None]
                    + dv_add_f[feat_of[receivers, ci]][None, :]
                )
                delta = delta - np.where(same, corr, 0)
            noisy = (
                delta
                + pref_sub[donors][:, None]
                + pref_add[receivers][None, :]
                + tie.random(delta.shape) * 0.3
            )
            di, ri = np.unravel_index(np.argmin(noisy), delta.shape)
            if delta[di, ri] >= 0:
                return False
            td, tr = donors[di], receivers[ri]
            c[td] -= 1
            c[tr] += 1
            counts += tf[tr] - tf[td]
        return bool(np.all(counts >= lo) and np.all(counts <= hi))

    from citizensassemblies_tpu.solvers.native_oracle import repair_slice_native

    out: List[np.ndarray] = []
    # j0 shifts the per-type apportionment phase (see native slice_stream):
    # repair-free slices are pure functions of the apportionment, so tie
    # noise alone cannot diversify them between passes
    phase = (
        (j0 * 0.38196601125 + tidx * 0.61803398875) % 1.0
        if j0
        else np.zeros(T)
    )
    for j in range(1, R + 1):
        need = (j + phase) * x - assigned
        c = np.maximum(np.floor(need + 1e-12), 0.0).astype(np.int64)
        c = np.minimum(c, msize)
        gap = k - int(c.sum())
        counts = c @ tf
        if gap != 0:
            # top up (or trim) by residual fraction; a per-slice golden-ratio
            # jitter rotates exact ties. Two sweeps, the first quota-aware
            # (additions below hi / removals above lo only) — quota-blind
            # top-up left ~10-20 violations for the swap repair, which was
            # most of the slicer's cost. Mirrors the native stream exactly.
            frac = need - np.floor(need + 1e-12)
            jitter = ((tidx * 0.6180339887 + (j + j0) * 0.7548776662) % 1.0) * 1e-6
            frac = frac + jitter
            order = np.argsort(-frac) if gap > 0 else np.argsort(frac)
            for sweep in range(2):
                if gap == 0:
                    break
                for t in order:
                    if gap == 0:
                        break
                    feats = feat_of[t]
                    if gap > 0:
                        if c[t] >= msize[t]:
                            continue
                        if sweep == 0 and np.any(counts[feats] + 1 > hi[feats]):
                            continue
                        c[t] += 1
                        counts[feats] += 1
                        gap -= 1
                    else:
                        if c[t] <= 0:
                            continue
                        if sweep == 0 and np.any(counts[feats] - 1 < lo[feats]):
                            continue
                        c[t] -= 1
                        counts[feats] -= 1
                        gap += 1
        if gap != 0:
            assigned += c  # feed back even on drop, keeping the stream honest
            continue
        # the repair loop is the slicer's host hot spot (tens of passes per
        # slice of small-array work): the native C++ implementation runs the
        # identical scoring ~100× faster; the python path remains as the
        # fallback when the toolchain is unavailable
        c32 = np.ascontiguousarray(c, dtype=np.int32)
        cnt32 = np.ascontiguousarray(counts, dtype=np.int32)
        ok = repair_slice_native(
            reduction, c32, cnt32, need, seed=j + j0, max_passes=max_passes
        )
        if ok is None:
            ok = swap_repair(c, counts, j + j0, need)
        else:
            c[:] = c32
        assigned += c
        if ok:
            out.append(c.astype(np.int32))
    return out


@dataclasses.dataclass
class TypeCGResult:
    compositions: np.ndarray  # int32 [C, T] generated portfolio
    probabilities: np.ndarray  # float64 [C]
    type_values: np.ndarray  # float64 [T]
    coverable: np.ndarray  # bool [T]
    stages: int
    lp_solves: int
    exact_prices: int
    eps_dev: float = 0.0  # accepted downward deviation of the distribution


def _stage_lp(
    MT: np.ndarray,
    fixed: np.ndarray,
) -> Tuple[float, np.ndarray, float, np.ndarray]:
    """Maximize the minimum unfixed type value over the portfolio.

    Returns ``(z*, y, mu, p)`` where ``y ≥ 0`` are per-unfixed-type duals
    (Σy = 1), ``mu`` the normalization dual — a candidate composition ``c``
    improves the stage iff ``Σ_t ŷ_t c_t/m_t > −mu`` with ``ŷ`` the full dual
    vector (fixed types included).
    """
    T, C = MT.shape
    unfixed = np.nonzero(fixed < 0)[0]
    done = np.nonzero(fixed >= 0)[0]
    nu, nd = len(unfixed), len(done)
    A_ub = np.zeros((nu + nd, C + 1))
    A_ub[:nu, :C] = -MT[unfixed]
    A_ub[:nu, C] = 1.0
    b_ub = np.zeros(nu + nd)
    if nd:
        A_ub[nu:, :C] = -MT[done]
        b_ub[nu:] = -(fixed[done] - _SLACK)
    A_eq = np.ones((1, C + 1))
    A_eq[0, C] = 0.0
    c_obj = np.zeros(C + 1)
    c_obj[C] = -1.0
    # interior point, sparse: the master is maximally degenerate (hundreds of
    # near-active rows), where simplex crawls — the same reason the reference
    # forces Gurobi's barrier (leximin.py:325-327); interior duals also fix
    # larger tranches via strict complementarity
    A_ub_s = scipy.sparse.csr_matrix(A_ub)
    A_eq_s = scipy.sparse.csr_matrix(A_eq)
    res = robust_linprog(
        c_obj, A_ub=A_ub_s, b_ub=b_ub, A_eq=A_eq_s, b_eq=[1.0],
        bounds=[(0, None)] * C + [(None, None)], methods=("highs-ipm", "highs"),
    )
    if res.status != 0:
        raise RuntimeError(f"type-space stage LP failed: {res.message}")
    marg = -np.asarray(res.ineqlin.marginals)  # ≥ 0
    y_full = np.zeros(T)
    y_full[unfixed] = marg[:nu]
    if nd:
        y_full[done] = marg[nu:]
    mu = float(res.eqlin.marginals[0])
    return float(res.x[C]), y_full, mu, np.maximum(res.x[:C], 0.0)


def leximin_cg_typespace(
    dense,
    reduction: TypeReduction,
    cfg: Optional[Config] = None,
    log: Optional[RunLog] = None,
    key=None,
    checkpoint_path: Optional[str] = None,
    households=None,
) -> TypeCGResult:
    """LEXIMIN via column generation over compositions.

    Outer/inner loop structure of ``leximin.py:383-449``; see module
    docstring for the type-space re-design.
    """
    import jax

    from citizensassemblies_tpu.models.legacy import sample_panels_batch

    cfg = cfg or default_config()
    log = log or RunLog(echo=False)
    if key is None:
        key = jax.random.PRNGKey(cfg.solver_seed)
    T = reduction.T
    msize = reduction.msize.astype(np.float64)
    type_id = reduction.type_id
    oracle = CompositionOracle(reduction, log=log)

    comps: List[np.ndarray] = []
    seen: Dict[bytes, int] = {}

    def add_comp(c: np.ndarray) -> bool:
        kb = c.astype(np.int16).tobytes()
        if kb in seen:
            return False
        seen[kb] = len(comps)
        comps.append(c.astype(np.int32))
        return True

    def panels_to_comps(panels: np.ndarray) -> np.ndarray:
        tids = type_id[panels]  # [B, k]
        B = panels.shape[0]
        out = np.zeros((B, T), dtype=np.int32)
        rows = np.repeat(np.arange(B), panels.shape[1])
        np.add.at(out, (rows, tids.ravel()), 1)
        return out

    # checkpoint resume: restore the generated portfolio + certified targets
    # so a preempted decomposition restarts from its seed columns and skips
    # the relaxation/coverage phases (coarse-grained — SURVEY §5; the
    # reference restarts 4,000 s runs from zero)
    ckpt_fp = ""
    resumed = None
    if checkpoint_path is not None:
        from citizensassemblies_tpu.utils.checkpoint import (
            load_ts_state,
            problem_fingerprint,
        )

        ckpt_fp = problem_fingerprint(dense, cfg, households)
        resumed = load_ts_state(checkpoint_path, T, ckpt_fp)

    # ---- seeding: relaxation-derived coverage (no device sampling) --------
    # Phase 1's columns come from the aimed slicer below, which outperforms
    # sampled panels; coverability comes from the relaxation leximin itself
    # (v_t > 0 ⟹ some marginal point includes type t), with one exact
    # forced-inclusion MILP per remaining suspect — so the expensive batched
    # panel kernel never compiles on this path (the reference's coverage
    # phase is per-uncovered-agent ILPs, leximin.py:279-289).
    if resumed is None:
        # Fractional coverage (v_relax > 0) does NOT imply integer coverage:
        # a type can carry relaxation mass yet appear in no integer
        # composition (observed en masse on tight repaired-quota household
        # instances — 171 of 400 agents), in which case the decomposition
        # target is unrealizable and the face loop stalls into the stage-CG
        # fallback. Certify every type by integer evidence — membership in
        # an aimed slice, or one exact forced-inclusion MILP — and re-run
        # the relaxation with proven-uncoverable types pinned to x_t = 0.
        with log.timer("relax_leximin"):
            excluded = np.zeros(T, dtype=bool)
            # integer-coverage evidence persists across rounds: a forced-
            # inclusion MILP's verdict cannot change when more types get
            # excluded (excluding only shrinks the polytope for OTHERS, and
            # a witness composition never contains an excluded type), so
            # certified/refuted types are never re-solved
            int_certified = np.zeros(T, dtype=bool)
            int_refuted = np.zeros(T, dtype=bool)
            probe_solves = 0
            # exclusion grows monotonically, so the loop terminates; 8
            # rounds is a generous bound (rounds after the first mostly pay
            # only the T-var relaxation re-run — refuted types regaining
            # mass re-exclude WITHOUT new MILP solves)
            for _cov_round in range(8):
                v_relax, _ = _leximin_relaxation(
                    reduction, log, probe_tol=cfg.probe_tol,
                    exclude=excluded if excluded.any() else None,
                )
                frac_cov = v_relax > 1e-9
                # a refuted type that regained relaxation mass after other
                # exclusions re-routed it must be excluded too (its MILP
                # verdict is permanent)
                regained = int_refuted & frac_cov & ~excluded
                newly_uncoverable = list(np.nonzero(regained)[0].astype(int))
                # integer evidence from a cheap aimed-slice pass
                trial = _slice_relaxation(v_relax * msize, reduction, R=256)
                present = (
                    np.any(np.stack(trial) > 0, axis=0)
                    if trial
                    else np.zeros(T, dtype=bool)
                ) | int_certified
                for t in np.nonzero(~present & ~excluded & ~int_refuted)[0]:
                    if present[t]:
                        continue  # certified by an earlier probe's witness
                    got = oracle.maximize(np.zeros(T), forced_type=int(t))
                    probe_solves += 1
                    if got is None:
                        int_refuted[t] = True
                        if frac_cov[t]:
                            newly_uncoverable.append(int(t))
                    else:
                        add_comp(got[0])
                        # the witness composition certifies EVERY type it
                        # contains — marking them all cuts the probe count
                        # ~10× on many-small-type pools (sf_e-like: the
                        # one-at-a-time loop cost ~7 s of 40 ms MILPs)
                        witness = got[0] > 0
                        present |= witness
                        int_certified |= witness
                if not newly_uncoverable:
                    break
                excluded[newly_uncoverable] = True
                log.emit(
                    f"Coverage round {_cov_round + 1}: "
                    f"{len(newly_uncoverable)} fractionally-covered type(s) "
                    "proven integer-uncoverable; re-running the relaxation "
                    "with them excluded."
                )
            else:
                # the round budget ended ON an exclusion: the target must
                # still be recomputed without the just-excluded mass or the
                # decomposition chases an unrealizable profile
                v_relax, _ = _leximin_relaxation(
                    reduction, log, probe_tol=cfg.probe_tol, exclude=excluded
                )
            # int-refuted types are never coverable regardless of the mass
            # the final relaxation left on them
            coverable = (present | (v_relax > 1e-9)) & ~excluded & ~int_refuted
            # the certification slices aim at the final target — keep them
            # as seed columns (the main injection below dedups against them)
            for c in trial:
                add_comp(c)
            log.emit(
                f"Coverage: {int(coverable.sum())}/{T} types coverable "
                f"(integer-certified; {probe_solves} probe solves)."
            )
    else:
        for c in resumed.compositions:
            add_comp(c)
        coverable = resumed.coverable.astype(bool)
        key = jax.numpy.asarray(resumed.key, dtype=jax.numpy.uint32)
        log.emit(
            f"Resumed type-space checkpoint: {len(comps)} compositions, "
            f"round {resumed.round}."
        )

    fixed = np.full(T, -1.0)
    fixed[~coverable] = 0.0
    if (~coverable).any():
        log.emit(f"{int((~coverable).sum())} type(s) in no feasible committee.")

    stages = 0
    lp_solves = 0
    exact_prices = 0
    probs = None
    # device PDHG for the recurring stage LP when an accelerator is present
    # (or forced via backend="jax"); host HiGHS otherwise and as fallback
    use_pdhg = cfg.backend == "jax" or (
        cfg.backend == "hybrid" and jax.default_backend() not in ("cpu",)
    )
    pdhg_warm = None
    rng = np.random.default_rng(cfg.solver_seed)

    # ---- phase 1: leximin of the marginal relaxation + one decomposition ----
    # Solve leximin exactly over the tiny relaxation polytope (T stages of
    # millisecond LPs), then try to realize that profile as one mixture of
    # integer compositions. Success (ε ≈ 0) certifies the true leximin without
    # any stage-wise column generation; an integrality residual falls back to
    # the certified stage loop below.
    if resumed is None:
        with log.timer("inject"):
            v_relax = np.where(coverable, v_relax, 0.0)
            # aim the column hull at the *target* marginal v·m — the mixture
            # the master must realize (M p = v ⇔ Σ p_c c = v·m). The last
            # stage's vertex optimum x_star is a poor proxy: its early-fixed
            # types sit above their floors, so slicing it leaves the master
            # dozens of correction rounds short of the actual target.
            x_target = v_relax * reduction.msize.astype(np.float64)
            injected = 0
            # R=1024 is the sweet spot for the first master: hd/obf-class
            # shapes certify on it directly, and when the round-0 master
            # misses (sf_d-class), the face loop's deep R=2048 pass (fresh
            # tie streams via j0) supplies the missing hull diversity at the
            # cost of one more master — cheaper than paying a deep stream
            # plus a large first master on every instance. Beyond ~1k types
            # the finer R=2048 stream pays for itself: the hull needs ~T
            # columns and repair-drop rates rise with the feature count
            # (the n=1200 household quotient, T=1199/F=626, kept only 331
            # of 1024 slices and ground 19 face rounds from ε=2e-2; at
            # R=2048 it keeps ~1400, starts at 1.4e-2, and runs 80→66 s —
            # unlike the measured-unhelpful top-up of SEPARATE phase-shifted
            # streams, one finer stream also tightens the cumulative
            # apportionment feedback to ~1/2048)
            for c in _slice_relaxation(
                x_target, reduction, R=1024 if reduction.T <= 1024 else 2048
            ):
                injected += add_comp(c)
            # NOTE (measured): topping the hull up with extra phase-shifted
            # streams when injected < T (household-quotient instances start
            # under-determined, ε ~ 2e-2) lowers the round-0 ε but does NOT
            # reduce the face-round count — n=1200 couples ran 187 s with
            # the top-up vs 170 s without — so the injection stays single-
            # stream; the ε tail there is integrality structure, not hull
            # bulk (same finding as the large-T deep-pass experiment in
            # face_decompose.py).
            if T <= 64:
                # independent roundings only help at small type counts — at
                # sf_e scale their quota-feasible yield is zero (measured)
                for c in _round_relaxation(x_target, reduction, rng, count=256):
                    injected += add_comp(c)
            log.emit(f"Injected {injected} aimed columns around the relaxation target.")
    else:
        v_relax = resumed.v_relax
    decomposed = False
    with log.timer("decomp"):
        if checkpoint_path is not None and comps:
            from citizensassemblies_tpu.utils.checkpoint import TypeCGState, save_ts_state

            save_ts_state(
                checkpoint_path,
                TypeCGState(
                    compositions=np.stack(comps, axis=0),
                    v_relax=v_relax,
                    coverable=coverable,
                    key=np.asarray(key),
                    round=0,
                    fingerprint=ckpt_fp,
                ),
            )
        from citizensassemblies_tpu.solvers.face_decompose import realize_profile

        C_sup, probs, eps_dev, solves = realize_profile(
            reduction,
            v_relax,
            list(comps),
            oracle,
            cfg.decomp_accept,
            log=log,
            max_rounds=cfg.decomp_max_rounds,
            cfg=cfg,
        )
        lp_solves += solves
    if eps_dev <= max(cfg.decomp_accept, cfg.decomp_accept_stalled):
        # the face loop targets decomp_accept; a stalled residual inside the
        # graded band is still accepted — the panel stage's tolerance is
        # coupled to eps_dev so the end-to-end contract holds (leximin.py) —
        # rather than paying the stage-CG fallback for ε the bar doesn't need
        decomposed = True
        comps = [c.astype(np.int32) for c in C_sup]
        band = " (stalled-band)" if eps_dev > cfg.decomp_accept else ""
        log.emit(
            f"Decomposition: profile realized, ε = {eps_dev:.2e} "
            f"(two-sided){band}, portfolio {len(comps)}."
        )
    else:
        log.emit(
            f"Face decomposition stalled at ε = {eps_dev:.2e} "
            f"(integrality residual); falling back to stage CG."
        )
        # carry the certified support into the stage-CG portfolio
        for c in C_sup:
            add_comp(c)
    if decomposed:
        fixed = v_relax
        C = np.stack(comps, axis=0)
        return TypeCGResult(
            compositions=C,
            probabilities=probs / probs.sum(),
            type_values=fixed,
            coverable=coverable,
            stages=0,
            lp_solves=lp_solves,
            exact_prices=exact_prices,
            eps_dev=eps_dev,
        )

    # ---- phase 2 (fallback): certified stage-wise column generation --------
    def prune_columns(p_now: np.ndarray, keep_last: int = 4000) -> bool:
        """Column management: keep the LP support plus the freshest columns.
        Only as a memory backstop — observed prunes visibly slowed the ε
        decay (discarded columns carry hull information), so the threshold
        sits well above the portfolio a normal stage loop reaches. Returns
        True when columns were actually dropped (the caller must then discard
        any PDHG warm start: its primal vector is ordered for the pre-prune
        column set and a misaligned warm start silently degrades convergence).
        """
        if len(comps) <= 12000:
            return False
        keep = set(np.nonzero(p_now > 1e-12)[0].tolist())
        keep.update(range(max(0, len(comps) - keep_last), len(comps)))
        kept = [comps[i] for i in sorted(keep)]
        comps.clear()
        seen.clear()
        for c in kept:
            add_comp(c)
        return True

    pdhg_warm = None
    while (fixed < 0).any():
        stages += 1
        # stage upper bound + targeted columns from the marginal LP relaxation
        with log.timer("relaxation"):
            z_ub, x_star = _relaxation_bound(reduction, fixed)
            injected = 0
            for c in _slice_relaxation(x_star, reduction, R=384):
                injected += add_comp(c)
            for c in _round_relaxation(x_star, reduction, rng):
                injected += add_comp(c)
        log.emit(
            f"Stage {stages}: relaxation bound {z_ub:.6f}, injected {injected} "
            f"aimed columns (portfolio {len(comps)})."
        )
        def fix_tranche(z: float, y: np.ndarray) -> int:
            """Fix a tranche at value ``z`` from authoritative stage duals:
            probe-certify the dual-proposed candidates on the marginal face
            (a valid certificate for the composition hull, see
            :func:`_marginal_probe_confirm`), keeping the reference's dual
            heuristic (``leximin.py:431-443``) only as the progress guard.
            Mutates ``fixed``; returns the tranche size."""
            nonlocal fixed
            unfixed_idx = np.nonzero(fixed < 0)[0]
            cand = unfixed_idx[y[unfixed_idx] > cfg.eps]
            if len(cand) == 0:
                cand = unfixed_idx[[int(np.argmax(y[unfixed_idx]))]]
            conf = _marginal_probe_confirm(
                reduction, fixed, z, cand, cfg.probe_tol, log=log
            )
            newly = np.zeros(T, dtype=bool)
            newly[cand[conf]] = True
            if not newly.any():
                # nothing marginal-certifiable (the hull face can be strictly
                # inside the marginal face): reference dual heuristic
                newly[unfixed_idx[np.argmax(y[unfixed_idx])]] = True
            fixed = np.where(newly, max(0.0, z - _FIX_MARGIN), fixed)
            return int(newly.sum())

        while True:
            M = np.stack(comps, axis=0).astype(np.float64) / msize[None, :]
            MT = np.ascontiguousarray(M.T)
            with log.timer("stage_lp"):
                # loose-tolerance device PDHG guides pricing; any *fixing*
                # decision below re-solves via host IPM first — approximate
                # duals must never drive the irreversible tranche fix
                authoritative = not use_pdhg
                if use_pdhg:
                    from citizensassemblies_tpu.solvers.lp_pdhg import solve_stage_lp_pdhg

                    z, y, mu, probs, ok, pdhg_warm = solve_stage_lp_pdhg(
                        MT, fixed, cfg=cfg, warm=pdhg_warm
                    )
                    if not ok:
                        z, y, mu, probs = _stage_lp(MT, fixed)
                        pdhg_warm = None
                        authoritative = True
                else:
                    z, y, mu, probs = _stage_lp(MT, fixed)
            lp_solves += 1
            if prune_columns(probs):
                pdhg_warm = None
            bound_tol = max(1e-7, 10 * _SLACK)
            if z >= z_ub - bound_tol:
                if not authoritative:
                    # the PDHG estimate may overshoot the bound; re-check with
                    # the authoritative solve (and keep pricing on its duals
                    # if it lands short)
                    with log.timer("stage_lp"):
                        z, y, mu, probs = _stage_lp(MT, fixed)
                    lp_solves += 1
                    authoritative = True
                if z >= z_ub - bound_tol:
                    # master reached the relaxation bound: certified stage
                    # optimum (the integer hull is inside the relaxation
                    # polytope), no exact pricing needed
                    count = fix_tranche(z, y)
                    log.emit(
                        f"Stage {stages}: z={z:.6f} meets relaxation bound — fixed "
                        f"{count} type(s) ({int((fixed >= 0).sum())}/{T} done)."
                    )
                    break
            w_type = y / msize  # pricing weights per type
            # stochastic pricing: weight-steered batched panel draw
            key, sub = jax.random.split(key)
            with log.timer("stochastic_pricing"):
                scores_w = w_type[type_id]
                from citizensassemblies_tpu.solvers.pricing import _pricing_scores

                scores = _pricing_scores(
                    np.asarray(scores_w, dtype=np.float64), cfg.pricing_batch
                )
                panels, ok = sample_panels_batch(dense, sub, cfg.pricing_batch, scores=scores)
                cand = panels_to_comps(np.asarray(panels)[np.asarray(ok)])
            values = cand.astype(np.float64) @ w_type
            order = np.argsort(-values)
            added = 0
            for i in order:
                if values[i] <= -mu + cfg.eps:
                    break
                if add_comp(cand[i]):
                    added += 1
                    if added >= cfg.cg_columns_typespace:
                        break
            # exact pricing every iteration (as the reference does,
            # leximin.py:420-424 — the MILP is ~40 ms in type space): its
            # column is the single most violated constraint, which first-order
            # sampling alone approaches only slowly
            with log.timer("exact_oracle"):
                got = oracle.maximize(w_type)
            exact_prices += 1
            assert got is not None, "pricing MILP must stay feasible"
            best_comp, value = got
            if value > -mu + cfg.eps and add_comp(best_comp):
                added += 1
            log.emit(
                f"  stage {stages} iter {lp_solves}: z={z:.6f} cap={-mu:.6f} "
                f"exact_best={value:.6f} "
                f"best_sampled={values[order[0]] if len(values) else float('nan'):.6f} "
                f"added {added} (portfolio {len(comps)})."
            )
            if added:
                continue
            log.emit(
                f"Stage {stages}: maximin ≤ {z + max(0.0, value + mu):.4%}, can do "
                f"{z:.4%} with {len(comps)} compositions (gap {value + mu:.2e})."
            )
            if value <= -mu + cfg.eps or not add_comp(best_comp):
                # converged (no composition beats the cap — or the exact
                # oracle repeated a known column, a numerical LP/MILP
                # disagreement we accept as the reference does)
                if not authoritative:
                    with log.timer("stage_lp"):
                        z, y, mu, probs = _stage_lp(MT, fixed)
                    lp_solves += 1
                    pdhg_warm = None
                    # the convergence certificate above priced against
                    # PDHG-approximate duals; re-price once against the
                    # authoritative optimum and keep generating if it still
                    # finds an improving column — a stage must never be
                    # declared converged on non-authoritative duals alone
                    with log.timer("exact_oracle"):
                        got = oracle.maximize(y / msize)
                    exact_prices += 1
                    if got is not None:
                        best_comp, value = got
                        if value > -mu + cfg.eps and add_comp(best_comp):
                            log.emit(
                                f"  stage {stages}: authoritative duals still "
                                f"price an improving column (gap "
                                f"{value + mu:.2e}); continuing."
                            )
                            continue
                count = fix_tranche(z, y)
                log.emit(
                    f"Fixed {count} type(s) "
                    f"({int((fixed >= 0).sum())}/{T} done)."
                )
                break

    C = np.stack(comps, axis=0)
    # final probabilities over the generated portfolio realizing the fixed
    # values (the caller decomposes into concrete panels)
    MT = np.ascontiguousarray((C.astype(np.float64) / msize[None, :]).T)
    A_ub = np.concatenate([-MT, -np.ones((T, 1))], axis=1)
    b_ub = -(fixed - _SLACK)
    A_eq = np.ones((1, C.shape[0] + 1))
    A_eq[0, -1] = 0.0
    c_obj = np.zeros(C.shape[0] + 1)
    c_obj[-1] = 1.0
    res = robust_linprog(
        c_obj, A_ub=A_ub, b_ub=b_ub, A_eq=A_eq, b_eq=[1.0],
        bounds=[(0, None)] * C.shape[0] + [(0, None)],
    )
    lp_solves += 1
    if res.status != 0:
        raise RuntimeError(f"type-space final LP failed: {res.message}")
    probs = np.maximum(res.x[: C.shape[0]], 0.0)
    probs = probs / probs.sum()
    return TypeCGResult(
        compositions=C,
        probabilities=probs,
        type_values=fixed,
        coverable=coverable,
        stages=stages,
        lp_solves=lp_solves,
        exact_prices=exact_prices,
    )
