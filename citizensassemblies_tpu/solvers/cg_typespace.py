"""Column-generation LEXIMIN in composition (type) space.

For instances with too many distinct agent types to enumerate every feasible
composition (``solvers/compositions.py``), the column-generation algorithm of
the reference (``leximin.py:338-470``) still collapses onto types: columns are
*compositions* ``c ∈ Z^T`` rather than agent subsets, the stage LP has one
constraint per type instead of one per agent, and the exact pricing ILP has T
bounded-integer variables and one row per feature — dramatically smaller than
the reference's n-binary-variable committee ILP (``leximin.py:190-233``) and
solved by HiGHS in tens of milliseconds where the agent-space search took
seconds.

Per inner iteration the dual weights steer a *batched* TPU draw of feasible
panels (``models/legacy.py::sample_panels_batch`` with weight-proportional
member scores); sampled panels map onto compositions by type-counting, giving
many violated columns per LP solve. The exact MILP oracle then certifies each
stage's termination, so the fixing logic keeps the reference's exactness
guarantee (``leximin.py:429-443``) at a fraction of its solve count.
"""

from __future__ import annotations

import dataclasses
from typing import Dict, List, Optional, Tuple

import numpy as np
import scipy.optimize
import scipy.sparse

from citizensassemblies_tpu.solvers.native_oracle import TypeReduction
from citizensassemblies_tpu.utils.config import Config, default_config
from citizensassemblies_tpu.utils.logging import RunLog

_SLACK = 1e-9


class CompositionOracle:
    """Exact ``max Σ_t w_t c_t`` over feasible compositions (HiGHS MILP).

    The type-space collapse of the reference's committee-generation ILP
    (``leximin.py:190-233``): variables are per-type member counts with bounds
    ``[0, m_t]``, constraints are ``Σc = k`` plus one row per feature quota.
    """

    def __init__(self, reduction: TypeReduction):
        self.red = reduction
        T, F = reduction.T, reduction.F
        tf = np.zeros((T, F))
        for t in range(T):
            tf[t, reduction.type_feature[t]] = 1.0
        A = scipy.sparse.vstack(
            [scipy.sparse.csr_matrix(np.ones((1, T))), scipy.sparse.csr_matrix(tf.T)]
        )
        self._constraints = scipy.optimize.LinearConstraint(
            A,
            np.concatenate([[reduction.k], reduction.qmin]),
            np.concatenate([[reduction.k], reduction.qmax]),
        )
        self._integrality = np.ones(T)

    def maximize(
        self, weights: np.ndarray, forced_type: Optional[int] = None
    ) -> Optional[Tuple[np.ndarray, float]]:
        """Best feasible composition for per-type ``weights``; optionally force
        ``c_t ≥ 1`` for one type (the coverage solves of ``leximin.py:279-289``).
        Returns None when infeasible."""
        lo = np.zeros(self.red.T)
        if forced_type is not None:
            lo[forced_type] = 1.0
        res = scipy.optimize.milp(
            c=-np.asarray(weights, dtype=np.float64),
            constraints=self._constraints,
            bounds=scipy.optimize.Bounds(lo, self.red.msize.astype(np.float64)),
            integrality=self._integrality,
        )
        if res.status != 0 or res.x is None:
            return None
        comp = np.round(res.x).astype(np.int32)
        return comp, float(-res.fun)


@dataclasses.dataclass
class TypeCGResult:
    compositions: np.ndarray  # int32 [C, T] generated portfolio
    probabilities: np.ndarray  # float64 [C]
    type_values: np.ndarray  # float64 [T]
    coverable: np.ndarray  # bool [T]
    stages: int
    lp_solves: int
    exact_prices: int


def _stage_lp(
    MT: np.ndarray, fixed: np.ndarray
) -> Tuple[float, np.ndarray, float, np.ndarray]:
    """Maximize the minimum unfixed type value over the portfolio.

    Returns ``(z*, y, mu, p)`` where ``y ≥ 0`` are per-unfixed-type duals
    (Σy = 1), ``mu`` the normalization dual — a candidate composition ``c``
    improves the stage iff ``Σ_t ŷ_t c_t/m_t > −mu`` with ``ŷ`` the full dual
    vector (fixed types included).
    """
    T, C = MT.shape
    unfixed = np.nonzero(fixed < 0)[0]
    done = np.nonzero(fixed >= 0)[0]
    nu, nd = len(unfixed), len(done)
    A_ub = np.zeros((nu + nd, C + 1))
    A_ub[:nu, :C] = -MT[unfixed]
    A_ub[:nu, C] = 1.0
    b_ub = np.zeros(nu + nd)
    if nd:
        A_ub[nu:, :C] = -MT[done]
        b_ub[nu:] = -(fixed[done] - _SLACK)
    A_eq = np.ones((1, C + 1))
    A_eq[0, C] = 0.0
    c_obj = np.zeros(C + 1)
    c_obj[C] = -1.0
    res = scipy.optimize.linprog(
        c_obj, A_ub=A_ub, b_ub=b_ub, A_eq=A_eq, b_eq=[1.0],
        bounds=[(0, None)] * C + [(None, None)], method="highs",
    )
    if res.status != 0:
        raise RuntimeError(f"type-space stage LP failed: {res.message}")
    marg = -np.asarray(res.ineqlin.marginals)  # ≥ 0
    y_full = np.zeros(len(fixed))
    y_full[unfixed] = marg[:nu]
    if nd:
        y_full[done] = marg[nu:]
    mu = float(res.eqlin.marginals[0])
    return float(res.x[C]), y_full, mu, np.maximum(res.x[:C], 0.0)


def leximin_cg_typespace(
    dense,
    reduction: TypeReduction,
    cfg: Optional[Config] = None,
    log: Optional[RunLog] = None,
    key=None,
) -> TypeCGResult:
    """LEXIMIN via column generation over compositions.

    Outer/inner loop structure of ``leximin.py:383-449``; see module
    docstring for the type-space re-design.
    """
    import jax

    from citizensassemblies_tpu.models.legacy import sample_panels_batch

    cfg = cfg or default_config()
    log = log or RunLog(echo=False)
    if key is None:
        key = jax.random.PRNGKey(cfg.solver_seed)
    T = reduction.T
    msize = reduction.msize.astype(np.float64)
    type_id = reduction.type_id
    oracle = CompositionOracle(reduction)

    comps: List[np.ndarray] = []
    seen: Dict[bytes, int] = {}

    def add_comp(c: np.ndarray) -> bool:
        kb = c.astype(np.int16).tobytes()
        if kb in seen:
            return False
        seen[kb] = len(comps)
        comps.append(c.astype(np.int32))
        return True

    def panels_to_comps(panels: np.ndarray) -> np.ndarray:
        tids = type_id[panels]  # [B, k]
        B = panels.shape[0]
        out = np.zeros((B, T), dtype=np.int32)
        rows = np.repeat(np.arange(B), panels.shape[1])
        np.add.at(out, (rows, tids.ravel()), 1)
        return out

    # ---- seeding: one batched device draw + per-uncovered-type coverage ----
    with log.timer("seed"):
        key, sub = jax.random.split(key)
        budget = max(256, min(cfg.mw_rounds_factor * T, cfg.seed_batch))
        panels, ok = sample_panels_batch(dense, sub, budget)
        panels = np.asarray(panels)
        ok = np.asarray(ok)
        for c in panels_to_comps(panels[ok]):
            add_comp(c)
        coverable = np.zeros(T, dtype=bool)
        for c in comps:
            coverable |= c > 0
        log.emit(
            f"Seeding: {len(comps)} distinct compositions from {int(ok.sum())} "
            f"sampled panels, covering {int(coverable.sum())}/{T} types."
        )
        for t in range(T):
            if coverable[t]:
                continue
            got = oracle.maximize((~coverable).astype(np.float64), forced_type=t)
            if got is None:
                continue
            add_comp(got[0])
            coverable |= got[0] > 0

    fixed = np.full(T, -1.0)
    fixed[~coverable] = 0.0
    if (~coverable).any():
        log.emit(f"{int((~coverable).sum())} type(s) in no feasible committee.")

    stages = 0
    lp_solves = 0
    exact_prices = 0
    probs = None
    # device PDHG for the recurring stage LP when an accelerator is present
    # (or forced via backend="jax"); host HiGHS otherwise and as fallback
    use_pdhg = cfg.backend == "jax" or (
        cfg.backend == "hybrid" and jax.default_backend() not in ("cpu",)
    )
    pdhg_warm = None

    while (fixed < 0).any():
        stages += 1
        while True:
            M = np.stack(comps, axis=0).astype(np.float64) / msize[None, :]
            MT = np.ascontiguousarray(M.T)
            with log.timer("stage_lp"):
                if use_pdhg:
                    from citizensassemblies_tpu.solvers.lp_pdhg import solve_stage_lp_pdhg

                    z, y, mu, probs, ok, pdhg_warm = solve_stage_lp_pdhg(
                        MT, fixed, cfg=cfg, warm=pdhg_warm
                    )
                    if not ok:
                        z, y, mu, probs = _stage_lp(MT, fixed)
                        pdhg_warm = None
                else:
                    z, y, mu, probs = _stage_lp(MT, fixed)
            lp_solves += 1
            w_type = y / msize  # pricing weights per type
            # stochastic pricing: weight-steered batched panel draw
            key, sub = jax.random.split(key)
            with log.timer("stochastic_pricing"):
                scores_w = w_type[type_id]
                from citizensassemblies_tpu.solvers.pricing import _pricing_scores

                scores = _pricing_scores(
                    np.asarray(scores_w, dtype=np.float64), cfg.pricing_batch
                )
                panels, ok = sample_panels_batch(dense, sub, cfg.pricing_batch, scores=scores)
                cand = panels_to_comps(np.asarray(panels)[np.asarray(ok)])
            values = cand.astype(np.float64) @ w_type
            order = np.argsort(-values)
            added = 0
            for i in order[: 4 * cfg.cg_columns_per_round]:
                if values[i] <= -mu + cfg.eps:
                    break
                if add_comp(cand[i]):
                    added += 1
                    if added >= cfg.cg_columns_per_round:
                        break
            if added:
                continue
            # certification: exact MILP pricing (leximin.py:420-431)
            with log.timer("exact_oracle"):
                got = oracle.maximize(w_type)
            exact_prices += 1
            assert got is not None, "pricing MILP must stay feasible"
            best_comp, value = got
            log.emit(
                f"Stage {stages}: maximin ≤ {z + max(0.0, value + mu):.4%}, can do "
                f"{z:.4%} with {len(comps)} compositions (gap {value + mu:.2e})."
            )
            if value <= -mu + cfg.eps:
                newly = (y > cfg.eps) & (fixed < 0)
                if not newly.any():
                    unfixed_idx = np.nonzero(fixed < 0)[0]
                    newly = np.zeros(T, dtype=bool)
                    newly[unfixed_idx[np.argmax(y[unfixed_idx])]] = True
                fixed = np.where(newly, max(0.0, z), fixed)
                log.emit(
                    f"Fixed {int(newly.sum())} type(s) "
                    f"({int((fixed >= 0).sum())}/{T} done)."
                )
                break
            if not add_comp(best_comp):
                # numerical disagreement between LP duals and MILP: accept
                newly = (y > cfg.eps) & (fixed < 0)
                if not newly.any():
                    unfixed_idx = np.nonzero(fixed < 0)[0]
                    newly = np.zeros(T, dtype=bool)
                    newly[unfixed_idx[np.argmax(y[unfixed_idx])]] = True
                fixed = np.where(newly, max(0.0, z), fixed)
                log.emit("Exact oracle repeated a known composition; accepting gap.")
                break

    C = np.stack(comps, axis=0)
    # final probabilities over the generated portfolio realizing the fixed
    # values (the caller decomposes into concrete panels)
    MT = np.ascontiguousarray((C.astype(np.float64) / msize[None, :]).T)
    A_ub = np.concatenate([-MT, -np.ones((T, 1))], axis=1)
    b_ub = -(fixed - _SLACK)
    A_eq = np.ones((1, C.shape[0] + 1))
    A_eq[0, -1] = 0.0
    c_obj = np.zeros(C.shape[0] + 1)
    c_obj[-1] = 1.0
    res = scipy.optimize.linprog(
        c_obj, A_ub=A_ub, b_ub=b_ub, A_eq=A_eq, b_eq=[1.0],
        bounds=[(0, None)] * C.shape[0] + [(0, None)], method="highs",
    )
    lp_solves += 1
    if res.status != 0:
        raise RuntimeError(f"type-space final LP failed: {res.message}")
    probs = np.maximum(res.x[: C.shape[0]], 0.0)
    probs = probs / probs.sum()
    return TypeCGResult(
        compositions=C,
        probabilities=probs,
        type_values=fixed,
        coverable=coverable,
        stages=stages,
        lp_solves=lp_solves,
        exact_prices=exact_prices,
    )
